from tpumon.app import main

raise SystemExit(main())
