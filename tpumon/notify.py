"""Alert webhook notification sinks.

The reference has no alert delivery at all — alerts exist only as long as
a browser polls ``/api/alerts`` (monitor_server.js:282-288); nobody is
told when a pod crash-loops at 3am. tpumon pushes alert *transitions*
(fired / resolved, as recorded on the AlertEngine event timeline) to
configured webhook URLs so alerts reach paging/chat systems without a
browser open.

Design:
- The sampler owns dispatch (single writer, same stance as SURVEY §5.2):
  after each alert evaluation it hands newly-appended timeline events to
  the notifier. Delivery is fire-and-forget on background asyncio tasks —
  a slow or dead sink never blocks the sample loop.
- Generic sinks get a JSON POST ``{"source": "tpumon", "host": ...,
  "events": [{ts, state, severity, title, desc, fix, key}]}``.
- Slack-compatible sinks (URL host ``hooks.slack.com`` or a ``slack+``
  scheme prefix) get ``{"text": "..."}`` with one line per event.
- Failures are counted per-sink and surfaced in ``/api/health`` — a
  misconfigured webhook is itself an observable condition, never an
  exception in the sample path.
"""

from __future__ import annotations

import asyncio
import json
import socket
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass, field

_SEV_RANK = {"minor": 0, "serious": 1, "critical": 2}

_EMOJI = {"minor": "🟡", "serious": "🟠", "critical": "🔴"}


def slack_text(events: list[dict], hostname: str) -> str:
    lines = [f"tpumon on {hostname}:"]
    for e in events:
        if e.get("state") == "resolved":
            lines.append(f"✅ resolved: {e.get('title')}")
        else:
            emoji = _EMOJI.get(e.get("severity", ""), "⚪")
            line = f"{emoji} {e.get('title')}: {e.get('desc')}"
            if e.get("fix"):
                line += f"\n    fix: {e['fix']}"
            lines.append(line)
    return "\n".join(lines)


@dataclass
class SinkStats:
    url: str
    kind: str  # "generic" | "slack"
    sent: int = 0
    failures: int = 0
    last_error: str | None = None
    last_sent_ts: float | None = None

    def to_json(self) -> dict:
        # Redact path+query: webhook URLs carry capability tokens (Slack
        # webhook paths ARE the secret) and /api/health is unauthenticated.
        parts = urllib.parse.urlsplit(self.url)
        return {
            "url": f"{parts.scheme}://{parts.netloc}/…",
            "kind": self.kind,
            "sent": self.sent,
            "failures": self.failures,
            "last_error": self.last_error,
            "last_sent_ts": self.last_sent_ts,
        }


@dataclass
class WebhookNotifier:
    """Pushes alert fired/resolved events to HTTP sinks."""

    urls: tuple[str, ...]
    min_severity: str = "minor"
    timeout_s: float = 5.0
    hostname: str = field(default_factory=socket.gethostname)

    def __post_init__(self) -> None:
        if self.min_severity not in _SEV_RANK:
            raise ValueError(
                f"webhook_min_severity: want one of {sorted(_SEV_RANK)}, "
                f"got {self.min_severity!r}"
            )
        self.sinks: list[SinkStats] = []
        for url in self.urls:
            kind = "generic"
            if url.startswith("slack+"):
                url, kind = url[len("slack+"):], "slack"
            elif urllib.parse.urlsplit(url).hostname == "hooks.slack.com":
                kind = "slack"
            self.sinks.append(SinkStats(url=url, kind=kind))
        # Per-sink delivery locks: batches must reach each sink in the
        # order notify() was called (a fast "resolved" POST overtaking its
        # slow "fired" would leave a pager stuck active). asyncio.Lock is
        # FIFO-fair, and notify() runs on the event loop in order.
        self._locks = [asyncio.Lock() for _ in self.sinks]
        self._inflight: set[asyncio.Task] = set()

    # ------------------------------------------------------------------

    def _wants(self, event: dict) -> bool:
        if event.get("state") == "resolved":
            return True  # resolutions always close the loop
        rank = _SEV_RANK.get(event.get("severity", ""), 0)
        return rank >= _SEV_RANK.get(self.min_severity, 0)

    def _post(self, sink: SinkStats, events: list[dict]) -> None:
        if sink.kind == "slack":
            payload = {"text": slack_text(events, self.hostname)}
        else:
            payload = {
                "source": "tpumon",
                "host": self.hostname,
                "ts": time.time(),
                "events": events,
            }
        req = urllib.request.Request(
            sink.url,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                r.read()
            sink.sent += 1
            sink.last_error = None
            sink.last_sent_ts = time.time()
        except Exception as e:
            sink.failures += 1
            sink.last_error = f"{type(e).__name__}: {e}"

    async def _post_ordered(
        self, sink: SinkStats, lock: asyncio.Lock, events: list[dict]
    ) -> None:
        async with lock:
            await asyncio.to_thread(self._post, sink, events)

    async def _dispatch(self, events: list[dict]) -> None:
        await asyncio.gather(
            *(
                self._post_ordered(s, lock, events)
                for s, lock in zip(self.sinks, self._locks)
            )
        )

    def notify(self, events: list[dict]) -> None:
        """Schedule delivery of timeline events. Non-blocking; safe to
        call from the sample loop."""
        batch = [e for e in events if self._wants(e)]
        if not batch or not self.sinks:
            return
        task = asyncio.ensure_future(self._dispatch(batch))
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    async def close(self) -> None:
        """Let in-flight deliveries finish (bounded by timeout_s)."""
        if self._inflight:
            await asyncio.gather(*list(self._inflight), return_exceptions=True)

    def to_json(self) -> dict:
        return {
            "min_severity": self.min_severity,
            "sinks": [s.to_json() for s in self.sinks],
        }
