"""SLO engine: error budgets + multi-window multi-burn-rate alerts.

The monitor's alerting so far is *level*-based: a gauge crossed a
threshold **now**. A latency SLO burns toward breach long before any
single reading looks alarming — and pages on a 5-minute blip it should
have ignored. This module closes that gap with the Google-SRE
multi-window multi-burn-rate recipe (SRE Workbook ch. 5), built on the
in-tree query engine (tpumon.query, docs/query.md):

- **Objectives** are declared in config (``slos: [{name, tenant, expr,
  target, window}]``). ``expr`` is the *bad-event condition*, written
  in the query language over the monitor's own TSDB series (typically
  the per-tenant ``serving.<tenant>.*`` series the traffic-driven
  engine lands — e.g. ``serving.ttft_p95_ms{tenant="chat"} > 250``).
  Each tick the compiled condition evaluates to a bad fraction in
  [0, 1] that is RECORDED as a ``slo.<name>.bad`` TSDB series — the
  raw material every window aggregate reads.
- **Burn rates** are compiled query expressions over that series
  (``avg_over_time(slo.bad{slo="x"}[5m]) / budget``), compiled ONCE
  per config — no hand-rolled rule closures — and re-evaluated on a
  short-window/24 cadence (a burn rate over a w-second window moves at
  w-granularity; the cadence bounds alert latency at ~4% of the short
  window while keeping per-tick cost flat). The window aggregates ride
  the recording-rule store (the sampler registers ``slo.bad[w]`` rules
  for every declared window), so each read is an O(sub-buckets)
  head-state merge, never a point walk.
  Two alert speeds, each gated on BOTH its windows (the short window
  suppresses flap, the long window proves it's real): the *fast* pair
  (5m/1h at 14.4× budget burn) pages, the *slow* pair (30m/6h at 6×)
  files a ticket. Windows derive from the SLO period by the SRE-
  workbook ratios (period/720 and /120, each with a 1/12 short window)
  and may be overridden per objective — the closed-loop soak runs
  second-scale windows. Clearing takes *either* window dropping below
  ``clear_ratio`` × threshold — recovery hysteresis, so a burn
  hovering at the line doesn't flap.
- **Error budget**: 1 − (bad fraction over the whole SLO window) /
  (1 − target); negative = exhausted. Windows longer than the ring's
  retention average over what exists (warmup semantics — tested).

Outputs: an ``slo`` journal event per fire/resolve, alert rows the
AlertEngine serves (fast → critical page, slow → minor ticket),
``GET /api/slo`` on its own epoch-cache section, ``tpumon_slo_*``
exporter gauges, the dashboard burn-down card, and ``tpumon slo``
(this module's CLI). docs/slo.md has the math and the soak walkthrough.
"""

from __future__ import annotations

import json
import re
import sys
import time
from dataclasses import dataclass

from tpumon.query import QueryError, parse, parse_range

# SRE-workbook defaults: 14.4× burn over 5m/1h pages (2% of a 30d
# budget in one hour), 6× over 30m/6h tickets (5% in six hours).
DEFAULT_FAST_BURN = 14.4
DEFAULT_SLOW_BURN = 6.0
DEFAULT_CLEAR_RATIO = 0.9

# Dot-free (the ``slo.<name>.bad`` series name and its derived {slo=}
# label both split on dots) and expression-safe.
_NAME_RE = re.compile(r"^[A-Za-z][A-Za-z0-9_-]*$")

SPEEDS = ("fast", "slow")


def _fmt_s(w: float) -> str:
    """Seconds as a plain-decimal duration literal — ``{:g}`` would
    produce exponent notation for month-scale windows, which the
    duration grammar rejects."""
    text = f"{w:.6f}".rstrip("0").rstrip(".")
    return f"{text}s"


def _dur(v, what: str) -> float:
    try:
        s = parse_range(str(v))
    except QueryError as e:
        raise ValueError(f"{what}: {e}")
    if s <= 0:
        raise ValueError(f"{what}: want a positive duration, got {v!r}")
    return s


@dataclass(frozen=True)
class SLOSpec:
    """One objective, validated. ``windows[speed]`` is the (short_s,
    long_s) burn-window pair; ``burns[speed]`` its fire threshold."""

    name: str
    expr: str
    target: float
    window_s: float
    tenant: str = ""
    fast: tuple[float, float] = (300.0, 3600.0)
    slow: tuple[float, float] = (1800.0, 21600.0)
    fast_burn: float = DEFAULT_FAST_BURN
    slow_burn: float = DEFAULT_SLOW_BURN
    clear_ratio: float = DEFAULT_CLEAR_RATIO

    @property
    def budget_fraction(self) -> float:
        return 1.0 - self.target

    def windows(self, speed: str) -> tuple[float, float]:
        return self.fast if speed == "fast" else self.slow

    def burn_threshold(self, speed: str) -> float:
        return self.fast_burn if speed == "fast" else self.slow_burn

    @classmethod
    def parse(cls, raw: dict) -> "SLOSpec":
        """Build a spec from one ``slos`` config entry; raises
        ValueError with an operator-readable message on any problem
        (a misdeclared objective must be an incident, not a silent
        no-op — the sampler journals it)."""
        if not isinstance(raw, dict):
            raise ValueError(f"slo entry must be an object, got {raw!r}")
        name = str(raw.get("name") or "")
        if not _NAME_RE.match(name):
            raise ValueError(
                f"slo name {name!r} must match {_NAME_RE.pattern} "
                f"(it names the slo.<name>.bad series)")
        expr = str(raw.get("expr") or "")
        try:
            parse(expr)
        except QueryError as e:
            raise ValueError(f"slo {name}: bad expr {expr!r}: {e}")
        try:
            target = float(raw.get("target", 0.0))
        except (TypeError, ValueError):
            raise ValueError(f"slo {name}: bad target {raw.get('target')!r}")
        if not 0.0 < target < 1.0:
            raise ValueError(
                f"slo {name}: target must be in (0, 1), got {target} "
                f"(0.99 = 99% of events good)")
        window_s = _dur(raw.get("window", "30d"), f"slo {name} window")
        # Burn windows: explicit ["5m","1h"] pairs, else the SRE-
        # workbook derivation from the SLO period (for 30d: 5m/1h fast,
        # 30m/6h slow).
        pairs: dict[str, tuple[float, float]] = {}
        for speed, divisor in (("fast", 720.0), ("slow", 120.0)):
            given = raw.get(speed)
            if given is not None:
                if not (isinstance(given, (list, tuple)) and len(given) == 2):
                    raise ValueError(
                        f"slo {name}: {speed} wants [short, long] "
                        f"durations, got {given!r}")
                short = _dur(given[0], f"slo {name} {speed} short")
                long_ = _dur(given[1], f"slo {name} {speed} long")
            else:
                long_ = window_s / divisor
                short = long_ / 12.0
            if short >= long_:
                raise ValueError(
                    f"slo {name}: {speed} short window ({short:g}s) must "
                    f"be below its long window ({long_:g}s)")
            pairs[speed] = (short, long_)
        extra = {}
        for key, default in (
            ("fast_burn", DEFAULT_FAST_BURN),
            ("slow_burn", DEFAULT_SLOW_BURN),
            ("clear_ratio", DEFAULT_CLEAR_RATIO),
        ):
            try:
                extra[key] = float(raw.get(key, default))
            except (TypeError, ValueError):
                raise ValueError(f"slo {name}: bad {key} {raw.get(key)!r}")
            if extra[key] <= 0:
                raise ValueError(f"slo {name}: {key} must be positive")
        if extra["clear_ratio"] > 1.0:
            raise ValueError(
                f"slo {name}: clear_ratio must be <= 1 (clearing above "
                f"the fire threshold would never clear)")
        known = {
            "name", "expr", "target", "window", "tenant", "fast", "slow",
            "fast_burn", "slow_burn", "clear_ratio",
        }
        unknown = set(raw) - known
        if unknown:
            raise ValueError(
                f"slo {name}: unknown keys {sorted(unknown)}")
        return cls(
            name=name, expr=expr, target=target, window_s=window_s,
            tenant=str(raw.get("tenant") or ""),
            fast=pairs["fast"], slow=pairs["slow"], **extra,
        )


def _is_condition(node) -> bool:
    """True when the expression's root is a comparison (possibly under
    and/or): its value is boolean — present/true means the tick is bad.
    Anything else is read as a bad *fraction* (e.g. an error-rate
    series already in [0, 1])."""
    from tpumon.query import Bin

    if isinstance(node, Bin):
        if node.op in ("and", "or"):
            return _is_condition(node.lhs) or _is_condition(node.rhs)
        return node.op in (">", "<", ">=", "<=", "==", "!=")
    return False


class _Compiled:
    """Per-spec compiled artifacts: the bad-event condition, the four
    burn-window aggregates and the budget aggregate — all parsed ONCE
    at construction (the no-hardcoded-rule-closures contract)."""

    def __init__(self, spec: SLOSpec):
        self.spec = spec
        self.bad_node = parse(spec.expr)
        self.condition = _is_condition(self.bad_node)
        # Fraction-mode staleness bound: a per-tick bad-fraction sample
        # read from data older than the objective's shortest burn
        # window is not a current observation — it reads as absent
        # (unknown), so a vanished source's windows actually drain and
        # a firing alert resolves instead of paging on the engine's
        # 5-minute default lookback forever. Condition mode keeps the
        # default lookback: its absent-never-fires contract already
        # fails safe, and Prometheus-style staleness there matches the
        # alert engine's comparison semantics.
        self.stale_s = min(spec.fast[0], spec.slow[0])
        self.series = f"slo.{spec.name}.bad"
        self.handle = None  # resolved lazily against the live ring
        # Page-state series (the actuation engine's trigger,
        # docs/actuation.md): slo.<name>.paging is 1.0 while the FAST
        # window pair burns (the paging pair — slow tickets don't
        # actuate), 0.0 otherwise, recorded every tick so a policy
        # condition like ``slo.paging{slo="x"} > 0`` reads live state
        # rather than the alert engine's internals.
        self.page_series = f"slo.{spec.name}.paging"
        self.page_handle = None
        sel = f'slo.bad{{slo="{spec.name}"}}'
        self.window_nodes = {
            speed: tuple(
                parse(f"avg_over_time({sel}[{_fmt_s(w)}])")
                for w in spec.windows(speed)
            )
            for speed in SPEEDS
        }
        self.budget_node = parse(
            f"avg_over_time({sel}[{_fmt_s(spec.window_s)}])")
        # speed -> firing state (the engine's hysteresis memory).
        self.firing = {speed: False for speed in SPEEDS}
        # Window-evaluation cadence (docs/slo.md): a burn rate over a
        # w-second window moves at w-granularity, so re-evaluating each
        # pair every short/24 seconds loses nothing (alert latency is
        # bounded by ~4% of the short window) and keeps the per-tick
        # cost flat no matter how slow the ticks' windows are. 0 =
        # evaluate on the next observe.
        self.next_eval = {speed: 0.0 for speed in SPEEDS}
        self.next_budget = 0.0
        self.burn: dict[str, dict] = {
            speed: {
                "short_s": spec.windows(speed)[0],
                "long_s": spec.windows(speed)[1],
                "threshold": spec.burn_threshold(speed),
                "short": None,
                "long": None,
                "firing": False,
            }
            for speed in SPEEDS
        }
        self.budget = {"bad_fraction": None, "used": None, "remaining": None}
        self.last_bad: float | None = None
        self.row: dict | None = None  # cached /api/slo row


def _first_value(v) -> float | None:
    """Collapse an eval result to one number: the slo.bad selector
    matches exactly one series, so a vector has 0 or 1 elements."""
    if isinstance(v, list):
        return v[0][1] if v else None
    if v is None or v != v:  # None / NaN
        return None
    return float(v)


class SLOEngine:
    """Per-tick evaluator over one sampler's query engine + ring.

    ``observe(ts)`` records each objective's bad fraction, re-evaluates
    the compiled burn-rate expressions, runs the both-windows-must-fire
    / either-window-clears state machine, journals ``slo`` events on
    transitions, and returns True when the published /api/slo payload
    changed (the sampler bumps the "slo" dirty section on that)."""

    def __init__(self, specs: list[SLOSpec], query, history, journal):
        self.query = query
        self.history = history
        self.journal = journal
        self.compiled = [_Compiled(s) for s in specs]
        # The slo.<name>.paging series exists FOR actuation conditions
        # (docs/actuation.md); the sampler flips this on when policies
        # are configured — a monitor with SLOs but no actuations must
        # not pay a per-objective TSDB append every tick for a series
        # nothing reads.
        self.record_paging = False
        self.evaluated_at: float | None = None
        self._payload: dict | None = None

    def rule_texts(self) -> list[str]:
        """Recording rules covering every burn/budget window over the
        ``slo.bad`` family: registered by the sampler alongside the
        config's own rules, so the per-tick ``avg_over_time`` reads are
        O(sub-buckets) head-state merges at any window length instead
        of point walks (the PR 12 append-time-aggregation contract;
        bench.py's ``slo`` phase pins the ≤2% tick overhead this
        buys)."""
        windows: set[float] = set()
        for c in self.compiled:
            for speed in SPEEDS:
                windows.update(c.spec.windows(speed))
            windows.add(c.spec.window_s)
        return [f"slo.bad[{_fmt_s(w)}]" for w in sorted(windows)]

    # ----------------------------- evaluation -----------------------------

    def _bad_fraction(self, c: _Compiled, ctx) -> float | None:
        if c.condition:
            # Boolean semantics with the alert engine's None contract:
            # a condition over absent data never fires (0.0 = good).
            # eval_condition short-circuits the selector-vs-constant
            # shape without materializing label vectors — the per-tick
            # hot path the ≤2% eval-overhead bound budgets for.
            try:
                return 1.0 if self.query.eval_condition(
                    c.bad_node, ctx=ctx) else 0.0
            except QueryError:
                return None
        ctx.lookback_s = c.stale_s  # see _Compiled.stale_s
        try:
            v = self.query.eval_compiled(c.bad_node, ctx=ctx)
        except QueryError:
            return None
        finally:
            ctx.lookback_s = None
        # Fraction semantics: no data is *unknown*, not good.
        if isinstance(v, list):
            vals = [x for _, x in v if x is not None and x == x]
            if not vals:
                return None
            v = sum(vals) / len(vals)
        if v is None or v != v:
            return None
        return min(1.0, max(0.0, float(v)))

    def _avg(self, c: _Compiled, node, ctx) -> float | None:
        try:
            return _first_value(self.query.eval_compiled(node, ctx=ctx))
        except QueryError:
            return None

    def observe(self, ts: float | None = None) -> bool:
        ts = time.time() if ts is None else ts
        # One evaluation context for the whole tick: the pod-attribution
        # augmenter builds once, and point fetches are shared across
        # every compiled expression at this instant.
        ctx = self.query.context(at=ts)
        batch = []
        changed = False
        for c in self.compiled:
            bad = self._bad_fraction(c, ctx)
            if bad != c.last_bad:
                c.last_bad = bad
                changed = True
                c.row = None
            if bad is not None:
                if c.handle is None or (
                        self.history.series.get(c.series) is not c.handle):
                    # Lazy + restore-safe: a snapshot restore replaces
                    # series objects (same contract as the sampler's
                    # handle caches).
                    c.handle = self.history.handle(c.series)
                batch.append((c.handle, bad))
        if batch:
            self.history.record_batch(batch, ts=ts)
        for c in self.compiled:
            spec = c.spec
            budget_frac = spec.budget_fraction
            for speed in SPEEDS:
                if ts < c.next_eval[speed]:
                    continue
                short_w = spec.windows(speed)[0]
                short_node, long_node = c.window_nodes[speed]
                short_avg = self._avg(c, short_node, ctx)
                long_avg = self._avg(c, long_node, ctx)
                # The cadence clock only starts once data exists: a
                # warmup eval over an empty series retries next tick
                # (cheap — no matching series) instead of holding the
                # None verdict for a whole cadence period.
                if short_avg is not None or long_avg is not None:
                    c.next_eval[speed] = ts + short_w / 24.0
                short_burn = (
                    None if short_avg is None else short_avg / budget_frac)
                long_burn = (
                    None if long_avg is None else long_avg / budget_frac)
                thr = spec.burn_threshold(speed)
                clear_thr = thr * spec.clear_ratio
                was = c.firing[speed]
                if not was:
                    # Both windows must exceed the threshold to fire —
                    # the short window proves it's current, the long
                    # window proves it's sustained.
                    if (short_burn is not None and long_burn is not None
                            and short_burn >= thr and long_burn >= thr):
                        c.firing[speed] = True
                        self._journal(c, speed, "fired",
                                      short_burn, long_burn, thr)
                else:
                    # Either window dropping below clear_ratio × the
                    # threshold clears (recovery hysteresis: between
                    # clear and fire the alert holds its state).
                    if (short_burn is not None and long_burn is not None
                            and (short_burn < clear_thr
                                 or long_burn < clear_thr)):
                        c.firing[speed] = False
                        self._journal(c, speed, "resolved",
                                      short_burn, long_burn, thr)
                    elif short_burn is None and long_burn is None:
                        # Both windows drained with no data at all (a
                        # fraction-mode objective whose source series
                        # vanished): no evidence of burn remains, so
                        # resolve instead of paging forever on stale
                        # state — the source-down / target-unreachable
                        # alerts own the outage story.
                        c.firing[speed] = False
                        self._journal(c, speed, "resolved",
                                      0.0, 0.0, thr)
                b = c.burn[speed]
                new = (_r(short_burn), _r(long_burn), c.firing[speed])
                if (b["short"], b["long"], b["firing"]) != new:
                    b["short"], b["long"], b["firing"] = new
                    changed = True
                    c.row = None
            if ts >= c.next_budget:
                # Budget moves at SLO-window granularity: the slow
                # pair's cadence is plenty. Same warmup rule as the
                # window pairs: no data, no cadence hold.
                window_avg = self._avg(c, c.budget_node, ctx)
                if window_avg is not None:
                    c.next_budget = ts + spec.slow[0] / 24.0
                used = (
                    None if window_avg is None else window_avg / budget_frac)
                new_budget = {
                    "bad_fraction": _r(window_avg),
                    "used": _r(used),
                    "remaining": None if used is None else _r(1.0 - used),
                }
                if new_budget != c.budget:
                    c.budget = new_budget
                    changed = True
                    c.row = None
            if c.row is None:
                c.row = {
                    "name": spec.name,
                    "tenant": spec.tenant,
                    "expr": spec.expr,
                    "target": spec.target,
                    "window_s": spec.window_s,
                    "bad": _r(c.last_bad),
                    "budget": c.budget,
                    "burn": c.burn,
                }
        # Page-state series AFTER the burn state machine so the value
        # reflects THIS tick's verdict (recording it with the bad batch
        # above would lag the fire/clear by one tick — an actuation
        # policy keyed on it would shed one tick late, and keep
        # shedding one tick past recovery).
        page_batch = []
        if self.record_paging:
            for c in self.compiled:
                if c.page_handle is None or (
                        self.history.series.get(c.page_series)
                        is not c.page_handle):
                    c.page_handle = self.history.handle(c.page_series)
                page_batch.append(
                    (c.page_handle, 1.0 if c.firing["fast"] else 0.0))
        if page_batch:
            self.history.record_batch(page_batch, ts=ts)
        first = self._payload is None
        self.evaluated_at = ts
        if changed or first:
            self._payload = {"slos": [c.row for c in self.compiled]}
        return changed or first

    def _journal(self, c: _Compiled, speed: str, state: str,
                 short_burn: float, long_burn: float, thr: float) -> None:
        spec = c.spec
        sev = ("critical" if speed == "fast" else "minor")
        if state == "resolved":
            sev = "info"
        self.journal.record(
            "slo", sev, "slo",
            f"SLO {spec.name} {speed}-window burn {state}: "
            f"{short_burn:.1f}x/{long_burn:.1f}x vs {thr:g}x budget burn",
            slo=spec.name,
            tenant=spec.tenant or None,
            window=speed,
            state=state,
            burn_short=round(short_burn, 3),
            burn_long=round(long_burn, 3),
            threshold=thr,
        )

    # ------------------------------ outputs -------------------------------

    def to_json(self) -> dict:
        return {
            "slos": list((self._payload or {}).get("slos") or []),
            "evaluated_at": self.evaluated_at,
        }

    def alert_rows(self) -> list[dict]:
        """Currently-firing burn windows for the AlertEngine: one row
        per (objective, speed), fast pages, slow tickets."""
        rows = []
        for c in self.compiled:
            for speed in SPEEDS:
                if not c.firing[speed]:
                    continue
                short_w, long_w = c.spec.windows(speed)
                rows.append({
                    "name": c.spec.name,
                    "tenant": c.spec.tenant,
                    "window": speed,
                    "short_s": short_w,
                    "long_s": long_w,
                    "threshold": c.spec.burn_threshold(speed),
                })
        return rows

    def exporter_rows(self) -> list[dict]:
        """Flat per-objective numbers for the tpumon_slo_* block."""
        out = []
        for row in (self._payload or {}).get("slos") or []:
            out.append(row)
        return out


def _r(v: float | None) -> float | None:
    return None if v is None else round(v, 4)


def parse_slos(raw_entries) -> tuple[list[SLOSpec], list[str]]:
    """(valid specs, error strings) from the ``slos`` config value —
    one bad objective must not take down the rest."""
    specs: list[SLOSpec] = []
    errors: list[str] = []
    for raw in raw_entries or ():
        try:
            specs.append(SLOSpec.parse(raw))
        except ValueError as e:
            errors.append(str(e))
    names = [s.name for s in specs]
    for dup in sorted({n for n in names if names.count(n) > 1}):
        errors.append(f"duplicate slo name {dup!r}")
        specs = [s for s in specs if s.name != dup]
    return specs, errors


# -------------------------------- CLI ----------------------------------


def slo_cli(argv: list[str]) -> int:
    """``tpumon slo`` — objectives, budget remaining and current burn
    rates from a running server's /api/slo."""
    import urllib.request

    url = "http://127.0.0.1:8888"
    as_json = False
    it = iter(argv)
    for a in it:
        if a == "--url":
            url = next(it, url)
        elif a == "--json":
            as_json = True
        elif a in ("-h", "--help"):
            print(
                "usage: python -m tpumon slo [--url HOST:8888] [--json]\n"
                "Objectives, error-budget remaining and fast/slow burn\n"
                "rates from GET /api/slo (docs/slo.md)."
            )
            return 0
        else:
            print(f"unknown argument {a!r}", file=sys.stderr)
            return 2
    if not url.startswith(("http://", "https://")):
        url = f"http://{url}"
    try:
        with urllib.request.urlopen(
            f"{url.rstrip('/')}/api/slo", timeout=10
        ) as r:
            payload = json.load(r)
    except Exception as e:
        print(f"slo: fetch failed: {e}", file=sys.stderr)
        return 1
    if as_json:
        print(json.dumps(payload, indent=1))
        return 0
    rows = payload.get("slos") or []
    if not rows:
        print("no SLOs configured (config key `slos`, docs/slo.md)")
        return 0

    def fmt_burn(b: dict) -> str:
        s, l = b.get("short"), b.get("long")
        txt = (f"{s:.1f}x/" if s is not None else "–/") + (
            f"{l:.1f}x" if l is not None else "–")
        return txt + (" FIRING" if b.get("firing") else "")

    print(f"{'NAME':<20} {'TENANT':<10} {'TARGET':>7} {'BUDGET':>8} "
          f"{'FAST':>16} {'SLOW':>16}")
    for row in rows:
        rem = (row.get("budget") or {}).get("remaining")
        print(
            f"{row['name']:<20} {row.get('tenant') or '–':<10} "
            f"{row['target'] * 100:>6.2f}% "
            f"{'–' if rem is None else f'{rem * 100:.1f}%':>8} "
            f"{fmt_burn(row['burn']['fast']):>16} "
            f"{fmt_burn(row['burn']['slow']):>16}"
        )
    firing = [
        f"{row['name']}/{speed}"
        for row in rows for speed in SPEEDS
        if row["burn"][speed].get("firing")
    ]
    if firing:
        print(f"burning: {', '.join(firing)}")
    return 0
