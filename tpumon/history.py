"""History: the in-process ring/TSDB store behind /api/history.

Reference parity (monitor_server.js:117-154 ``getHistoryMetrics``): 30-min
window / 30-s step curves for cpu, memory, disk and accelerator series,
rendered as ``{labels: [HH:mm], data: [...]}`` per series (SURVEY §2.3).

Differences (deliberate, SURVEY §3.3 + §5.8):
- The reference delegated history to an **external Prometheus** (six
  sequential PromQL range queries, empty series on outage,
  monitor_server.js:117-154). That sidecar dependency is retired: the
  sampler records every series into the in-process columnar TSDB each
  tick, /api/history renders directly from it, and rich expressions run
  through the in-tree query engine (tpumon.query, ``/api/query``,
  docs/query.md). ``prometheus_url`` is accepted but deprecated (a
  warning, not a behavior).
- ``gpuTemp`` was collected but never rendered by the reference
  (monitor_server.js:134 vs monitor.html:523-526); here temperature is a
  first-class rendered series.
- Values are numbers, not toFixed(1) strings (SURVEY §2.1 quirk, fixed).
- Storage is the columnar time-series core (tpumon.tsdb): typed-array
  head columns + Gorilla-style compressed chunks in three retention
  tiers (fine / mid / coarse), ~8-20x smaller resident history than
  the tuple-deque rings it replaced — which is what lets the sampler
  keep per-chip series at the 256-chip federation scale (docs/perf.md
  "History engine").
"""

from __future__ import annotations

import asyncio
import bisect
import contextlib
import fnmatch
import json
import os
import sys
import tempfile
import time
from array import array

from tpumon import tsdb

# The fleet series contract /api/history serves (SURVEY §5.8 re-keying).
# Keys are the ring series the sampler records each tick; values are the
# *equivalent PromQL* over tpumon's own /metrics exporter — kept as
# documentation for deployments that also scrape us with an external
# Prometheus, and as the provenance of each series' aggregation choice.
# In-process, these names evaluate directly (tpumon.query:
# ``avg_over_time(mxu[5m])``, ``topk(5, rate(chip.hbm))``, ...).
PROM_QUERIES: dict[str, str] = {
    "cpu": "avg(tpumon_host_cpu_pct)",
    "memory": "avg(tpumon_host_memory_pct)",
    "disk": "avg(tpumon_host_disk_pct)",
    "mxu": "avg(tpu_mxu_duty_cycle_pct)",
    "hbm": "avg(tpu_hbm_used_pct)",
    "temp": "avg(tpu_temp_celsius)",
    "ici": "sum(rate(tpu_ici_tx_bytes_total[1m]))",
    # Cross-host DCN traffic proxy: NIC tx rate summed over hosts.
    "dcn": "sum(rate(tpumon_host_net_tx_bytes_total[1m]))",
    # Worst-of-fleet libtpu SDK scores (0-10): max so one degrading
    # link / throttling chip shows in the fleet curve.
    "ici_health_max": "max(tpu_ici_link_health_score)",
    "throttle_max": "max(tpu_throttle_score)",
    "tokens_per_sec": "sum(tpumon_serving_tokens_per_sec)",
    "ttft_p50_ms": "avg(tpumon_serving_ttft_p50_ms)",
    # Scheduler pressure (docs/perf.md "Continuous-batching scheduler"):
    # waiting requests across targets, and the worst per-request decode
    # cadence — the SLO-soak inputs for the serving alert layer.
    "queue_depth": "sum(jetstream_queue_size)",
    "tpot_p95_ms": "max(tpumon_serving_tpot_p95_ms)",
    # The `> 0` clause drops idle samples instead of producing 0/0
    # NaN points (which would serialize as invalid JSON).
    "spec_accept_pct": (
        "100 * sum(rate(tpumon_serving_spec_accepted[5m])) "
        "/ (sum(rate(tpumon_serving_spec_proposed[5m])) > 0)"
    ),
    "kv_pool_pct": (
        "max(100 * (tpumon_serving_kv_pages_total "
        "- tpumon_serving_kv_pages_free) / tpumon_serving_kv_pages_total)"
    ),
    "prefix_hit_pct": (
        "100 * sum(rate(tpumon_serving_prefix_hits[5m])) "
        "/ ((sum(rate(tpumon_serving_prefix_hits[5m])) "
        "+ sum(rate(tpumon_serving_prefix_misses[5m]))) > 0)"
    ),
    # Direct trainer series preferred; tpumon's re-export (distinct name,
    # tpumon/exporter.py) is the fallback when Prometheus only scrapes us.
    # Limitation: PromQL `or` is all-or-nothing — in a mixed deployment
    # where Prometheus reaches some trainers directly and others only via
    # the re-export, the left side wins and re-export-only trainers drop
    # out of the aggregate. Scrape uniformly (all direct or all via
    # tpumon) for exact aggregates.
    "train_loss": "avg(tpumon_train_loss) or avg(tpumon_monitor_train_loss)",
    "train_tokens_per_sec": (
        "sum(rate(tpumon_train_tokens_total[1m])) or "
        "sum(rate(tpumon_monitor_train_tokens_total[1m]))"
    ),
}


def format_hhmm(ts: float) -> str:
    return time.strftime("%H:%M", time.localtime(ts))


def format_label(ts: float, window_s: float) -> str:
    """HH:MM for intraday windows; month-day prefix once a window is long
    enough that the same wall-clock time appears twice."""
    if window_s > 12 * 3600:
        return time.strftime("%m-%d %H:%M", time.localtime(ts))
    return format_hhmm(ts)


class RingSeries:
    """One bounded time series over the columnar core (tpumon.tsdb):
    a fine tier of raw ms-quantized points in typed-array columns +
    compressed sealed chunks, an optional mid tier of ``mid_step_s``
    bucket means, and an optional coarse tier of ``coarse_step_s``
    bucket means retained for ``long_window_s`` — long-range charts
    without keeping every 1 s sample for a day, at ~2-12 resident
    bytes/point instead of the old tuple-deque's ~120.

    ``points`` and ``coarse`` keep their deque-shaped API (len/iter/
    index/extend) as views over the tiers; ``version`` bumps on every
    mutation and keys the render memo (RingHistory.snapshot_series).
    """

    __slots__ = (
        "window_s", "long_window_s", "coarse_step_s", "fine", "down",
        "_mid", "_coarse", "version", "slot", "rec",
    )

    def __init__(
        self,
        window_s: float,
        long_window_s: float = 0.0,  # <= window_s => fine tier only
        coarse_step_s: float = 60.0,
        mid_step_s: float = 0.0,  # 0 => no mid tier
        mid_window_s: float = 0.0,
        slot_stores: tuple | None = None,  # (slot, mid store, coarse store)
    ):
        self.window_s = window_s
        self.long_window_s = long_window_s
        self.coarse_step_s = coarse_step_s
        self.fine = tsdb.Tier(window_s)
        self.down: list[tsdb.Downsample] = []  # finest -> coarsest
        # Ring-owned series are slot-backed: their downsample
        # accumulators live in the ring's contiguous AccumStore columns
        # so RingHistory.record_batch updates every series' buckets in
        # one kernel call per tick. Standalone series (slot is None)
        # keep plain object-held accumulators.
        self.slot = slot_stores[0] if slot_stores else None
        mid_store = slot_stores[1] if slot_stores else None
        coarse_store = slot_stores[2] if slot_stores else None
        self._mid = None
        if mid_step_s > 0 and mid_window_s > window_s:
            self._mid = (
                tsdb.SlotDownsample(mid_store, self.slot, mid_window_s)
                if mid_store is not None
                else tsdb.Downsample(mid_step_s, mid_window_s)
            )
            self.down.append(self._mid)
        # The coarse tier exists even when disabled for accumulation
        # (long_window_s <= window_s): restore paths may extend it
        # directly, and merged_points must then still serve it.
        coarse_window = max(long_window_s, window_s)
        self._coarse = (
            tsdb.SlotDownsample(coarse_store, self.slot, coarse_window)
            if coarse_store is not None
            else tsdb.Downsample(coarse_step_s, coarse_window)
        )
        self.down.append(self._coarse)
        self.version = 0
        # Recording-rule accumulators (tpumon.query.RuleAccum) for the
        # registered rules whose family matches this series' name —
        # None when no rule matches, so the per-append guard is one
        # attribute load on the unmatched (common) path.
        self.rec = None

    def __repr__(self) -> str:
        return (
            f"RingSeries(window_s={self.window_s}, "
            f"long_window_s={self.long_window_s}, points={len(self.fine)})"
        )

    @property
    def points(self) -> tsdb.PointsView:
        return tsdb.PointsView(self.fine, on_write=self._bump)

    @property
    def coarse(self) -> tsdb.PointsView:
        return tsdb.PointsView(self._coarse.tier, on_write=self._bump)

    def _bump(self) -> None:
        self.version += 1

    def add(self, ts: float, value: float) -> None:
        ts = tsdb.quantize_ts(ts)
        value = tsdb.quantize_val(value)
        self.fine.append(ts, value)
        if self._mid is not None:
            self._mid.observe(ts, value)
        if self.long_window_s > self.window_s:
            self._coarse.observe(ts, value)
        if self.rec is not None:
            for a in self.rec:
                a.observe(ts, value)
        self.version += 1

    def add_batch(self, ts_list, values) -> bool:
        """Append N (ts, value) pairs in one call: one quantize pass,
        slice-extend into the head columns, downsample accumulation per
        batch — the per-point interpreter work of add() amortizes to
        near zero (native kernel) or a few C-array ops (fallback).
        Returns True on the batch path; False when the batch was out of
        order and fell back to per-point sorted inserts (same end state,
        O(tier) cost — callers count it)."""
        n = len(ts_list)
        if not n:
            return True
        ts_q, val_q, ordered = tsdb.quantize_batch(
            ts_list, values, self.fine.last_ts()
        )
        if not ordered:
            for t, v in zip(ts_list, values):
                self.add(t, float(v))
            return False
        self.fine.append_batch(ts_q, val_q)
        if self._mid is not None:
            self._mid.observe_batch(ts_q, val_q)
        if self.long_window_s > self.window_s:
            self._coarse.observe_batch(ts_q, val_q)
        if self.rec is not None:
            # val_q is the array('f') column: values observed by the
            # rule accumulators are exactly the stored (f32) values.
            for a in self.rec:
                obs = a.observe
                for i in range(n):
                    obs(ts_q[i], val_q[i])
        self.version += 1
        return True

    def _fine_since(self, start: float) -> list[tuple[float, float]]:
        """Fine points with ts >= start — O(log chunks + matched):
        bisect over the sealed-chunk time index, decode only the
        overlap (tsdb.Tier.since)."""
        return self.fine.since(start)

    def merged_points(self, window_s: float, end: float) -> list[tuple[float, float]]:
        """Points covering [end - window_s, end]: downsampled tiers for
        the span older than the fine tier, fine points (raw) for the
        recent span (tsdb.merged)."""
        return tsdb.merged(self.fine, self.down, window_s, end)

    def last_ts(self) -> float | None:
        candidates = [self.fine.last_ts()] + [d.tier.last_ts() for d in self.down]
        ts = [c for c in candidates if c is not None]
        return max(ts) if ts else None

    def resident_bytes(self) -> int:
        return self.fine.resident_bytes() + sum(
            d.tier.resident_bytes() for d in self.down
        )

    def count_points(self) -> int:
        return self.fine.approx_len() + sum(
            d.tier.approx_len() for d in self.down
        )

    def resample(
        self,
        step_s: float,
        end: float | None = None,
        window_s: float | None = None,
    ) -> tuple[list[float], list[float]]:
        """Downsample to a fixed step grid (last-value-wins per bucket)."""
        window_s = window_s if window_s is not None else self.window_s
        if end is None:
            end = self.last_ts()
            if end is None:
                return [], []
        pts = (
            self.merged_points(window_s, end)
            if window_s > self.window_s
            else self._fine_since(end - window_s)
        )
        if not pts:
            return [], []
        start = max(pts[0][0], end - window_s)
        times = [t for t, _ in pts]
        grid: list[float] = []
        vals: list[float] = []
        t = start
        while t <= end + 1e-9:
            i = bisect.bisect_right(times, t) - 1
            if i >= 0:
                grid.append(t)
                vals.append(pts[i][1])
            t += step_s
        # The grid is start-anchored; when end isn't a whole step away it
        # would miss the newest sample — a monitor must show the freshest
        # value, so close the grid at end.
        if grid and end - grid[-1] > 1e-9:
            grid.append(end)
            vals.append(pts[-1][1])
        return grid, vals


class RingHistory:
    """Named ring-buffer series, fed by the sampler each tick.

    ``mutations`` counts every write — the history snapshotter's dirty
    check (an idle cadence skips the disk write entirely), and the
    per-series ``version`` keys a bounded resample memo so an epoch
    render-cache miss on one window does not re-walk series that did
    not move (tpumon.server serves multiple clamped windows per tick).
    """

    _MEMO_CAP = 4096  # (name, step, window) keys; cleared when full

    def __init__(
        self,
        window_s: float = 1800,
        long_window_s: float = 24 * 3600,
        coarse_step_s: float = 60.0,
        mid_step_s: float = 30.0,
        mid_window_s: float = 6 * 3600,
    ):
        self.window_s = window_s
        self.long_window_s = max(long_window_s, window_s)
        self.coarse_step_s = coarse_step_s
        self.mid_step_s = mid_step_s
        # The mid tier never outlives the coarse one.
        self.mid_window_s = min(mid_window_s, self.long_window_s)
        self.series: dict[str, RingSeries] = {}
        self.mutations = 0
        # Live-path out-of-order appends (a backwards clock): counted
        # here (surfaced in /api/health history stats + a one-shot
        # journal event via the sampler) — restore paths replay ordered
        # dumps and never bump this.
        self.out_of_order = 0
        # Bumped whenever series OBJECTS are replaced (snapshot restore)
        # so callers holding resolved series handles (the sampler's
        # per-chip cache) know to re-resolve.
        self.generation = 0
        # Slot-backed downsample accumulator columns shared by every
        # ring-owned series: RingHistory.record_batch updates all open
        # buckets in one accum_many call per tick (tpumon.tsdb).
        self._mid_enabled = mid_step_s > 0 and self.mid_window_s > window_s
        self._mid_store = (
            tsdb.AccumStore(mid_step_s) if self._mid_enabled else None
        )
        self._coarse_store = tsdb.AccumStore(coarse_step_s)
        self._slot_series: list[RingSeries] = []
        self._memo: dict[tuple, tuple[int, dict]] = {}
        # Registered recording rules (tpumon.query.RuleSet): append-time
        # aggregate accumulators attached per matching series. None =
        # no rules, zero per-append cost.
        self.rules = None

    def set_recording_rules(self, ruleset) -> None:
        """Register recording rules (tpumon.query.RuleSet) and attach
        accumulators to every existing matching series. Accumulation
        starts NOW — history is not backfilled (the same contract as
        Prometheus recording rules)."""
        self.rules = ruleset
        for name, s in self.series.items():
            s.rec = (
                ruleset.attach(name, ring_slot=s.slot)
                if ruleset is not None
                else None
            )

    def _make_series(self, name: str) -> RingSeries:
        if self._mid_store is not None:
            slot = self._mid_store.add_slot()
            assert self._coarse_store.add_slot() == slot
        else:
            slot = self._coarse_store.add_slot()
        s = RingSeries(
            window_s=self.window_s,
            long_window_s=self.long_window_s,
            coarse_step_s=self.coarse_step_s,
            mid_step_s=self.mid_step_s,
            mid_window_s=self.mid_window_s,
            slot_stores=(slot, self._mid_store, self._coarse_store),
        )
        if self.rules is not None:
            s.rec = self.rules.attach(name, ring_slot=s.slot)
        self._slot_series.append(s)
        return s

    def handle(self, name: str) -> RingSeries:
        """Resolve (creating if absent) a series once; callers on the
        per-tick hot path keep the handle and pass it to record_batch
        instead of paying a dict lookup per series per tick. Handles go
        stale when ``generation`` moves (snapshot restore replaced the
        series objects) — re-resolve then."""
        s = self.series.get(name)
        if s is None:
            s = self.series[name] = self._make_series(name)
        return s

    def record(self, name: str, value: float | None, ts: float | None = None) -> None:
        """Record one point — the thin per-point shim over the batch
        machinery (same quantization, same ordering fallback), kept for
        callers without a batch to amortize."""
        if value is None:
            return
        ts = time.time() if ts is None else ts
        s = self.handle(name)
        lt = s.fine.last_ts()
        if lt is not None and tsdb.quantize_ts(ts) < lt:
            self.out_of_order += 1
        s.add(ts, float(value))
        self.mutations += 1

    def record_batch(self, points, ts: float | None = None) -> None:
        """Record one point for MANY series at a shared timestamp — the
        sampler's per-tick shape (fleet aggregates + 4 series × every
        tracked chip). ``points`` holds (name-or-handle, value) pairs;
        None values are skipped (same contract as record()).

        The hot loop touches each series only for its two head-column
        appends and a seal check; value quantization is one vectorized
        pass, downsample bucket accumulation is one accum_many call per
        tier level (native kernel when built), and eviction is paced
        (Tier.maybe_evict) instead of per point. ``mutations`` bumps
        ONCE per batch — the snapshotter's dirty-skip sees "a tick
        happened", not one bump per series — while each touched series'
        ``version`` still bumps so the per-series resample memo stays
        correct."""
        ts = time.time() if ts is None else ts
        tsq = tsdb.quantize_ts(ts)
        get = self.series.get
        fast: list[RingSeries] = []
        vals: list[float] = []
        slow: list[tuple[RingSeries, float]] = []
        fast_append = fast.append
        vals_append = vals.append
        touched = False
        # Single pass: the head-column appends happen inline (array('f')
        # applies the f32 quantization itself, identically to
        # quantize_val), values are collected raw for the one vectorized
        # accum_many pass below. ~10 bytecodes of per-series work — the
        # rest of the per-point cost lives in C.
        for name, v in points:
            if v is None:
                continue
            if type(name) is str:
                # get() first: the hot path is an existing series, and
                # handle() is only needed to create missing ones.
                s = get(name)
                if s is None:
                    s = self.handle(name)
            else:
                s = name
            f = s.fine
            lt = f._last_ts
            if (lt is None or tsq >= lt) and s.slot is not None:
                f._last_ts = tsq
                f.head_ts.append(tsq)
                f.head_val.append(v)
                if len(f.head_ts) >= f.seal_points:
                    f.seal()
                    f.evict(tsq)
                else:
                    due = f._evict_due
                    if due is None or tsq >= due:
                        f.evict(tsq)
                        f._evict_due = tsq + f.window_s * 0.0625
                s.version += 1
                fast_append(s)
                vals_append(v)
                continue
            if lt is not None and tsq < lt:
                self.out_of_order += 1
            slow.append((s, float(v)))
        if fast:
            self._accum_many(tsq, array("f", vals), fast)
            touched = True
        for s, v in slow:
            s.add(ts, v)
            touched = True
        if touched:
            self.mutations += 1

    def _accum_many(self, tsq: float, val_q, series_list) -> None:
        """Per-batch downsample + recording-rule accumulation for
        slot-backed series: one accum_many call per tier level over the
        shared state columns (closed buckets appended through each
        series' own downsample tier, f32-quantized exactly like
        Downsample.flush), then one rule-store call per registered
        recording rule over the SAME slots/values arrays — matched
        series update their open sub-bucket summaries in the kernel,
        unmatched series cost a slot_map lookup (tpumon.query)."""
        levels: list[tuple[tsdb.AccumStore, str]] = []
        if self._mid_store is not None:
            levels.append((self._mid_store, "_mid"))
        if self.long_window_s > self.window_s:
            levels.append((self._coarse_store, "_coarse"))
        if not levels and self.rules is None:
            return
        slots = array("i", [s.slot for s in series_list])
        by_slot = self._slot_series
        for store, attr in levels:
            for slot, fts, fmean in tsdb.accum_many(tsq, val_q, slots, store):
                d = getattr(by_slot[slot], attr)
                d.tier.append(fts, tsdb.quantize_val(fmean))
        if self.rules is not None:
            self.rules.accum_batch(tsq, val_q, slots)

    def record_series(self, name: str, ts_list, values) -> None:
        """Record N (ts, value) pairs into ONE series in a single call
        (RingSeries.add_batch): the bulk shape — replaying a restore,
        ingesting a peer's backlog, the bench's ingest phase."""
        s = self.handle(name)
        if not s.add_batch(ts_list, values):
            self.out_of_order += 1
        self.mutations += 1

    def resident_bytes(self) -> int:
        return sum(s.resident_bytes() for s in self.series.values())

    def count_points(self) -> int:
        return sum(s.count_points() for s in self.series.values())

    def restore_coarse(self, name: str, points: list[tuple[float, float]]) -> None:
        """Seed a series' coarse tier from a state snapshot (tpumon.state).
        Caller guarantees points are time-ordered and predate any fine
        points subsequently replayed through record()."""
        if not points:
            return
        s = self.series.get(name)
        if s is None:
            s = self.series[name] = self._make_series(name)
        s.coarse.extend((float(t), float(v)) for t, v in points)
        self.mutations += 1

    # --------------- crash-safe persistence (dump/load) ----------------

    def dump_points(self) -> dict[str, list[list[float]]]:
        """Fine-tier raw points per series, JSON-shaped. Decodes via
        Tier.dump (cache-bypassing): the state checkpoint walks every
        series every save and must not pin decoded chunks resident."""
        return {
            name: [[round(t, 3), v] for t, v in s.fine.dump()]
            for name, s in self.series.items()
        }

    def dump_coarse(self) -> dict[str, list[list[float]]]:
        """Coarse-tier (bucket-mean) points per series, JSON-shaped.
        Series with no coarse data are omitted."""
        out = {}
        for name, s in self.series.items():
            pts = s._coarse.tier.dump()
            if pts:
                out[name] = [[round(t, 3), v] for t, v in pts]
        return out

    def load_points(
        self,
        points: dict,
        coarse: dict | None = None,
        now: float | None = None,
    ) -> None:
        """Restore dumped fine + coarse tiers into this (assumed-fresh)
        ring. Raises TypeError/ValueError/AttributeError on malformed
        input — callers decide whether a bad snapshot is fatal.

        Window cutoffs are applied against ``now``; replaying fine
        points through record() re-derives every coarse bucket the fine
        points touch — including a *partial* re-derivation of the bucket
        the oldest fine point lands mid-way in — so restored coarse
        entries stop at that bucket's START boundary, or the seam bucket
        would appear twice with the partial mean shadowing the correct
        full-bucket mean.
        """
        now = time.time() if now is None else now
        cutoff = now - self.window_s
        # Per-series (ts, value) columns: the replay below feeds each
        # series through the batch ingest path in one call instead of a
        # record() per point — dump files are time-ordered per series,
        # so the ordered fast path applies (and a disordered file still
        # restores via add_batch's per-point fallback).
        fine: dict[str, tuple[list[float], list[float]]] = {}
        for name, pts in points.items():
            ts_col, val_col = fine.setdefault(str(name), ([], []))
            for t, v in pts:
                t = float(t)
                if t >= cutoff:
                    ts_col.append(t)
                    val_col.append(float(v))
        long_cutoff = now - self.long_window_s
        coarse_ok = {
            str(name): [
                (float(t), float(v)) for t, v in pts if float(t) >= long_cutoff
            ]
            for name, pts in (coarse or {}).items()
        }
        step = self.coarse_step_s
        oldest_fine = {
            name: min(ts_col) for name, (ts_col, _) in fine.items() if ts_col
        }
        for name, pts in coarse_ok.items():
            bound = oldest_fine.get(name)
            bucket_start = None if bound is None else (bound // step) * step
            self.restore_coarse(
                name,
                [p for p in pts if bucket_start is None or p[0] < bucket_start],
            )
        for name, (ts_col, val_col) in fine.items():
            if not ts_col:
                continue
            self.handle(name).add_batch(ts_col, val_col)
            self.mutations += 1
        self.generation += 1

    def snapshot_series(
        self, name: str, step_s: float, window_s: float | None = None
    ) -> dict:
        s = self.series.get(name)
        if s is None:
            return {"labels": [], "data": []}
        window = window_s if window_s is not None else self.window_s
        # Resample memo keyed on the series' own version: a request
        # that misses the epoch render cache (new window, or another
        # section ticked) re-renders ONLY the series that moved since
        # their last resample at this (step, window). Callers treat the
        # payload as immutable (it goes straight to json.dumps).
        key = (name, step_s, window)
        hit = self._memo.get(key)
        if hit is not None and hit[0] == s.version:
            return hit[1]
        grid, vals = s.resample(step_s, window_s=window)
        out = {
            "labels": [format_label(t, window) for t in grid],
            "data": [round(v, 2) for v in vals],
        }
        if len(self._memo) >= self._MEMO_CAP:
            self._memo.clear()
        self._memo[key] = (s.version, out)
        return out


def atomic_write_text(path: str, text: str) -> None:
    """tmp-in-same-dir + fsync + rename: a crash mid-write leaves the
    previous file intact. Raises OSError on failure. Shared by the
    JSON state snapshots here/tpumon.state and the JSONL event journal
    (tpumon.events.EventLog)."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".tpumon-hist.", suffix=".tmp", dir=directory)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def atomic_write_json(path: str, obj: dict) -> None:
    """Atomic JSON dump (see atomic_write_text)."""
    atomic_write_text(path, json.dumps(obj, separators=(",", ":")))


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Atomic binary write (see atomic_write_text) — the v2 history
    snapshot format (tpumon.tsdb.dump_snapshot) rides this."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".tpumon-hist.", suffix=".tmp", dir=directory)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


HISTORY_SNAPSHOT_VERSION = 1  # the legacy full-JSON format (read-only path)


class HistorySnapshotter:
    """Crash-safe ring history: periodic atomic snapshot of all tiers
    to disk, restore-on-start — a monitor restart no longer erases the
    cluster's recent past even without Prometheus or a full state_path
    checkpoint (tpumon.state covers alerts + pods; this is the
    history-only, always-cheap subset).

    The default on-disk format is the v2 binary one
    (tpumon.tsdb.dump_snapshot): magic + version header, sealed chunks
    written verbatim — ~10x cheaper to write and restore than the v1
    full-JSON dump, which remains readable (``restore`` sniffs the
    magic) so pre-existing snapshot files warm-start the new store.
    A mutation ("dirty") check skips the periodic write entirely when
    nothing was recorded since the last save; skips are counted and
    surfaced in /api/health.
    """

    def __init__(
        self,
        ring: RingHistory,
        path: str,
        interval_s: float = 30.0,
        journal=None,
        fmt: str = "binary",
    ):
        if fmt not in ("binary", "json"):
            raise ValueError(f"unknown history snapshot format {fmt!r}")
        self.ring = ring
        self.path = path
        self.interval_s = interval_s
        self.format = fmt
        # Optional event journal (tpumon.events): restore success and
        # save-failure transitions are lifecycle moments worth keeping.
        self.journal = journal
        self.last_save_ts: float | None = None
        self.last_error: str | None = None
        self.saves = 0
        self.skipped_unchanged = 0
        self._saved_mutations: int | None = None
        self._task: asyncio.Task | None = None

    def save(self) -> bool:
        """Snapshot + write in one call, unconditionally. Only safe
        where nothing is concurrently mutating the ring (tests,
        shutdown after loops stopped); the live periodic path is
        save_async()."""
        return self._write(*self._snapshot())

    async def save_async(self) -> bool:
        """Snapshot on the event loop — the ring is only mutated there,
        so this never races a tick — then write the frozen blob in a
        worker thread. An unchanged ring (no record() since the last
        save) skips the write: idle clusters stop rewriting the same
        bytes every cadence."""
        if self._saved_mutations == self.ring.mutations:
            self.skipped_unchanged += 1
            return True
        blob, saved_at, mutations = self._snapshot()
        ok = await asyncio.to_thread(self._write, blob, saved_at, mutations)
        return ok

    def _snapshot(self) -> tuple[bytes | dict, float, int]:
        saved_at = time.time()
        mutations = self.ring.mutations
        if self.format == "binary":
            return tsdb.dump_snapshot(self.ring.series, saved_at), saved_at, mutations
        return (
            {
                "version": HISTORY_SNAPSHOT_VERSION,
                "saved_at": saved_at,
                "points": self.ring.dump_points(),
                "coarse": self.ring.dump_coarse(),
            },
            saved_at,
            mutations,
        )

    def _write(self, state: bytes | dict, saved_at: float, mutations: int) -> bool:
        try:
            if isinstance(state, bytes):
                atomic_write_bytes(self.path, state)
            else:
                atomic_write_json(self.path, state)
        except OSError as e:
            # Journal only the TRANSITION into failure — a full disk
            # must not generate one event per 30 s cadence forever.
            if self.journal is not None and self.last_error is None:
                self.journal.record(
                    "history", "serious", "history",
                    f"history snapshot write failing: {e}", path=self.path,
                )
            self.last_error = str(e)
            return False
        self.last_save_ts = saved_at
        self.last_error = None
        self.saves += 1
        self._saved_mutations = mutations
        return True

    def _refuse(self, why: str) -> bool:
        """A snapshot file that exists but cannot be used: record why
        (journal + last_error) and start fresh — never crash the
        server over a torn restore file."""
        self.last_error = why
        if self.journal is not None:
            self.journal.record(
                "history", "serious", "history",
                f"history snapshot refused: {why}", path=self.path,
            )
        return False

    def restore(self) -> bool:
        """Best-effort warm start; False (restoring nothing) on a
        missing, corrupt, wrong-version or stale snapshot. Binary (v2)
        and legacy JSON (v1) files are both readable; the ring is only
        mutated after the whole file parsed clean."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except OSError as e:
            self.last_error = str(e)
            return False
        if raw[: len(tsdb.MAGIC)] == tsdb.MAGIC:
            return self._restore_binary(raw)
        return self._restore_json(raw)

    def _stale(self, saved_at: float, now: float) -> bool:
        # A snapshot older than the ring's long window holds nothing
        # servable — the cutoff tracks the configured window, not a
        # fixed day, so a 72 h ring keeps a 30 h-old snapshot.
        return now - saved_at > self.ring.long_window_s

    def _restore_binary(self, raw: bytes) -> bool:
        now = time.time()
        try:
            saved_at, dumps = tsdb.load_snapshot(raw)
        except ValueError as e:
            return self._refuse(f"corrupt binary snapshot: {e}")
        if self._stale(saved_at, now):
            return False
        ring = self.ring
        replay_fine: dict[str, list] = {}
        replay_coarse: dict[str, list] = {}
        for d in dumps:
            s = ring._make_series(d["name"])
            if self._adoptable(s, d):
                self._adopt(s, d, now)
                if s.count_points() or any(x.bn for x in s.down):
                    ring.series[d["name"]] = s
            else:
                # Tier geometry changed since the file was written
                # (config edit): decode and replay instead of adopting.
                replay_fine[d["name"]] = tsdb.tier_points(d["fine"])
                if d["down"]:
                    replay_coarse[d["name"]] = tsdb.tier_points(
                        d["down"][-1]["tier"]
                    )
        if replay_fine or replay_coarse:
            ring.load_points(replay_fine, replay_coarse, now=now)
        ring.mutations += 1
        # Series objects were replaced wholesale: handles cached by the
        # sampler's batch path must re-resolve.
        ring.generation += 1
        ring._memo.clear()
        if self.journal is not None:
            self.journal.record(
                "history", "info", "history",
                f"restored {len(dumps)} history series from {self.path}",
                path=self.path,
            )
        return True

    @staticmethod
    def _adoptable(s: RingSeries, d: dict) -> bool:
        if s.fine.window_s != d["fine"]["window_s"]:
            return False
        if len(s.down) != len(d["down"]):
            return False
        return all(
            ds.step_s == dd["step_s"] and ds.tier.window_s == dd["tier"]["window_s"]
            for ds, dd in zip(s.down, d["down"])
        )

    @staticmethod
    def _adopt(s: RingSeries, d: dict, now: float) -> None:
        """Move a parsed tier dump into a fresh series verbatim (chunks
        stay compressed), then apply retention against ``now``."""

        def fill(tier: tsdb.Tier, td: dict) -> None:
            tier.chunks = td["chunks"]
            tier.head_ts = td["head_ts"]
            tier.head_val = td["head_val"]
            tier.sync_last()
            tier.evict(now)

        fill(s.fine, d["fine"])
        for ds, dd in zip(s.down, d["down"]):
            fill(ds.tier, dd["tier"])
            ds.bucket = dd["bucket"]
            ds.bsum = dd["bsum"]
            ds.bn = dd["bn"]
        s.version += 1

    def _restore_json(self, raw: bytes) -> bool:
        try:
            state = json.loads(raw)
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            self.last_error = str(e)
            return False
        if not isinstance(state, dict) or state.get("version") != HISTORY_SNAPSHOT_VERSION:
            return False
        saved_at = state.get("saved_at")
        now = time.time()
        if not isinstance(saved_at, (int, float)) or self._stale(saved_at, now):
            return False
        try:
            self.ring.load_points(
                state.get("points") or {}, state.get("coarse") or {}, now=now
            )
        except (AttributeError, KeyError, TypeError, ValueError) as e:
            self.last_error = f"malformed snapshot: {e}"
            return False
        if self.journal is not None:
            self.journal.record(
                "history", "info", "history",
                f"restored {len(state.get('points') or {})} history series "
                f"from {self.path}",
                path=self.path,
            )
        return True

    def to_json(self) -> dict:
        return {
            "path": self.path,
            "format": self.format,
            "interval_s": self.interval_s,
            "last_save_ts": self.last_save_ts,
            "last_error": self.last_error,
            "saves": self.saves,
            "skipped_unchanged": self.skipped_unchanged,
        }

    # ---------------------------- lifecycle ----------------------------

    async def start(self) -> None:
        async def loop() -> None:
            while True:
                await asyncio.sleep(self.interval_s)
                try:
                    await self.save_async()
                except Exception as e:  # never let the snapshot loop die
                    self.last_error = str(e)

        self._task = asyncio.create_task(loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        try:
            await self.save_async()  # final snapshot
        except Exception as e:
            self.last_error = str(e)


class HistoryService:
    """Serves the /api/history contract from the in-process ring/TSDB.

    The external-Prometheus path is retired (the paper's fourth
    collector, monitor_server.js:117-154): the sampler records every
    contract series each tick, so history is always local — and rich
    expressions over the same store go through the in-tree query
    engine (tpumon.query, ``/api/query``). ``prometheus_url`` is kept
    as an accepted-but-deprecated knob so existing configs load."""

    def __init__(
        self,
        ring: RingHistory,
        prometheus_url: str | None = None,
        window_s: float = 1800,
        step_s: float = 30,
    ):
        self.ring = ring
        self.window_s = window_s
        self.step_s = step_s
        # Retired dependency: warn once, then behave exactly like an
        # unconfigured instance (the ring has served this contract
        # since PR 5; collectors/prometheus.py is gone).
        self.prometheus_deprecated = bool(prometheus_url)
        if prometheus_url:
            print(
                "tpumon: prometheus_url is deprecated and ignored — "
                "/api/history serves the in-process TSDB and rich "
                "queries run in-tree via /api/query (docs/query.md)",
                file=sys.stderr,
                flush=True,
            )

    def clamp_window(self, window_s: float) -> float:
        return min(max(window_s, 60.0), self.ring.long_window_s)

    def step_for(self, window_s: float) -> float:
        """Step targeting ~60 rendered points, never finer than the
        configured base step (the reference's 30 s)."""
        if window_s <= self.window_s:
            return self.step_s
        return max(self.step_s, round(window_s / 60.0))

    @staticmethod
    def _matches(name: str, series: str | None) -> bool:
        """``?series=`` glob filter (fnmatch: * ? [..]); None => all.
        Matched against the full internal series name — fleet series
        ("cpu", "mxu") and per-chip ("chip.<id>.<metric>") alike, so
        ``series=chip.*`` selects the drill-down curves only."""
        return series is None or fnmatch.fnmatchcase(name, series)

    def snapshot_ring(
        self, window_s: float | None = None, series: str | None = None
    ) -> dict:
        """Ring-only /api/history payload, synchronously — the fast
        path the server's epoch render cache serves when no Prometheus
        is configured (the payload is then a pure function of the ring,
        so repeated same-tick requests reuse the serialized bytes).
        ``series`` (a glob) restricts to matching series — the per-chip
        drill-down fetch at 256 chips asks for ``chip.<id>.*`` instead
        of the whole fleet payload."""
        window = self.clamp_window(window_s) if window_s else self.window_s
        step = self.step_for(window)
        out: dict = {"source": "ring", "window_s": window, "step_s": step}
        if series is not None:
            out["series"] = series
        for name in PROM_QUERIES:
            if self._matches(name, series):
                out[name] = self.ring.snapshot_series(name, step, window_s=window)
        # Ring-only per-chip series (chip.<id>.<field>) for the per-chip
        # drill-down charts, and per-slice rollup series
        # (slice.<id>.<stat>) landed by the federation hub at ingest
        # (tpumon.federation — an aggregator/root's group-by-slice
        # curves); Prometheus equivalents are labelled series the
        # client can also get via its own PromQL if deployed.
        self._add_prefixed(out, "per_chip", "chip.", step, window, series)
        self._add_prefixed(out, "per_slice", "slice.", step, window, series)
        return out

    def _add_prefixed(
        self,
        out: dict,
        key: str,
        prefix: str,
        step: float,
        window: float,
        series: str | None = None,
    ) -> None:
        got: dict[str, dict] = {}
        for name in self.ring.series:
            if name.startswith(prefix) and self._matches(name, series):
                got[name[len(prefix) :]] = self.ring.snapshot_series(
                    name, step, window_s=window
                )
        if got:
            out[key] = got

    async def snapshot(
        self, window_s: float | None = None, series: str | None = None
    ) -> dict:
        """Async alias kept for callers written against the old
        Prometheus-or-ring contract; the answer is always the ring."""
        return self.snapshot_ring(window_s=window_s, series=series)
