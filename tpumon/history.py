"""History: in-process ring buffers + optional Prometheus-backed range data.

Reference parity (monitor_server.js:117-154 ``getHistoryMetrics``): 30-min
window / 30-s step curves for cpu, memory, disk and accelerator series,
rendered as ``{labels: [HH:mm], data: [...]}`` per series (SURVEY §2.3).

Differences (deliberate, SURVEY §3.3 + §5.8):
- The six PromQL range queries the reference awaited **sequentially** are
  issued in **parallel**, and they are re-keyed from ``DCGM_FI_DEV_*`` to
  the ``tpu_*`` / ``tpumon_*`` series our own exporter publishes.
- A Prometheus outage (or no Prometheus configured at all) degrades to an
  **in-process ring buffer** the sampler feeds every tick, so the
  dashboard always has history (the reference returns empty series,
  monitor_server.js:139).
- ``gpuTemp`` was collected but never rendered by the reference
  (monitor_server.js:134 vs monitor.html:523-526); here temperature is a
  first-class rendered series.
- Values are numbers, not toFixed(1) strings (SURVEY §2.1 quirk, fixed).
"""

from __future__ import annotations

import asyncio
import bisect
import time
from collections import deque
from dataclasses import dataclass, field

from tpumon.collectors.prometheus import PrometheusClient

# PromQL re-keying (SURVEY §5.8): all queries ride tpumon's own exporter.
PROM_QUERIES: dict[str, str] = {
    "cpu": "avg(tpumon_host_cpu_pct)",
    "memory": "avg(tpumon_host_memory_pct)",
    "disk": "avg(tpumon_host_disk_pct)",
    "mxu": "avg(tpu_mxu_duty_cycle_pct)",
    "hbm": "avg(tpu_hbm_used_pct)",
    "temp": "avg(tpu_temp_celsius)",
    "ici": "sum(rate(tpu_ici_tx_bytes_total[1m]))",
    "tokens_per_sec": "sum(tpumon_serving_tokens_per_sec)",
    "ttft_p50_ms": "avg(tpumon_serving_ttft_p50_ms)",
}


def format_hhmm(ts: float) -> str:
    return time.strftime("%H:%M", time.localtime(ts))


@dataclass
class RingSeries:
    """One bounded time series of (ts, value)."""

    window_s: float
    points: deque = field(default_factory=deque)  # (ts, value)

    def add(self, ts: float, value: float) -> None:
        self.points.append((ts, value))
        cutoff = ts - self.window_s
        while self.points and self.points[0][0] < cutoff:
            self.points.popleft()

    def resample(self, step_s: float, end: float | None = None) -> tuple[list[float], list[float]]:
        """Downsample to a fixed step grid (last-value-wins per bucket)."""
        if not self.points:
            return [], []
        pts = list(self.points)
        end = end if end is not None else pts[-1][0]
        start = max(pts[0][0], end - self.window_s)
        times = [t for t, _ in pts]
        grid: list[float] = []
        vals: list[float] = []
        t = start
        while t <= end + 1e-9:
            i = bisect.bisect_right(times, t) - 1
            if i >= 0:
                grid.append(t)
                vals.append(pts[i][1])
            t += step_s
        return grid, vals


class RingHistory:
    """Named ring-buffer series, fed by the sampler each tick."""

    def __init__(self, window_s: float = 1800):
        self.window_s = window_s
        self.series: dict[str, RingSeries] = {}

    def record(self, name: str, value: float | None, ts: float | None = None) -> None:
        if value is None:
            return
        ts = time.time() if ts is None else ts
        s = self.series.get(name)
        if s is None:
            s = self.series[name] = RingSeries(window_s=self.window_s)
        s.add(ts, float(value))

    def snapshot_series(self, name: str, step_s: float) -> dict:
        s = self.series.get(name)
        if s is None:
            return {"labels": [], "data": []}
        grid, vals = s.resample(step_s)
        return {
            "labels": [format_hhmm(t) for t in grid],
            "data": [round(v, 2) for v in vals],
        }


class HistoryService:
    """Serves the /api/history contract from Prometheus when available,
    falling back per-series to the ring buffer."""

    def __init__(
        self,
        ring: RingHistory,
        prometheus_url: str | None = None,
        window_s: float = 1800,
        step_s: float = 30,
    ):
        self.ring = ring
        self.window_s = window_s
        self.step_s = step_s
        self.prom = PrometheusClient(prometheus_url) if prometheus_url else None
        self.last_prom_ok: bool | None = None

    async def _prom_series(self) -> dict[str, dict] | None:
        if self.prom is None:
            return None
        names = list(PROM_QUERIES)
        results = await asyncio.gather(
            *(
                self.prom.query_range(PROM_QUERIES[n], self.window_s, self.step_s)
                for n in names
            )
        )
        out: dict[str, dict] = {}
        any_ok = False
        for name, series_list in zip(names, results):
            if not series_list:
                continue
            any_ok = True
            s = series_list[0]
            out[name] = {
                "labels": [format_hhmm(t) for t in s.times],
                "data": [round(v, 2) for v in s.values],
            }
        self.last_prom_ok = any_ok
        return out if any_ok else None

    async def snapshot(self) -> dict:
        prom = await self._prom_series()
        out: dict = {"source": "prometheus" if prom else "ring"}
        # Per-series fallback: Prometheus result wins, ring fills gaps.
        for name in PROM_QUERIES:
            if prom and name in prom:
                out[name] = prom[name]
            else:
                out[name] = self.ring.snapshot_series(name, self.step_s)
        # Ring-only per-chip series (chip.<id>.<field>) for the per-chip
        # drill-down charts; Prometheus equivalents are labelled series the
        # client can also get via its own PromQL if deployed.
        per_chip: dict[str, dict] = {}
        for name in self.ring.series:
            if name.startswith("chip."):
                per_chip[name[len("chip.") :]] = self.ring.snapshot_series(
                    name, self.step_s
                )
        if per_chip:
            out["per_chip"] = per_chip
        return out
