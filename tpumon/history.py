"""History: in-process ring buffers + optional Prometheus-backed range data.

Reference parity (monitor_server.js:117-154 ``getHistoryMetrics``): 30-min
window / 30-s step curves for cpu, memory, disk and accelerator series,
rendered as ``{labels: [HH:mm], data: [...]}`` per series (SURVEY §2.3).

Differences (deliberate, SURVEY §3.3 + §5.8):
- The six PromQL range queries the reference awaited **sequentially** are
  issued in **parallel**, and they are re-keyed from ``DCGM_FI_DEV_*`` to
  the ``tpu_*`` / ``tpumon_*`` series our own exporter publishes.
- A Prometheus outage (or no Prometheus configured at all) degrades to an
  **in-process ring buffer** the sampler feeds every tick, so the
  dashboard always has history (the reference returns empty series,
  monitor_server.js:139).
- ``gpuTemp`` was collected but never rendered by the reference
  (monitor_server.js:134 vs monitor.html:523-526); here temperature is a
  first-class rendered series.
- Values are numbers, not toFixed(1) strings (SURVEY §2.1 quirk, fixed).
"""

from __future__ import annotations

import asyncio
import bisect
import contextlib
import json
import os
import tempfile
import time
from collections import deque
from dataclasses import dataclass, field

from tpumon.collectors.prometheus import PrometheusClient

# PromQL re-keying (SURVEY §5.8): all queries ride tpumon's own exporter.
PROM_QUERIES: dict[str, str] = {
    "cpu": "avg(tpumon_host_cpu_pct)",
    "memory": "avg(tpumon_host_memory_pct)",
    "disk": "avg(tpumon_host_disk_pct)",
    "mxu": "avg(tpu_mxu_duty_cycle_pct)",
    "hbm": "avg(tpu_hbm_used_pct)",
    "temp": "avg(tpu_temp_celsius)",
    "ici": "sum(rate(tpu_ici_tx_bytes_total[1m]))",
    # Cross-host DCN traffic proxy: NIC tx rate summed over hosts.
    "dcn": "sum(rate(tpumon_host_net_tx_bytes_total[1m]))",
    # Worst-of-fleet libtpu SDK scores (0-10): max so one degrading
    # link / throttling chip shows in the fleet curve.
    "ici_health_max": "max(tpu_ici_link_health_score)",
    "throttle_max": "max(tpu_throttle_score)",
    "tokens_per_sec": "sum(tpumon_serving_tokens_per_sec)",
    "ttft_p50_ms": "avg(tpumon_serving_ttft_p50_ms)",
    # The `> 0` clause drops idle samples instead of producing 0/0
    # NaN points (which would serialize as invalid JSON).
    "spec_accept_pct": (
        "100 * sum(rate(tpumon_serving_spec_accepted[5m])) "
        "/ (sum(rate(tpumon_serving_spec_proposed[5m])) > 0)"
    ),
    "kv_pool_pct": (
        "max(100 * (tpumon_serving_kv_pages_total "
        "- tpumon_serving_kv_pages_free) / tpumon_serving_kv_pages_total)"
    ),
    "prefix_hit_pct": (
        "100 * sum(rate(tpumon_serving_prefix_hits[5m])) "
        "/ ((sum(rate(tpumon_serving_prefix_hits[5m])) "
        "+ sum(rate(tpumon_serving_prefix_misses[5m]))) > 0)"
    ),
    # Direct trainer series preferred; tpumon's re-export (distinct name,
    # tpumon/exporter.py) is the fallback when Prometheus only scrapes us.
    # Limitation: PromQL `or` is all-or-nothing — in a mixed deployment
    # where Prometheus reaches some trainers directly and others only via
    # the re-export, the left side wins and re-export-only trainers drop
    # out of the aggregate. Scrape uniformly (all direct or all via
    # tpumon) for exact aggregates.
    "train_loss": "avg(tpumon_train_loss) or avg(tpumon_monitor_train_loss)",
    "train_tokens_per_sec": (
        "sum(rate(tpumon_train_tokens_total[1m])) or "
        "sum(rate(tpumon_monitor_train_tokens_total[1m]))"
    ),
}


def format_hhmm(ts: float) -> str:
    return time.strftime("%H:%M", time.localtime(ts))


def format_label(ts: float, window_s: float) -> str:
    """HH:MM for intraday windows; month-day prefix once a window is long
    enough that the same wall-clock time appears twice."""
    if window_s > 12 * 3600:
        return time.strftime("%m-%d %H:%M", time.localtime(ts))
    return format_hhmm(ts)


@dataclass
class RingSeries:
    """One bounded time series: a fine tier of raw (ts, value) points over
    ``window_s``, plus an optional coarse tier of ``coarse_step_s``-bucket
    means retained for ``long_window_s`` — long-range charts without
    keeping every 1 s sample for a day."""

    window_s: float
    long_window_s: float = 0.0  # 0 => fine tier only
    coarse_step_s: float = 60.0
    points: deque = field(default_factory=deque)  # fine: (ts, value)
    coarse: deque = field(default_factory=deque)  # (bucket_mid_ts, mean)
    _bucket: int | None = field(default=None, repr=False)
    _bucket_sum: float = field(default=0.0, repr=False)
    _bucket_n: int = field(default=0, repr=False)

    def add(self, ts: float, value: float) -> None:
        self.points.append((ts, value))
        cutoff = ts - self.window_s
        while self.points and self.points[0][0] < cutoff:
            self.points.popleft()
        if self.long_window_s > self.window_s:
            b = int(ts // self.coarse_step_s)
            if self._bucket is not None and b != self._bucket:
                self._flush_bucket()
            self._bucket = b
            self._bucket_sum += value
            self._bucket_n += 1
            long_cutoff = ts - self.long_window_s
            while self.coarse and self.coarse[0][0] < long_cutoff:
                self.coarse.popleft()

    def _flush_bucket(self) -> None:
        if self._bucket is not None and self._bucket_n:
            mid = (self._bucket + 0.5) * self.coarse_step_s
            self.coarse.append((mid, self._bucket_sum / self._bucket_n))
        self._bucket_sum, self._bucket_n = 0.0, 0

    def _fine_since(self, start: float) -> list[tuple[float, float]]:
        """Fine points with ts >= start, O(matched) not O(ring): the
        deque is time-ordered, so walk from the newest end and stop at
        the first point before the window — a 30 m query over a 24 h
        ring no longer scans the whole fine tier."""
        out: list[tuple[float, float]] = []
        for p in reversed(self.points):
            if p[0] < start:
                break
            out.append(p)
        out.reverse()
        return out

    def merged_points(self, window_s: float, end: float) -> list[tuple[float, float]]:
        """Points covering [end - window_s, end]: coarse tier for the span
        older than the fine tier, fine points (raw) for the recent span."""
        start = end - window_s
        fine = self._fine_since(start)
        # No fine points => every coarse point qualifies (an empty fine
        # tier must not mask the newest coarse value).
        fine_start = fine[0][0] if fine else float("inf")
        out = [(t, v) for t, v in self.coarse if start <= t < fine_start]
        # The live (unflushed) bucket only matters when it predates fine.
        if self._bucket is not None and self._bucket_n:
            mid = (self._bucket + 0.5) * self.coarse_step_s
            if start <= mid < fine_start:
                out.append((mid, self._bucket_sum / self._bucket_n))
        out.extend(fine)
        return out

    def resample(
        self,
        step_s: float,
        end: float | None = None,
        window_s: float | None = None,
    ) -> tuple[list[float], list[float]]:
        """Downsample to a fixed step grid (last-value-wins per bucket)."""
        window_s = window_s if window_s is not None else self.window_s
        if end is None:
            last_fine = self.points[-1][0] if self.points else None
            last_coarse = self.coarse[-1][0] if self.coarse else None
            candidates = [t for t in (last_fine, last_coarse) if t is not None]
            if not candidates:
                return [], []
            end = max(candidates)
        pts = (
            self.merged_points(window_s, end)
            if window_s > self.window_s
            else self._fine_since(end - window_s)
        )
        if not pts:
            return [], []
        start = max(pts[0][0], end - window_s)
        times = [t for t, _ in pts]
        grid: list[float] = []
        vals: list[float] = []
        t = start
        while t <= end + 1e-9:
            i = bisect.bisect_right(times, t) - 1
            if i >= 0:
                grid.append(t)
                vals.append(pts[i][1])
            t += step_s
        # The grid is start-anchored; when end isn't a whole step away it
        # would miss the newest sample — a monitor must show the freshest
        # value, so close the grid at end.
        if grid and end - grid[-1] > 1e-9:
            grid.append(end)
            vals.append(pts[-1][1])
        return grid, vals


class RingHistory:
    """Named ring-buffer series, fed by the sampler each tick."""

    def __init__(
        self,
        window_s: float = 1800,
        long_window_s: float = 24 * 3600,
        coarse_step_s: float = 60.0,
    ):
        self.window_s = window_s
        self.long_window_s = max(long_window_s, window_s)
        self.coarse_step_s = coarse_step_s
        self.series: dict[str, RingSeries] = {}

    def record(self, name: str, value: float | None, ts: float | None = None) -> None:
        if value is None:
            return
        ts = time.time() if ts is None else ts
        s = self.series.get(name)
        if s is None:
            s = self.series[name] = RingSeries(
                window_s=self.window_s,
                long_window_s=self.long_window_s,
                coarse_step_s=self.coarse_step_s,
            )
        s.add(ts, float(value))

    def restore_coarse(self, name: str, points: list[tuple[float, float]]) -> None:
        """Seed a series' coarse tier from a state snapshot (tpumon.state).
        Caller guarantees points are time-ordered and predate any fine
        points subsequently replayed through record()."""
        if not points:
            return
        s = self.series.get(name)
        if s is None:
            s = self.series[name] = RingSeries(
                window_s=self.window_s,
                long_window_s=self.long_window_s,
                coarse_step_s=self.coarse_step_s,
            )
        s.coarse.extend((float(t), float(v)) for t, v in points)

    # --------------- crash-safe persistence (dump/load) ----------------

    def dump_points(self) -> dict[str, list[list[float]]]:
        """Fine-tier raw points per series, JSON-shaped."""
        return {
            name: [[round(t, 3), v] for t, v in s.points]
            for name, s in self.series.items()
        }

    def dump_coarse(self) -> dict[str, list[list[float]]]:
        """Coarse-tier (bucket-mean) points per series, JSON-shaped.
        Series with no coarse data are omitted."""
        return {
            name: [[round(t, 3), v] for t, v in s.coarse]
            for name, s in self.series.items()
            if s.coarse
        }

    def load_points(
        self,
        points: dict,
        coarse: dict | None = None,
        now: float | None = None,
    ) -> None:
        """Restore dumped fine + coarse tiers into this (assumed-fresh)
        ring. Raises TypeError/ValueError/AttributeError on malformed
        input — callers decide whether a bad snapshot is fatal.

        Window cutoffs are applied against ``now``; replaying fine
        points through record() re-derives every coarse bucket the fine
        points touch — including a *partial* re-derivation of the bucket
        the oldest fine point lands mid-way in — so restored coarse
        entries stop at that bucket's START boundary, or the seam bucket
        would appear twice with the partial mean shadowing the correct
        full-bucket mean.
        """
        now = time.time() if now is None else now
        cutoff = now - self.window_s
        fine = [
            (str(name), float(v), float(t))
            for name, pts in points.items()
            for t, v in pts
            if float(t) >= cutoff
        ]
        long_cutoff = now - self.long_window_s
        coarse_ok = {
            str(name): [
                (float(t), float(v)) for t, v in pts if float(t) >= long_cutoff
            ]
            for name, pts in (coarse or {}).items()
        }
        step = self.coarse_step_s
        oldest_fine: dict[str, float] = {}
        for name, _value, ts in fine:
            oldest_fine[name] = min(oldest_fine.get(name, ts), ts)
        for name, pts in coarse_ok.items():
            bound = oldest_fine.get(name)
            bucket_start = None if bound is None else (bound // step) * step
            self.restore_coarse(
                name,
                [p for p in pts if bucket_start is None or p[0] < bucket_start],
            )
        for name, value, ts in fine:
            self.record(name, value, ts=ts)

    def snapshot_series(
        self, name: str, step_s: float, window_s: float | None = None
    ) -> dict:
        s = self.series.get(name)
        if s is None:
            return {"labels": [], "data": []}
        window = window_s if window_s is not None else self.window_s
        grid, vals = s.resample(step_s, window_s=window)
        return {
            "labels": [format_label(t, window) for t in grid],
            "data": [round(v, 2) for v in vals],
        }


def atomic_write_text(path: str, text: str) -> None:
    """tmp-in-same-dir + fsync + rename: a crash mid-write leaves the
    previous file intact. Raises OSError on failure. Shared by the
    JSON state snapshots here/tpumon.state and the JSONL event journal
    (tpumon.events.EventLog)."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".tpumon-hist.", suffix=".tmp", dir=directory)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def atomic_write_json(path: str, obj: dict) -> None:
    """Atomic JSON dump (see atomic_write_text)."""
    atomic_write_text(path, json.dumps(obj, separators=(",", ":")))


HISTORY_SNAPSHOT_VERSION = 1


class HistorySnapshotter:
    """Crash-safe ring history: periodic atomic snapshot of the fine +
    coarse tiers to disk, restore-on-start — a monitor restart no longer
    erases the cluster's recent past even without Prometheus or a full
    state_path checkpoint (tpumon.state covers alerts + pods; this is
    the history-only, always-cheap subset).
    """

    def __init__(
        self,
        ring: RingHistory,
        path: str,
        interval_s: float = 30.0,
        journal=None,
    ):
        self.ring = ring
        self.path = path
        self.interval_s = interval_s
        # Optional event journal (tpumon.events): restore success and
        # save-failure transitions are lifecycle moments worth keeping.
        self.journal = journal
        self.last_save_ts: float | None = None
        self.last_error: str | None = None
        self._task: asyncio.Task | None = None

    def save(self) -> bool:
        """Snapshot + write in one call. Only safe where nothing is
        concurrently mutating the ring (tests, shutdown after loops
        stopped); the live periodic path is save_async()."""
        return self._write(self._snapshot())

    async def save_async(self) -> bool:
        """Snapshot on the event loop — the ring is only mutated there,
        so this never races a tick — then write the frozen dict in a
        worker thread."""
        state = self._snapshot()
        return await asyncio.to_thread(self._write, state)

    def _snapshot(self) -> dict:
        return {
            "version": HISTORY_SNAPSHOT_VERSION,
            "saved_at": time.time(),
            "points": self.ring.dump_points(),
            "coarse": self.ring.dump_coarse(),
        }

    def _write(self, state: dict) -> bool:
        try:
            atomic_write_json(self.path, state)
        except OSError as e:
            # Journal only the TRANSITION into failure — a full disk
            # must not generate one event per 30 s cadence forever.
            if self.journal is not None and self.last_error is None:
                self.journal.record(
                    "history", "serious", "history",
                    f"history snapshot write failing: {e}", path=self.path,
                )
            self.last_error = str(e)
            return False
        self.last_save_ts = state["saved_at"]
        self.last_error = None
        return True

    def restore(self) -> bool:
        """Best-effort warm start; False (restoring nothing) on a
        missing, corrupt, wrong-version or stale snapshot."""
        try:
            with open(self.path) as f:
                state = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            self.last_error = str(e)
            return False
        if not isinstance(state, dict) or state.get("version") != HISTORY_SNAPSHOT_VERSION:
            return False
        saved_at = state.get("saved_at")
        now = time.time()
        # A snapshot older than the ring's long window holds nothing
        # servable — the cutoff tracks the configured window, not a
        # fixed day, so a 72 h ring keeps a 30 h-old snapshot.
        if (
            not isinstance(saved_at, (int, float))
            or now - saved_at > self.ring.long_window_s
        ):
            return False
        try:
            self.ring.load_points(
                state.get("points") or {}, state.get("coarse") or {}, now=now
            )
        except (AttributeError, KeyError, TypeError, ValueError) as e:
            self.last_error = f"malformed snapshot: {e}"
            return False
        if self.journal is not None:
            self.journal.record(
                "history", "info", "history",
                f"restored {len(state.get('points') or {})} history series "
                f"from {self.path}",
                path=self.path,
            )
        return True

    def to_json(self) -> dict:
        return {
            "path": self.path,
            "interval_s": self.interval_s,
            "last_save_ts": self.last_save_ts,
            "last_error": self.last_error,
        }

    # ---------------------------- lifecycle ----------------------------

    async def start(self) -> None:
        async def loop() -> None:
            while True:
                await asyncio.sleep(self.interval_s)
                try:
                    await self.save_async()
                except Exception as e:  # never let the snapshot loop die
                    self.last_error = str(e)

        self._task = asyncio.create_task(loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        try:
            await self.save_async()  # final snapshot
        except Exception as e:
            self.last_error = str(e)


class HistoryService:
    """Serves the /api/history contract from Prometheus when available,
    falling back per-series to the ring buffer."""

    def __init__(
        self,
        ring: RingHistory,
        prometheus_url: str | None = None,
        window_s: float = 1800,
        step_s: float = 30,
    ):
        self.ring = ring
        self.window_s = window_s
        self.step_s = step_s
        self.prom = PrometheusClient(prometheus_url) if prometheus_url else None
        self.last_prom_ok: bool | None = None

    def clamp_window(self, window_s: float) -> float:
        return min(max(window_s, 60.0), self.ring.long_window_s)

    def step_for(self, window_s: float) -> float:
        """Step targeting ~60 rendered points, never finer than the
        configured base step (the reference's 30 s)."""
        if window_s <= self.window_s:
            return self.step_s
        return max(self.step_s, round(window_s / 60.0))

    async def _prom_series(
        self, window_s: float, step_s: float
    ) -> dict[str, dict] | None:
        if self.prom is None:
            return None
        names = list(PROM_QUERIES)
        results = await asyncio.gather(
            *(
                self.prom.query_range(PROM_QUERIES[n], window_s, step_s)
                for n in names
            )
        )
        out: dict[str, dict] = {}
        any_ok = False
        for name, series_list in zip(names, results):
            if not series_list:
                continue
            any_ok = True
            s = series_list[0]
            out[name] = {
                "labels": [format_label(t, window_s) for t in s.times],
                "data": [round(v, 2) for v in s.values],
            }
        self.last_prom_ok = any_ok
        return out if any_ok else None

    def snapshot_ring(self, window_s: float | None = None) -> dict:
        """Ring-only /api/history payload, synchronously — the fast
        path the server's epoch render cache serves when no Prometheus
        is configured (the payload is then a pure function of the ring,
        so repeated same-tick requests reuse the serialized bytes)."""
        window = self.clamp_window(window_s) if window_s else self.window_s
        step = self.step_for(window)
        out: dict = {"source": "ring", "window_s": window, "step_s": step}
        for name in PROM_QUERIES:
            out[name] = self.ring.snapshot_series(name, step, window_s=window)
        self._add_per_chip(out, step, window)
        return out

    def _add_per_chip(self, out: dict, step: float, window: float) -> None:
        # Ring-only per-chip series (chip.<id>.<field>) for the per-chip
        # drill-down charts; Prometheus equivalents are labelled series the
        # client can also get via its own PromQL if deployed.
        per_chip: dict[str, dict] = {}
        for name in self.ring.series:
            if name.startswith("chip."):
                per_chip[name[len("chip.") :]] = self.ring.snapshot_series(
                    name, step, window_s=window
                )
        if per_chip:
            out["per_chip"] = per_chip

    async def snapshot(self, window_s: float | None = None) -> dict:
        if self.prom is None:
            return self.snapshot_ring(window_s=window_s)
        window = self.clamp_window(window_s) if window_s else self.window_s
        step = self.step_for(window)
        prom = await self._prom_series(window, step)
        out: dict = {
            "source": "prometheus" if prom else "ring",
            "window_s": window,
            "step_s": step,
        }
        # Per-series fallback: Prometheus result wins, ring fills gaps.
        for name in PROM_QUERIES:
            if prom and name in prom:
                out[name] = prom[name]
            else:
                out[name] = self.ring.snapshot_series(name, step, window_s=window)
        self._add_per_chip(out, step, window)
        return out
