"""Actuation engine: SLO-driven policies that drive the serving engine.

Everything before this module observes; the paper's L0 world ended at a
human watching alert badges and turning knobs (PAPER.md — the dashboard
modal IS the end of the pipeline). This module closes the loop
(ROADMAP item 4): declarative policies consume the monitor's own
signals — SLO page state, queue-depth trends, dark slices — and drive
the serving engine through a narrow, journaled actuator interface, so a
page-able outage becomes a TTFT blip with no human in the loop.

- **Policies** are declared in config (``actuations: [{name, when,
  action, ...}]``). ``when`` is a query-language condition (compiled
  ONCE, like the SLO bad-event expressions — docs/query.md), evaluated
  once per fast tick over the monitor's own TSDB: e.g.
  ``slo.paging{slo="chat_ttft"} > 0`` (the SLO engine's page-state
  series) or ``avg_over_time(queue_depth[30s]) > 8`` (a recording-rule
  trend, never a point walk — ``rule_texts`` registers the windows).
- **Action families** (docs/actuation.md has the catalog):
  ``shed`` — per-tenant admission throttling: shed requests complete
  with a distinct ``shed`` terminal status that is NEVER distilled into
  the tenant's error rate (counting the remedy as an error would latch
  the very SLO that triggered it), and the fraction is doubly capped
  (config ``shed_max_fraction``, engine ``SHED_CAP``);
  ``capacity`` — nudge the scheduler's prefill chunk budget and paged
  admit-lookahead window, reverting to the pre-fire baseline;
  ``drain`` — drain-and-requeue off a dark slice: when federation
  marks a placement domain dark, its in-flight requests abort and
  re-admit through the prefix cache so recomputation is prefix-cheap.
- **The engine itself is guarded** (robustness is the point): per-policy
  cooldowns and fire/clear hysteresis (consecutive-tick holds, like
  tpumon.anomaly), a global performed-actions-per-window rate limit (a
  misconfigured policy set cannot thrash the engine; reverts are never
  rate-limited), ``dry_run`` that journals intent without acting, and
  automatic revert once the triggering condition clears.

Every transition — armed / fired / reverted / suppressed (cooldown) /
rate-limited — lands in the event journal (kind ``actuate``) with the
triggering expression and observed value. Surfaces: ``GET
/api/actuate`` on its own epoch-cache section, the dashboard Actuation
card (SSE realtime payload), ``tpumon_actuate_*`` exporter gauges, and
the closed-loop soak (tests/test_actuate_soak.py): fault → burn page →
journaled actuation → measurably faster recovery than the un-actuated
PR 13 soak → revert, asserted in journal seq order.
"""

from __future__ import annotations

import re
import time
from collections import deque
from dataclasses import dataclass

from tpumon.query import (
    Bin,
    Num,
    QueryError,
    Selector,
    parse,
    parse_range,
)
from tpumon.slo import _fmt_s

ACTIONS = ("shed", "capacity", "drain")

# Dot-free and expression-safe (the name rides journal attrs and the
# per-policy exporter label).
_NAME_RE = re.compile(r"^[A-Za-z][A-Za-z0-9_-]*$")

DEFAULT_COOLDOWN_S = 30.0
DEFAULT_FIRE_HOLD = 2
DEFAULT_CLEAR_HOLD = 2

# Ring series the engine records each tick when the dark-slice
# provider reports a fleet (a wired federation hub; a None result
# means standalone — nothing recorded): the count of federation-dark
# placement domains, so a drain policy's condition
# (``federation.dark > 0``) reads live fleet state through the query
# engine like any other series.
DARK_SERIES = "federation.dark"

_CMP_OPS = (">", "<", ">=", "<=", "==", "!=")


def _walk(node):
    """Every node of a compiled query AST (Selector/Call/Agg/Bin/Neg
    leaves and branches)."""
    yield node
    for attr in ("args", "lhs", "rhs", "arg"):
        v = getattr(node, attr, None)
        if v is None:
            continue
        if isinstance(v, list):
            for c in v:
                yield from _walk(c)
        else:
            yield from _walk(v)


def _dur(v, what: str) -> float:
    """Duration: a bare number (seconds) or a duration literal."""
    try:
        return float(v)
    except (TypeError, ValueError):
        pass
    try:
        return parse_range(str(v))
    except QueryError as e:
        raise ValueError(f"{what}: {e}")


@dataclass(frozen=True)
class ActuationSpec:
    """One policy, validated. Action-specific params ride flat."""

    name: str
    when: str
    action: str
    clear: str = ""
    cooldown_s: float = DEFAULT_COOLDOWN_S
    fire_hold: int = DEFAULT_FIRE_HOLD
    clear_hold: int = DEFAULT_CLEAR_HOLD
    dry_run: bool = False
    # shed
    tenant: str = "*"
    fraction: float = 0.25
    # capacity (0 / -1 = leave that knob alone)
    prefill_budget: int = 0
    admit_lookahead: int = -1
    # drain ("" = every slice the federation currently marks dark)
    slice: str = ""

    _BASE_KEYS = frozenset({
        "name", "when", "action", "clear", "cooldown_s", "fire_hold",
        "clear_hold", "dry_run",
    })
    _ACTION_KEYS = {
        "shed": frozenset({"tenant", "fraction"}),
        "capacity": frozenset({"prefill_budget", "admit_lookahead"}),
        "drain": frozenset({"slice"}),
    }

    @classmethod
    def parse(cls, raw: dict) -> "ActuationSpec":
        """Build a spec from one ``actuations`` config entry; raises
        ValueError with an operator-readable message (a misdeclared
        policy must be an incident, not a silent no-op — the sampler
        journals it)."""
        if not isinstance(raw, dict):
            raise ValueError(f"actuation entry must be an object, got {raw!r}")
        name = str(raw.get("name") or "")
        if not _NAME_RE.match(name):
            raise ValueError(
                f"actuation name {name!r} must match {_NAME_RE.pattern}")
        when = str(raw.get("when") or "")
        try:
            parse(when)
        except QueryError as e:
            raise ValueError(f"actuation {name}: bad when {when!r}: {e}")
        clear = str(raw.get("clear") or "")
        if clear:
            try:
                parse(clear)
            except QueryError as e:
                raise ValueError(
                    f"actuation {name}: bad clear {clear!r}: {e}")
        action = str(raw.get("action") or "")
        if action not in ACTIONS:
            raise ValueError(
                f"actuation {name}: unknown action {action!r} "
                f"(want one of {', '.join(ACTIONS)})")
        known = cls._BASE_KEYS | cls._ACTION_KEYS[action]
        unknown = set(raw) - known
        if unknown:
            raise ValueError(
                f"actuation {name}: unknown keys {sorted(unknown)} for "
                f"action {action!r}")
        cooldown_s = _dur(raw.get("cooldown_s", DEFAULT_COOLDOWN_S),
                          f"actuation {name} cooldown_s")
        if cooldown_s < 0:
            raise ValueError(f"actuation {name}: cooldown_s must be >= 0")
        holds = {}
        for key, default in (("fire_hold", DEFAULT_FIRE_HOLD),
                             ("clear_hold", DEFAULT_CLEAR_HOLD)):
            try:
                holds[key] = int(raw.get(key, default))
            except (TypeError, ValueError):
                raise ValueError(
                    f"actuation {name}: bad {key} {raw.get(key)!r}")
            if holds[key] < 1:
                raise ValueError(f"actuation {name}: {key} must be >= 1")
        kw: dict = {}
        if action == "shed":
            tenant = str(raw.get("tenant", "*") or "*")
            try:
                fraction = float(raw.get("fraction", 0.25))
            except (TypeError, ValueError):
                raise ValueError(
                    f"actuation {name}: bad fraction {raw.get('fraction')!r}")
            if not 0.0 < fraction <= 1.0:
                raise ValueError(
                    f"actuation {name}: fraction must be in (0, 1], got "
                    f"{fraction} (1.0 still clamps to the shed caps)")
            kw.update(tenant=tenant, fraction=fraction)
        elif action == "capacity":
            try:
                budget = int(raw.get("prefill_budget", 0))
                look = int(raw.get("admit_lookahead", -1))
            except (TypeError, ValueError):
                raise ValueError(
                    f"actuation {name}: prefill_budget/admit_lookahead "
                    f"want integers")
            if budget <= 0 and look < 0:
                raise ValueError(
                    f"actuation {name}: capacity wants prefill_budget "
                    f">= 1 and/or admit_lookahead >= 0")
            kw.update(prefill_budget=max(0, budget), admit_lookahead=look)
        else:  # drain
            kw.update(slice=str(raw.get("slice", "") or ""))
        return cls(
            name=name, when=when, action=action, clear=clear,
            cooldown_s=cooldown_s, dry_run=bool(raw.get("dry_run", False)),
            **holds, **kw,
        )


def parse_actuations(raw_entries) -> tuple[list[ActuationSpec], list[str]]:
    """(valid specs, error strings) from the ``actuations`` config
    value — one bad policy must not take down the rest."""
    specs: list[ActuationSpec] = []
    errors: list[str] = []
    for raw in raw_entries or ():
        try:
            specs.append(ActuationSpec.parse(raw))
        except ValueError as e:
            errors.append(str(e))
    names = [s.name for s in specs]
    for dup in sorted({n for n in names if names.count(n) > 1}):
        errors.append(f"duplicate actuation name {dup!r}")
        specs = [s for s in specs if s.name != dup]
    return specs, errors


# ------------------------------ actuators ------------------------------


class EngineActuator:
    """The narrow interface the policy engine drives a ServingEngine
    through — seven verbs, nothing else. Keeping the surface this small
    is the robustness contract: a policy cannot reach into scheduler
    internals, only through the engine's own clamped, locked entry
    points (set_shed's SHED_CAP, nudge_capacity's floors)."""

    def __init__(self, engine):
        self.engine = engine

    def shed(self, tenant: str, fraction: float) -> float:
        return self.engine.set_shed(tenant, fraction)

    def unshed(self, tenant: str) -> None:
        self.engine.set_shed(tenant, 0.0)

    def capacity(self) -> dict:
        cfg = self.engine.cfg
        return {"prefill_budget": cfg.prefill_chunk_budget,
                "admit_lookahead": cfg.admit_lookahead}

    def nudge(self, prefill_budget=None, admit_lookahead=None) -> dict:
        return self.engine.nudge_capacity(
            prefill_budget=prefill_budget, admit_lookahead=admit_lookahead)

    def drain(self, slice_id: str) -> None:
        self.engine.drain_slice(slice_id)

    def undrain(self, slice_id: str) -> None:
        self.engine.undrain_slice(slice_id)

    def set_slices(self, names) -> None:
        """Declare the placement domains requests are attributed to —
        the drain family's prerequisite (a request with no domain can
        never be drained off one). The policy engine keeps this synced
        to the fleet's slice namespace; see observe()."""
        self.engine.set_slices(names)


# ------------------------------- engine --------------------------------


class _Policy:
    """Per-spec live state: the compiled condition, the fire/clear
    hysteresis counters, guard bookkeeping and the cached /api row."""

    def __init__(self, spec: ActuationSpec):
        self.spec = spec
        self.when_node = parse(spec.when)
        self.clear_node = parse(spec.clear) if spec.clear else None
        self.state = "idle"  # idle | armed | fired
        self.hold = 0          # consecutive ticks the condition held
        self.clear_count = 0   # consecutive clearing ticks while fired
        self.last_fired_ts: float | None = None
        self.fired = 0
        self.reverted = 0
        self.suppressed = 0
        self.rate_limited = 0
        self.fenced = 0
        # One journal event per suppression/rate-limit/fencing EPISODE
        # (the armed policy retries every tick; flooding the bounded
        # journal with per-tick repeats would evict real incidents).
        self.suppress_logged = False
        self.limit_logged = False
        self.fence_logged = False
        self.last_value: float | None = None
        self.last = ""          # "<transition> · <detail>" for the card
        self.last_ts: float | None = None
        self.drained: list[str] = []        # slices this policy drained
        self.row: dict | None = None        # cached /api/actuate row


class ActuationEngine:
    """Per-tick policy evaluator over one sampler's query engine.

    ``observe(ts)`` records the dark-slice count series, evaluates
    every compiled condition once against a shared context, runs each
    policy's guarded state machine (journaling every transition), and
    returns True when the published /api/actuate payload changed (the
    sampler bumps the "actuate" dirty section on that)."""

    def __init__(self, specs, query, history, journal, *,
                 actuator=None, dark_slices=None, placement_domains=None,
                 dry_run: bool = False,
                 max_actions: int = 10, window_s: float = 60.0,
                 shed_max_fraction: float = 0.5,
                 leader_check=None):
        self.query = query
        self.history = history
        self.journal = journal
        self.actuator = actuator
        # Root-HA fencing (tpumon.leader): callable -> bool asked at
        # every FIRE decision. None means "no HA deployment here" —
        # standalone monitors always actuate. A False answer fences the
        # fire (journaled once per episode); the policy stays armed and
        # fires for real if leadership arrives while the condition still
        # holds. Reverts are deliberately NOT fenced: un-shedding is the
        # safe direction, and a demoted root must be able to release
        # remedies it applied while it led — the hazard the fence exists
        # for is two roots BOTH shedding, never both un-shedding.
        self.leader_check = leader_check
        # Last leadership answer published: a flip with no policy
        # transition must still count as a payload change, or the
        # cached /api/actuate render keeps saying "leader": true on a
        # root that just fenced itself (observe()).
        self._last_leader: bool | None = None
        self.dark_slices = dark_slices  # callable -> iterable of slice ids
        # callable -> iterable of ALL fleet placement domains (dark or
        # not) — kept synced into the engine so requests are attributed
        # to domains BEFORE a drain ever fires (a request with no
        # domain can never be drained off one).
        self.placement_domains = placement_domains
        self.dry_run = bool(dry_run)
        self.max_actions = max(1, int(max_actions))
        self.window_s = max(1.0, float(window_s))
        self.shed_max_fraction = min(1.0, max(0.0, float(shed_max_fraction)))
        self.policies = [_Policy(s) for s in specs]
        # Live shed fractions per tenant, per POLICY: the engine's
        # set_shed holds one fraction per tenant, so overlapping shed
        # policies (a mild slow-burn shed and an aggressive fast-page
        # shed on the same tenant) must combine here — the tenant sheds
        # at the max of every fired policy's fraction, and a revert
        # relaxes to the remaining max instead of removing the throttle
        # out from under a policy that is still fired.
        self._tenant_sheds: dict[str, dict[str, float]] = {}
        # Capacity nudges combine the same way: the TRUE pre-actuation
        # baseline is captured once, when the first capacity policy
        # fires (a later policy reading act.capacity() would capture
        # the first one's nudged values and "restore" to them forever),
        # and live nudges are held per policy in fire order so a revert
        # re-layers the remaining fired policies over the baseline
        # instead of yanking capacity out from under them.
        self._capacity_base: dict | None = None
        self._capacity_nudges: dict[str, dict] = {}
        # Drained slices are refcounted by policy name: a slice stays
        # drained until the LAST policy holding it reverts (one
        # policy's clear must not undrain a slice another still-fired
        # policy drained).
        self._drain_holds: dict[str, set[str]] = {}
        # Timestamps of PERFORMED actions (dry-run journals consume no
        # budget) — the global rate limiter's window.
        self._action_ts: deque[float] = deque()
        self._dark_handle = None
        self._darks: list[str] = []
        self._synced_domains: tuple[str, ...] | None = None
        # Whether anything here READS fleet darkness: a drain policy's
        # target set, or a condition on the federation.dark family. A
        # shed/capacity-only policy set must not pay the per-tick
        # hub.slices() walk + TSDB append for a series nothing reads.
        fams = {
            n.family
            for pol in self.policies
            for root in (pol.when_node, pol.clear_node)
            if root is not None
            for n in _walk(root)
            if isinstance(n, Selector)
        }
        self._wants_dark = (
            DARK_SERIES in fams
            or any(p.spec.action == "drain" for p in self.policies))
        self.evaluated_at: float | None = None
        self._payload: dict | None = None

    # ----------------------------- binding -----------------------------

    def bind_engine(self, engine) -> None:
        """Attach an in-process ServingEngine behind the narrow
        actuator interface (tpumon.app wires --serve-loadgen here)."""
        self.bind_actuator(EngineActuator(engine))

    def bind_actuator(self, actuator) -> None:
        self.actuator = actuator
        self.journal.record(
            "actuate", "info", "actuate",
            f"actuator bound: {type(actuator).__name__} drives "
            f"{len(self.policies)} policies"
            + (" (DRY-RUN: intent only)" if self.dry_run else ""),
            state="bound",
        )

    def rule_texts(self) -> list[str]:
        """Recording rules for every plain range selector a condition
        reads (``avg_over_time(queue_depth[30s])`` → ``queue_depth
        [30s]``): registered by the sampler so per-tick trend reads are
        O(sub-buckets) head-state merges, never point walks — the
        bench.py ``actuate`` phase pins the ≤1% tick bound this buys.
        Matcher-carrying selectors register their FAMILY's rule (rules
        are per-family but keep per-matched-series state, so a
        ``{tenant="chat"}`` read rides them too — the same way slo.py's
        windows ride the family-wide ``slo.bad[w]`` rules)."""
        out: set[str] = set()
        for pol in self.policies:
            for root in (pol.when_node, pol.clear_node):
                if root is None:
                    continue
                for n in _walk(root):
                    if isinstance(n, Selector) and n.range_s:
                        out.add(f"{n.family}[{_fmt_s(n.range_s)}]")
        return sorted(out)

    # ---------------------------- evaluation ----------------------------

    def _observed(self, pol: _Policy, ctx) -> float | None:
        """The condition's observed value for journaling: the
        non-constant side of a comparison, collapsed to one number.
        Computed only when a transition journals — never on the
        steady-state tick, whose whole cost must stay at ONE condition
        eval per policy (bench.py's ``actuate`` phase pins ≤1% of a
        v5p-256 tick; a per-tick value refresh would re-materialize
        every expression's vector and roughly triple the stage)."""
        node = pol.when_node
        if not (isinstance(node, Bin) and node.op in _CMP_OPS):
            return None
        for side in (node.lhs, node.rhs):
            if isinstance(side, Num):
                continue
            try:
                v = self.query.eval_compiled(side, ctx=ctx)
            except QueryError:
                return None
            if isinstance(v, list):
                vals = [x for _, x in v if x is not None and x == x]
                if not vals:
                    return None
                v = sum(vals) / len(vals)
            if v is None or v != v:
                return None
            return round(float(v), 4)
        return None

    def _data_absent(self, node, ctx) -> bool:
        """True when the expression's data side reads no samples at
        all — distinct from present-but-false. Used only on FIRED
        policies with an explicit ``clear``: `_cond` maps absent data
        to False for both expressions (absent never *actuates*), which
        would wedge the policy fired forever once its series vanishes
        (collector dies, source drains) — a when-only policy in the
        same situation reverts via ``not when``. Same staleness class
        slo.py hardens (a firing alert must resolve when all window
        data vanishes); the safe direction for a remedy is revert."""
        if isinstance(node, Bin) and node.op in _CMP_OPS:
            sides = [s for s in (node.lhs, node.rhs)
                     if not isinstance(s, Num)]
            if not sides:
                return False  # constants are never absent
        else:
            sides = [node]
        for side in sides:
            try:
                v = self.query.eval_compiled(side, ctx=ctx)
            except QueryError:
                continue  # broken reads as absent
            if isinstance(v, list):
                if any(x is not None and x == x for _, x in v):
                    return False
            elif v is not None and v == v:
                return False
        return True

    def _effective_dry(self, pol: _Policy) -> bool:
        return self.dry_run or pol.spec.dry_run or self.actuator is None

    def _prune_actions(self, ts: float) -> None:
        while self._action_ts and ts - self._action_ts[0] > self.window_s:
            self._action_ts.popleft()

    def _detail(self, pol: _Policy, perform: bool) -> str:
        """Describe — and with ``perform`` actually execute — the
        policy's action. The dry-run path journals exactly this string
        with ``perform=False``, so intent and act read identically."""
        spec = pol.spec
        act = self.actuator
        if spec.action == "shed":
            frac = min(spec.fraction, self.shed_max_fraction)
            if perform:
                sheds = self._tenant_sheds.setdefault(spec.tenant, {})
                sheds[spec.name] = frac
                frac = act.shed(spec.tenant, max(sheds.values()))
            return f"shed tenant {spec.tenant} at {frac:.2f}"
        if spec.action == "capacity":
            budget = spec.prefill_budget or None
            look = spec.admit_lookahead if spec.admit_lookahead >= 0 else None
            if perform:
                if self._capacity_base is None:
                    self._capacity_base = act.capacity()
                # Re-fires move to the back of the layering order.
                self._capacity_nudges.pop(spec.name, None)
                self._capacity_nudges[spec.name] = {
                    "prefill_budget": budget, "admit_lookahead": look}
                eff = act.nudge(prefill_budget=budget, admit_lookahead=look)
                return (f"capacity -> prefill_budget "
                        f"{eff['prefill_budget']}, admit_lookahead "
                        f"{eff['admit_lookahead']}")
            return (f"capacity -> prefill_budget {budget or '(keep)'}, "
                    f"admit_lookahead {'(keep)' if look is None else look}")
        # drain: explicit slice, else whatever federation marks dark NOW
        targets = [spec.slice] if spec.slice else list(self._darks)
        if perform:
            for s in targets:
                holders = self._drain_holds.setdefault(s, set())
                if not holders:
                    act.drain(s)
                holders.add(spec.name)
            pol.drained = targets
        return f"drain slice(s): {', '.join(targets) or '(none dark)'}"

    def _revert_detail(self, pol: _Policy, perform: bool) -> str:
        spec = pol.spec
        act = self.actuator
        if spec.action == "shed":
            if perform:
                sheds = self._tenant_sheds.get(spec.tenant, {})
                sheds.pop(spec.name, None)
                if sheds:
                    # Another fired policy still sheds this tenant:
                    # relax to the remaining max, don't remove.
                    frac = max(sheds.values())
                    act.shed(spec.tenant, frac)
                    return (f"shed tenant {spec.tenant} relaxed to "
                            f"{frac:.2f} ({len(sheds)} polic"
                            f"{'y' if len(sheds) == 1 else 'ies'} "
                            f"still shedding)")
                self._tenant_sheds.pop(spec.tenant, None)
                act.unshed(spec.tenant)
            return f"unshed tenant {spec.tenant}"
        if spec.action == "capacity":
            base = self._capacity_base
            if perform:
                self._capacity_nudges.pop(spec.name, None)
                if base:
                    act.nudge(**base)
                    # Other fired capacity policies re-layer over the
                    # baseline in fire order — their nudges survive
                    # this policy's revert.
                    for kw in self._capacity_nudges.values():
                        act.nudge(**kw)
                if self._capacity_nudges:
                    n = len(self._capacity_nudges)
                    return (f"capacity restored to {base} then "
                            f"re-layered ({n} polic"
                            f"{'y' if n == 1 else 'ies'} still nudging)")
                self._capacity_base = None
            return f"capacity restored to {base or '(baseline unknown)'}"
        targets = list(pol.drained)
        if perform:
            kept: list[str] = []
            for s in targets:
                holders = self._drain_holds.get(s)
                if holders is not None:
                    holders.discard(spec.name)
                    if holders:
                        kept.append(s)
                        continue
                    self._drain_holds.pop(s, None)
                act.undrain(s)
            pol.drained = []
            if kept:
                undrained = [s for s in targets if s not in kept]
                return (f"undrain slice(s): "
                        f"{', '.join(undrained) or '(none)'} "
                        f"(still drained by other policies: "
                        f"{', '.join(kept)})")
        pol.drained = []
        return f"undrain slice(s): {', '.join(targets) or '(none)'}"

    def _journal(self, pol: _Policy, state: str, sev: str, detail: str,
                 ts: float, dry: bool, ctx=None) -> None:
        if ctx is not None:
            pol.last_value = self._observed(pol, ctx)
        self.journal.record(
            "actuate", sev, "actuate",
            f"policy {pol.spec.name} {state}"
            + (" (dry-run)" if dry and state in ("fired", "reverted")
               else "")
            + f": {detail}",
            ts=ts,
            policy=pol.spec.name,
            action=pol.spec.action,
            state=state,
            expr=pol.spec.when,
            value=pol.last_value,
            dry_run=True if dry else None,
        )
        pol.last = f"{state} · {detail}"
        pol.last_ts = ts

    def _sync_domains(self, ts: float) -> None:
        """Keep the engine's placement-domain namespace synced to the
        fleet's, so requests carry a slice attribution BEFORE any drain
        fires. Only when a live (non-dry) drain policy exists — dry-run
        deployments provably change no engine state — and only on
        change (set_slices resets attribution round-robin)."""
        setter = getattr(self.actuator, "set_slices", None)
        if self.placement_domains is None or setter is None:
            return
        if not any(p.spec.action == "drain" and not self._effective_dry(p)
                   for p in self.policies):
            return
        doms = self.placement_domains()
        doms = tuple(sorted({str(d) for d in doms})) if doms else ()
        # An empty read (fleet view warming up, every leaf silent)
        # keeps the last known namespace — dropping attribution
        # mid-outage would make the outage undrainable.
        if not doms or doms == self._synced_domains:
            return
        setter(doms)
        self._synced_domains = doms
        self.journal.record(
            "actuate", "info", "actuate",
            f"placement domains synced: {len(doms)} "
            f"({', '.join(doms[:8])}{', …' if len(doms) > 8 else ''})",
            ts=ts, state="domains",
        )

    def observe(self, ts: float | None = None) -> bool:
        ts = time.time() if ts is None else ts
        changed = False
        # Leadership rides the published payload (to_json "leader"):
        # losing or gaining the lease re-renders /api/actuate even when
        # no policy moved this tick.
        lead = self._is_leader()
        if lead != self._last_leader:
            self._last_leader = lead
            changed = True
        # Dark-slice count series FIRST, so this very tick's drain
        # conditions read current fleet state. A None provider result
        # means "no fleet here" (standalone monitor, no federation
        # hub): skip the record — the per-tick append is nearly half
        # the stage cost, and an absent series and a 0.0 read alike
        # under `federation.dark > 0` (absent never fires). The
        # provider is not even CALLED unless a policy reads darkness
        # (_wants_dark): shed/capacity-only sets skip the walk too.
        darks = (self.dark_slices()
                 if self.dark_slices is not None and self._wants_dark
                 else None)
        if darks is not None:
            self._darks = sorted(darks)
            if self._dark_handle is None or (
                    self.history.series.get(DARK_SERIES)
                    is not self._dark_handle):
                self._dark_handle = self.history.handle(DARK_SERIES)
            self.history.record_batch(
                [(self._dark_handle, float(len(self._darks)))], ts=ts)
        self._sync_domains(ts)
        self._prune_actions(ts)
        ctx = self.query.context(at=ts)
        # Condition results memoized by expression TEXT for this tick:
        # real policy sets share trigger expressions (every per-tenant
        # shed keyed on the same page-state read), so each distinct
        # condition is evaluated once per tick no matter how many
        # policies gate on it.
        cond_memo: dict[str, bool] = {}
        for pol in self.policies:
            if self._step_policy(pol, ctx, ts, cond_memo):
                changed = True
                pol.row = None
        for pol in self.policies:
            if pol.row is None:
                spec = pol.spec
                pol.row = {
                    "name": spec.name,
                    "action": spec.action,
                    "when": spec.when,
                    "state": pol.state,
                    "dry_run": self._effective_dry(pol),
                    "value": pol.last_value,
                    "last": pol.last,
                    "last_ts": pol.last_ts,
                    "fired": pol.fired,
                    "reverted": pol.reverted,
                    "suppressed": pol.suppressed,
                    "rate_limited": pol.rate_limited,
                    "fenced": pol.fenced,
                }
        first = self._payload is None
        self.evaluated_at = ts
        if changed or first:
            self._payload = {"policies": [p.row for p in self.policies]}
        return changed or first

    def _is_leader(self) -> bool:
        """May this engine perform (or even dry-journal) a FIRE right
        now? True with no leader_check wired — fencing is an HA-root
        concern only."""
        return self.leader_check is None or bool(self.leader_check())

    def _cond(self, node, text: str, ctx, memo: dict) -> bool:
        try:
            return memo[text]
        except KeyError:
            pass
        try:
            v = self.query.eval_condition(node, ctx=ctx)
        except QueryError:
            v = False  # absent/broken data never actuates
        memo[text] = v
        return v

    def _step_policy(self, pol: _Policy, ctx, ts: float,
                     memo: dict) -> bool:
        """One tick of one policy's guarded state machine; returns True
        when its published row changed."""
        spec = pol.spec
        cond = self._cond(pol.when_node, spec.when, ctx, memo)
        changed = False
        dry = self._effective_dry(pol)

        if pol.state == "idle":
            if cond:
                pol.state = "armed"
                pol.hold = 1
                pol.suppress_logged = pol.limit_logged = False
                pol.fence_logged = False
                self._journal(pol, "armed", "info",
                              f"condition holds: {spec.when}", ts, dry,
                              ctx=ctx)
                changed = True
        elif pol.state == "armed":
            if not cond:
                pol.state = "idle"
                pol.hold = 0
                changed = True
            else:
                pol.hold += 1
        if pol.state == "armed" and pol.hold >= spec.fire_hold:
            in_cooldown = (
                pol.last_fired_ts is not None
                and ts - pol.last_fired_ts < spec.cooldown_s)
            if not self._is_leader():
                # Fencing precedes every other fire gate INCLUDING the
                # dry-run path: a standby root runs the same policy set
                # (so promotion inherits armed state instantly) but must
                # not even dry-fire — the journal would read as a second
                # root acting. Episode-logged like suppression; the
                # policy stays armed and fires on the first tick after
                # promotion if the condition still holds.
                if not pol.fence_logged:
                    pol.fence_logged = True
                    pol.fenced += 1
                    self._journal(
                        pol, "fenced", "serious",
                        "not fleet leader (leadership lease lost, "
                        "expired, or never held): refusing to actuate",
                        ts, dry, ctx=ctx)
                    changed = True
            elif in_cooldown:
                if not pol.suppress_logged:
                    pol.suppress_logged = True
                    pol.suppressed += 1
                    left = spec.cooldown_s - (ts - pol.last_fired_ts)
                    self._journal(
                        pol, "suppressed", "minor",
                        f"cooldown: {left:.1f}s of {spec.cooldown_s:g}s "
                        f"remain", ts, dry, ctx=ctx)
                    changed = True
            elif not dry and len(self._action_ts) >= self.max_actions:
                if not pol.limit_logged:
                    pol.limit_logged = True
                    pol.rate_limited += 1
                    self._journal(
                        pol, "rate-limited", "minor",
                        f"global budget spent: {len(self._action_ts)} "
                        f"actions in the last {self.window_s:g}s "
                        f"(max {self.max_actions})", ts, dry, ctx=ctx)
                    changed = True
            else:
                detail = self._detail(pol, perform=not dry)
                if not dry:
                    self._action_ts.append(ts)
                pol.state = "fired"
                pol.fired += 1
                pol.last_fired_ts = ts
                pol.clear_count = 0
                self._journal(pol, "fired", "serious", detail, ts, dry,
                              ctx=ctx)
                changed = True
        elif pol.state == "fired":
            # The explicit clear expression is consumed ONLY here, so
            # it is evaluated only while fired — an idle policy's
            # steady-state tick stays at ONE condition eval (the cost
            # contract bench.py's ``actuate`` phase pins).
            if pol.clear_node is not None:
                clearing = self._cond(pol.clear_node, spec.clear, ctx,
                                      memo)
                if not clearing and self._data_absent(pol.clear_node,
                                                      ctx):
                    # The explicit clear reads NO data at all: treat
                    # as clearing (through the normal clear_hold)
                    # instead of holding the remedy applied forever on
                    # a vanished source — see _data_absent.
                    clearing = True
            else:
                clearing = not cond
            if clearing:
                pol.clear_count += 1
                if pol.clear_count >= spec.clear_hold:
                    detail = self._revert_detail(pol, perform=not dry)
                    pol.state = "idle"
                    pol.hold = 0
                    pol.reverted += 1
                    self._journal(pol, "reverted", "info", detail, ts, dry,
                                  ctx=ctx)
                    changed = True
            else:
                pol.clear_count = 0
        return changed

    # ------------------------------ outputs ------------------------------

    @property
    def actions_in_window(self) -> int:
        """Performed actions inside the current rate-limit window —
        the exporter reads this scalar without building the payload."""
        return len(self._action_ts)

    def to_json(self) -> dict:
        return {
            "policies": list((self._payload or {}).get("policies") or []),
            "dry_run": self.dry_run,
            "engine_bound": self.actuator is not None,
            "max_actions": self.max_actions,
            "window_s": self.window_s,
            "actions_in_window": self.actions_in_window,
            "leader": self._is_leader(),
            "evaluated_at": self.evaluated_at,
        }

    def exporter_rows(self) -> list[dict]:
        """Flat per-policy rows for the tpumon_actuate_* block."""
        return list((self._payload or {}).get("policies") or [])
