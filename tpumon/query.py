"""In-tree PromQL-subset query engine over the columnar TSDB.

The paper's fourth collector was an external Prometheus doing instant +
range queries (monitor_server.js:14-63,117-154); until this module we
mirrored that dependency — rich questions about the monitor's own data
required deploying a second monitoring system next to the monitor. This
is the replacement (ROADMAP item 1): a small expression language that
evaluates **directly over tpumon.tsdb sealed chunks** (window seeks
ride ``Tier.since``'s bisect — O(log chunks + matched)), with three
layers on top:

- **Topology labels from series names.** The ring's flat series names
  already encode the topology: ``chip.<id>.<metric>`` becomes family
  ``chip.<metric>`` with labels ``chip``/``host`` (plus ``pod`` and the
  accelerator family ``accel`` — "tpu" | "gpu" — when the sampler's
  augmenter hook is wired), ``slice.<node>.<id>.<stat>`` becomes
  ``slice.<stat>`` with labels ``node``/``slice`` (and ``accel`` at a
  federation hub), and fleet series (``cpu``, ``mxu``, ...) are
  label-less families. ``by (label)`` grouping and ``{label="..."}``
  matchers work over exactly these; ``topk``/``bottomk`` additionally
  accept ``by`` for per-group ranking (``topk(5, rate(chip.hbm)) by
  (accel)``).
- **Incremental recording rules** (``recording_rules`` config):
  a registered ``family[window]`` selector maintains running aggregates
  — count/sum/min/max, rate endpoints, reset-aware increase — in
  per-series sub-bucket summary rows updated **at append time**, one
  native call per tick for ALL rules (the PR 6 ``accum_many`` idea
  applied to query aggregates; bit-exact Python fallback). An instant
  ``*_over_time``/``rate`` read over a registered (family, window) is
  then an O(sub-buckets) merge of head-row state, never a point walk.
- **Distributed (fleet) evaluation** over the federation tree
  (tpumon.federation): the root plans a top-level aggregation, pushes
  the sub-query down the existing uplink streams (protowire TPWQ/TPWR
  frames), and merges **partial aggregates** — mergeable
  sum/count/min/max states, topk row sets, and a fixed-bucket mergeable
  histogram sketch (QSketch) for quantiles — so ``topk(5,
  rate(chip.hbm))`` over a v5p-2048 fleet never ships raw points
  upstream. ``partial_eval`` / ``merge_partials`` / ``finalize`` are
  the three phases; the transport lives in tpumon.federation.

Grammar (docs/query.md has the full table)::

    expr      := or  ;  or := and ('or' and)*  ;  and := cmp ('and' cmp)*
    cmp       := sum (('>'|'<'|'>='|'<='|'=='|'!=') sum)?
    sum       := term (('+'|'-') term)*  ;  term := unary (('*'|'/') unary)*
    unary     := '-' unary | atom
    atom      := NUMBER | '(' expr ')' | agg | call | selector
    agg       := AGGOP by? '(' args ')' by?       -- avg by (host) (v)
    call      := FUNC '(' args ')'                -- rate(chip.hbm[1m])
    selector  := NAME matchers? range?            -- chip.mxu{host="h0"}[5m]

Functions: ``rate increase avg_over_time min_over_time max_over_time
sum_over_time count_over_time quantile_over_time``; aggregations:
``sum avg min max count quantile topk bottomk`` (all accept ``by``).
Comparisons filter vectors (Prometheus semantics); on scalars they
yield 1.0/0.0. The same AST doubles as the alert engine's rule
compiler (``compile_env``): threshold rules are expressions over a
flat ``chip.hbm``-style environment, compiled once per config.

Defined semantics (the golden parity tests pin the engine bit-compatible
against a brute-force reference over tests/fixtures/tsdb_fuzz.json):
window functions read the closed interval ``[t-w, t]``; ``increase``
sums deltas with counter-reset handling (a drop contributes the new
value); ``rate`` divides by the actual first→last span, not the window;
quantiles interpolate linearly at rank ``q*(n-1)``; selectors return
series sorted by name and aggregations fold in that order.
"""

from __future__ import annotations

import json
import re
import sys
import time
from array import array
from bisect import bisect_left, bisect_right

# ----------------------------- registries ------------------------------

# Range functions: FUNC(sel[window]) (+ a leading scalar for quantile_*).
RANGE_FUNCTIONS: tuple[str, ...] = (
    "rate",
    "increase",
    "avg_over_time",
    "min_over_time",
    "max_over_time",
    "sum_over_time",
    "count_over_time",
    "quantile_over_time",
)
# Cross-series aggregations (accept ``by (label, ...)``).
AGG_OPS: tuple[str, ...] = (
    "sum",
    "avg",
    "min",
    "max",
    "count",
    "quantile",
    "topk",
    "bottomk",
)
# The documented function vocabulary — tools/tpulint's registry pass
# pins every name here against docs/query.md's function table.
FUNCTIONS: tuple[str, ...] = RANGE_FUNCTIONS + AGG_OPS

_KEYWORDS = frozenset({"and", "or", "by"})

DEFAULT_RANGE_S = 60.0  # rate(chip.hbm) without [w] reads the last minute
DEFAULT_LOOKBACK_S = 300.0  # instant selector staleness bound


class QueryError(ValueError):
    """Malformed expression or unevaluable query (HTTP 400)."""


# ------------------------------- lexer ---------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<num>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
  | (?P<name>[A-Za-z_][A-Za-z0-9_.]*)
  | (?P<str>"(?:[^"\\]|\\.)*")
  | (?P<op>>=|<=|==|!=|=~|[-+*/(),{}=<>\[\]])
    """,
    re.X,
)

_DUR_RE = re.compile(r"^(\d+(?:\.\d+)?)([smhd]?)$")
_DUR_UNITS = {"": 1.0, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def _lex(src: str) -> list[tuple[str, str]]:
    out: list[tuple[str, str]] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise QueryError(f"bad character {src[pos]!r} at offset {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        out.append((kind, m.group()))
    out.append(("eof", ""))
    return out


def parse_range(text: str) -> float:
    """``[30m]``-style duration (bare numbers are seconds)."""
    m = _DUR_RE.match(text.strip())
    if not m:
        raise QueryError(f"bad range duration {text!r} (want e.g. 30s, 5m)")
    return float(m.group(1)) * _DUR_UNITS[m.group(2)]


# -------------------------------- AST ----------------------------------


class Num:
    __slots__ = ("v",)

    def __init__(self, v: float):
        self.v = v


class Selector:
    __slots__ = ("family", "matchers", "range_s")

    def __init__(self, family: str, matchers, range_s: float | None):
        self.family = family
        self.matchers = matchers  # tuple of (label, op, value)
        self.range_s = range_s


class Call:
    __slots__ = ("fn", "args")

    def __init__(self, fn: str, args: list):
        self.fn = fn
        self.args = args


class Agg:
    __slots__ = ("op", "by", "args")

    def __init__(self, op: str, by: tuple[str, ...], args: list):
        self.op = op
        self.by = by
        self.args = args


class Bin:
    __slots__ = ("op", "lhs", "rhs")

    def __init__(self, op: str, lhs, rhs):
        self.op = op
        self.lhs = lhs
        self.rhs = rhs


class Neg:
    __slots__ = ("arg",)

    def __init__(self, arg):
        self.arg = arg


def _const_value(node) -> float | None:
    """The node's compile-time constant value (Num, possibly under
    Neg), or None when it isn't one — the eval_condition fast path's
    shape test."""
    if isinstance(node, Num):
        return node.v
    if isinstance(node, Neg):
        v = _const_value(node.arg)
        return None if v is None else -v
    return None


class _Parser:
    def __init__(self, src: str):
        self.src = src
        self.toks = _lex(src)
        self.i = 0

    def peek(self) -> tuple[str, str]:
        return self.toks[self.i]

    def next(self) -> tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, text: str) -> None:
        kind, val = self.next()
        if val != text:
            raise QueryError(
                f"expected {text!r}, got {val or 'end of input'!r} "
                f"in {self.src!r}"
            )

    def parse(self):
        e = self.expr()
        if self.peek()[0] != "eof":
            raise QueryError(f"trailing input at {self.peek()[1]!r}")
        return e

    def expr(self):
        return self._or()

    def _or(self):
        e = self._and()
        while self.peek() == ("name", "or"):
            self.next()
            e = Bin("or", e, self._and())
        return e

    def _and(self):
        e = self._cmp()
        while self.peek() == ("name", "and"):
            self.next()
            e = Bin("and", e, self._cmp())
        return e

    def _cmp(self):
        e = self._sum()
        if self.peek()[1] in (">", "<", ">=", "<=", "==", "!="):
            op = self.next()[1]
            e = Bin(op, e, self._sum())
        return e

    def _sum(self):
        e = self._term()
        while self.peek()[1] in ("+", "-"):
            op = self.next()[1]
            e = Bin(op, e, self._term())
        return e

    def _term(self):
        e = self._unary()
        while self.peek()[1] in ("*", "/"):
            op = self.next()[1]
            e = Bin(op, e, self._unary())
        return e

    def _unary(self):
        if self.peek()[1] == "-":
            self.next()
            return Neg(self._unary())
        return self._atom()

    def _by_clause(self) -> tuple[str, ...]:
        self.expect("(")
        labels: list[str] = []
        while True:
            kind, val = self.next()
            if kind != "name":
                raise QueryError(f"bad by() label {val!r}")
            labels.append(val)
            kind, val = self.next()
            if val == ")":
                return tuple(labels)
            if val != ",":
                raise QueryError(f"expected , or ) in by(), got {val!r}")

    def _args(self) -> list:
        self.expect("(")
        args = [self.expr()]
        while self.peek()[1] == ",":
            self.next()
            args.append(self.expr())
        self.expect(")")
        return args

    def _atom(self):
        kind, val = self.peek()
        if kind == "num":
            self.next()
            return Num(float(val))
        if val == "(":
            self.next()
            e = self.expr()
            self.expect(")")
            return e
        if kind != "name":
            raise QueryError(f"unexpected {val or 'end of input'!r}")
        if val in _KEYWORDS:
            raise QueryError(f"unexpected keyword {val!r}")
        if val in AGG_OPS:
            self.next()
            by: tuple[str, ...] = ()
            if self.peek() == ("name", "by"):
                self.next()
                by = self._by_clause()
            args = self._args()
            if self.peek() == ("name", "by"):
                if by:
                    raise QueryError("duplicate by() clause")
                self.next()
                by = self._by_clause()
            return Agg(val, by, args)
        if val in RANGE_FUNCTIONS:
            self.next()
            return Call(val, self._args())
        return self._selector()

    def _selector(self) -> Selector:
        kind, family = self.next()
        matchers: list[tuple[str, str, str]] = []
        if self.peek()[1] == "{":
            self.next()
            while True:
                k, label = self.next()
                if k != "name":
                    raise QueryError(f"bad matcher label {label!r}")
                op = self.next()[1]
                if op not in ("=", "!=", "=~"):
                    raise QueryError(f"bad matcher operator {op!r}")
                k, raw = self.next()
                if k != "str":
                    raise QueryError("matcher value wants a \"string\"")
                matchers.append((label, op, json.loads(raw)))
                k, sep = self.next()
                if sep == "}":
                    break
                if sep != ",":
                    raise QueryError(f"expected , or }} in matchers, got {sep!r}")
        range_s = None
        if self.peek()[1] == "[":
            self.next()
            parts: list[str] = []
            while self.peek()[1] not in ("]", ""):
                parts.append(self.next()[1])
            self.expect("]")
            range_s = parse_range("".join(parts))
        return Selector(family, tuple(matchers), range_s)


def parse(src: str):
    """Parse an expression; raises QueryError on malformed input."""
    if not src or not src.strip():
        raise QueryError("empty expression")
    return _Parser(src).parse()


# ------------------------ series name → labels -------------------------


def parse_series_name(name: str) -> tuple[str, dict[str, str]]:
    """Map a flat ring series name onto (family, labels) — the topology
    labels are *derived from the naming contract*, not stored:

      chip.<id>.<metric>        -> ("chip.<metric>", {chip, host})
      slice.<node>.<id>.<stat>  -> ("slice.<stat>",  {node, slice})
      serving.<tenant>.<metric> -> ("serving.<metric>", {tenant})
      slo.<name>.<metric>       -> ("slo.<metric>",  {slo})
      anything else             -> (name, {})

    ``host`` is the chip id's host component (``host-0/chip-3``).
    Tenant names and SLO names are dot-free by contract (the traffic
    driver and the SLO engine both validate), so the serving/slo forms
    split unambiguously. Limitation: a federation node name containing
    dots mis-splits the slice form (the hub's series contract puts
    node first)."""
    if name.startswith("serving."):
        rest = name[8:]
        tenant, _, metric = rest.partition(".")
        if tenant and metric and "." not in metric:
            return f"serving.{metric}", {"tenant": tenant}
        # Multi-dot metric tails (none exist today) fall through to
        # the verbatim form rather than guessing a split.
        if tenant and metric:
            return name, {}
    elif name.startswith("slo."):
        rest = name[4:]
        slo, _, metric = rest.partition(".")
        if slo and metric and "." not in metric:
            return f"slo.{metric}", {"slo": slo}
        if slo and metric:
            return name, {}
    if name.startswith("chip."):
        rest = name[5:]
        cid, _, metric = rest.rpartition(".")
        if cid and metric:
            labels = {"chip": cid}
            if "/" in cid:
                labels["host"] = cid.split("/", 1)[0]
            return f"chip.{metric}", labels
    elif name.startswith("slice."):
        rest = name[6:]
        mid, _, stat = rest.rpartition(".")
        if mid and stat:
            node, _, sid = mid.partition(".")
            return f"slice.{stat}", {"node": node, "slice": sid or node}
    return name, {}


def _has_glob(s: str) -> bool:
    return any(ch in s for ch in "*?[")


def _match_one(value: str | None, op: str, want: str) -> bool:
    if value is None:
        return op == "!=" and want != ""
    if op == "=":
        return value == want
    if op == "!=":
        return value != want
    import fnmatch

    return fnmatch.fnmatchcase(value, want)


# --------------------------- quantile sketch ---------------------------

# Fixed log-spaced bucket bounds (4 per decade, 1e-3 .. 1e12) shared by
# every sketch — what makes two sketches built anywhere in the tree
# mergeable by plain per-bucket addition. Bucket 0 holds <= lower-bound
# values (zeros, negatives).
QSKETCH_BOUNDS: tuple[float, ...] = tuple(
    10.0 ** (k / 4.0) for k in range(-12, 49)
)


class QSketch:
    """Bounded mergeable value sketch for distributed quantiles.

    Exact (a value list) up to ``cap`` values; beyond that it collapses
    to fixed log-bucket counts + exact min/max. Merging two sketches
    anywhere in the federation tree yields the same state as building
    one sketch from the concatenated values — which is what lets an
    aggregator fold its leaves' states without raw points. Quantiles
    are exact in list mode and bucket-interpolated (clamped to
    [min, max]) in bucket mode; docs/query.md documents the error
    bound (one bucket ≈ ±33%)."""

    __slots__ = ("cap", "n", "mn", "mx", "values", "buckets")

    def __init__(self, cap: int = 1024):
        self.cap = cap
        self.n = 0
        self.mn: float | None = None
        self.mx: float | None = None
        self.values: list[float] | None = []
        self.buckets: list[int] | None = None

    def add(self, v: float) -> None:
        self.n += 1
        if self.mn is None or v < self.mn:
            self.mn = v
        if self.mx is None or v > self.mx:
            self.mx = v
        if self.values is not None:
            self.values.append(v)
            if len(self.values) > self.cap:
                self._collapse()
        else:
            self.buckets[self._bucket(v)] += 1

    @staticmethod
    def _bucket(v: float) -> int:
        return bisect_left(QSKETCH_BOUNDS, v) if v > 0 else 0

    def _collapse(self) -> None:
        counts = [0] * (len(QSKETCH_BOUNDS) + 1)
        for v in self.values:
            counts[self._bucket(v)] += 1
        self.values = None
        self.buckets = counts

    def merge(self, other: "QSketch") -> None:
        self.n += other.n
        for attr, pick in (("mn", min), ("mx", max)):
            ov = getattr(other, attr)
            if ov is not None:
                sv = getattr(self, attr)
                setattr(self, attr, ov if sv is None else pick(sv, ov))
        if self.values is not None and other.values is not None:
            self.values.extend(other.values)
            if len(self.values) > self.cap:
                self._collapse()
            return
        if self.values is not None:
            self._collapse()
        if other.values is not None:
            for v in other.values:
                self.buckets[self._bucket(v)] += 1
        else:
            for i, c in enumerate(other.buckets):
                self.buckets[i] += c

    def quantile(self, q: float) -> float | None:
        if not self.n:
            return None
        if self.values is not None:
            return _quantile(sorted(self.values), q)
        rank = q * (self.n - 1)
        seen = 0.0
        for i, c in enumerate(self.buckets):
            if not c:
                continue
            if seen + c > rank:
                lo = QSKETCH_BOUNDS[i - 1] if i > 0 else (self.mn or 0.0)
                hi = QSKETCH_BOUNDS[i] if i < len(QSKETCH_BOUNDS) else self.mx
                v = (lo + hi) / 2.0
                return max(self.mn, min(self.mx, v))
            seen += c
        return self.mx

    def to_json(self) -> dict:
        out: dict = {"n": self.n, "mn": self.mn, "mx": self.mx}
        if self.values is not None:
            out["v"] = self.values
        else:
            out["b"] = {
                str(i): c for i, c in enumerate(self.buckets) if c
            }
        return out

    @classmethod
    def from_json(cls, d: dict, cap: int = 1024) -> "QSketch":
        sk = cls(cap)
        sk.n = int(d.get("n") or 0)
        sk.mn = d.get("mn")
        sk.mx = d.get("mx")
        if "v" in d:
            sk.values = [float(x) for x in d["v"]]
            if len(sk.values) > cap:
                sk._collapse()
        else:
            sk.values = None
            sk.buckets = [0] * (len(QSKETCH_BOUNDS) + 1)
            for k, c in (d.get("b") or {}).items():
                i = int(k)
                if 0 <= i < len(sk.buckets):
                    sk.buckets[i] = int(c)
        return sk


def _quantile(sorted_vals: list[float], q: float) -> float | None:
    """Linear interpolation at rank q*(n-1) — Prometheus's
    quantile_over_time method, and the single definition every path
    (direct, recording rule, distributed sketch in exact mode) shares."""
    n = len(sorted_vals)
    if not n:
        return None
    if n == 1:
        return sorted_vals[0]
    rank = max(0.0, min(1.0, q)) * (n - 1)
    lo = int(rank)
    hi = min(lo + 1, n - 1)
    frac = rank - lo
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * frac


# --------------------------- recording rules ---------------------------
#
# Append-time aggregate state lives in CONTIGUOUS COLUMNS, not per-point
# Python objects — the PR 6 ``accum_many`` trick applied to query
# aggregates. Each rule owns a RuleStore: per matched series ("rule
# slot") one dense OPEN row plus a ring of RULE_SUB_BUCKETS closed rows,
# (bucket index, count, sum, min, max, first/last point, reset-aware
# increase) spread across ten array('d') columns. The per-tick batch
# ingest path (tpumon.history.RingHistory.record_batch) updates every
# matched series' open row in ONE call per rule — the native kernel
# (tsdbkern.cpp tpumon_tsdb_rule_accum) when built, a bit-exact Python
# loop otherwise — so unmatched series pay nothing and matched series
# pay ~one C iteration. Instant reads merge <= 17 rows (O(1)).
# quantile_over_time deliberately has no rule backing (a per-point
# sketch would put Python work back in the hot path); it always takes
# the direct window read.

RULE_SUB_BUCKETS = 16  # window/16 closed sub-buckets (+ the open row)

# Row-major summary layout: one row = 10 consecutive doubles (80 bytes,
# ~2 cache lines) — [bucket index (NaN = empty), n, sum, min, max,
# first_ts, first_v, last_ts, last_v, increase].
R_BIDX, R_N, R_SUM, R_MN, R_MX = 0, 1, 2, 3, 4
R_FTS, R_FV, R_LTS, R_LV, R_INC = 5, 6, 7, 8, 9
RULE_ROW_STRIDE = 10

_NAN = float("nan")
_EMPTY_ROW = [_NAN] + [0.0] * (RULE_ROW_STRIDE - 1)


class RuleStore:
    """One recording rule's state (see the block comment above), split
    hot/cold for the per-tick update's sake: ``open`` holds ONE row per
    matched series — the sub-bucket currently accumulating, densely
    packed (80 B/series, so a 256-series rule's whole per-tick working
    set is ~20 KB and stays cache-resident) — and ``hist`` holds the
    RULE_SUB_BUCKETS closed rows per series as a ring (touched only on
    a bucket rollover, once per sub_s). ``slot_map`` maps the RING's
    global series slot -> this store's slot (-1 = not matched), which
    is what lets the batched update take the ring's existing (slots,
    values) arrays verbatim with no per-tick collection pass."""

    __slots__ = ("sub_s", "hh", "slot_map", "open", "hist", "_kptrs")

    def __init__(self, sub_s: float):
        self.sub_s = sub_s
        self.hh = array("i")  # per slot: next hist-ring write position
        self.slot_map = array("i")
        self.open = array("d")  # one open row per slot (hot)
        self.hist = array("d")  # RULE_SUB_BUCKETS closed rows per slot
        # Kernel-call cache (tpumon.native.TsdbKernel.rule_accum): the
        # arrays only ever move on add_slot, so the struct of pointers
        # is rebuilt per topology change, not per tick.
        self._kptrs = None

    def add_slot(self, ring_slot: int | None) -> int:
        r = len(self.hh)
        self.hh.append(0)
        self.open.extend(_EMPTY_ROW)
        self.hist.extend(_EMPTY_ROW * RULE_SUB_BUCKETS)
        if ring_slot is not None:
            while len(self.slot_map) <= ring_slot:
                self.slot_map.append(-1)
            self.slot_map[ring_slot] = r
        self._kptrs = None  # arrays may have reallocated
        return r

    def observe_one(self, r: int, ts: float, v: float) -> None:
        """Per-point update (the non-batched ingest paths: add(),
        add_batch replays, slotless series). Bit-identical to one
        iteration of the batched kernel."""
        self._observe_prebucketed(r, ts // self.sub_s, ts, v)

    def accum_batch(self, ts: float, val_q: array, slots: array, k=None) -> None:
        """One shared-timestamp update for every matched series in the
        tick's batch: the ring hands its existing slots/values arrays;
        non-members skip via slot_map. One native call when the kernel
        is loaded; the Python loop is its bit-exact mirror."""
        if k is not None:
            k.rule_accum(ts, val_q, slots, self)
            return
        b = ts // self.sub_s
        smap = self.slot_map
        mlen = len(smap)
        for i, g in enumerate(slots):
            if g < 0 or g >= mlen:
                continue
            r = smap[g]
            if r < 0:
                continue
            self._observe_prebucketed(r, b, ts, val_q[i])

    def _observe_prebucketed(self, r: int, b: float, ts: float, v: float) -> None:
        op = self.open
        base = r * RULE_ROW_STRIDE
        if op[base] == b:
            op[base + R_N] += 1.0
            op[base + R_SUM] += v
            if v < op[base + R_MN]:
                op[base + R_MN] = v
            elif v > op[base + R_MX]:
                op[base + R_MX] = v
            delta = v - op[base + R_LV]
            op[base + R_INC] += delta if delta >= 0 else v
            op[base + R_LTS] = ts
            op[base + R_LV] = v
            return
        if op[base] == op[base]:  # open row holds a closed bucket: bank it
            h = self.hh[r]
            dst = (r * RULE_SUB_BUCKETS + h) * RULE_ROW_STRIDE
            self.hist[dst : dst + RULE_ROW_STRIDE] = op[
                base : base + RULE_ROW_STRIDE
            ]
            self.hh[r] = (h + 1) % RULE_SUB_BUCKETS
        op[base] = b
        op[base + R_N] = 1.0
        op[base + R_SUM] = v
        op[base + R_MN] = op[base + R_MX] = v
        op[base + R_FTS] = op[base + R_LTS] = ts
        op[base + R_FV] = op[base + R_LV] = v
        op[base + R_INC] = 0.0

    def rows(self, r: int) -> list[tuple[array, int]]:
        """Populated (array, row base) pairs for slot ``r`` — the hist
        ring's closed buckets plus the open row — oldest bucket first."""
        out: list[tuple[array, int]] = []
        hist = self.hist
        lo = r * RULE_SUB_BUCKETS * RULE_ROW_STRIDE
        for base in range(
            lo, lo + RULE_SUB_BUCKETS * RULE_ROW_STRIDE, RULE_ROW_STRIDE
        ):
            if hist[base] == hist[base]:
                out.append((hist, base))
        ob = r * RULE_ROW_STRIDE
        if self.open[ob] == self.open[ob]:
            out.append((self.open, ob))
        out.sort(key=lambda p: p[0][p[1]])
        return out


def _rule_kernel():
    """The native rule-accumulation entry point, or None. Rides the
    same loaded TsdbKernel as the ingest spine (tpumon.tsdb.kernel) —
    one .so, one ABI gate, one enable switch."""
    from tpumon import tsdb

    k = tsdb.kernel()
    return k if k is not None and hasattr(k, "rule_accum") else None


class RuleAccum:
    """One series' view onto one rule's store slot — what
    RingSeries.rec holds. ``observe`` is the per-point path; ``merged``
    the O(sub-buckets) instant read."""

    __slots__ = ("rule", "store", "slot")

    def __init__(self, rule: "RecordingRule", slot: int):
        self.rule = rule
        self.store = rule.store
        self.slot = slot

    def observe(self, ts: float, v: float) -> None:
        self.store.observe_one(self.slot, ts, v)

    def covers(self, at: float) -> bool:
        """A rule read is only honest for "now"-ish instants: the state
        holds the trailing window, so ``at`` must not predate the
        newest sub-bucket."""
        st = self.store
        b = st.open[self.slot * RULE_ROW_STRIDE]
        return b == b and at >= b * st.sub_s

    def merged(self, at: float):
        """Merge the sub-bucket rows covering [at - window, at];
        returns (n, sum, mn, mx, first_ts, first_v, last_ts, last_v,
        inc) or None when empty. The window is bucket-quantized: the
        oldest overlapping sub-bucket is included whole, so the
        effective span is [w, w + w/16) — documented in docs/query.md.

        Single allocation-free pass in hist-ring order (hh points at
        the oldest banked bucket, so ring order IS time order for
        in-order appends — the only order the store ever banks; a
        violated monotonicity check falls back to the sorted walk,
        preserving identical fold order). This is the per-tick rule
        read every trend condition pays — bench.py's ``actuate`` phase
        pins the ≤1% tick bound it serves."""
        st = self.store
        b_lo = (at - self.rule.window_s) // st.sub_s
        r = self.slot
        hist = st.hist
        lo = r * RULE_SUB_BUCKETS * RULE_ROW_STRIDE
        h0 = st.hh[r]
        n = 0
        total = 0.0
        mn = mx = None
        inc = 0.0
        prev_last = None
        prev_b = None
        first_arr = first_base = last_arr = last_base = None
        for k in range(RULE_SUB_BUCKETS + 1):
            if k < RULE_SUB_BUCKETS:
                arr = hist
                base = lo + ((h0 + k) % RULE_SUB_BUCKETS) * RULE_ROW_STRIDE
            else:
                arr = st.open
                base = r * RULE_ROW_STRIDE
            b = arr[base]
            if b != b or b < b_lo:
                continue
            if prev_b is not None and b < prev_b:
                return self._merged_sorted(at, b_lo)
            prev_b = b
            n += int(arr[base + R_N])
            total += arr[base + R_SUM]
            row_mn = arr[base + R_MN]
            row_mx = arr[base + R_MX]
            mn = row_mn if mn is None else (mn if mn < row_mn else row_mn)
            mx = row_mx if mx is None else (mx if mx > row_mx else row_mx)
            inc += arr[base + R_INC]
            if prev_last is not None:
                step = arr[base + R_FV] - prev_last
                inc += step if step >= 0 else arr[base + R_FV]
            prev_last = arr[base + R_LV]
            if first_base is None:
                first_arr, first_base = arr, base
            last_arr, last_base = arr, base
        if first_base is None:
            return None
        return (
            n, total, mn, mx,
            first_arr[first_base + R_FTS], first_arr[first_base + R_FV],
            last_arr[last_base + R_LTS], last_arr[last_base + R_LV], inc,
        )

    def _merged_sorted(self, at: float, b_lo: float):
        """The pre-optimization sorted walk — identical fold order for
        any bucket layout; ``merged`` delegates here if ring order ever
        disagrees with time order."""
        st = self.store
        sel = [
            (arr, base)
            for arr, base in st.rows(self.slot)
            if arr[base] >= b_lo
        ]
        if not sel:
            return None
        n = 0
        total = 0.0
        mn = mx = None
        inc = 0.0
        prev_last = None
        for arr, base in sel:
            n += int(arr[base + R_N])
            total += arr[base + R_SUM]
            row_mn = arr[base + R_MN]
            row_mx = arr[base + R_MX]
            mn = row_mn if mn is None else min(mn, row_mn)
            mx = row_mx if mx is None else max(mx, row_mx)
            inc += arr[base + R_INC]
            if prev_last is not None:
                step = arr[base + R_FV] - prev_last
                inc += step if step >= 0 else arr[base + R_FV]
            prev_last = arr[base + R_LV]
        farr, first = sel[0]
        larr, last = sel[-1]
        return (
            n, total, mn, mx,
            farr[first + R_FTS], farr[first + R_FV],
            larr[last + R_LTS], larr[last + R_LV], inc,
        )


class RecordingRule:
    """One registered ``family[window]`` selector (e.g. ``chip.mxu[5m]``)
    and its column store."""

    __slots__ = ("text", "family", "window_s", "sub_s", "store")

    def __init__(self, text: str):
        node = parse(text)
        if (
            not isinstance(node, Selector)
            or node.range_s is None
            or node.matchers
        ):
            raise QueryError(
                f"recording rule {text!r} must be a plain range selector "
                f"like chip.mxu[5m]"
            )
        self.text = text
        self.family = node.family
        self.window_s = node.range_s
        self.sub_s = node.range_s / RULE_SUB_BUCKETS
        self.store = RuleStore(self.sub_s)


class RuleSet:
    """The registered recording rules + the per-series attach logic the
    ring calls at series creation (tpumon.history)."""

    def __init__(self, rules: list[RecordingRule]):
        self.rules = rules
        self._by_key = {(r.family, r.window_s): r for r in rules}
        # Kernel multi-call cache (TsdbKernel.rule_accum_multi): the
        # struct-pointer vector covering every rule's store, rebuilt
        # when any store's arrays move.
        self._kmulti = None

    def attach(self, name: str, ring_slot: int | None = None) -> list[RuleAccum] | None:
        family, _labels = parse_series_name(name)
        accums = [
            RuleAccum(r, r.store.add_slot(ring_slot))
            for r in self.rules
            if r.family == family
        ]
        return accums or None

    def accum_batch(self, ts: float, val_q: array, slots: array) -> None:
        """The per-tick batched update over the ring's existing
        (slots, f32 values) arrays: ONE native round trip covering
        every rule (FFI + pointer casts dominate a per-rule spelling);
        the Python fallback loops per rule, bit-exactly."""
        k = _rule_kernel()
        if k is not None:
            k.rule_accum_multi(ts, val_q, slots, self)
            return
        for r in self.rules:
            r.store.accum_batch(ts, val_q, slots, None)

    def lookup(self, family: str, window_s: float) -> RecordingRule | None:
        return self._by_key.get((family, window_s))

    def to_json(self) -> list[str]:
        return [r.text for r in self.rules]


# ----------------------------- evaluation ------------------------------


_UNRESOLVED = object()


class _Ctx:
    __slots__ = (
        "engine", "at", "win_cache", "exclude", "lookback_s", "_augment")

    def __init__(self, engine: "QueryEngine", at: float, exclude=None):
        self.engine = engine
        self.at = at
        self.win_cache: dict = {}
        self.exclude = exclude
        # Instant-selector staleness override for THIS evaluation
        # (None = the engine's lookback_s). The SLO engine tightens it
        # for fraction-mode bad-event samples: a per-tick sample read
        # from data older than the objective's shortest burn window is
        # not a current observation — it must read as absent, or a
        # vanished source would keep "reporting" its last value for the
        # whole 5-minute default lookback and a firing burn alert could
        # never drain to resolution (tests/test_slo.py).
        self.lookback_s: float | None = None
        # The label augmenter (pod attribution — O(chips) to build)
        # resolves lazily on first selector match and at most once per
        # evaluation: expressions that never touch an augmentable
        # family (the SLO engine's per-tick slo.bad/serving.* reads)
        # never pay for the attribution walk.
        self._augment = _UNRESOLVED

    @property
    def augment(self):
        if self._augment is _UNRESOLVED:
            self._augment = (
                self.engine.augment()
                if self.engine.augment is not None
                else None
            )
        return self._augment


def _labels_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class QueryEngine:
    """Expression evaluation over one RingHistory.

    Stateless apart from two bounded caches: the compiled-expression
    cache (parse once per distinct query text) and the per-series
    name→labels parse cache. Owned by the Sampler (one per process);
    the server routes, the alert engine's env compiler, the CLI and
    the federation planner all go through it."""

    _COMPILE_CAP = 256

    # Labels an augmenter may ADD to derived labels (the sampler's pod
    # attribution, and the accelerator family — chip id → accel_kind,
    # slice → accel_kind, ISSUE 15). Matchers referencing any of these
    # must resolve per evaluation (the attribution changes tick to
    # tick); matchers over naming-derived labels only are resolvable
    # once per series set and ride the selector cache below.
    AUGMENT_LABELS = frozenset({"pod", "accel"})

    def __init__(
        self,
        ring,
        default_range_s: float = DEFAULT_RANGE_S,
        lookback_s: float = DEFAULT_LOOKBACK_S,
        augment=None,
    ):
        self.ring = ring
        self.default_range_s = default_range_s
        self.lookback_s = lookback_s
        # Optional label augmenter: a zero-arg callable returning a
        # ``fn(family, labels) -> None`` that mutates labels in place —
        # the server wires pod attribution (chip id -> owning pod)
        # through this so ``by (pod)`` works without the engine knowing
        # about k8s.
        self.augment = augment
        self._compiled: dict[str, object] = {}
        self._names: dict[str, tuple[str, dict]] = {}
        # Family -> candidate series names (the _matching pre-filter):
        # a selector eval walks only its family's series instead of the
        # whole ring. Invalidated whenever the ring's series set can
        # have changed (new series appeared / snapshot restore replaced
        # the objects). Matchers/augment/exclude still run per eval —
        # only the family scan is cached.
        self._family_cache: dict[str, list[str]] = {}
        self._family_gen: tuple | None = None
        # (family, matchers) -> [(name, family, base labels)] for
        # selectors whose matchers touch only naming-derived labels:
        # those can be resolved once per series set instead of per
        # eval (the SLO engine's per-tick hot path). Augment/exclude
        # still run per eval on the cached rows' label copies.
        self._sel_cache: dict[tuple, list] = {}
        self.compiles = 0
        self.evals = 0

    # --------------------------- compile cache --------------------------

    def compile(self, src: str):
        node = self._compiled.get(src)
        if node is None:
            node = parse(src)
            if len(self._compiled) >= self._COMPILE_CAP:
                self._compiled.clear()
            self._compiled[src] = node
            self.compiles += 1
        return node

    # ----------------------------- matching -----------------------------

    def _series_labels(self, name: str) -> tuple[str, dict]:
        hit = self._names.get(name)
        if hit is None:
            hit = self._names[name] = parse_series_name(name)
        return hit

    def _family_names(self, fam: str) -> list[str]:
        """Series names whose derived family matches ``fam`` (exact or
        glob) — the O(all series) scan, cached per family until the
        ring's series set moves. Sorted, so _matching's output order
        (the parity-pinned fold order) is already deterministic."""
        gen = (len(self.ring.series), getattr(self.ring, "generation", None))
        if gen != self._family_gen:
            self._family_cache.clear()
            self._sel_cache.clear()
            self._family_gen = gen
        names = self._family_cache.get(fam)
        if names is None:
            import fnmatch

            glob = _has_glob(fam)
            names = [
                name
                for name in self.ring.series
                if (
                    fnmatch.fnmatchcase(self._series_labels(name)[0], fam)
                    if glob
                    else self._series_labels(name)[0] == fam
                )
            ]
            names.sort()
            if len(self._family_cache) >= self._COMPILE_CAP:
                self._family_cache.clear()
            self._family_cache[fam] = names
        return names

    def _matching(self, sel: Selector, ctx: _Ctx) -> list[tuple[str, dict]]:
        """(series name, labels) pairs matching the selector, sorted by
        name — the deterministic fold order the parity tests pin.

        Matchers over naming-derived labels resolve against the cached
        pre-filtered rows (_sel_cache); a matcher that references an
        augmenter-added label (AUGMENT_LABELS — pod attribution moves
        tick to tick) forces the per-eval path."""
        out: list[tuple[str, dict]] = []
        cacheable = not any(
            label in self.AUGMENT_LABELS for label, _, _ in sel.matchers
        )
        if cacheable:
            key = (sel.family, sel.matchers)
            self._family_names(sel.family)  # validates the gen / caches
            rows = self._sel_cache.get(key)
            if rows is None:
                rows = []
                for name in self._family_names(sel.family):
                    family, base = self._series_labels(name)
                    if all(
                        _match_one(base.get(label), op, want)
                        for label, op, want in sel.matchers
                    ):
                        rows.append((name, family, base))
                if len(self._sel_cache) >= self._COMPILE_CAP:
                    self._sel_cache.clear()
                self._sel_cache[key] = rows
            if ctx.augment is None and ctx.exclude is None:
                # Hottest path (no per-eval label derivation at all):
                # hand out fresh label dicts, keep the cached bases
                # immutable.
                return [(name, dict(base)) for name, _, base in rows]
            for name, family, base in rows:
                labels = dict(base)
                if ctx.augment is not None:
                    ctx.augment(family, labels)
                if ctx.exclude is not None and ctx.exclude(family, labels):
                    continue
                out.append((name, labels))
            return out
        for name in self._family_names(sel.family):
            family, base = self._series_labels(name)
            labels = dict(base)
            if ctx.augment is not None:
                ctx.augment(family, labels)
            if ctx.exclude is not None and ctx.exclude(family, labels):
                continue
            ok = True
            for label, op, want in sel.matchers:
                if not _match_one(labels.get(label), op, want):
                    ok = False
                    break
            if ok:
                out.append((name, labels))
        out.sort(key=lambda p: p[0])
        return out

    def _matching_names(self, sel: Selector, ctx: _Ctx):
        """Matching series names only, no label materialization — the
        eval_condition hot path, which discards labels. Identical
        match set to _matching: when an exclude filter or a matcher
        over an augmenter-added label is in play (both can change the
        match set per evaluation), it defers to _matching."""
        if ctx.exclude is None and not any(
            label in self.AUGMENT_LABELS for label, _, _ in sel.matchers
        ):
            key = (sel.family, sel.matchers)
            self._family_names(sel.family)  # validates the series gen
            rows = self._sel_cache.get(key)
            if rows is None:
                self._matching(sel, ctx)  # builds + caches the rows
                rows = self._sel_cache[key]
            return [name for name, _, _ in rows]
        return [name for name, _ in self._matching(sel, ctx)]

    # --------------------------- point access ---------------------------

    def _window_points(
        self, ctx: _Ctx, name: str, w: float
    ) -> tuple[list[float], list[float]]:
        """(ts, vals) covering at least [at - w, at] for one series,
        cached per (name, w) within the evaluation (range queries reuse
        one fetch across every grid step). The underlying seek is
        Tier.since's bisect over sealed-chunk bounds."""
        key = (name, w)
        hit = ctx.win_cache.get(key)
        if hit is not None:
            return hit
        rs = self.ring.series[name]
        start = ctx.at - w
        if w <= rs.window_s:
            pts = rs.fine.since(start)
            if not pts and rs.fine.last_ts() is None:
                pts = rs.merged_points(w, ctx.at)
        else:
            pts = rs.merged_points(ctx.at - start, ctx.at)
        ts = [p[0] for p in pts]
        vals = [p[1] for p in pts]
        ctx.win_cache[key] = (ts, vals)
        return ts, vals

    # The store quantizes timestamps to 1 ms (round-half-up), so a
    # point recorded at ``at`` can land up to 0.5 ms in at's future;
    # instant reads tolerate exactly that round-up, or a query at the
    # record instant would miss its own point on a coin-flip of the
    # microsecond fraction.
    _TS_QUANT_EPS = 1e-3

    def _instant_value(self, ctx: _Ctx, name: str) -> float | None:
        rs = self.ring.series[name]
        at = ctx.at + self._TS_QUANT_EPS
        lookback = (
            self.lookback_s if ctx.lookback_s is None else ctx.lookback_s)
        last_ts = rs.fine.last_ts()
        if last_ts is not None and last_ts <= at:
            # ``at`` is at/after the newest fine point: the answer is
            # the tail point, read O(1) off the head columns — no
            # lookback-window fetch (the per-tick instant-selector hot
            # path; historical ``at`` takes the window walk below).
            if last_ts < ctx.at - lookback:
                return None
            return rs.fine.last()[1]
        ts, vals = self._window_points(ctx, name, lookback)
        hi = bisect_right(ts, at)
        if not hi:
            return None
        if ts[hi - 1] < ctx.at - lookback:
            return None
        return vals[hi - 1]

    # ------------------------------ eval --------------------------------

    def _eval(self, node, ctx: _Ctx):
        if isinstance(node, Num):
            return node.v
        if isinstance(node, Neg):
            v = self._eval(node.arg, ctx)
            if isinstance(v, list):
                return [(lb, -x) for lb, x in v]
            return -v
        if isinstance(node, Selector):
            if node.range_s is not None:
                raise QueryError(
                    f"range selector {node.family}[...] needs a function "
                    f"(rate, avg_over_time, ...)"
                )
            out = []
            for name, labels in self._matching(node, ctx):
                v = self._instant_value(ctx, name)
                if v is not None:
                    out.append((labels, v))
            return out
        if isinstance(node, Call):
            return self._eval_call(node, ctx)
        if isinstance(node, Agg):
            return self._eval_agg(node, ctx)
        if isinstance(node, Bin):
            return self._eval_bin(node, ctx)
        raise QueryError(f"unevaluable node {type(node).__name__}")

    # range functions ----------------------------------------------------

    def _range_args(self, node: Call) -> tuple[float | None, Selector]:
        args = node.args
        q = None
        if node.fn == "quantile_over_time":
            if len(args) != 2 or not isinstance(args[0], Num):
                raise QueryError("quantile_over_time wants (q, selector[w])")
            q = args[0].v
            sel = args[1]
        else:
            if len(args) != 1:
                raise QueryError(f"{node.fn} wants exactly one selector")
            sel = args[0]
        if not isinstance(sel, Selector):
            raise QueryError(f"{node.fn} wants a series selector argument")
        return q, sel

    def _eval_call(self, node: Call, ctx: _Ctx) -> list:
        q, sel = self._range_args(node)
        w = sel.range_s if sel.range_s is not None else self.default_range_s
        out = []
        rules = getattr(self.ring, "rules", None)
        rule = rules.lookup(sel.family, w) if rules is not None else None
        cache = ctx.win_cache
        for name, labels in self._matching(sel, ctx):
            # The computed (fn, series, window) value is memoized on
            # the evaluation context alongside the point fetches it
            # rides: several expressions reading the same trend at the
            # same instant (actuation policies + SLO conditions in one
            # tick) pay the rule merge / window walk once (bench.py's
            # ``actuate`` phase pins the ≤1% tick bound this serves).
            key = ("rangefn", node.fn, q, name, w)
            if key in cache:
                v = cache[key]
            elif rule is not None:
                v = self._rule_read(node.fn, q, rule, name, ctx)
                if v is _NO_RULE:
                    # series without a covering accumulator (created
                    # before registration / historical ``at``): direct.
                    v = self._direct_range(node.fn, q, name, w, ctx)
                cache[key] = v
            else:
                v = self._direct_range(node.fn, q, name, w, ctx)
                cache[key] = v
            if v is not None:
                out.append((labels, v))
        return out

    def _direct_range(
        self, fn: str, q: float | None, name: str, w: float, ctx: _Ctx
    ) -> float | None:
        ts, vals = self._window_points(ctx, name, w)
        lo = bisect_left(ts, ctx.at - w)
        hi = bisect_right(ts, ctx.at)
        if hi <= lo:
            return None
        window = vals[lo:hi]
        if fn == "avg_over_time":
            return sum(window) / len(window)
        if fn == "sum_over_time":
            return sum(window)
        if fn == "min_over_time":
            return min(window)
        if fn == "max_over_time":
            return max(window)
        if fn == "count_over_time":
            return float(len(window))
        if fn == "quantile_over_time":
            return _quantile(sorted(window), q)
        # rate / increase: need two points; counter resets contribute
        # the post-reset value (the Prometheus reset rule).
        if hi - lo < 2:
            return None
        inc = 0.0
        for i in range(lo + 1, hi):
            d = vals[i] - vals[i - 1]
            inc += d if d >= 0 else vals[i]
        if fn == "increase":
            return inc
        span = ts[hi - 1] - ts[lo]
        return inc / span if span > 0 else None

    def _rule_read(
        self, fn: str, q: float | None, rule: RecordingRule, name: str, ctx: _Ctx
    ):
        """O(sub-buckets) read of append-time rule state; returns
        _NO_RULE when this series carries no (covering) accumulator so
        the caller can fall back to the direct path."""
        if fn == "quantile_over_time":
            # Deliberately unbacked: a per-point sketch would put
            # Python work back in the append hot path. Direct read.
            return _NO_RULE
        rs = self.ring.series[name]
        accums = getattr(rs, "rec", None)
        if not accums:
            return _NO_RULE
        for a in accums:
            if a.rule is rule:
                if not a.covers(ctx.at):
                    return _NO_RULE
                m = a.merged(ctx.at)
                if m is None:
                    return None
                n, total, mn, mx, fts, fv, lts, lv, inc = m
                if fn == "avg_over_time":
                    return total / n
                if fn == "sum_over_time":
                    return total
                if fn == "min_over_time":
                    return mn
                if fn == "max_over_time":
                    return mx
                if fn == "count_over_time":
                    return float(n)
                if n < 2:
                    return None
                if fn == "increase":
                    return inc
                span = lts - fts
                return inc / span if span > 0 else None
        return _NO_RULE

    # aggregations -------------------------------------------------------

    def _eval_agg(self, node: Agg, ctx: _Ctx):
        args = node.args
        k = q = None
        if node.op in ("topk", "bottomk"):
            if len(args) != 2 or not isinstance(args[0], Num):
                raise QueryError(f"{node.op} wants (k, expr)")
            k = int(args[0].v)
            vec = self._eval(args[1], ctx)
        elif node.op == "quantile":
            if len(args) != 2 or not isinstance(args[0], Num):
                raise QueryError("quantile wants (q, expr)")
            q = args[0].v
            vec = self._eval(args[1], ctx)
        else:
            if len(args) != 1:
                raise QueryError(f"{node.op} wants exactly one argument")
            vec = self._eval(args[0], ctx)
        if not isinstance(vec, list):
            raise QueryError(f"{node.op} wants a vector, got a scalar")
        if node.op in ("topk", "bottomk"):
            rows = sorted(
                vec,
                key=lambda p: (p[1], _labels_key(p[0])),
                reverse=(node.op == "topk"),
            )
            if not node.by:
                return rows[: max(0, k)]
            # Per-group top-k (Prometheus semantics, ISSUE 15:
            # ``topk(5, rate(chip.hbm)) by (accel)``): k rows per
            # by-group, each row keeping its FULL label set so the
            # answer says which chip won, not just which family.
            taken: dict[tuple, int] = {}
            out = []
            for labels, v in rows:
                gk = _labels_key({
                    l: labels[l] for l in node.by if labels.get(l) is not None
                })
                n = taken.get(gk, 0)
                if n < max(0, k):
                    taken[gk] = n + 1
                    out.append((labels, v))
            return out
        groups: dict[tuple, tuple[dict, list[float]]] = {}
        for labels, v in vec:
            out_labels = {
                l: labels[l] for l in node.by if labels.get(l) is not None
            }
            gk = _labels_key(out_labels)
            ent = groups.get(gk)
            if ent is None:
                groups[gk] = (out_labels, [v])
            else:
                ent[1].append(v)
        out = []
        for gk in sorted(groups):
            labels, vs = groups[gk]
            if node.op == "sum":
                out.append((labels, sum(vs)))
            elif node.op == "avg":
                out.append((labels, sum(vs) / len(vs)))
            elif node.op == "min":
                out.append((labels, min(vs)))
            elif node.op == "max":
                out.append((labels, max(vs)))
            elif node.op == "count":
                out.append((labels, float(len(vs))))
            else:  # quantile
                out.append((labels, _quantile(sorted(vs), q)))
        return out

    # binary operators ---------------------------------------------------

    _ARITH = {
        "+": lambda a, b: a + b,
        "-": lambda a, b: a - b,
        "*": lambda a, b: a * b,
        "/": lambda a, b: (a / b) if b else None,
    }
    _CMP = {
        ">": lambda a, b: a > b,
        "<": lambda a, b: a < b,
        ">=": lambda a, b: a >= b,
        "<=": lambda a, b: a <= b,
        "==": lambda a, b: a == b,
        "!=": lambda a, b: a != b,
    }

    def _eval_bin(self, node: Bin, ctx: _Ctx):
        if node.op in ("and", "or"):
            lhs = self._eval(node.lhs, ctx)
            rhs = self._eval(node.rhs, ctx)
            if isinstance(lhs, list) and isinstance(rhs, list):
                rkeys = {_labels_key(lb) for lb, _ in rhs}
                if node.op == "and":
                    return [p for p in lhs if _labels_key(p[0]) in rkeys]
                lkeys = {_labels_key(lb) for lb, _ in lhs}
                return lhs + [p for p in rhs if _labels_key(p[0]) not in lkeys]
            # Mixed scalar/vector: a vector operand collapses to its
            # non-emptiness (has-any-sample), scalars to truthiness.
            lv = bool(lhs)
            rv = bool(rhs)
            return 1.0 if (lv and rv if node.op == "and" else lv or rv) else 0.0
        lhs = self._eval(node.lhs, ctx)
        rhs = self._eval(node.rhs, ctx)
        arith = self._ARITH.get(node.op)
        if arith is not None:
            return self._combine(lhs, rhs, arith, filter_mode=False)
        cmp = self._CMP[node.op]
        return self._combine(lhs, rhs, cmp, filter_mode=True)

    @staticmethod
    def _combine(lhs, rhs, fn, filter_mode: bool):
        lv = isinstance(lhs, list)
        rv = isinstance(rhs, list)
        if not lv and not rv:
            r = fn(lhs, rhs)
            if isinstance(r, bool):
                return 1.0 if r else 0.0
            return r if r is not None else float("nan")
        if lv and not rv:
            out = []
            for lb, v in lhs:
                r = fn(v, rhs)
                if filter_mode:
                    if r:
                        out.append((lb, v))
                elif r is not None:
                    out.append((lb, r))
            return out
        if rv and not lv:
            out = []
            for lb, v in rhs:
                r = fn(lhs, v)
                if filter_mode:
                    if r:
                        out.append((lb, v))
                elif r is not None:
                    out.append((lb, r))
            return out
        right = {_labels_key(lb): v for lb, v in rhs}
        out = []
        for lb, v in lhs:
            ov = right.get(_labels_key(lb))
            if ov is None:
                continue
            r = fn(v, ov)
            if filter_mode:
                if r:
                    out.append((lb, v))
            elif r is not None:
                out.append((lb, r))
        return out

    # ----------------------------- public API ---------------------------

    def context(self, at: float | None = None, exclude=None) -> _Ctx:
        """An evaluation context reusable across several eval_compiled
        calls at the same instant: the label augmenter (pod
        attribution — O(chips) to build) and the per-(series, window)
        point fetches are shared instead of redone per expression."""
        return _Ctx(self, time.time() if at is None else at,
                    exclude=exclude)

    def eval_compiled(self, node, at: float | None = None, exclude=None,
                      ctx: _Ctx | None = None):
        """Evaluate an already-compiled AST node at one instant and
        return the raw value (scalar, or [(labels, value), ...] vector)
        — the per-tick hot path for callers that compile once per
        config (the SLO engine's burn-rate expressions, docs/slo.md)
        and must not depend on the bounded compile cache."""
        if ctx is None:
            ctx = self.context(at, exclude)
        self.evals += 1
        return self._eval(node, ctx)

    def eval_condition(self, node, at: float | None = None,
                       ctx: _Ctx | None = None) -> bool:
        """Boolean evaluation of a compiled condition: True when any
        sample satisfies it (absent data never fires — the alert
        engine's None contract). Semantically identical to
        ``bool(eval_compiled(node))`` with vector-non-emptiness /
        scalar-truthiness collapse, but the common per-tick shape — a
        single comparison between an instant selector and a constant —
        short-circuits on the first satisfying sample without
        materializing label vectors (the SLO engine's bad-condition
        hot path; bench.py's ``slo`` phase pins the ≤2% tick bound
        this serves). Every other shape — and/or (whose vector
        operands intersect/union BY LABELS in _eval_bin, not by
        truthiness), arithmetic, vector-vector comparisons — falls
        through to the generic evaluator, so the fast path can never
        disagree with it (tests/test_query.py pins the parity)."""
        if ctx is None:
            ctx = self.context(at)
        if isinstance(node, Bin):
            cmp = self._CMP.get(node.op)
            if cmp is not None:
                sel = const = None
                flip = False
                if (isinstance(node.lhs, Selector)
                        and node.lhs.range_s is None):
                    sel, const = node.lhs, _const_value(node.rhs)
                elif (isinstance(node.rhs, Selector)
                        and node.rhs.range_s is None):
                    sel, const = node.rhs, _const_value(node.lhs)
                    flip = True
                if sel is not None and const is not None:
                    self.evals += 1
                    for name in self._matching_names(sel, ctx):
                        v = self._instant_value(ctx, name)
                        if v is None:
                            continue
                        if cmp(const, v) if flip else cmp(v, const):
                            return True
                    return False
        v = self.eval_compiled(node, ctx=ctx)
        if isinstance(v, list):
            return bool(v)
        if v is None or v != v:  # None / NaN: absent never fires
            return False
        return bool(v)

    def instant(self, src: str, at: float | None = None, exclude=None) -> dict:
        """Evaluate ``src`` at one instant; returns the /api/query
        payload shape: {"result_type": "vector"|"scalar", "result":
        [{"labels", "value"}, ...]}."""
        at = time.time() if at is None else at
        self.evals += 1
        node = self.compile(src)
        ctx = _Ctx(self, at, exclude=exclude)
        v = self._eval(node, ctx)
        if isinstance(v, list):
            return {
                "result_type": "vector",
                "at": round(at, 3),
                "result": [
                    {"labels": lb, "value": _round(x)} for lb, x in v
                ],
            }
        return {
            "result_type": "scalar",
            "at": round(at, 3),
            "result": [{"labels": {}, "value": _round(v)}],
        }

    def range_query(
        self,
        src: str,
        window_s: float,
        step_s: float,
        end: float | None = None,
    ) -> dict:
        """Evaluate ``src`` on a step grid over the trailing window;
        returns {"series": [{"labels", "points": [[ts, v], ...]}]}.
        The per-(series, window) point fetch is shared across grid
        steps (one chunk decode per sealed chunk, not per step)."""
        end = time.time() if end is None else end
        self.evals += 1
        node = self.compile(src)
        if step_s <= 0 or window_s <= 0:
            raise QueryError("window and step must be positive")
        steps = int(window_s // step_s)
        if steps > 100_000:
            raise QueryError("window/step grid too fine")
        out: dict[tuple, dict] = {}
        ctx = _Ctx(self, end)
        t = end - (window_s // step_s) * step_s
        while t <= end + 1e-9:
            ctx.at = t
            v = self._eval(node, ctx)
            if not isinstance(v, list):
                v = [({}, v)]
            for lb, x in v:
                gk = _labels_key(lb)
                ent = out.get(gk)
                if ent is None:
                    ent = out[gk] = {"labels": lb, "points": []}
                ent["points"].append([round(t, 3), _round(x)])
            t += step_s
        return {
            "end": round(end, 3),
            "window_s": window_s,
            "step_s": step_s,
            "series": [out[k] for k in sorted(out)],
        }

    # ----------------------- distributed (fleet) ------------------------

    def partial_eval(
        self, src: str, at: float | None = None, exclude=None
    ) -> dict:
        """Phase 1 of a fleet query, run at every node: evaluate the
        aggregation's *inner* expression over local data only and
        reduce it to a mergeable per-group state — counts and sums,
        min/max, topk row sets, quantile sketches — never raw points.
        Raises QueryError unless the expression is a top-level
        aggregation (the distributable contract, docs/query.md)."""
        at = time.time() if at is None else at
        node = self.compile(src)
        if not isinstance(node, Agg):
            raise QueryError(
                "fleet queries must be a top-level aggregation "
                "(sum/avg/min/max/count/quantile/topk/bottomk over an "
                "inner expression)"
            )
        k = q = None
        if node.op in ("topk", "bottomk"):
            k = int(node.args[0].v)
            inner = node.args[1]
        elif node.op == "quantile":
            q = node.args[0].v
            inner = node.args[1]
        else:
            if len(node.args) != 1:
                raise QueryError(f"{node.op} wants exactly one argument")
            inner = node.args[0]
        ctx = _Ctx(self, at, exclude=exclude)
        vec = self._eval(inner, ctx)
        if not isinstance(vec, list):
            raise QueryError("fleet aggregation needs a vector inner expression")
        groups: dict[tuple, dict] = {}
        if node.op in ("topk", "bottomk"):
            rows = sorted(
                vec,
                key=lambda p: (p[1], _labels_key(p[0])),
                reverse=(node.op == "topk"),
            )
            if not node.by:
                return {
                    "op": node.op,
                    "arg": k,
                    "by": [],
                    "groups": [
                        {
                            "labels": {},
                            "state": {
                                "rows": [
                                    [lb, v] for lb, v in rows[: max(0, k)]
                                ]
                            },
                        }
                    ],
                }
            # Grouped top-k partial: k candidate rows PER by-group —
            # still never raw points (at most k × groups rows upstream),
            # and any tier merging fewer groups than exist below it
            # stays correct because each group's k-set is locally
            # complete.
            for labels, v in rows:
                out_labels = {
                    l: labels[l] for l in node.by if labels.get(l) is not None
                }
                gk = _labels_key(out_labels)
                ent = groups.get(gk)
                if ent is None:
                    ent = groups[gk] = {
                        "labels": out_labels,
                        "state": {"rows": []},
                    }
                if len(ent["state"]["rows"]) < max(0, k):
                    ent["state"]["rows"].append([labels, v])
            return {
                "op": node.op,
                "arg": k,
                "by": list(node.by),
                "groups": [groups[gk] for gk in sorted(groups)],
            }
        for labels, v in vec:
            out_labels = {
                l: labels[l] for l in node.by if labels.get(l) is not None
            }
            gk = _labels_key(out_labels)
            ent = groups.get(gk)
            if ent is None:
                ent = groups[gk] = {"labels": out_labels, "_vals": []}
            ent["_vals"].append(v)
        out_groups = []
        for gk in sorted(groups):
            ent = groups[gk]
            vs = ent.pop("_vals")
            if node.op == "quantile":
                sk = QSketch()
                for v in vs:
                    sk.add(v)
                ent["state"] = {"sk": sk.to_json()}
            else:
                ent["state"] = {
                    "n": len(vs),
                    "sum": sum(vs),
                    "min": min(vs),
                    "max": max(vs),
                }
            out_groups.append(ent)
        return {
            "op": node.op,
            "arg": q if node.op == "quantile" else None,
            "by": list(node.by),
            "groups": out_groups,
        }

    @staticmethod
    def merge_partials(parts: list[dict]) -> dict:
        """Phase 2: fold any number of partial states (an aggregator's
        children + its own local partial) into one. Associative and
        commutative by construction, so the tree shape doesn't matter."""
        parts = [p for p in parts if p is not None]
        if not parts:
            raise QueryError("no partial results to merge")
        base = parts[0]
        op = base["op"]
        if op in ("topk", "bottomk"):
            # Group-aware merge: partials from every tier carry one
            # entry per by-group (the ungrouped case is the single
            # group with empty labels, so pre-by peers merge
            # unchanged); rows re-rank within their group and each
            # group keeps its own k.
            k = int(base["arg"])
            by_groups: dict[tuple, dict] = {}
            for p in parts:
                for g in p["groups"]:
                    gk = _labels_key(g["labels"])
                    ent = by_groups.get(gk)
                    if ent is None:
                        ent = by_groups[gk] = {
                            "labels": dict(g["labels"]),
                            "rows": [],
                        }
                    ent["rows"].extend(
                        (dict(lb), v) for lb, v in g["state"]["rows"]
                    )
            out_groups = []
            for gk in sorted(by_groups):
                ent = by_groups[gk]
                ent["rows"].sort(
                    key=lambda r: (r[1], _labels_key(r[0])),
                    reverse=(op == "topk"),
                )
                out_groups.append(
                    {
                        "labels": ent["labels"],
                        "state": {
                            "rows": [[lb, v] for lb, v in ent["rows"][:k]]
                        },
                    }
                )
            return {
                "op": op,
                "arg": k,
                "by": base.get("by") or [],
                "groups": out_groups,
            }
        merged: dict[tuple, dict] = {}
        for p in parts:
            if p["op"] != op:
                raise QueryError("partial results disagree on the aggregation")
            for g in p["groups"]:
                gk = _labels_key(g["labels"])
                ent = merged.get(gk)
                if ent is None:
                    st = g["state"]
                    merged[gk] = {
                        "labels": dict(g["labels"]),
                        "state": (
                            {"sk": QSketch.from_json(st["sk"]).to_json()}
                            if "sk" in st
                            else dict(st)
                        ),
                    }
                    continue
                st = ent["state"]
                gs = g["state"]
                if "sk" in st:
                    sk = QSketch.from_json(st["sk"])
                    sk.merge(QSketch.from_json(gs["sk"]))
                    ent["state"] = {"sk": sk.to_json()}
                else:
                    st["n"] += gs["n"]
                    st["sum"] += gs["sum"]
                    st["min"] = min(st["min"], gs["min"])
                    st["max"] = max(st["max"], gs["max"])
        return {
            "op": op,
            "arg": base.get("arg"),
            "by": base.get("by") or [],
            "groups": [merged[k] for k in sorted(merged)],
        }

    @staticmethod
    def finalize(partial: dict) -> list[dict]:
        """Phase 3, root only: partial state → the instant-vector
        result rows /api/query serves."""
        op = partial["op"]
        out = []
        if op in ("topk", "bottomk"):
            for g in partial["groups"]:
                for lb, v in g["state"]["rows"]:
                    out.append({"labels": dict(lb), "value": _round(v)})
            return out
        for g in partial["groups"]:
            st = g["state"]
            if "sk" in st:
                v = QSketch.from_json(st["sk"]).quantile(partial["arg"])
            elif op == "sum":
                v = st["sum"]
            elif op == "avg":
                v = st["sum"] / st["n"] if st["n"] else None
            elif op == "min":
                v = st["min"]
            elif op == "max":
                v = st["max"]
            else:  # count
                v = float(st["n"])
            if v is not None:
                out.append({"labels": dict(g["labels"]), "value": _round(v)})
        return out

    def to_json(self) -> dict:
        rules = getattr(self.ring, "rules", None)
        return {
            "functions": list(FUNCTIONS),
            "series": len(self.ring.series),
            "compiled": len(self._compiled),
            "compiles": self.compiles,
            "evals": self.evals,
            "default_range_s": self.default_range_s,
            "lookback_s": self.lookback_s,
            "rules": rules.to_json() if rules is not None else [],
        }


_NO_RULE = object()  # sentinel: no covering accumulator, use direct path


def _round(v: float) -> float:
    """Payload rounding: floats serialize at a stable precision (the
    render layer's contract); NaN/inf degrade to None-safe values."""
    if v != v or v in (float("inf"), float("-inf")):
        return None
    return v


# ------------------------- env-predicate compiler -----------------------


def compile_env(src: str):
    """Compile an expression into an evaluator over a flat environment
    (``{"chip.hbm": 91.0, "chip.mxu": 3.0, ...}``) — the alert engine's
    rule compiler (tpumon.alerts): threshold rules are expression
    strings formatted once per config, parsed by THIS parser, and the
    per-tick loop evaluates the compiled closures.

    Missing data (None) follows alerting semantics: arithmetic over
    None is None, a comparison against None is False (no data never
    fires a page), and/or treat None as False."""
    node = parse(src)
    _env_check(node)

    def run(env: dict):
        return _eval_env(node, env)

    return run


def _env_check(node) -> None:
    if isinstance(node, Selector):
        if node.range_s is not None or node.matchers:
            raise QueryError(
                "env expressions use plain names (no ranges/matchers)"
            )
        return
    if isinstance(node, Num):
        return
    if isinstance(node, Neg):
        _env_check(node.arg)
        return
    if isinstance(node, Bin):
        _env_check(node.lhs)
        _env_check(node.rhs)
        return
    raise QueryError(
        f"env expressions are scalar (no {type(node).__name__} nodes)"
    )


def _eval_env(node, env: dict):
    if isinstance(node, Num):
        return node.v
    if isinstance(node, Selector):
        return env.get(node.family)
    if isinstance(node, Neg):
        v = _eval_env(node.arg, env)
        return None if v is None else -v
    op = node.op
    a = _eval_env(node.lhs, env)
    b = _eval_env(node.rhs, env)
    if op in ("and", "or"):
        ta = bool(a) if a is not None else False
        tb = bool(b) if b is not None else False
        return (ta and tb) if op == "and" else (ta or tb)
    if op in QueryEngine._CMP:
        if a is None or b is None:
            return False
        return bool(QueryEngine._CMP[op](a, b))
    if a is None or b is None:
        return None
    return QueryEngine._ARITH[op](a, b)


# -------------------------------- CLI ----------------------------------


def _labels_str(labels: dict) -> str:
    if not labels:
        return "·"
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))


def query_cli(argv: list[str]) -> int:
    """``tpumon query 'expr'`` — run an instant or range query against a
    running server over the same /api/query routes the dashboard uses."""
    import urllib.parse
    import urllib.request

    url = "http://127.0.0.1:8888"
    expr = None
    rng = None
    step = "30s"
    as_json = False
    fleet = False
    at = None
    it = iter(argv)
    for a in it:
        if a == "--url":
            url = next(it, url)
        elif a == "--range":
            rng = next(it, None)
        elif a == "--step":
            step = next(it, step)
        elif a == "--json":
            as_json = True
        elif a == "--fleet":
            fleet = True
        elif a == "--time":
            at = next(it, None)
        elif a in ("-h", "--help"):
            print(
                "usage: python -m tpumon query 'expr' [--url HOST:8888]\n"
                "         [--range 30m [--step 30s]] [--fleet] [--time TS]\n"
                "         [--json]\n"
                "Instant by default; --range evaluates on a step grid;\n"
                "--fleet plans a distributed query over the federation\n"
                "tree (aggregator/root only). Grammar: docs/query.md."
            )
            return 0
        elif expr is None and not a.startswith("-"):
            expr = a
        else:
            print(f"unknown argument {a!r}", file=sys.stderr)
            return 2
    if not expr:
        print("query: an expression argument is required", file=sys.stderr)
        return 2
    if not url.startswith(("http://", "https://")):
        url = f"http://{url}"
    params = {"query": expr}
    if rng is not None:
        path = "/api/query_range"
        params["window"] = rng
        params["step"] = step
    else:
        path = "/api/query"
        if fleet:
            params["fleet"] = "1"
        if at is not None:
            params["time"] = at
    full = f"{url.rstrip('/')}{path}?{urllib.parse.urlencode(params)}"
    try:
        with urllib.request.urlopen(full, timeout=30) as r:
            payload = json.load(r)
    except Exception as e:
        body = getattr(e, "read", lambda: b"")()
        try:
            msg = json.loads(body).get("error", "")
        except Exception:
            msg = ""
        print(f"query failed: {msg or e}", file=sys.stderr)
        return 1
    if as_json:
        print(json.dumps(payload, indent=1))
        return 0
    if rng is not None:
        for s in payload.get("series", []):
            pts = s.get("points") or []
            vals = [p[1] for p in pts if p[1] is not None]
            if not vals:
                continue
            print(
                f"{_labels_str(s.get('labels') or {}):<40} "
                f"n={len(pts)} min={min(vals):.3f} "
                f"mean={sum(vals) / len(vals):.3f} max={max(vals):.3f} "
                f"last={vals[-1]:.3f}"
            )
        return 0
    if payload.get("partial"):
        missing = ", ".join(payload.get("missing") or [])
        print(f"[partial: missing {missing}]", file=sys.stderr)
    for row in payload.get("result", []):
        v = row.get("value")
        vs = "null" if v is None else f"{v:.6g}"
        print(f"{_labels_str(row.get('labels') or {}):<40} {vs}")
    return 0
