"""Prometheus text-exposition format: writer and parser.

Writer: backs the in-tree exporter (``/metrics``) that replaces the
reference's out-of-tree DCGM exporter dependency (README.md:135) — the
``tpu_*`` series that /api/history PromQL re-keys onto (SURVEY §5.8).

Parser: backs the serving-metrics ingest (JetStream / MaxText expose a
Prometheus ``/metrics`` endpoint) — the TPU-native replacement for the
reference's aspirational vLLM scrape (README.md:73; no vLLM code exists
in the reference snapshot, SURVEY §5.7).

Both sides are dependency-free and handle the subset of the format that
Prometheus clients actually emit: HELP/TYPE comments, labels with escaped
values, counters/gauges, histogram/summary series (exposed as plain
sample lines with _bucket/_sum/_count suffixes).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field


# --------------------------------------------------------------------------
# Writer
# --------------------------------------------------------------------------


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def format_value(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if isinstance(v, bool):
        return "1" if v else "0"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


@dataclass
class MetricFamily:
    name: str
    mtype: str  # "gauge" | "counter" | "histogram" | "untyped"
    help: str = ""
    # (labels, value) pairs, or (labels, value, name-suffix) triples —
    # the suffix form carries histogram series ("_bucket"/"_sum"/
    # "_count") under one TYPE header.
    samples: list[tuple] = field(default_factory=list)

    def add(self, labels: dict[str, str] | None = None, value: float = 0.0) -> None:
        self.samples.append((labels or {}, value))

    def add_series(
        self, suffix: str, labels: dict[str, str] | None, value: float
    ) -> None:
        self.samples.append((labels or {}, value, suffix))

    def add_histogram(
        self,
        labels: dict[str, str],
        cumulative: list[tuple[float, int]],
        total_count: int,
        total_sum: float,
    ) -> None:
        """Emit a full Prometheus histogram: cumulative le-labelled
        ``_bucket`` series (``cumulative`` excludes +Inf, which is
        appended as ``total_count``), plus ``_sum`` and ``_count``."""
        for le, cum in cumulative:
            self.add_series("_bucket", {**labels, "le": format_value(le)}, cum)
        self.add_series("_bucket", {**labels, "le": "+Inf"}, total_count)
        self.add_series("_sum", labels, total_sum)
        self.add_series("_count", labels, total_count)


class MetricsWriter:
    def __init__(self) -> None:
        self.families: list[MetricFamily] = []

    def family(self, name: str, mtype: str, help: str = "") -> MetricFamily:
        fam = MetricFamily(name=name, mtype=mtype, help=help)
        self.families.append(fam)
        return fam

    def gauge(self, name: str, help: str = "") -> MetricFamily:
        return self.family(name, "gauge", help)

    def counter(self, name: str, help: str = "") -> MetricFamily:
        return self.family(name, "counter", help)

    def histogram(self, name: str, help: str = "") -> MetricFamily:
        return self.family(name, "histogram", help)

    def render(self) -> str:
        lines: list[str] = []
        for fam in self.families:
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.mtype}")
            for sample in fam.samples:
                labels, value = sample[0], sample[1]
                name = fam.name + (sample[2] if len(sample) > 2 else "")
                if labels:
                    inner = ",".join(
                        f'{k}="{_escape_label_value(str(v))}"' for k, v in labels.items()
                    )
                    lines.append(f"{name}{{{inner}}} {format_value(value)}")
                else:
                    lines.append(f"{name} {format_value(value)}")
        return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------
# Parser
# --------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>[^\s]+)"
    r"(?:\s+(?P<ts>-?\d+))?\s*$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")


def _parse_value(text: str) -> float:
    if text == "+Inf":
        return math.inf
    if text == "-Inf":
        return -math.inf
    if text == "NaN":
        return math.nan
    return float(text)


@dataclass
class ParsedSample:
    name: str
    labels: dict[str, str]
    value: float


def parse_metrics_text(text: str) -> list[ParsedSample]:
    """Parse Prometheus exposition text into a flat sample list."""
    out: list[ParsedSample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        labels: dict[str, str] = {}
        if m.group("labels"):
            for lm in _LABEL_RE.finditer(m.group("labels")):
                labels[lm.group(1)] = _unescape(lm.group(2))
        try:
            value = _parse_value(m.group("value"))
        except ValueError:
            continue
        out.append(ParsedSample(name=m.group("name"), labels=labels, value=value))
    return out


def samples_by_name(samples: list[ParsedSample]) -> dict[str, list[ParsedSample]]:
    by: dict[str, list[ParsedSample]] = {}
    for s in samples:
        by.setdefault(s.name, []).append(s)
    return by


def histogram_quantile(
    samples: list[ParsedSample], q: float
) -> float | None:
    """Estimate a quantile from _bucket samples (cumulative, le-labelled),
    linearly interpolating within the bucket — same approach as PromQL's
    histogram_quantile."""
    buckets: list[tuple[float, float]] = []
    for s in samples:
        le = s.labels.get("le")
        if le is None:
            continue
        buckets.append((_parse_value(le), s.value))
    if not buckets:
        return None
    buckets.sort(key=lambda b: b[0])
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_count = 0.0, 0.0
    for le, count in buckets:
        if count >= rank:
            if math.isinf(le):
                return prev_le if prev_le > 0 else None
            if count == prev_count:
                return le
            frac = (rank - prev_count) / (count - prev_count)
            return prev_le + (le - prev_le) * frac
        prev_le, prev_count = le, count
    return buckets[-1][0] if not math.isinf(buckets[-1][0]) else prev_le
