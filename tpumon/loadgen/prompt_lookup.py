"""Prompt-lookup speculative drafting (n-gram proposal from context).

Speculative decoding needs a proposer the target can cheaply verify;
a draft MODEL is one choice, but for repetitive workloads (extraction,
summarization-with-quotes, code edit, periodic logs — anywhere the
continuation echoes earlier context) the context itself is a better
one: find the most recent prior occurrence of the sequence's trailing
n-gram and propose the tokens that followed it. No trained model, no
draft cache, no extra device dispatches — the proposal is a host-side
list search — and the verify step (speculative.decode_block /
paged_kv.paged_decode_block) is unchanged, so the lossless-greedy
contract holds no matter how bad the guesses are.

This is the "prompt lookup decoding" idea used by production serving
stacks (e.g. vLLM's ngram speculator and transformers'
prompt_lookup_num_tokens); implemented from the idea, not anyone's
code. Engine integration: ServeConfig(spec_source="prompt");
measured honestly in bench.py `serving_spec_prompt_*` on a workload
that is repetitive by construction (the use case this exists for),
with a model trained by the in-repo trainer to actually continue the
repetition (acceptance is a property of target agreement — an
untrained target makes any proposer's acceptance noise).
"""

from __future__ import annotations


def ngram_propose(
    context: list[int], g: int, max_n: int = 3, window: int = 1024
) -> list[int]:
    """Propose ``g`` next tokens for ``context`` by n-gram lookup.

    Searches for the most recent PRIOR occurrence of the longest
    trailing n-gram (n = max_n down to 1) and copies the tokens that
    followed it; if the copied run is shorter than ``g`` it extends by
    continuing the copy from where the match's continuation itself
    repeats (natural for periodic text) and finally pads by repeating
    the last token. With no match at any n (or an empty context), the
    fallback is ``g`` repeats of the last token — acceptance then just
    measures how often the target emits runs, and the verify step makes
    any wrong guess harmless.

    The backward scan only visits the last ``window`` tokens (0 = no
    bound): the proposal runs on the host once per slot per speculative
    round, so an unbounded scan would grow per-round cost linearly with
    context length — and for the repetitive workloads this proposer
    exists for, the recent period carries the signal anyway.
    """
    if g <= 0:
        return []
    if not context:
        return [0] * g
    last = context[-1]
    lo = max(0, len(context) - window) if window and window > 0 else 0
    for n in range(min(max_n, len(context)), 0, -1):
        tail = context[-n:]
        # Rightmost occurrence strictly before the trailing one, with
        # at least one continuation token available; candidates older
        # than the window are never visited.
        hi = len(context) - n - 1  # last candidate start index
        for i in range(hi, lo - 1, -1):
            if context[i:i + n] == tail:
                prop = context[i + n:i + n + g]
                if not prop:
                    continue  # match flush against the tail: no info
                span = len(context) - i - n  # tokens after the match
                while len(prop) < g:
                    # Cycle the post-match span: for periodic text this
                    # continues the period past the end of context.
                    prop.append(context[i + n + (len(prop) % span)])
                return prop[:g]
    return [last] * g
