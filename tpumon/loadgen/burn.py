"""Targeted burn kernels: drive MXU / HBM / ICI to validate monitoring.

The TPU-native analogue of NVIDIA's dcgmproftester: deterministic
synthetic load so the exporter's duty-cycle/HBM/ICI readings can be
checked against a known workload (SURVEY §6: the bench metric is
measured *under load*).

Each burn is a single jitted program with lax control flow (no Python
loops inside jit) and static shapes.
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _sync(x) -> float:
    """Force completion AND fetch: on remote-execution backends (the
    axon tunnel this repo benches through) ``block_until_ready()`` on a
    warm cached program returns before the device finishes, which lets a
    timing loop count dispatches instead of work (measured: a 4096³×64
    burst "ran" 3400×/s that way). Pulling the scalar result is the only
    sync that holds everywhere, so every burn program reduces to a
    scalar and timers sync through this helper."""
    return float(jax.device_get(x))


@partial(jax.jit, static_argnames=("size", "iters", "use_pallas"))
def _mxu_burn_program(
    key: jax.Array, size: int, iters: int, use_pallas: bool = False
) -> jax.Array:
    """Chained bf16 matmuls: 2*size^3*iters FLOPs on the MXU."""
    a = jax.random.normal(key, (size, size), jnp.bfloat16)
    b = jax.random.normal(jax.random.fold_in(key, 1), (size, size), jnp.bfloat16)

    if use_pallas:
        from tpumon.ops.matmul import matmul as mm
    else:
        mm = None

    def body(carry, _):
        a, b = carry
        c = mm(a, b) if use_pallas else a @ b
        # Renormalize to keep values finite across iterations.
        c = (c / jnp.float32(size).astype(jnp.bfloat16)).astype(jnp.bfloat16)
        return (c, b), ()

    (out, _), _ = jax.lax.scan(body, (a, b), None, length=iters)
    return jnp.sum(out.astype(jnp.float32))


def mxu_burn(
    seconds: float = 2.0,
    size: int = 4096,
    iters: int = 64,
    use_pallas: bool | None = None,
) -> dict:
    """Run matmul bursts for ~`seconds`; returns achieved TFLOP/s.

    Defaults to XLA's native matmul: slope-timed r02 measurement
    (BENCH_NOTES.md) showed it ~1.6x faster than the Pallas tiled
    kernel on v5e — the r01 claim the Pallas default rested on was a
    timing artifact. use_pallas=True keeps the kernel exercisable.
    """
    key = jax.random.PRNGKey(0)
    if use_pallas is None:
        use_pallas = False
    # Warm up / compile.
    _sync(_mxu_burn_program(key, size, iters, use_pallas))
    flops_per_call = 2 * size**3 * iters
    calls = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        _sync(_mxu_burn_program(
            jax.random.fold_in(key, calls), size, iters, use_pallas
        ))
        calls += 1
    dt = time.perf_counter() - t0
    return {
        "calls": calls,
        "seconds": dt,
        "pallas": use_pallas,
        "tflops": flops_per_call * calls / dt / 1e12,
    }


@partial(jax.jit, static_argnames=("size", "iters", "use_pallas"))
def _int8_burn_program(
    key: jax.Array, size: int, iters: int, use_pallas: bool = False
) -> jax.Array:
    """Chained int8-weight matmuls: the serving engine's quantized hot op
    (activations bf16, weights streamed as int8 + per-channel scale)."""
    a = jax.random.normal(key, (size, size), jnp.bfloat16)
    q = jax.random.randint(
        jax.random.fold_in(key, 1), (size, size), -127, 128, jnp.int8
    )
    scale = jnp.full((size,), 1.0 / 127.0, jnp.float32)

    if use_pallas:
        from tpumon.ops.quant_matmul import quantized_matmul_pallas

    def body(carry, _):
        a = carry
        if use_pallas:
            c = quantized_matmul_pallas(a, q, scale)
        else:
            # Tie q to the carry (adds a value-preserving 0) so XLA can't
            # hoist the loop-invariant dequant out of the scan — otherwise
            # the loop would stream a materialized bf16 copy and the
            # 1-byte/weight accounting below would be a lie.
            jitter = (a[0, 0] * 0).astype(jnp.int8)
            c = a @ (
                (q + jitter).astype(jnp.bfloat16) * scale.astype(jnp.bfloat16)
            )
        c = (c / jnp.float32(size).astype(jnp.bfloat16)).astype(jnp.bfloat16)
        return c, ()

    out, _ = jax.lax.scan(body, a, None, length=iters)
    return jnp.sum(out.astype(jnp.float32))


def int8_burn(
    seconds: float = 2.0,
    size: int = 4096,
    iters: int = 64,
    use_pallas: bool | None = None,
) -> dict:
    """Int8 weight-only matmul bursts; reports TFLOP/s and the effective
    int8 weight-streaming rate (the bandwidth decode is bound by)."""
    key = jax.random.PRNGKey(0)
    if use_pallas is None:
        use_pallas = jax.devices()[0].platform == "tpu" and size % 512 == 0
    _sync(_int8_burn_program(key, size, iters, use_pallas))
    flops_per_call = 2 * size**3 * iters
    weight_bytes_per_call = size * size * iters  # int8: 1 byte/weight
    calls = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        _sync(_int8_burn_program(
            jax.random.fold_in(key, calls), size, iters, use_pallas
        ))
        calls += 1
    dt = time.perf_counter() - t0
    return {
        "calls": calls,
        "seconds": dt,
        "pallas": use_pallas,
        "tflops": flops_per_call * calls / dt / 1e12,
        "weight_gbps": weight_bytes_per_call * calls / dt / 1e9,
    }


def paged_burn(
    seconds: float = 2.0,
    batch: int = 16,
    n_heads: int = 32,
    n_kv_heads: int = 8,
    head_dim: int = 128,
    page_size: int = 128,
    context: int = 4096,
    use_pallas: bool | None = None,
) -> dict:
    """Paged-attention decode bursts over a shared page pool.

    Measures the serving decode step's attention at a given context
    length with the Pallas paged kernel (tpumon.ops.paged_attention) or
    the dense-gather XLA path, over a SHUFFLED page table (the
    fragmented layout a churned pool converges to) — the regime where
    the kernel streams KV ~2x faster than the fused gather
    (ops/paged_attention module docstring has the full measured regime
    map; an earlier round's ~555 GB/s parity claim predated the
    noise-floor guards and is superseded). Reports decode steps/s and
    the KV bytes the step streams.
    """
    from tpumon.ops.paged_attention import (
        paged_attention,
        paged_attention_reference,
    )

    if use_pallas is None:
        use_pallas = jax.devices()[0].platform == "tpu"
    assert context > 0 and context % page_size == 0, (context, page_size)
    max_pages = context // page_size
    num_pages = batch * max_pages
    key = jax.random.PRNGKey(0)
    dt_ = jnp.bfloat16
    k_pages = jax.random.normal(
        key, (n_kv_heads, num_pages, page_size, head_dim), dt_)
    v_pages = jax.random.normal(
        jax.random.fold_in(key, 1), k_pages.shape, dt_)
    # Shuffled page ids: a fresh pool would be contiguous, but the
    # point of the measurement is the data-dependent indirection of a
    # fragmented pool (sequences' pages interleaved after churn).
    table = jax.random.permutation(
        jax.random.fold_in(key, 2), num_pages
    ).astype(jnp.int32).reshape(batch, max_pages)
    lengths = jnp.full((batch,), context, jnp.int32)
    fn = paged_attention if use_pallas else jax.jit(
        paged_attention_reference)

    # inner_steps decode steps run inside ONE jitted scan per timed call
    # (q re-drawn per step so execution-result caching can't falsify the
    # numbers), reduced to a scalar and synced by fetching it (_sync) —
    # dispatch/RTT overhead amortizes over the scan instead of dominating
    # a per-step timing loop on the remote-execution tunnel.
    inner_steps = 8

    @partial(jax.jit, static_argnames=())
    def burst(call_key, k_pages, v_pages, table, lengths):
        def body(acc, step_key):
            q = jax.random.normal(
                step_key, (batch, n_heads, head_dim), dt_)
            out = fn(q, k_pages, v_pages, table, lengths)
            return acc + jnp.sum(out.astype(jnp.float32)), ()
        keys = jax.random.split(call_key, inner_steps)
        total, _ = jax.lax.scan(body, jnp.float32(0), keys)
        return total

    _sync(burst(key, k_pages, v_pages, table, lengths))  # compile
    calls = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        _sync(burst(jax.random.fold_in(key, 3 + calls),
                    k_pages, v_pages, table, lengths))
        calls += 1
    dt = time.perf_counter() - t0
    steps = calls * inner_steps
    kv_bytes_per_step = 2 * num_pages * page_size * n_kv_heads * head_dim * 2
    return {
        "calls": calls,
        "seconds": dt,
        "pallas": use_pallas,
        "decode_steps_per_sec": steps / dt,
        "kv_gbps": kv_bytes_per_step * steps / dt / 1e9,
    }


# ---------------------------------------------------------------------------
# Slope-timed kernel measurements (bench.py). The burns above are load
# generators; these exist to produce *honest* perf numbers on remote-
# execution backends, where every call pays a large fixed cost (dispatch
# RTT + scalar fetch; argument re-ship if any device-array args are
# passed). Timing the same program at n and 2n inner iterations and
# taking the difference cancels every per-call constant — only the
# marginal on-device work remains. All programs take a PRNG key only
# (inputs generated in-program; generation cost is per-call-constant,
# so it cancels too).
#
# Two integrity guards (round-2 lesson: BENCH_r02 published a paged-
# attention bandwidth 1.4x the v5e HBM roofline because the marginal
# work at the default scale resolved *below* the tunnel's ±60 ms noise
# floor, so the slope was noise):
#
#   1. Noise floor — each measurement's marginal duration must be at
#      least MIN_MARGINAL_S of device time; below that the scale is
#      grown (iteration count multiplied) and the measurement redone.
#   2. Roofline — a computed rate above the device's physical peak
#      (HBM GB/s for bandwidth phases, MXU TFLOP/s for matmul phases)
#      is impossible, therefore noise: the measurement is retried at a
#      larger scale, and raises rather than publishes if it persists.
#
# Every measure_* result carries "marginal_s" (the resolved marginal
# duration) so the artifact itself proves each phase sat above noise.
# ---------------------------------------------------------------------------

#: Minimum marginal device time per slope measurement. The tunnel's
#: per-call overhead varies by ±60 ms (BENCH_NOTES.md); 0.5 s marginal
#: keeps worst-case noise ~12% before min-of-reps tightens it further.
MIN_MARGINAL_S = 0.5

#: Peak HBM bandwidth per chip by device kind (public spec sheets);
#: the bandwidth roofline. Prefix-matched like PEAK_TFLOPS_BY_KIND.
HBM_PEAK_GBPS_BY_KIND = {
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,
    "TPU v5e": 819.0,
    "TPU v5p": 2765.0,
    "TPU v5": 2765.0,
    "TPU v6 lite": 1640.0,
    "TPU v6e": 1640.0,
}

#: Peak int8 TOP/s per chip (2x bf16 on v5e+; v4 has no int8 fast path).
INT8_PEAK_TOPS_BY_KIND = {
    "TPU v4": 275.0,
    "TPU v5 lite": 394.0,
    "TPU v5e": 394.0,
    "TPU v5p": 918.0,
    "TPU v5": 918.0,
    "TPU v6 lite": 1836.0,
    "TPU v6e": 1836.0,
}


def _lookup_peak(table: dict[str, float]) -> float | None:
    """Per-chip peak for the local device kind, or None (unknown/CPU —
    guards disengage rather than guess)."""
    try:
        d = jax.devices()[0]
        if d.platform != "tpu":
            return None
        kind = getattr(d, "device_kind", "")
    except Exception:
        return None
    for name, val in table.items():
        if kind.startswith(name):
            return val
    return None


def device_rooflines() -> dict:
    """Physical per-chip peaks for the local device: bf16 matmul TFLOP/s,
    int8 TOP/s, HBM GB/s. None-valued where the kind is unknown."""
    from tpumon.loadgen.train import PEAK_TFLOPS_BY_KIND

    return {
        "bf16_tflops": _lookup_peak(PEAK_TFLOPS_BY_KIND),
        "int8_tops": _lookup_peak(INT8_PEAK_TOPS_BY_KIND),
        "hbm_gbps": _lookup_peak(HBM_PEAK_GBPS_BY_KIND),
    }


def _slope_time(run, n1: int, n2: int, reps: int = 3) -> float:
    """min-of-reps [t(n2) - t(n1)] in seconds."""

    def best(n: int) -> float:
        b = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            run(n)
            b = min(b, time.perf_counter() - t0)
        return b

    run(n1)  # compile both variants outside the timed reps
    run(n2)
    dt = best(n2) - best(n1)
    if dt <= 0:
        # Noise/caching inverted the slope: a clamped dt would publish an
        # absurd "measurement" into BENCH_r{N}.json as if it were a win.
        raise RuntimeError(
            f"non-positive timing slope ({dt:.6f}s between {n1} and {n2} "
            "iters): measurement invalid on this backend"
        )
    return dt


def _guarded_slope(
    run,
    iters: int,
    units_per_iter: float,
    peak_per_sec: float | None,
    what: str,
    reps: int = 3,
    min_marginal_s: float = MIN_MARGINAL_S,
    attempts: int = 3,
) -> tuple[float, int, float]:
    """Slope-time ``run`` at (n, 4n), auto-scaling n until the marginal
    duration clears the noise floor AND the computed rate sits within
    2% of the physical roofline (spec-sheet peaks are rounded, and XLA
    genuinely reaches 99-100% of them — published rates may therefore
    read up to 1.02x the pinned peak). Returns (rate_per_sec,
    marginal_iters, marginal_seconds); raises if the guards can't be
    satisfied — an unresolvable measurement must never be published.
    """
    last_err: Exception | None = None
    for _ in range(attempts):
        n1, n2 = iters, 4 * iters
        try:
            dt = _slope_time(run, n1, n2, reps)
        except RuntimeError as e:
            last_err = e
            iters *= 2
            continue
        marginal = n2 - n1
        rate = units_per_iter * marginal / dt
        if dt < min_marginal_s:
            # Below the noise floor: grow to clear it with ~30% headroom.
            last_err = RuntimeError(
                f"{what}: marginal {dt * 1e3:.0f} ms below the "
                f"{min_marginal_s * 1e3:.0f} ms noise floor"
            )
            iters = max(2 * iters, int(iters * 1.3 * min_marginal_s / dt) + 1)
            continue
        # 2% headroom over the nominal peak: spec-sheet rooflines are
        # rounded, and XLA's matmul genuinely sits at 99-100% of them —
        # r05 observed a clean 197.4 TFLOP/s run rejected against the
        # "197" v5e figure. The guard exists to catch wildly-impossible
        # rates (BENCH_NOTES r02: 1.4x over), which 1.02x still does.
        if peak_per_sec is not None and rate > 1.02 * peak_per_sec:
            last_err = RuntimeError(
                f"{what}: measured {rate:.3e}/s exceeds the device "
                f"roofline {peak_per_sec:.3e}/s by >2% — noise, not a win"
            )
            iters *= 2
            continue
        return rate, marginal, dt
    raise last_err or RuntimeError(f"{what}: slope measurement failed")


def measure_mxu_tflops(
    size: int = 4096, iters: int = 192, use_pallas: bool = False, reps: int = 5
) -> dict:
    """Slope-timed bf16 matmul throughput (Pallas tiled kernel vs XLA's
    native matmul), noise-floor- and roofline-guarded."""
    key = jax.random.PRNGKey(0)

    def run(n: int):
        _sync(_mxu_burn_program(key, size, n, use_pallas))

    from tpumon.loadgen.train import PEAK_TFLOPS_BY_KIND

    peak = _lookup_peak(PEAK_TFLOPS_BY_KIND)
    rate, _, dt = _guarded_slope(
        run,
        iters,
        units_per_iter=2 * size**3,
        peak_per_sec=peak * 1e12 if peak else None,
        what=f"mxu_matmul[pallas={use_pallas}]",
        reps=reps,
    )
    return {
        "tflops": rate / 1e12,
        "pallas": use_pallas,
        "marginal_s": round(dt, 3),
    }


def measure_int8_tflops(
    size: int = 4096, iters: int = 192, use_pallas: bool = True, reps: int = 5
) -> dict:
    """Slope-timed int8 weight-only matmul throughput, noise-floor- and
    roofline-guarded. The Pallas kernel may use the int8 MXU path (2x
    peak); the XLA fallback dequantizes to bf16 before the matmul, so
    its physical ceiling is the bf16 peak — each path is guarded by its
    own roofline.
    """
    key = jax.random.PRNGKey(0)

    def run(n: int):
        _sync(_int8_burn_program(key, size, n, use_pallas))

    if use_pallas:
        peak = _lookup_peak(INT8_PEAK_TOPS_BY_KIND)
    else:
        from tpumon.loadgen.train import PEAK_TFLOPS_BY_KIND

        peak = _lookup_peak(PEAK_TFLOPS_BY_KIND)
    rate, marginal, dt = _guarded_slope(
        run,
        iters,
        units_per_iter=2 * size**3,
        peak_per_sec=peak * 1e12 if peak else None,
        what=f"int8_matmul[pallas={use_pallas}]",
        reps=reps,
    )
    return {
        "tflops": rate / 1e12,
        # rate = 2*size^3 flops per iteration; weights are size^2 int8
        # bytes per iteration => bytes/s = rate / (2*size).
        "weight_gbps": rate / (2 * size) / 1e9,
        "pallas": use_pallas,
        "marginal_s": round(dt, 3),
    }


@partial(jax.jit, static_argnames=(
    "batch", "n_heads", "n_kv_heads", "head_dim", "page_size", "context",
    "steps", "use_pallas"))
def _paged_measure_program(
    key, batch, n_heads, n_kv_heads, head_dim, page_size, context,
    steps, use_pallas,
):
    """Self-contained paged-decode burst: pool, table and queries all
    generated in-program so calls ship only a PRNG key."""
    from tpumon.ops.paged_attention import (
        paged_attention,
        paged_attention_reference,
    )

    fn = paged_attention if use_pallas else paged_attention_reference
    max_pages = context // page_size
    num_pages = batch * max_pages
    dt_ = jnp.bfloat16
    k_pages = jax.random.normal(
        key, (n_kv_heads, num_pages, page_size, head_dim), dt_)
    v_pages = jax.random.normal(
        jax.random.fold_in(key, 1), k_pages.shape, dt_)
    table = jax.random.permutation(
        jax.random.fold_in(key, 2), num_pages
    ).astype(jnp.int32).reshape(batch, max_pages)
    lengths = jnp.full((batch,), context, jnp.int32)

    def body(acc, step_key):
        q = jax.random.normal(step_key, (batch, n_heads, head_dim), dt_)
        out = fn(q, k_pages, v_pages, table, lengths)
        return acc + jnp.sum(out.astype(jnp.float32)), ()

    total, _ = jax.lax.scan(
        body, jnp.float32(0), jax.random.split(jax.random.fold_in(key, 3), steps)
    )
    return total


def measure_paged_gbps(
    batch: int = 16,
    n_heads: int = 32,
    n_kv_heads: int = 8,
    head_dim: int = 128,
    page_size: int = 128,
    context: int = 4096,
    use_pallas: bool = True,
    inner_steps: int = 96,
    reps: int = 5,
) -> dict:
    """Slope-timed paged-attention decode KV-streaming bandwidth
    (n -> 4n scan steps), noise-floor- and HBM-roofline-guarded.

    The decode step must stream the full KV pool (~268 MB at the
    defaults), so a bandwidth above the HBM peak is physically
    impossible — BENCH_r02's 1182.6 GB/s "measurement" came from an
    inner_steps=8 scale whose ~40 ms marginal sat below the tunnel's
    ±60 ms noise; the default is now 96 (marginal ≈ 77 GB ≈ 0.5+ s).
    """
    assert context % page_size == 0, (context, page_size)
    key = jax.random.PRNGKey(0)

    def run(n: int):
        _sync(_paged_measure_program(
            key, batch, n_heads, n_kv_heads, head_dim, page_size,
            context, n, use_pallas,
        ))

    num_pages = batch * (context // page_size)
    kv_bytes_per_step = 2 * num_pages * page_size * n_kv_heads * head_dim * 2
    peak = _lookup_peak(HBM_PEAK_GBPS_BY_KIND)
    rate, marginal, dt = _guarded_slope(
        run,
        inner_steps,
        units_per_iter=kv_bytes_per_step,
        peak_per_sec=peak * 1e9 if peak else None,
        what=f"paged_attention[pallas={use_pallas}]",
        reps=reps,
    )
    return {
        "kv_gbps": rate / 1e9,
        "decode_steps_per_sec": rate / kv_bytes_per_step,
        "pallas": use_pallas,
        "marginal_s": round(dt, 3),
    }


@partial(jax.jit, static_argnames=("cfg", "steps"), donate_argnums=(2,))
def _paged_engine_step_program(cfg, params, pool, last, positions, tables,
                               steps):
    """``steps`` engine decode steps (the REAL serving step fn —
    tpumon.loadgen.paged_kv.paged_decode_step, gather or kernel read
    path per cfg.paged_attn) scanned in one dispatch, so the per-call
    tunnel/dispatch latency that dominates the end-to-end engine bench
    is amortized away and only the step's device time remains.

    Positions ride the scan carry and advance one row per step, exactly
    like the production engine's write cursor — a fixed position would
    rewrite the same (page, offset) every step and never cross a page
    boundary, hiding the table-walk cost the bench exists to measure.
    They cycle within the last ``page_size + 1`` rows (a band that
    always contains one page boundary) so context stays ~max while the
    scatter keeps switching pages.
    """
    from tpumon.loadgen.paged_kv import paged_decode_step

    ps = cfg.prefill_len
    s_max = tables.shape[1] * ps
    hi = s_max - 2  # last position with a valid next row
    lo = max(hi - ps, 0)

    def body(carry, _):
        pool, last, pos = carry
        pool, logits = paged_decode_step(
            cfg, params, pool, last, pos, tables)
        pos = jnp.where(pos >= hi, lo, pos + 1)
        return (pool, jnp.argmax(logits, -1).astype(jnp.int32), pos), ()

    (pool, last, positions), _ = jax.lax.scan(
        body, (pool, last, positions), None, length=steps)
    return pool, last, positions


def measure_paged_engine_step_ms(cfg, inner_steps: int = 24,
                                 reps: int = 3) -> dict:
    """Slope-timed device ms per engine paged-decode step at ``cfg``'s
    exact shape, with FULL scrambled page tables (every slot at
    max_seq-1 context, tables a random permutation of the pool — the
    fully-fragmented worst case). This isolates what the
    ``paged_attn`` read path buys at the step level: the end-to-end
    engine tokens/s comparison in bench.py is dispatch-bound on the
    axon tunnel (each block dispatch pays ~100 ms of round-trip before
    any HBM traffic), so the 2x KV-streaming difference between gather
    and kernel (ops/paged_attention docstring) only shows once the
    dispatch is amortized — which a production multi-step server does
    and this scan reproduces."""
    import numpy as np

    from tpumon.loadgen.model import init_params
    from tpumon.loadgen.paged_kv import init_pool

    m = cfg.model
    ps = cfg.prefill_len
    max_pages = m.max_seq // ps
    num_pages = cfg.slots * max_pages + 1
    rng = np.random.default_rng(0)
    perm = rng.permutation(np.arange(1, num_pages))
    tables = jnp.asarray(
        perm[: cfg.slots * max_pages].reshape(cfg.slots, max_pages),
        jnp.int32)
    params = init_params(m, jax.random.PRNGKey(0))

    state = {
        "pool": init_pool(cfg, num_pages),
        "last": jnp.zeros((cfg.slots,), jnp.int32),
        "positions": jnp.full((cfg.slots,), m.max_seq - 2, jnp.int32),
    }

    def run(n: int):
        pool, last, positions = _paged_engine_step_program(
            cfg, params, state["pool"], state["last"], state["positions"],
            tables, n)
        _sync(jnp.sum(last))
        # The previous pool was donated into the call; carry the new one
        # (and the advanced positions, so reps keep walking pages).
        state["pool"], state["last"] = pool, last
        state["positions"] = positions

    # Per step the attention read streams the full table width of KV:
    # slots * max_pages * ps rows * nkv * hd * 2 (K+V) * itemsize,
    # per layer — plus the weights, which we exclude from units so the
    # reported GB/s is a lower bound on KV streaming rate.
    kv_bytes = (m.n_layers * 2 * cfg.slots * max_pages * ps
                * m.n_kv_heads * m.head_dim
                * jnp.dtype(m.compute_dtype).itemsize)
    peak = _lookup_peak(HBM_PEAK_GBPS_BY_KIND)
    rate, marginal, dt = _guarded_slope(
        run,
        inner_steps,
        units_per_iter=kv_bytes,
        peak_per_sec=peak * 1e9 if peak else None,
        what=f"paged_engine_step[{cfg.paged_attn}]",
        reps=reps,
    )
    return {
        "ms_per_step": kv_bytes / rate * 1e3,
        "kv_gbps_floor": rate / 1e9,
        "paged_attn": cfg.paged_attn,
        "marginal_s": round(dt, 3),
    }


def hbm_fill(fraction: float = 0.5, hbm_bytes: int | None = None) -> list[jax.Array]:
    """Allocate ~fraction of HBM (holds references; caller drops to free).

    Used to validate the HBM% reading: allocate, observe the exporter
    report the committed fraction, release.
    """
    dev = jax.devices()[0]
    if hbm_bytes is None:
        stats = dev.memory_stats() or {}
        hbm_bytes = stats.get("bytes_limit", 16 * 2**30)
    n = int(hbm_bytes * fraction) // 4
    chunk = 64 * 2**20 // 4  # 64 MB chunks avoid one giant alloc
    arrays = []
    remaining = n
    i = 0
    while remaining > 0:
        size = min(chunk, remaining)
        arrays.append(jnp.ones((size,), jnp.float32) * i)
        remaining -= size
        i += 1
    jax.block_until_ready(arrays)
    return arrays


def ici_burn(mesh: Mesh, mb_per_shift: int = 64, iters: int = 8) -> dict:
    """Ring-permute a sharded buffer around the mesh's first axis,
    driving ICI links. Uses shard_map + lax.ppermute (the explicit
    collective is the point here — we are generating interconnect
    traffic, not letting XLA elide it)."""
    from jax import shard_map

    axis = mesh.axis_names[0]
    n = mesh.shape[axis]
    floats = mb_per_shift * 2**20 // 4
    x = jnp.arange(n * floats, dtype=jnp.float32).reshape(n, floats)
    x = jax.device_put(x, NamedSharding(mesh, P(axis, None)))

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis, None),
        out_specs=P(axis, None),
    )
    def ring(block):
        def body(b, _):
            b = jax.lax.ppermute(
                b, axis, perm=[(i, (i + 1) % n) for i in range(n)]
            )
            return b, ()

        out, _ = jax.lax.scan(body, block, None, length=iters)
        return out

    t0 = time.perf_counter()
    out = jax.jit(ring)(x)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    total_bytes = n * floats * 4 * iters
    return {
        "devices": n,
        "bytes_shifted": total_bytes,
        "seconds": dt,
        "gbps": total_bytes / dt / 1e9,
    }
