"""Multi-tenant traffic simulator: production-shaped load for the SLO soak.

The serving loadgen's demo arrival loop models ONE anonymous Poisson
stream. Production serving traffic is nothing like that: several tenants
share the engine, each with its own arrival rate, diurnal swing, prompt
shape and latency sensitivity — and the monitor's job (docs/slo.md) is
to notice when *one tenant's* experience regresses. This module drives
the existing ``ServingEngine`` with exactly that shape:

- **Tenants** (``TenantSpec``): a scenario mix of ``chat`` (short
  prompts, latency-sensitive), ``rag`` (long multi-chunk prompts behind
  a tenant-shared prefix, so the prefix cache actually hits) and
  ``batch`` (offline bulk generation, throughput SLO only). Every
  request carries its ``tenant`` tag through ``Request`` → completion
  accounting → the engine's ``tpumon_serving_tenant_*`` gauges → the
  serving collector → ``serving.<tenant>.*`` TSDB series.
- **Arrival processes**: per-tenant Poisson at ``rps`` × a
  deterministic diurnal ramp (sinusoid, ``diurnal_amp``/
  ``diurnal_period_s``; ``time_scale`` compresses simulated days into
  bench seconds). Seeded per-tenant RNGs, so a run replays: the k-th
  request a tenant submits is the same prompt in every run with the
  same seed (tests/test_traffic.py pins this).
- **Degradation knob** (``degrade``): stalls the engine's step loop by
  a fixed per-step sleep — the serving-path fault the closed-loop SLO
  soak injects (tests/test_slo_soak.py): queues grow, TTFT/TPOT
  balloon, the burn-rate alert fires; releasing the knob drains the
  queue and the alert clears. The knob rides ``ArrivalPump``'s ``step``
  seam, so the arrival schedule itself stays undisturbed.

The driver COMPOSES the arrival pump extracted from
``tpumon.loadgen.serving`` (``ArrivalPump``/``ArrivalSource``) rather
than re-implementing the Poisson loop.
"""

from __future__ import annotations

import math
import random
import threading
import time
import zlib
from dataclasses import dataclass, field

from tpumon.loadgen.serving import ArrivalPump, ArrivalSource

SCENARIOS = ("chat", "rag", "batch")

# Scenario presets: (prompt_chunks, max_new, temperature). ``chat`` is
# short-prompt/short-answer and latency-sensitive; ``rag`` front-loads
# long prefix-shared prompts (32 chunks of prefill_len tokens — the
# retrieval context); ``batch`` is offline bulk generation where only
# throughput matters. Specs may override any of the three.
_PRESETS: dict[str, tuple[int, int, float]] = {
    "chat": (1, 16, 0.7),
    "rag": (32, 32, 0.0),
    "batch": (1, 64, 0.0),
}


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's traffic shape. ``rps`` is the Poisson base rate;
    the effective rate at sim-time t is
    ``rps * (1 + diurnal_amp * sin(2π t / diurnal_period_s))`` (clamped
    at 0) — a deterministic diurnal profile, not noise, so two seeded
    runs see identical rate curves. Fields at their 0/None defaults
    adopt the scenario preset."""

    name: str
    scenario: str = "chat"
    rps: float = 1.0
    diurnal_amp: float = 0.0
    diurnal_period_s: float = 86400.0
    prompt_chunks: int = 0  # prompt length in prefill_len chunks
    max_new: int = 0
    temperature: float | None = None

    def resolved(self) -> tuple[int, int, float]:
        if self.scenario not in _PRESETS:
            raise ValueError(
                f"unknown scenario {self.scenario!r} (want one of "
                f"{', '.join(SCENARIOS)})")
        chunks, max_new, temp = _PRESETS[self.scenario]
        return (
            self.prompt_chunks or chunks,
            self.max_new or max_new,
            self.temperature if self.temperature is not None else temp,
        )


@dataclass
class _TenantState:
    spec: TenantSpec
    rng: object
    shared: list[int]
    submitted: int = 0
    requests: list = field(default_factory=list)


class TrafficSim:
    """Multi-tenant scenario driver over one ``ServingEngine``.

    Owns one seeded RNG per tenant (``seed`` xor a CRC of the tenant
    name, so adding a tenant never perturbs another tenant's stream)
    and one ``ArrivalSource`` per tenant over the shared pump. The
    engine is duck-typed: anything with ``cfg``, ``submit`` and
    ``step`` works, which is what keeps the seeded-replay tests free of
    a real model."""

    def __init__(self, engine, tenants: list[TenantSpec], seed: int = 0,
                 time_scale: float = 1.0, keep_requests: int = 0):
        if not tenants:
            raise ValueError("TrafficSim needs at least one TenantSpec")
        names = [t.name for t in tenants]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant names in {names}")
        for name in names:
            # Dot-free by the series-naming contract: a dotted tenant
            # would mis-split serving.<tenant>.<metric> and the sampler
            # would never land its series — the SLO over it could
            # silently never fire.
            if not name or "." in name:
                raise ValueError(
                    f"tenant name {name!r} must be non-empty and "
                    f"dot-free (it names serving.<tenant>.* series)")
        self.engine = engine
        self.seed = seed
        self.time_scale = time_scale
        # Bound on retained Request handles per tenant (tests/bench
        # read completion stats from them); 0 keeps none.
        self.keep_requests = keep_requests
        self._stall_s = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.tenants: dict[str, _TenantState] = {}
        for spec in tenants:
            spec.resolved()  # validate scenario up front
            rng = random.Random(seed ^ zlib.crc32(spec.name.encode()))
            chunks, _, _ = spec.resolved()
            p = engine.cfg.prefill_len
            # rag-style tenants share a per-tenant retrieval prefix of
            # chunks-1 chunks (chunk-aligned, so the prefix cache's
            # chunk-granular keys actually hit); the tail chunk is
            # per-request. Single-chunk tenants have no shared prefix.
            shared = (
                [rng.randrange(engine.cfg.model.vocab)
                 for _ in range((chunks - 1) * p)]
                if chunks > 1 else []
            )
            self.tenants[spec.name] = _TenantState(
                spec=spec, rng=rng, shared=shared)
        self.pump = ArrivalPump(
            engine, [self._source(st) for st in self.tenants.values()],
            step=self._step)

    # ------------------------------ driving ------------------------------

    def _rate_fn(self, spec: TenantSpec):
        def rate(rel_t: float) -> float:
            if spec.diurnal_amp <= 0:
                return spec.rps
            phase = (2.0 * math.pi * (rel_t * self.time_scale)
                     / spec.diurnal_period_s)
            return max(0.0, spec.rps * (
                1.0 + spec.diurnal_amp * math.sin(phase)))

        return rate

    def _source(self, st: _TenantState) -> ArrivalSource:
        return ArrivalSource(
            rate=self._rate_fn(st.spec),
            fire=lambda _rel, st=st: self.fire(st.spec.name),
            interval=st.rng.expovariate,
        )

    def fire(self, tenant: str):
        """Submit one request for ``tenant`` (the pump's per-arrival
        callback; also callable directly — the seeded-replay tests
        drive it without a clock). Returns the Request."""
        st = self.tenants[tenant]
        chunks, max_new, temp = st.spec.resolved()
        p = self.engine.cfg.prefill_len
        vocab = self.engine.cfg.model.vocab
        tail_n = st.rng.randint(2, p)
        prompt = st.shared + [st.rng.randrange(vocab) for _ in range(tail_n)]
        req = self.engine.submit(
            prompt, max_new=max_new, temperature=temp,
            tenant=st.spec.name)
        st.submitted += 1
        if self.keep_requests:
            st.requests.append(req)
            del st.requests[:-self.keep_requests]
        return req

    # Per-step stall ceiling: a stalled step must stay short enough
    # that stop() joins promptly and arrivals keep draining.
    MAX_STALL_S = 1.0

    def _step(self) -> bool:
        stall = self._stall_s
        if stall > 0:
            # The scheduler-degradation knob: every engine step pays a
            # fixed stall, so queues grow and TTFT/TPOT balloon — the
            # serving-path fault of the closed-loop SLO soak.
            time.sleep(stall)
        return self.engine.step()

    def degrade(self, stall_s: float) -> None:
        """Set the per-step stall (seconds); 0 removes the fault.
        Clamped to MAX_STALL_S (1 s) at SET time so the reported state
        is the effective fault, not a silently-milder one."""
        self._stall_s = max(0.0, min(float(stall_s), self.MAX_STALL_S))

    @property
    def degraded(self) -> bool:
        return self._stall_s > 0

    # ----------------------------- lifecycle -----------------------------

    def run(self, duration: float = 0.0) -> None:
        """Drive arrivals + engine steps inline until ``duration``
        elapses (0 = until ``stop()``)."""
        self.pump.run(self._stop, duration=duration)

    def start(self) -> "TrafficSim":
        """Run in a daemon thread; ``stop()`` joins it."""
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def to_json(self) -> dict:
        return {
            "degraded": self.degraded,
            "stall_s": self._stall_s,
            "tenants": {
                name: {
                    "scenario": st.spec.scenario,
                    "rps": st.spec.rps,
                    "submitted": st.submitted,
                }
                for name, st in sorted(self.tenants.items())
            },
        }


def start_traffic_background(
    tenants: list[TenantSpec], cfg=None, port: int = 0, seed: int = 0,
    time_scale: float = 1.0,
):
    """Engine + /metrics endpoint + traffic sim, all in-process: the
    multi-tenant analogue of ``serving.start_background``. Returns
    ``(engine, sim, url, stop)``; setting ``stop`` drains the sim
    thread and closes the metrics listener."""
    from tpumon.loadgen.serving import ServingEngine, start_metrics_server

    engine = ServingEngine(cfg=cfg, seed=seed)
    server, bound = start_metrics_server(engine, port=port)
    sim = TrafficSim(engine, tenants, seed=seed, time_scale=time_scale)

    def _run():
        try:
            sim.run()
        finally:
            # shutdown() alone leaks the listening socket (tpulint's
            # serve-forever-unclosed pass) — close it too.
            server.shutdown()
            server.server_close()

    sim._thread = threading.Thread(target=_run, daemon=True)
    sim._thread.start()
    return engine, sim, f"http://127.0.0.1:{bound}/metrics", sim._stop
