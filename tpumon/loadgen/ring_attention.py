"""Ring attention: sequence-parallel attention over a device mesh.

Long-context jobs on TPU pods shard the sequence axis across chips and
pass K/V blocks around the ICI ring (ring attention / context
parallelism). tpumon's loadgen includes it for two reasons:

1. It is the *realistic* ICI workload for monitoring validation — unlike
   the synthetic ``ici_burn``, its traffic pattern (block rotation each
   step, compute overlapped with the permute) matches what the monitor
   sees under a real long-context training/serving job.
2. It documents, in-tree, the sharding pattern the monitor's slice
   topology model is built to observe (BASELINE config 5).

Implementation: shard_map over the sequence axis; per step each device
attends its local Q block against the visiting K/V block, accumulating
with the online-softmax (flash-attention) update, then rotates K/V with
``lax.ppermute`` — the collective rides the ICI ring. Static step count
(mesh size), no data-dependent control flow, float32 accumulators.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = float("-inf")


def _block_attend(q, k, v, q_off, k_off, scale, causal, m, l, o):
    """One online-softmax accumulation step.

    q: [B, Tq, H, D], k/v: [B, Tk, H, D]; m/l: [B, H, Tq]; o like q.
    q_off/k_off are the blocks' global sequence offsets (traced scalars).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        qpos = q_off + jnp.arange(tq)[:, None]
        kpos = k_off + jnp.arange(tk)[None, :]
        s = jnp.where((qpos >= kpos)[None, None], s, _NEG_INF)
    m_blk = jnp.max(s, axis=-1)  # [B, H, Tq]
    m_new = jnp.maximum(m, m_blk)
    # exp(-inf - -inf) guards: a fully-masked row keeps m_new == -inf;
    # use a zeroed-safe exponent there (its p rows are all zero anyway).
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.where(
        jnp.isneginf(s), 0.0, jnp.exp(s - m_safe[..., None])
    )  # [B, H, Tq, Tk]
    corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
    l_new = l * corr + jnp.sum(p, axis=-1)
    # corr: [B, H, Tq] -> broadcast over o's [B, Tq, H, D] layout.
    corr_o = corr.swapaxes(1, 2)[..., None]
    o_new = o * corr_o + jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m_new, l_new, o_new


def reference_attention(q, k, v, causal: bool = True) -> jax.Array:
    """Plain full-sequence softmax attention (the correctness oracle)."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / d**0.5
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    probs = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32)).astype(
        q.dtype
    )


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "seq",
    causal: bool = True,
) -> jax.Array:
    """Attention with Q/K/V sharded over `axis` on the sequence dimension.

    Arrays are [B, T, H, D] with T divisible by the mesh axis size.
    Returns the output with the same sharding as q.
    """
    n = mesh.shape[axis]
    scale = 1.0 / q.shape[-1] ** 0.5
    spec = P(None, axis, None, None)

    @partial(
        jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    def run(q_blk, k_blk, v_blk):
        b, tq, h, _ = q_blk.shape
        my = jax.lax.axis_index(axis)
        q_off = my * tq
        m = jnp.full((b, h, tq), _NEG_INF, jnp.float32)
        l = jnp.zeros((b, h, tq), jnp.float32)
        o = jnp.zeros(q_blk.shape[:3] + (q_blk.shape[3],), jnp.float32)
        k_cur, v_cur = k_blk, v_blk
        perm = [(i, (i + 1) % n) for i in range(n)]
        for step in range(n):
            # Block j visits us at step s where j = (my - s) mod n.
            j = (my - step) % n
            k_off = j * tq
            m, l, o = _block_attend(
                q_blk, k_cur, v_cur, q_off, k_off, scale, causal, m, l, o
            )
            if step != n - 1:
                # Rotate K/V around the ICI ring; XLA overlaps this
                # collective-permute with the next block's compute.
                k_cur = jax.lax.ppermute(k_cur, axis, perm)
                v_cur = jax.lax.ppermute(v_cur, axis, perm)
        l_safe = jnp.where(l == 0.0, 1.0, l)  # [B, H, Tq]
        out = o / l_safe.swapaxes(1, 2)[..., None]
        return out.astype(q_blk.dtype)

    return run(q, k, v)
