"""Ring attention: sequence-parallel attention over a device mesh.

Long-context jobs on TPU pods shard the sequence axis across chips and
pass K/V blocks around the ICI ring (ring attention / context
parallelism). tpumon's loadgen includes it for two reasons:

1. It is the *realistic* ICI workload for monitoring validation — unlike
   the synthetic ``ici_burn``, its traffic pattern (block rotation each
   step, compute overlapped with the permute) matches what the monitor
   sees under a real long-context training/serving job.
2. It documents, in-tree, the sharding pattern the monitor's slice
   topology model is built to observe (BASELINE config 5).

Implementation: shard_map over the sequence axis; per step each device
attends its local Q block against the visiting K/V block, accumulating
with the online-softmax (flash-attention) update, then rotates K/V with
``lax.ppermute`` — the collective rides the ICI ring. Static step count
(mesh size), no data-dependent control flow, float32 accumulators.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = float("-inf")


def _block_attend(q, k, v, q_off, k_off, scale, causal, m, l, o):
    """One online-softmax accumulation step.

    q: [B, Tq, H, D], k/v: [B, Tk, H, D]; m/l: [B, H, Tq]; o like q.
    q_off/k_off are the blocks' global sequence offsets (traced
    scalars), except q_off may also be a [B] vector — per-slot decode
    frontiers, the paged ring path — which masks per batch row.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        qpos = jnp.asarray(q_off)[..., None, None] + jnp.arange(tq)[:, None]
        kpos = k_off + jnp.arange(tk)[None, :]
        keep = qpos >= kpos  # [Tq, Tk] or [B, Tq, Tk]
        keep = keep[None, None] if keep.ndim == 2 else keep[:, None]
        s = jnp.where(keep, s, _NEG_INF)
    m_blk = jnp.max(s, axis=-1)  # [B, H, Tq]
    m_new = jnp.maximum(m, m_blk)
    # exp(-inf - -inf) guards: a fully-masked row keeps m_new == -inf;
    # use a zeroed-safe exponent there (its p rows are all zero anyway).
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.where(
        jnp.isneginf(s), 0.0, jnp.exp(s - m_safe[..., None])
    )  # [B, H, Tq, Tk]
    corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
    l_new = l * corr + jnp.sum(p, axis=-1)
    # corr: [B, H, Tq] -> broadcast over o's [B, Tq, H, D] layout.
    corr_o = corr.swapaxes(1, 2)[..., None]
    o_new = o * corr_o + jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m_new, l_new, o_new


def reference_attention(q, k, v, causal: bool = True) -> jax.Array:
    """Plain full-sequence softmax attention (the correctness oracle)."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / d**0.5
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    probs = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32)).astype(
        q.dtype
    )


def paged_ring_decode_attend(pk: jax.Array, pv: jax.Array, q: jax.Array,
                             tables: jax.Array, positions: jax.Array
                             ) -> jax.Array:
    """Single-query decode attention over a paged KV pool, pages
    visited one block at a time with the online-softmax accumulator —
    the engine's ring read path (``ServeConfig.paged_attn="ring"``).

    On a real tp ring each logical page stripe lives on a different
    chip and the blocks rotate over ICI (``ring_attend_inner``); here
    the rotation is a ``lax.scan`` over the slot's page table — same
    block order, same accumulation math, so the monitor-visible
    traffic shape (one page-sized K/V read per visit instead of one
    s_max-row gather) matches the ring schedule. Unlike the fused
    gather-softmax this is NOT bitwise-equal to naive attention (the
    online softmax reassociates the reduction); tests pin it to the
    gather path by tolerance, never in the exact golden matrix.

    pk/pv: [nkv, num_pages, ps, hd] (one layer of the pool);
    q: [B, 1, nh, hd]; tables: [B, max_pages] page tables;
    positions: [B] decode frontiers. Rows past a slot's frontier —
    including every unreserved logical page, whose table entry still
    points at the trash page — are masked per batch row via the [B]
    ``q_off`` form of ``_block_attend``. Returns [B, 1, nh, hd] in
    q's dtype.
    """
    nkv, _, ps, hd = pk.shape
    b, _, nh, _ = q.shape
    kv_rep = nh // nkv
    scale = 1.0 / hd**0.5
    m0 = jnp.full((b, nh, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, nh, 1), jnp.float32)
    o0 = jnp.zeros((b, 1, nh, hd), jnp.float32)

    def visit(carry, page_ids):  # page_ids: [B], one logical page
        m, l, o, k_off = carry
        kb = pk[:, page_ids].transpose(1, 2, 0, 3)  # [B, ps, nkv, hd]
        vb = pv[:, page_ids].transpose(1, 2, 0, 3)
        if kv_rep > 1:
            kb = jnp.repeat(kb, kv_rep, axis=2)
            vb = jnp.repeat(vb, kv_rep, axis=2)
        m, l, o = _block_attend(q, kb, vb, positions, k_off, scale,
                                True, m, l, o)
        return (m, l, o, k_off + ps), None

    (_, l, o, _), _ = jax.lax.scan(
        visit, (m0, l0, o0, jnp.int32(0)), tables.T)
    # Every slot attends at least its own frontier row, so l >= the
    # frontier's softmax weight > 0 — no masked-row zero guard needed.
    return (o / l.swapaxes(1, 2)[..., None]).astype(q.dtype)


def ring_attend_inner(
    q_blk: jax.Array,
    k_blk: jax.Array,
    v_blk: jax.Array,
    axis: str,
    n: int,
    causal: bool = True,
    kv_rep: int = 1,
) -> jax.Array:
    """Per-device ring-attention body: local q against rotating K/V.

    For use INSIDE an existing shard_map over ``axis`` (shard_map does
    not nest) — the sp training step (loadgen.sp_train) calls this with
    its layer activations; ``ring_attention`` below is the standalone
    wrapper. Arrays are the LOCAL blocks [B, T/n, H, D].

    ``kv_rep``: GQA head-repeat factor applied LOCALLY at each use —
    the ppermute rotates the narrow nkv-head K/V (repeating before the
    ring would multiply the ICI traffic by nh/nkv for nothing).
    """
    b, tq, h, d = q_blk.shape
    scale = 1.0 / d**0.5
    my = jax.lax.axis_index(axis)
    q_off = my * tq

    def widen(x):
        return jnp.repeat(x, kv_rep, axis=2) if kv_rep > 1 else x

    m = jnp.full((b, h, tq), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, tq), jnp.float32)
    o = jnp.zeros(q_blk.shape[:3] + (q_blk.shape[3],), jnp.float32)
    k_cur, v_cur = k_blk, v_blk
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        # Block j visits us at step s where j = (my - s) mod n.
        j = (my - step) % n
        k_off = j * tq
        m, l, o = _block_attend(
            q_blk, widen(k_cur), widen(v_cur), q_off, k_off, scale,
            causal, m, l, o
        )
        if step != n - 1:
            # Rotate K/V around the ICI ring; XLA overlaps this
            # collective-permute with the next block's compute.
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)
    l_safe = jnp.where(l == 0.0, 1.0, l)  # [B, H, Tq]
    out = o / l_safe.swapaxes(1, 2)[..., None]
    return out.astype(q_blk.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "seq",
    causal: bool = True,
) -> jax.Array:
    """Attention with Q/K/V sharded over `axis` on the sequence dimension.

    Arrays are [B, T, H, D] with T divisible by the mesh axis size.
    Returns the output with the same sharding as q.
    """
    n = mesh.shape[axis]
    spec = P(None, axis, None, None)

    @partial(
        jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    def run(q_blk, k_blk, v_blk):
        return ring_attend_inner(q_blk, k_blk, v_blk, axis, n, causal)

    return run(q, k, v)


# ---------------------------------------------------------------------------
# Zigzag schedule: load-balanced CAUSAL ring attention.
#
# Plain ring attention wastes half the machine under a causal mask: chip 0
# holds the earliest block and is needed in 1 of n steps, chip n-1 in all
# n — but the ring synchronizes at every ppermute, so each step's wall
# time is the BUSIEST chip's attend and the total stays O(T²/n), as if
# the mask didn't exist. The zigzag layout (as used by production
# context-parallel trainers) gives every chip one EARLY and one LATE
# half-block — chip i holds half-blocks (i, 2n-1-i) of the sequence cut
# into 2n — so at every step every chip has ~the same two causally-live
# (q half, k half) pairs to compute and the per-step critical path is
# half a plain-ring attend: causal-optimal O(T²/2n) total, with the
# same ppermute traffic.
# ---------------------------------------------------------------------------


def zigzag_indices(t: int, n: int) -> jnp.ndarray:
    """Gather indices mapping a contiguous sequence to zigzag order.

    ``x[:, zigzag_indices(t, n)]`` puts rows so that an even split over
    n chips gives chip i the half-blocks (i, 2n-1-i). 2n must divide t.
    """
    if t % (2 * n):
        # ValueError, not assert: under ``python -O`` an assert is
        # stripped and a non-divisible t would silently produce a
        # wrong permutation (run_train pre-checks, but direct callers
        # are unprotected).
        raise ValueError(
            f"zigzag layout needs 2*n ({2 * n}) to divide t ({t})")
    hb = t // (2 * n)
    order: list[int] = []
    for i in range(n):
        order.extend(range(i * hb, (i + 1) * hb))
        order.extend(range((2 * n - 1 - i) * hb, (2 * n - i) * hb))
    return jnp.asarray(order, jnp.int32)


def zigzag_inverse(t: int, n: int) -> jnp.ndarray:
    """Inverse permutation: zigzag order back to contiguous."""
    return jnp.argsort(zigzag_indices(t, n))


def zigzag_ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "seq",
) -> jax.Array:
    """Causal ring attention over zigzag-ordered inputs.

    Arrays are [B, T, H, D] with the sequence axis ALREADY in zigzag
    order (``zigzag_indices``) — long-context pipelines keep this layout
    end to end; one-off callers can permute in/out:

        zi = zigzag_indices(t, n)
        out = zigzag_ring_attention(q[:, zi], k[:, zi], v[:, zi], mesh)
        out = out[:, zigzag_inverse(t, n)]

    Output is returned in the same zigzag layout/sharding as q.
    """
    n = mesh.shape[axis]
    t = q.shape[1]
    if t % (2 * n):
        raise ValueError(
            f"zigzag ring attention needs 2*n ({2 * n}) to divide the "
            f"sequence length ({t})")
    spec = P(None, axis, None, None)

    @partial(
        jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    def run(q_blk, k_blk, v_blk):
        return zigzag_attend_inner(q_blk, k_blk, v_blk, axis, n)

    return run(q, k, v)


def zigzag_attend_inner(
    q_blk: jax.Array,
    k_blk: jax.Array,
    v_blk: jax.Array,
    axis: str,
    n: int,
    kv_rep: int = 1,
) -> jax.Array:
    """Per-device zigzag body, for use inside an existing shard_map over
    ``axis`` (the sp training step) — local blocks hold the zigzag
    halves (my, 2n-1-my), each of hb rows. ``kv_rep``: GQA head-repeat
    applied locally inside each live pair (the ring rotates the narrow
    nkv-head K/V)."""
    b, tq, h, d = q_blk.shape  # tq == 2*hb: halves (my, 2n-1-my)
    hb = tq // 2
    scale = 1.0 / d**0.5
    my = jax.lax.axis_index(axis)

    def widen(x):
        return jnp.repeat(x, kv_rep, axis=2) if kv_rep > 1 else x
    # Global row offsets of this chip's early/late q halves.
    qa_off = my * hb
    qb_off = (2 * n - 1 - my) * hb
    q_a, q_b = q_blk[:, :hb], q_blk[:, hb:]

    def fresh():
        return (
            jnp.full((b, h, hb), _NEG_INF, jnp.float32),
            jnp.zeros((b, h, hb), jnp.float32),
            jnp.zeros((b, hb, h, q_blk.shape[3]), jnp.float32),
        )

    # Mark the accumulators device-varying up front: the attend
    # branch's outputs depend on axis_index, and lax.cond requires
    # both branches (and so the carry) to agree on that. Varying over
    # q's FULL vma, not just the ring axis — under a composed mesh
    # (dp x sp, sp_train dp_axis) the blocks also vary over the batch
    # axis and the carry must match.
    vma = tuple(getattr(jax.typeof(q_blk), "vma", None) or (axis,))
    acc = jax.tree.map(
        lambda x: jax.lax.pcast(x, vma, to="varying"),
        {"a": fresh(), "b": fresh()})
    k_cur, v_cur = k_blk, v_blk
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        j = (my - step) % n  # owner of the visiting K/V
        ka_off = j * hb
        kb_off = (2 * n - 1 - j) * hb
        k_a, v_a = k_cur[:, :hb], v_cur[:, :hb]
        k_b, v_b = k_cur[:, hb:], v_cur[:, hb:]
        # The causally-possible (q half, k half) pairs; a pair is
        # live iff its k half starts at or before its q half's last
        # row. q_a × k_b is omitted: an early q half (block < n)
        # can never see a late k half (block >= n). Of the three
        # below, ~2 are live per chip per step (all 3 on the
        # self-step, 2 of them half-masked diagonals) — and every
        # chip has the same load, which is the whole point
        # (balanced critical path).
        for q_half, q_off, tag, kvs in (
            (q_a, qa_off, "a", ((k_a, v_a, ka_off),)),
            (q_b, qb_off, "b", ((k_a, v_a, ka_off),
                                (k_b, v_b, kb_off))),
        ):
            for k_half, v_half, k_off in kvs:
                live = k_off <= q_off + (hb - 1)
                acc[tag] = jax.lax.cond(
                    live,
                    # widen() inside the branch: a skipped pair never
                    # materializes the repeated heads.
                    lambda c, qh=q_half, kh=k_half, vh=v_half,
                    qo=q_off, ko=k_off: _block_attend(
                        qh, widen(kh), widen(vh), qo, ko, scale,
                        True, *c),
                    lambda c: c,
                    acc[tag],
                )
        if step != n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)

    outs = []
    for tag in ("a", "b"):
        m, l, o = acc[tag]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        outs.append(o / l_safe.swapaxes(1, 2)[..., None])
    return jnp.concatenate(outs, axis=1).astype(q_blk.dtype)
