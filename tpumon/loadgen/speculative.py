"""Speculative decoding: draft-propose + one-dispatch target verify.

Beyond-reference capability (the reference ships no serving code at all —
SURVEY §5.7; its README only *names* vLLM metric collection): tpumon's
in-tree serving engine (tpumon.loadgen.serving) gains the standard
latency optimization of production TPU serving stacks. A cheap draft
model proposes ``spec_len`` tokens autoregressively; the target model
scores all of them in ONE multi-token forward; the longest prefix the
target agrees with is accepted plus one bonus token from the target's
own distribution. Under greedy decoding the output matches plain decode
whatever the draft quality — only the number of target dispatches
changes. (Exactly so in deterministic dtypes, which the tests pin in
float32; under bfloat16 the block-shaped verify can reassociate
reductions differently from a [B, 1] step and flip an argmax near-tie.)

TPU-first design:
- ``decode_block`` is the verify kernel: advance every slot ``T`` tokens
  in one fused dispatch — the same batched cache-append/attention
  structure as ``decode_step`` but with a [B, T] token block, so the
  MXU sees a T-times-larger matmul instead of T serial launches. Jitted
  once per (B, T); T = spec_len+1 is static.
- rejection needs no cache rollback: K/V for rejected rows are written
  but the per-slot position pointer simply doesn't advance past the
  accepted frontier; attention masks rows ``> position`` and later
  appends overwrite stale rows in order (the same mechanism that makes
  slot reuse safe in the engine).
- mixed batches degrade gracefully: slots sampling with temperature > 0
  accept zero drafts and emit one token from the target's verified
  logits at their current position — exactly plain decode — while
  greedy slots in the same round still get multi-token acceptance.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def decode_block(cfg, params: dict, cache: dict, tokens: jax.Array,
                 positions: jax.Array) -> tuple[dict, jax.Array]:
    """Advance every slot ``T`` tokens in one dispatch.

    tokens: [B, T] int32 (token block per slot; tokens[:, 0] is the
    feed token at row ``positions``); positions: [B] int32 start rows.
    Returns (cache, logits [B, T, vocab]) where logits[:, t] predicts
    the token at row ``positions + t + 1``. Generalizes
    ``serving.decode_step`` (T == 1 produces identical logits); the
    serving engine uses it as the speculative verify step.
    """
    m = cfg.model
    dt = jnp.dtype(m.compute_dtype)
    b, t = tokens.shape
    pos = positions[:, None] + jnp.arange(t, dtype=jnp.int32)[None]  # [B, T]
    row = jnp.arange(m.max_seq, dtype=jnp.int32)
    # mask[b, 1, t, row]: row <= positions[b] + t — prior context plus
    # causal order within the block (same frontier rule as decode_step).
    mask = (row[None, None] <= pos[:, :, None])[:, None]  # [B, 1, T, S]

    from tpumon.loadgen.serving import decoder_forward

    def append(cache_l: jax.Array, kv: jax.Array, p: jax.Array) -> jax.Array:
        # cache_l: [S, nkv, hd]; kv: [T, nkv, hd] — contiguous T-row write.
        return lax.dynamic_update_slice(cache_l, kv, (p, 0, 0))

    def append_scale(scale_l: jax.Array, s: jax.Array, p: jax.Array) -> jax.Array:
        # scale_l: [S, nkv]; s: [T, nkv].
        return lax.dynamic_update_slice(scale_l, s, (p, 0))

    def kv_update(li, k, v):
        if "ks" in cache:  # int8 cache layout (serving.init_cache)
            from tpumon.loadgen.serving import _kv_dequant, _kv_quant

            (qk, sk), (qv, sv) = _kv_quant(k), _kv_quant(v)
            new_k = jax.vmap(append)(cache["k"][li], qk, positions)
            new_v = jax.vmap(append)(cache["v"][li], qv, positions)
            new_ks = jax.vmap(append_scale)(cache["ks"][li], sk, positions)
            new_vs = jax.vmap(append_scale)(cache["vs"][li], sv, positions)
            cache["k"] = cache["k"].at[li].set(new_k)
            cache["v"] = cache["v"].at[li].set(new_v)
            cache["ks"] = cache["ks"].at[li].set(new_ks)
            cache["vs"] = cache["vs"].at[li].set(new_vs)
            return (_kv_dequant(new_k, new_ks, k.dtype),
                    _kv_dequant(new_v, new_vs, v.dtype))
        new_k = jax.vmap(append)(cache["k"][li], k, positions)
        new_v = jax.vmap(append)(cache["v"][li], v, positions)
        cache["k"] = cache["k"].at[li].set(new_k)
        cache["v"] = cache["v"].at[li].set(new_v)
        return new_k, new_v  # [B, S, nkv, hd]

    x = decoder_forward(cfg, params, tokens, pos, mask, kv_update)
    logits = (x @ params["lm_head"].astype(dt)).astype(jnp.float32)
    return cache, logits


def greedy_accept_len(proposed: list[int], target: list[int]) -> int:
    """Longest prefix of draft proposals the target's greedy choice
    agrees with. proposed: the spec_len draft tokens for one slot;
    target: the target's argmax at each verified position (len
    spec_len+1; target[i] is what the target would emit after consuming
    proposed[:i])."""
    a = 0
    while a < len(proposed) and proposed[a] == target[a]:
        a += 1
    return a
