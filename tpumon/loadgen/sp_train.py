"""Sequence-parallel (sp) training: the full train step over a "seq" mesh.

Round-3 shipped ring attention as an *op* (loadgen.ring_attention);
this module makes sequence parallelism a *training mode*: activations
are sharded on the sequence axis end to end — embedding, norms, and
MLPs are per-token (trivially local), attention runs through the
ring/zigzag inner bodies inside one enclosing shard_map, and the loss
reduces with a psum. The only cross-chip traffic per layer is the K/V
ppermute ring — rotating the NARROW nkv-head K/V (GQA widening happens
locally after each receive) — the long-context layout the reference's
NCCL world has no counterpart for (SURVEY §5.7; the monitor observes
this traffic as ICI counters).

Design notes (TPU-first):
- one shard_map over the WHOLE loss: shard_map does not nest, so the
  attention uses ring_attend_inner / zigzag_attend_inner via
  model._attention's ``attn_core`` hook (one copy of the per-layer
  projection/RoPE/residual math for all schedules).
- positions travel as data: each row's GLOBAL position is passed in as
  a sharded array, so RoPE and the loss are layout-agnostic — the
  contiguous and zigzag layouts differ only in a host-side gather of
  (inputs, labels, positions) before the step. No layout logic inside
  the traced step.
- labels are pre-shifted on the host (labels = tokens[:, 1:] against
  inputs = tokens[:, :-1]) and sharded alongside the inputs, so no
  boundary exchange is needed for the shifted targets.
- grads: jax.grad through ppermute/cond transposes cleanly (pinned by
  tests/test_ring_attention.py grad tests); the layer body is
  checkpointed when cfg.remat is set, same as the dp×tp path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpumon.loadgen.model import ModelConfig, _attention, _mlp, _rms_norm
from tpumon.loadgen.ring_attention import (
    ring_attend_inner,
    zigzag_attend_inner,
    zigzag_indices,
)

SCHEDULES = ("ring", "zigzag")


def sp_batch(tokens: jax.Array, n: int, schedule: str):
    """Host-side prep: (inputs, labels, positions), layout-applied.

    tokens: [B, T+1]; n (ring) resp. 2n (zigzag) must divide T. Returns
    the three arrays to shard over the sequence axis. The layout MUST
    match the step's schedule — prefer the ``prep`` bound to the step
    by ``make_sp_train_step``, which can't mismatch.
    """
    if schedule not in SCHEDULES:
        raise ValueError(
            f"unknown sp schedule {schedule!r} (expected {SCHEDULES})")
    inputs, labels = tokens[:, :-1], tokens[:, 1:]
    t = inputs.shape[1]
    pos = jnp.arange(t, dtype=jnp.int32)
    if schedule == "zigzag":
        zi = zigzag_indices(t, n)
        inputs, labels, pos = inputs[:, zi], labels[:, zi], pos[zi]
    return inputs, labels, pos


def sp_loss_fn(
    cfg: ModelConfig,
    params: dict,
    inputs: jax.Array,
    labels: jax.Array,
    positions: jax.Array,
    mesh: Mesh,
    axis: str = "seq",
    schedule: str = "zigzag",
    dp_axis: str | None = None,
    tp_axis: str | None = None,
) -> jax.Array:
    """Mean next-token NLL with activations sharded over ``axis``.

    Composition (r05, pinning the make_sp_train_step promise):
    ``dp_axis`` additionally shards the BATCH dimension — a second
    manual mesh axis, with the loss psum running over both axes.
    ``tp_axis`` Megatron-shards the WEIGHTS over that mesh axis, left
    in shard_map "auto" mode (``axis_names`` excludes it): inside the
    body those arrays keep their global sharding and XLA inserts the
    tensor-parallel collectives declaratively, while the sp ring's
    ppermute stays manual over ``axis``. The caller device_puts params
    with model.param_shardings (which names the axis "model") — see
    make_sp_train_step.
    """
    if schedule not in SCHEDULES:
        raise ValueError(
            f"unknown sp schedule {schedule!r} (expected {SCHEDULES})")
    n = mesh.shape[axis]
    total = inputs.shape[0] * inputs.shape[1]
    kv_rep = cfg.n_heads // cfg.n_kv_heads
    manual = {axis} | ({dp_axis} if dp_axis else set())
    if tp_axis and tp_axis in manual:
        raise ValueError(f"tp_axis {tp_axis!r} must be distinct")
    reduce_axes = (dp_axis, axis) if dp_axis else (axis,)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(dp_axis, axis), P(dp_axis, axis), P(axis)),
        out_specs=P(),
        axis_names=frozenset(manual),
    )
    def run(p, inp, lab, pos):
        dt = jnp.dtype(cfg.compute_dtype)
        x = p["embed"].astype(dt)[inp]

        def core(q, k, v):
            if schedule == "zigzag":
                return zigzag_attend_inner(q, k, v, axis, n, kv_rep=kv_rep)
            return ring_attend_inner(q, k, v, axis, n, causal=True,
                                     kv_rep=kv_rep)

        def layer_block(x, layer):
            x = x + _attention(cfg, layer, _rms_norm(x, layer["attn_norm"]),
                               positions=pos, attn_core=core)
            return x + _mlp(layer, _rms_norm(x, layer["mlp_norm"]))

        blk = jax.checkpoint(layer_block) if cfg.remat else layer_block
        for layer in p["layers"]:
            x = blk(x, layer)
        x = _rms_norm(x, p["final_norm"])
        logits = (x @ p["lm_head"].astype(dt)).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, lab[..., None], axis=-1)[..., 0]
        # Every local row has a valid pre-shifted label; the mean is a
        # psum of local sums over the global token count (both manual
        # axes when dp composes; tp's vocab reductions are XLA's).
        return jax.lax.psum(jnp.sum(nll), reduce_axes) / total

    return run(params, inputs, labels, positions)


def make_sp_train_step(
    cfg: ModelConfig,
    mesh: Mesh,
    params: dict,
    axis: str = "seq",
    schedule: str = "zigzag",
    lr: float = 1e-3,
    dp_axis: str | None = None,
    tp_axis: str | None = None,
):
    """jit an SGD step over the seq mesh; returns (step_fn, placed).

    step_fn(params, inputs, labels, positions) -> (params, loss), with
    (inputs, labels, positions) from ``step_fn.prep(tokens)`` — prep is
    bound to this step's mesh size and schedule so the batch layout
    can't silently mismatch the traced step. Activations shard over
    ``axis``; params replicate unless ``tp_axis`` is given.

    Composition over a multi-axis mesh (pinned by
    tests/test_sp_train.py::test_dp_sp parity tests and the dryrun):
    ``dp_axis`` shards the batch (gradients all-reduce over it via the
    loss psum's transpose), ``tp_axis`` Megatron-shards the weights
    using model.PARAM_SPECS — that axis must be NAMED "model" (the
    declarative spec table is keyed on it), e.g.
    ``Mesh(devs.reshape(2, 2, 2), ("data", "model", "seq"))`` with
    ``dp_axis="data", tp_axis="model"`` for dp2 x tp2 x sp2.
    """
    if tp_axis and tp_axis != "model":
        raise ValueError(
            "tp_axis must be the mesh axis named 'model' — "
            "model.PARAM_SPECS (the Megatron split table) is keyed on "
            f"that name; got {tp_axis!r}")
    n = mesh.shape[axis]
    rep = NamedSharding(mesh, P())
    seq2 = NamedSharding(mesh, P(dp_axis, axis))
    seq1 = NamedSharding(mesh, P(axis))
    if tp_axis:
        from tpumon.loadgen.model import param_shardings

        p_shard = param_shardings(mesh, params)
    else:
        p_shard = jax.tree.map(lambda _: rep, params)
    placed = jax.device_put(params, p_shard)

    @partial(
        jax.jit,
        in_shardings=(p_shard, seq2, seq2, seq1),
        out_shardings=(p_shard, rep),
    )
    def step(p, inputs, labels, positions):
        loss, grads = jax.value_and_grad(
            lambda p_: sp_loss_fn(cfg, p_, inputs, labels, positions,
                                  mesh, axis, schedule,
                                  dp_axis=dp_axis, tp_axis=tp_axis)
        )(p)
        new = jax.tree_util.tree_map(lambda w, g: w - lr * g, p, grads)
        return new, loss

    step.prep = partial(sp_batch, n=n, schedule=schedule)
    return step, placed
