"""Paged KV cache mode for the serving engine.

Dense mode (serving.init_cache) reserves ``slots x max_seq`` KV rows
forever; a slot serving a 40-token request pins the same HBM as one
serving 4k tokens. Paged mode (beyond-reference; the reference ships no
serving code — SURVEY §5.7) allocates fixed-size pages from a shared
pool instead: a request pins ``ceil((prompt+max_new)/page_size)`` pages
for its lifetime and frees them on completion, so resident KV scales
with admitted work, not with the worst case. The pool can therefore be
sized well under ``slots x max_seq`` and admission blocks (requests
stay queued) when no pages are free — KV memory backpressure instead
of OOM.

TPU-first design:
- **page == prefill chunk**: each fixed-shape prefill call fills
  exactly one fresh page, so prefill needs no partial-page bookkeeping
  and pages never interleave requests.
- the pool is head-major ``[layers, kv_heads, num_pages, page, hd]``
  (the layout tpumon.ops.paged_attention established for TPU lowering);
  per-slot page tables are host-owned ints, shipped as one small
  ``[slots, max_pages]`` device array per step.
- decode attention has two read paths, selected by
  ``ServeConfig.paged_attn``: ``"gather"`` (default) lets XLA fuse the
  table gather into the attention einsum; ``"kernel"`` routes the T=1
  decode step through the Pallas kernel (tpumon.ops.paged_attention),
  which streams pages through VMEM via scalar-prefetched tables. The
  kernel wins at production scale — 1.49x on the full engine step at
  370M params / 16 slots x 4k context (bench ``paged_engine_step_*``),
  1.98x on the isolated op over a big fragmented pool — while gather
  wins at demo/test scale where the pool fits on-chip memory (the
  ServeConfig.paged_attn comment has the full regime map). Appends are
  one batched scatter at ``(page, offset)`` per slot in both paths.
- allocation is reservation-style (``ceil((prompt+max_new)/page_size)``
  pages claimed at admission — the last K/V row written is index
  ``prompt+max_new-1``; the final emitted token is never fed back, so
  no extra page is needed for it): the hot loop never allocates, and a
  mid-decode out-of-pages state cannot exist.

Composes with int8 weights/KV, sampling, streaming, prefix caching
(``PagePrefixCache`` below — pages of a cached prompt prefix are SHARED
into new requests' tables, refcounted, zero-copy), and speculative
decoding (``paged_decode_block`` is the verify step over the pool; the
shallow draft keeps its own dense cache).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


@dataclass
class PageAllocator:
    """Host-side refcounted free-list allocator over the shared pool.

    Pages are refcounted so the paged prefix cache can SHARE a cached
    prompt prefix's pages across requests (and pin them itself): alloc
    gives each page one reference, ``retain`` adds one per additional
    user, and ``release`` only returns a page to the free list when its
    last reference drops. Plain alloc/release pairs behave exactly as
    the unrefcounted r03 allocator did.
    """

    num_pages: int
    _free: list[int] = field(default_factory=list)
    _refs: dict = field(default_factory=dict)

    def __post_init__(self):
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._refs = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        """n fresh pages (refcount 1 each), or None if not enough free."""
        if n > len(self._free):
            return None
        taken = [self._free.pop() for _ in range(n)]
        for pg in taken:
            self._refs[pg] = 1
        return taken

    def retain(self, pages: list[int]) -> None:
        """Add a reference per page (a new sharer)."""
        for pg in pages:
            self._refs[pg] += 1

    def release(self, pages: list[int]) -> None:
        """Drop a reference per page; last reference frees the page."""
        for pg in pages:
            left = self._refs[pg] - 1
            if left:
                self._refs[pg] = left
            else:
                del self._refs[pg]
                self._free.append(pg)


def init_pool(cfg, num_pages: int) -> dict:
    m = cfg.model
    shape = (m.n_layers, m.n_kv_heads, num_pages, cfg.prefill_len,
             m.head_dim)
    if getattr(cfg, "kv_dtype", "compute") == "int8":
        # Quantized pool: int8 rows + per-(page-row, kv-head) f32 scales
        # (same scheme as the dense int8 cache, serving.init_cache).
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "ks": jnp.zeros(shape[:-1], jnp.float32),
            "vs": jnp.zeros(shape[:-1], jnp.float32),
        }
    dt = jnp.dtype(m.compute_dtype)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def paged_prefill(cfg, params: dict, pool: dict, tokens: jax.Array,
                  length: jax.Array, page_id: jax.Array,
                  table_row: jax.Array, start: jax.Array
                  ) -> tuple[dict, jax.Array]:
    """One prompt chunk into fresh page ``page_id`` of one sequence.

    tokens: [page_size] int32 padded chunk; length: true tokens in this
    chunk; page_id: the fresh page this chunk fills; table_row:
    [max_pages] int32 — the sequence's table with page_id already at
    position start//page_size (earlier entries are its earlier pages;
    later entries may be anything — masked); start: global row of the
    chunk's first token. Returns (pool, logits[vocab] at local position
    length-1). Mirrors serving.prefill's math over the paged layout.
    """
    m = cfg.model
    p = cfg.prefill_len  # == page_size
    dt = jnp.dtype(m.compute_dtype)
    nkv, hd = m.n_kv_heads, m.head_dim
    max_pages = table_row.shape[0]
    s_max = max_pages * p

    from tpumon.loadgen.serving import decoder_forward

    pos = start + jnp.arange(p, dtype=jnp.int32)[None]  # [1, P]
    row = jnp.arange(s_max, dtype=jnp.int32)
    mask = (row[None, :] <= pos[0][:, None])[None, None]  # [1,1,P,S]

    def kv_update(li, k, v):
        # Write the chunk into its fresh page, then attend over the
        # sequence's pages (this chunk's page included). "ks" present =
        # the int8 pool layout (init_pool) — trace-time branch.
        quant = "ks" in pool
        from tpumon.loadgen.serving import _kv_dequant, _kv_quant

        for name, sname, new in (("k", "ks", k), ("v", "vs", v)):
            if quant:
                new, scale = _kv_quant(new)
                sblock = scale[0].transpose(1, 0)[:, None]  # [nkv, 1, ps]
                pool[sname] = pool[sname].at[li].set(
                    lax.dynamic_update_slice(
                        pool[sname][li], sblock, (0, page_id, 0)))
            block = new[0].transpose(1, 0, 2)[:, None]  # [nkv, 1, ps, hd]
            pool[name] = pool[name].at[li].set(
                lax.dynamic_update_slice(
                    pool[name][li], block, (0, page_id, 0, 0)))
        ck = pool["k"][li][:, table_row]  # [nkv, max_pages, ps, hd]
        cv = pool["v"][li][:, table_row]
        if quant:
            ck = _kv_dequant(ck, pool["ks"][li][:, table_row], k.dtype)
            cv = _kv_dequant(cv, pool["vs"][li][:, table_row], v.dtype)
        ck = ck.reshape(nkv, s_max, hd).transpose(1, 0, 2)[None]
        cv = cv.reshape(nkv, s_max, hd).transpose(1, 0, 2)[None]
        return ck, cv  # [1, S, nkv, hd]

    x = decoder_forward(cfg, params, tokens[None], pos, mask, kv_update)
    last = lax.dynamic_index_in_dim(x[0], length - 1, axis=0, keepdims=False)
    logits = (last @ params["lm_head"].astype(dt)).astype(jnp.float32)
    return pool, logits


def paged_decode_step(cfg, params: dict, pool: dict,
                      last_tokens: jax.Array, positions: jax.Array,
                      tables: jax.Array) -> tuple[dict, jax.Array]:
    """Advance every slot one token over the paged pool.

    last_tokens/positions: [B] as in serving.decode_step; tables:
    [B, max_pages] int32 per-slot page tables. The new token's K/V is
    scattered to (tables[b, positions[b]//ps], positions[b]%ps); the
    page must already be reserved (reservation-style allocation).
    Returns (pool, logits [B, vocab]).

    ``cfg.paged_attn="kernel"`` swaps the XLA gather read for the
    Pallas paged-attention kernel (module docstring; the scatter-write
    is identical either way).
    """
    m = cfg.model
    ps = cfg.prefill_len
    dt = jnp.dtype(m.compute_dtype)
    nkv, hd = m.n_kv_heads, m.head_dim
    b, max_pages = tables.shape
    s_max = max_pages * ps

    from tpumon.loadgen.serving import decoder_forward

    page = jnp.take_along_axis(
        tables, (positions // ps)[:, None], axis=1)[:, 0]  # [B]
    off = positions % ps  # [B]
    pos = positions[:, None]
    row = jnp.arange(s_max, dtype=jnp.int32)
    mask = (row[None] <= positions[:, None])[:, None, None]  # [B,1,1,S]

    def scatter(li, k, v):
        # Batched scatter: pool[li, :, page[b], off[b]] = kv[b]. The
        # mixed basic/advanced index puts the broadcast batch dim FIRST,
        # so the update value is [B, nkv, hd] (no transpose — passing
        # [nkv, B, hd] would broadcast silently whenever nkv == B).
        quant = "ks" in pool  # int8 pool layout (init_pool)
        from tpumon.loadgen.serving import _kv_quant

        for name, sname, new in (("k", "ks", k), ("v", "vs", v)):
            if quant:
                new, scale = _kv_quant(new)
                pool[sname] = pool[sname].at[li, :, page, off].set(
                    scale[:, 0])
            pool[name] = pool[name].at[li, :, page, off].set(new[:, 0])

    def kv_update(li, k, v):
        from tpumon.loadgen.serving import _kv_dequant

        scatter(li, k, v)
        quant = "ks" in pool
        ck = pool["k"][li][:, tables]  # [nkv, B, max_pages, ps, hd]
        cv = pool["v"][li][:, tables]
        if quant:
            ck = _kv_dequant(ck, pool["ks"][li][:, tables], k.dtype)
            cv = _kv_dequant(cv, pool["vs"][li][:, tables], v.dtype)
        ck = ck.reshape(nkv, b, s_max, hd).transpose(1, 2, 0, 3)
        cv = cv.reshape(nkv, b, s_max, hd).transpose(1, 2, 0, 3)
        return ck, cv  # [B, S, nkv, hd]

    attend = None
    if getattr(cfg, "paged_attn", "gather") == "kernel":
        if "ks" in pool:
            # The kernel reads pool["k"]/pool["v"] raw — on an int8 pool
            # (init_pool with kv_dtype="int8") that means attending over
            # undequantized pages: garbage logits, no error. The engine
            # rejects the combination at init; direct callers must fail
            # just as loudly.
            raise ValueError(
                "paged_attn='kernel' cannot read a quantized (int8) pool; "
                "use the gather path or a compute-dtype pool"
            )
        from tpumon.ops.paged_attention import paged_attention

        # Trace-time backend check: interpret mode on CPU/virtual
        # devices (tests, dryrun), compiled Mosaic on real TPU.
        interpret = jax.default_backend() != "tpu"
        lengths = positions + 1  # rows 0..positions inclusive

        def attend(li, q, k, v):
            scatter(li, k, v)  # int8 pools also rejected above
            out = paged_attention(q[:, 0], pool["k"][li], pool["v"][li],
                                  tables, lengths, interpret=interpret)
            return out[:, None]  # [B, 1, nh, hd]
    elif getattr(cfg, "paged_attn", "gather") == "ring":
        if "ks" in pool:
            # Same failure mode as the kernel branch: the blockwise
            # reader pages pool["k"]/pool["v"] raw, so an int8 pool
            # would attend over undequantized garbage silently.
            raise ValueError(
                "paged_attn='ring' cannot read a quantized (int8) pool; "
                "use the gather path or a compute-dtype pool"
            )
        from tpumon.loadgen.ring_attention import paged_ring_decode_attend

        def attend(li, q, k, v):
            scatter(li, k, v)
            return paged_ring_decode_attend(
                pool["k"][li], pool["v"][li], q, tables, positions)

    x = decoder_forward(cfg, params, last_tokens[:, None], pos, mask,
                        kv_update, attend=attend)
    logits = (x[:, 0] @ params["lm_head"].astype(dt)).astype(jnp.float32)
    return pool, logits


def paged_decode_block(cfg, params: dict, pool: dict, tokens: jax.Array,
                       positions: jax.Array, tables: jax.Array
                       ) -> tuple[dict, jax.Array]:
    """Advance every slot ``T`` tokens in one dispatch over the paged
    pool — the paged twin of ``speculative.decode_block`` (the engine's
    speculative VERIFY step). tokens: [B, T] (tokens[:, 0] is the feed
    token at row ``positions``); returns (pool, logits [B, T, vocab])
    where logits[:, t] predicts the token at row positions + t + 1.

    Each of the T tokens' K/V scatters to its own (page, offset) via
    the slot's table, so a block may span a page boundary; overshooting
    a request's reserved rows lands on the trash page (same guard as
    paged_decode_rounds), and rejected draft rows are simply
    overwritten by later true tokens — identical rollback semantics to
    the dense verify.
    """
    m = cfg.model
    ps = cfg.prefill_len
    dt = jnp.dtype(m.compute_dtype)
    nkv, hd = m.n_kv_heads, m.head_dim
    b, t_blk = tokens.shape
    max_pages = tables.shape[1]
    s_max = max_pages * ps

    from tpumon.loadgen.serving import decoder_forward

    pos = positions[:, None] + jnp.arange(t_blk, dtype=jnp.int32)[None]
    pos = jnp.minimum(pos, s_max - 1)  # [B, T]
    page = jnp.take_along_axis(tables, pos // ps, axis=1)  # [B, T]
    off = pos % ps
    row = jnp.arange(s_max, dtype=jnp.int32)
    # Prior context plus causal order within the block (decode_block's
    # frontier rule).
    mask = (row[None, None] <= pos[:, :, None])[:, None]  # [B, 1, T, S]

    def kv_update(li, k, v):  # k/v: [B, T, nkv, hd]
        quant = "ks" in pool  # int8 pool layout (init_pool)
        from tpumon.loadgen.serving import _kv_dequant, _kv_quant

        for name, sname, new in (("k", "ks", k), ("v", "vs", v)):
            scale = None
            if quant:
                new, scale = _kv_quant(new)  # scale: [B, T, nkv]
            # One batched scatter per block position (T is small —
            # spec_len+1); same mixed basic/advanced indexing as
            # paged_decode_step, value [B, nkv, ...] batch-first.
            for tt in range(t_blk):
                if quant:
                    pool[sname] = pool[sname].at[
                        li, :, page[:, tt], off[:, tt]].set(scale[:, tt])
                pool[name] = pool[name].at[
                    li, :, page[:, tt], off[:, tt]].set(new[:, tt])
        ck = pool["k"][li][:, tables]  # [nkv, B, max_pages, ps, hd]
        cv = pool["v"][li][:, tables]
        if quant:
            ck = _kv_dequant(ck, pool["ks"][li][:, tables], k.dtype)
            cv = _kv_dequant(cv, pool["vs"][li][:, tables], v.dtype)
        ck = ck.reshape(nkv, b, s_max, hd).transpose(1, 2, 0, 3)
        cv = cv.reshape(nkv, b, s_max, hd).transpose(1, 2, 0, 3)
        return ck, cv  # [B, S, nkv, hd]

    x = decoder_forward(cfg, params, tokens, pos, mask, kv_update)
    logits = (x @ params["lm_head"].astype(dt)).astype(jnp.float32)
    return pool, logits


def paged_decode_rounds(cfg, params: dict, pool: dict,
                        last_tokens: jax.Array, positions: jax.Array,
                        tables: jax.Array, base_key: jax.Array,
                        rids: jax.Array, ctr0: jax.Array,
                        temps: jax.Array, topks: jax.Array, steps: int,
                        seq_cap: int = 0):
    """``steps`` (paged_decode_step -> sample) pairs in ONE dispatch —
    the paged twin of serving.decode_rounds (rids/ctr0 carry each
    request's (id, next token index) for the schedule-independent
    sampling keys). Tables are loop-invariant: pages are reserved for
    the whole request at admission, and trailing table entries point at
    the permanent trash page, so a block that overshoots a request's
    reserved rows writes harmlessly (the same guard that protects freed
    slots). ``seq_cap`` overrides the position clamp ceiling for ring
    layouts whose tables span more than ``cfg.model.max_seq`` rows (0 =
    the model's own max_seq). Returns (pool, last_tokens, positions,
    tokens [B, steps])."""
    from tpumon.loadgen.serving import sample_tokens

    cap = seq_cap or cfg.model.max_seq

    def body(carry, _):
        pool, last, pos, ctr = carry
        pool, logits = paged_decode_step(cfg, params, pool, last, pos, tables)
        nxt = sample_tokens(logits, base_key, rids, ctr, temps, topks)
        pos = jnp.minimum(pos + 1, cap - 1)
        return (pool, nxt, pos, ctr + 1), nxt

    (pool, last, pos, _), toks = lax.scan(
        body, (pool, last_tokens, positions, ctr0), None, length=steps)
    return pool, last, pos, toks.T


class PagePrefixCache:
    """Prefix caching for the paged layout: share pages, copy nothing.

    The dense prefix cache (tpumon.loadgen.prefix_cache) snapshots a
    prompt prefix's K/V rows and restores them with an HBM copy. Paged
    mode does strictly better: because page == prefill chunk, a
    chunk-aligned prompt prefix IS a whole number of pages, so a later
    prompt sharing the prefix just points its page table at the SAME
    pages (vLLM-style sharing) — zero HBM traffic, prefill elided for
    every shared chunk. The allocator's refcounts keep a shared page
    alive until its last user (cache entry or live request) drops it.

    Entries are keyed by the exact token tuple of the chunk-aligned
    STRICT prefix (the chunk holding the prompt's last token is always
    recomputed, so prefill still yields first-token logits — same
    contract as the dense cache). Bounded LRU; ``evict_one`` lets the
    engine reclaim pinned pages under pool pressure instead of
    deadlocking admission.
    """

    def __init__(self, chunk: int, allocator: PageAllocator,
                 max_entries: int = 16):
        from collections import OrderedDict

        self.chunk = chunk
        self.allocator = allocator
        self.max_entries = max_entries
        self._store: "OrderedDict[tuple, list[int]]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.saved_tokens = 0
        self.page_bytes = 0  # set by the engine (pool row bytes / page)

    def peek(self, prompt: list[int]) -> tuple[int, list[int]]:
        """Side-effect-free ``lookup``: (prefix_len, shared_pages) for
        the longest cached chunk-aligned strict prefix, WITHOUT
        retaining pages, touching the LRU order, or counting a hit or
        miss. The admission scheduler probes with this (a page-blocked
        queue head is re-probed every step — probes must leave no
        trace); ``lookup`` runs only when the admission actually
        happens. (0, []) on miss."""
        n = len(prompt)
        m = ((n - 1) // self.chunk) * self.chunk
        while m >= self.chunk:
            pages = self._store.get(tuple(prompt[:m]))
            if pages is not None:
                return m, list(pages)
            m -= self.chunk
        return 0, []

    def lookup(self, prompt: list[int]) -> tuple[int, list[int]]:
        """(prefix_len, shared_pages) for the longest cached
        chunk-aligned strict prefix; retains the pages for the caller
        (who must release them — normally at request completion).
        ``peek`` plus the accounting: LRU touch, page retain, hit/miss
        and saved-token counters. (0, []) on miss."""
        m, pages = self.peek(prompt)
        if not m:
            self.misses += 1
            return 0, []
        self._store.move_to_end(tuple(prompt[:m]))
        self.allocator.retain(pages)
        self.hits += 1
        self.saved_tokens += m
        return m, pages

    def store(self, prompt: list[int], pages: list[int]) -> None:
        """Pin the chunk-aligned strict prefix's pages (``pages`` is
        the request's full page list, one page per prefill chunk first).
        No-op if already cached or shorter than one chunk."""
        n = len(prompt)
        m = ((n - 1) // self.chunk) * self.chunk
        if m < self.chunk:
            return
        key = tuple(prompt[:m])
        if key in self._store:
            self._store.move_to_end(key)
            return
        pinned = pages[: m // self.chunk]
        self.allocator.retain(pinned)
        self._store[key] = list(pinned)
        while len(self._store) > self.max_entries:
            self.evict_one()

    def evict_one(self, protect: tuple | None = None) -> bool:
        """Drop the least-recently-used entry (its pages free once no
        live request shares them); False when nothing evictable.
        ``protect`` names one key that must survive — the admission
        scheduler passes the queue head's own peeked prefix so freeing
        pages FOR the head can't evict the very prefix it is about to
        share (the old lookup-first admission protected it by retaining
        + LRU-touching; the side-effect-free probe protects it by
        name)."""
        for key in self._store:
            if key != protect:
                pages = self._store.pop(key)
                self.allocator.release(pages)
                return True
        return False

    @property
    def entries(self) -> int:
        return len(self._store)

    def resident_bytes(self) -> int:
        pinned = {pg for pages in self._store.values() for pg in pages}
        return len(pinned) * self.page_bytes
