"""Orbax checkpoint/resume for loadgen & serving model params.

SURVEY §5.4: the reference has no checkpointing at all (its only state is
one in-memory dict, monitor_server.js:157). For the *monitor* tpumon
keeps the same stateless stance (tpumon.state is a warm-start snapshot);
for the *TPU workloads* the framework ships — the Llama-style loadgen
trainer and the JetStream-style serving engine — checkpoint/resume is a
real obligation, and is done the TPU-native way: orbax saves the jax
pytree with its shardings, and restore places leaves directly onto the
target `jax.sharding.Mesh` (each host restores only its shards; no
gather-to-host round trip).

Layout: one orbax StandardCheckpointer directory per step
(``<dir>/step_<n>``) plus a tiny ``meta.json`` naming the latest step and
the ModelConfig it was saved with, so resume can refuse a mismatched
architecture instead of loading garbage.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax

from tpumon.loadgen.model import ModelConfig

_META = "meta.json"


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"step_{step:08d}")


def save_checkpoint(
    directory: str, params: Any, step: int, cfg: ModelConfig | None = None
) -> str:
    """Save a params pytree at ``<directory>/step_<step>``; updates
    meta.json last so a crash mid-save never points latest at a partial
    checkpoint. Returns the step directory path."""
    os.makedirs(directory, exist_ok=True)
    path = _step_dir(directory, step)
    ckptr = _checkpointer()
    ckptr.save(path, params, force=True)
    ckptr.wait_until_finished()
    meta = {
        "latest_step": step,
        "model_config": dataclasses.asdict(cfg) if cfg is not None else None,
    }
    tmp = os.path.join(directory, _META + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(directory, _META))
    return path


def latest_step(directory: str) -> int | None:
    """The step named by meta.json, or None if no usable checkpoint."""
    try:
        with open(os.path.join(directory, _META)) as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    step = meta.get("latest_step")
    if not isinstance(step, int) or not os.path.isdir(_step_dir(directory, step)):
        return None
    return step


# Execution-schedule fields: they change memory/scheduling, never the
# parameter pytree, so differing values must not invalidate a resume
# (e.g. extending a run with --remat or --attention chunked).
_SCHEDULE_FIELDS = ("remat", "attention", "attn_block_k")


def _arch_key(cfg: ModelConfig) -> dict:
    import dataclasses as _dc

    d = _dc.asdict(cfg)
    for f in _SCHEDULE_FIELDS:
        d.pop(f, None)
    return d


def saved_model_config(directory: str) -> ModelConfig | None:
    try:
        with open(os.path.join(directory, _META)) as f:
            raw = json.load(f).get("model_config")
        return ModelConfig(**raw) if raw else None
    except (OSError, json.JSONDecodeError, TypeError):
        # TypeError: meta written by a build whose ModelConfig had
        # different fields — treat as no usable config, caller cold-starts.
        return None


def restore_checkpoint(
    directory: str,
    like: Any,
    step: int | None = None,
    cfg: ModelConfig | None = None,
) -> tuple[Any, int] | None:
    """Restore ``(params, step)`` from the latest (or given) step.

    ``like`` is a pytree of arrays or jax.ShapeDtypeStruct with the
    target shardings — orbax restores each leaf straight onto its
    devices. Returns None when there is nothing (or nothing compatible)
    to resume from; the caller then cold-starts, which keeps resume
    strictly best-effort like the rest of tpumon's degraded modes.
    """
    if step is None:
        step = latest_step(directory)
    if step is None:
        return None
    if cfg is not None:
        saved = saved_model_config(directory)
        if saved is not None and _arch_key(saved) != _arch_key(cfg):
            return None  # architecture changed under the checkpoint dir
    abstract = jax.tree.map(
        lambda x: x
        if isinstance(x, jax.ShapeDtypeStruct)
        else jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=_sharding_of(x)),
        like,
    )
    try:
        params = _checkpointer().restore(_step_dir(directory, step), abstract)
    except Exception:
        return None
    return params, step


def _sharding_of(x: Any):
    s = getattr(x, "sharding", None)
    # SingleDeviceShardings on a to-be-sharded tree would pin restore to
    # one device; let orbax pick placement instead.
    if s is not None and isinstance(s, jax.sharding.NamedSharding):
        return s
    return None
