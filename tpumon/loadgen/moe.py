"""Mixture-of-Experts FFN with expert parallelism (GShard-style).

Completes the loadgen's parallelism coverage: dp (data axis), tp
(Megatron splits in model.py), sp (ring_attention.py) — and ep here:
experts sharded over a mesh "expert" axis, tokens dispatched to them
with dense one-hot dispatch/combine einsums so XLA inserts the
all-to-all collectives over ICI (the reference pattern from
GShard/Switch: top-1 routing, fixed expert capacity, dropped overflow).

Everything is static-shaped and jit-friendly: routing uses cumsum of
one-hot assignments (no sorting, no dynamic shapes), capacity overflow
tokens pass through on the residual path (combine weights are zero for
them), and sharding is expressed with with_sharding_constraint only —
no hand-written collectives.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class MoEConfig:
    d_model: int = 64
    d_ff: int = 128
    n_experts: int = 8
    capacity_factor: float = 1.25

    def capacity(self, n_tokens: int) -> int:
        cap = int(self.capacity_factor * n_tokens / self.n_experts)
        return max(cap, 1)


def init_moe_params(cfg: MoEConfig, key: jax.Array) -> dict:
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = (1.0 / cfg.d_model) ** 0.5
    scale_out = (1.0 / cfg.d_ff) ** 0.5
    return {
        "router": jax.random.normal(k1, (cfg.d_model, cfg.n_experts), jnp.float32)
        * scale_in,
        "w_in": jax.random.normal(
            k2, (cfg.n_experts, cfg.d_model, cfg.d_ff), jnp.float32
        )
        * scale_in,
        "w_out": jax.random.normal(
            k3, (cfg.n_experts, cfg.d_ff, cfg.d_model), jnp.float32
        )
        * scale_out,
    }


MOE_PARAM_SPECS = {
    "router": P(None, None),
    "w_in": P("expert", None, None),
    "w_out": P("expert", None, None),
}


def moe_param_shardings(mesh: Mesh, params: dict):
    return {
        name: NamedSharding(mesh, MOE_PARAM_SPECS[name]) for name in params
    }


def _route(cfg: MoEConfig, router_w: jax.Array, x: jax.Array, capacity: int):
    """Top-1 routing with fixed capacity.

    x: [G, d]. Returns (dispatch [G, E, C] one-hot, combine [G, E, C]).
    """
    logits = x @ router_w  # [G, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # [G]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]  # [G]
    onehot = jax.nn.one_hot(expert, cfg.n_experts, dtype=jnp.float32)  # [G, E]
    # Position of each token within its expert's queue (arrival order):
    # (cumsum - 1) at the assigned column, zero elsewhere.
    position = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [G, E]
    pos_in_expert = jnp.sum(position, axis=-1)  # [G]
    kept = pos_in_expert < capacity
    pos_onehot = jax.nn.one_hot(
        pos_in_expert.astype(jnp.int32), capacity, dtype=jnp.float32
    )
    dispatch = onehot[:, :, None] * pos_onehot[:, None, :]  # [G, E, C]
    dispatch = dispatch * kept[:, None, None]
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def moe_ffn(
    cfg: MoEConfig,
    params: dict,
    x: jax.Array,
    mesh: Mesh | None = None,
    capacity: int | None = None,
) -> jax.Array:
    """x: [G, d_model] -> [G, d_model]; dropped tokens return zeros
    (callers add the residual). ``capacity`` overrides the
    capacity-factor default — the serving engine passes G (no drops)
    so routing is independent of batch SHAPE and every decode mode
    (step/block/spec-verify/paged) emits identical tokens."""
    g = x.shape[0]
    capacity = cfg.capacity(g) if capacity is None else capacity
    dispatch, combine = _route(cfg, params["router"], x, capacity)
    # Dispatch: [G, d] x [G, E, C] -> [E, C, d]. With tokens sharded over
    # "data" and experts over "expert", XLA lowers this to an all-to-all.
    expert_in = jnp.einsum("gd,gec->ecd", x, dispatch)
    if mesh is not None:
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P("expert", None, None))
        )
    h = jax.nn.relu(jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"]))
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w_out"])
    if mesh is not None:
        expert_out = jax.lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P("expert", None, None))
        )
    # Combine: [E, C, d] x [G, E, C] -> [G, d] (all-to-all back).
    out = jnp.einsum("ecd,gec->gd", expert_out, combine)
    if mesh is not None:
        out = jax.lax.with_sharding_constraint(
            out, NamedSharding(mesh, P("data", None))
        )
    return out


def make_sharded_moe_step(cfg: MoEConfig, mesh: Mesh, params: dict):
    """jit a forward+grad step over a (data, expert) mesh."""
    shardings = moe_param_shardings(mesh, params)
    placed = jax.device_put(params, shardings)
    x_sharding = NamedSharding(mesh, P("data", None))

    def loss(p, x):
        y = moe_ffn(cfg, p, x, mesh)
        return jnp.mean(jnp.square(y - x))  # autoencoding burn objective

    @partial(
        jax.jit,
        in_shardings=(shardings, x_sharding),
        out_shardings=(shardings, NamedSharding(mesh, P())),
    )
    def step(p, x):
        l, grads = jax.value_and_grad(loss)(p, x)
        new_p = jax.tree_util.tree_map(lambda w, g: w - 1e-2 * g, p, grads)
        return new_p, l

    return step, placed
