"""Weight-only int8 quantization for serving.

TPU decode is HBM-bandwidth-bound: every step streams all weights
through the MXU for one token per slot, so weight bytes ≈ step time.
Storing weights as int8 with a per-output-channel float scale halves
traffic vs bf16 (4× vs the f32 master weights) and cuts resident HBM
the same way — which the monitor's per-chip HBM% panel shows directly.

Design: ``QTensor`` is a registered pytree holding ``(q: int8, scale:
f32[out])`` whose ``.astype(dt)`` *dequantizes*. The serving kernels
(tpumon.loadgen.serving prefill/decode) only ever touch weights as
``x @ layer["w"].astype(dt)``, so quantized params drop in with no
kernel changes, and inside jit XLA fuses the dequant multiply into the
consuming matmul — the int8 array is what lives in and streams from
HBM. Symmetric per-output-channel scales keep the matmul error small
without zero-points (cheap on MXU, standard for weight-only quant).

For explicit control of the tiling/dequant schedule there is also a
hand-written Pallas kernel, ``tpumon.ops.quant_matmul.quantized_matmul``
— int8 tiles widened in VMEM, scale applied once to the f32 accumulator
at store — with the fused XLA path as its automatic fallback for
decode-sized batches. Slope-timed measurement (BENCH_NOTES.md) puts the
fused XLA path at or slightly above the kernel on v5e, so XLA fusion is
the production path and the kernel is the explicitly-scheduled variant;
``bench.py``'s kernels phase pins both every round.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

# Leaves never worth quantizing: tiny 1-D norm gains (quantizing them
# saves nothing and hurts), and the embedding table — its consumer is a
# gather, so dequant can't fuse into a matmul and XLA would materialize
# the whole dequantized table per step.
# "router": quantizing routing logits would silently perturb the
# argmax expert assignment — routing stays f32 (tiny weight anyway).
SKIP_NAMES = ("embed", "attn_norm", "mlp_norm", "final_norm", "router")


@jax.tree_util.register_pytree_node_class
@dataclass
class QTensor:
    """int8 weights + per-output-channel scale; dequantizes on astype."""

    q: jax.Array  # int8, [..., out]
    scale: jax.Array  # float32, [out]

    def astype(self, dt) -> jax.Array:
        return self.q.astype(dt) * self.scale.astype(dt)

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self) -> int:
        return self.q.nbytes + self.scale.nbytes

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)


def quantize(w: jax.Array) -> QTensor:
    """Symmetric per-output-channel (last axis) int8 quantization."""
    scale = jnp.max(jnp.abs(w), axis=tuple(range(w.ndim - 1))) / 127.0
    scale = jnp.maximum(scale, 1e-8)  # all-zero columns
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return QTensor(q=q, scale=scale.astype(jnp.float32))


def quantize_params(params, skip_names: tuple[str, ...] = SKIP_NAMES):
    """Quantize every >=2-D weight leaf except ``skip_names``."""

    def leaf(path, w):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in skip_names or getattr(w, "ndim", 0) < 2:
            return w
        return quantize(w)

    return jax.tree_util.tree_map_with_path(leaf, params)


def param_bytes(params) -> int:
    """Resident weight bytes (QTensor counts its int8 + scale)."""
    return sum(
        leaf.nbytes
        for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QTensor)
        )
    )
