"""Mini Llama-style decoder-only transformer in pure JAX.

Purpose (see tpumon.loadgen): a realistic, shardable TPU workload for
validating the monitoring pipeline and benchmarking scrape→render latency
under load. It mirrors the architecture family of the models the
north-star deployment serves (Llama-3 via JetStream, BASELINE config 4):
RMSNorm, rotary position embeddings, grouped-query attention, SwiGLU MLP,
untied LM head.

TPU-first design notes:
- all matmuls in bfloat16 with float32 accumulation (MXU-friendly),
  params kept in float32 for optimizer stability;
- static shapes, no data-dependent Python control flow — everything
  traces once under jit;
- parallelism is expressed with jax.sharding (Mesh + NamedSharding +
  with_sharding_constraint): data parallel over axis "data", tensor
  parallel over axis "model" (attention heads / FFN columns split),
  letting XLA insert the all-reduces over ICI. No hand-written
  collectives in the model body.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class ModelConfig:
    vocab: int = 512
    d_model: int = 256
    n_layers: int = 2
    n_heads: int = 8
    n_kv_heads: int = 4
    d_ff: int = 1024
    max_seq: int = 256
    rope_theta: float = 10000.0
    compute_dtype: str = "bfloat16"
    # Per-layer rematerialization (jax.checkpoint): the backward pass
    # recomputes each layer's activations instead of keeping them —
    # notably the [B, H, T, T] attention scores that otherwise dominate
    # training HBM (a d2048/L12/seq1024 model OOMs a 16 GiB v5e without
    # this and trains with it). ~1/3 extra forward FLOPs.
    remat: bool = False
    # Attention schedule: "naive" materializes [B, H, T, T] scores
    # (fastest at short seq); "chunked" streams K/V in attn_block_k-row
    # blocks with an online softmax (lax.scan, checkpointed body) —
    # peak attention memory O(T * block) instead of O(T^2), fully
    # differentiable, the long-context single-chip path (the multi-chip
    # counterpart is loadgen.ring_attention); "flash" runs BOTH passes
    # through the triangle-grid Pallas kernels
    # (tpumon.ops.flash_attention_tri_fwd / _tri_bwd — only
    # lower-diagonal block pairs are iterated or DMA'd; dQ accumulated
    # row-major, dK/dV column-major, P rebuilt from the saved lse;
    # attn_block_k sets the pair block size, T pads internally).
    # Measured r05 (BENCH_NOTES): "flash" WINS both bench shapes —
    # seq-8k 72.8% MFU without remat vs 45.0 for remat+chunked (the
    # kernel never materializes T^2, so the shape fits 16 GiB with
    # full residuals), and even seq-1024 55.5 -> 72.2% (naive's score
    # materialization traffic, not FLOPs, was the cost). "flash" is
    # the recommended single-chip TPU schedule; the default stays
    # "naive" only because CPU tests would crawl through interpret
    # mode. Under a dp x tp mesh flash compiles and matches exactly
    # (pinned by test) but the partitioner may replicate around the
    # kernel; multi-chip long-context stays sp_train ring/zigzag.
    attention: str = "naive"
    attn_block_k: int = 512
    # Mixture-of-Experts FFN (Mixtral-style model family): n_experts>0
    # replaces each layer's dense SwiGLU with a top-1 routed expert FFN
    # (loadgen.moe — GShard dispatch/combine einsums, fixed capacity,
    # dropped-overflow-to-residual). Works across training (dp x tp:
    # experts shard over the "model" axis via PARAM_SPECS) and the full
    # serving engine (decoder_forward routes per decoded token; decode
    # batches are small so capacity floors at 1 token/expert). 0 = the
    # dense Llama-style family.
    n_experts: int = 0
    moe_capacity_factor: float = 1.25

    def __post_init__(self) -> None:
        # Validate at construction (a typo'd schedule string silently
        # falling through to the naive path would defeat the point of
        # selecting the memory-saving one).
        if self.attention not in ("naive", "chunked", "flash"):
            raise ValueError(f"unknown attention schedule {self.attention!r}")
        if self.attn_block_k < 1:
            raise ValueError(f"attn_block_k must be >= 1, got {self.attn_block_k}")
        if self.n_experts < 0:
            raise ValueError(f"n_experts must be >= 0, got {self.n_experts}")

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    def abstract(self) -> "ModelConfig":
        assert self.n_heads % self.n_kv_heads == 0
        return self


def init_params(cfg: ModelConfig, key: jax.Array) -> dict:
    """Initialize a param pytree (float32 master weights)."""
    keys = iter(jax.random.split(key, 4 + 8 * cfg.n_layers))

    def dense(k, shape, scale=None):
        scale = scale if scale is not None else (1.0 / shape[0]) ** 0.5
        return (jax.random.normal(k, shape, jnp.float32) * scale)

    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    layers = []
    for _ in range(cfg.n_layers):
        layer = {
            "attn_norm": jnp.ones((cfg.d_model,), jnp.float32),
            "wq": dense(next(keys), (cfg.d_model, nh * hd)),
            "wk": dense(next(keys), (cfg.d_model, nkv * hd)),
            "wv": dense(next(keys), (cfg.d_model, nkv * hd)),
            "wo": dense(next(keys), (nh * hd, cfg.d_model)),
            "mlp_norm": jnp.ones((cfg.d_model,), jnp.float32),
        }
        if cfg.n_experts:
            from tpumon.loadgen.moe import MoEConfig, init_moe_params

            layer["moe"] = init_moe_params(
                MoEConfig(d_model=cfg.d_model, d_ff=cfg.d_ff,
                          n_experts=cfg.n_experts,
                          capacity_factor=cfg.moe_capacity_factor),
                next(keys))
        else:
            layer.update({
                "w_gate": dense(next(keys), (cfg.d_model, cfg.d_ff)),
                "w_up": dense(next(keys), (cfg.d_model, cfg.d_ff)),
                "w_down": dense(next(keys), (cfg.d_ff, cfg.d_model)),
            })
        layers.append(layer)
    return {
        "embed": dense(next(keys), (cfg.vocab, cfg.d_model), scale=0.02),
        "layers": layers,
        "final_norm": jnp.ones((cfg.d_model,), jnp.float32),
        "lm_head": dense(next(keys), (cfg.d_model, cfg.vocab)),
    }


# ---------------------------------------------------------------------------
# Sharding rules: tensor parallel over "model", replicated elsewhere.
# Column-parallel for wq/wk/wv/w_gate/w_up, row-parallel for wo/w_down —
# the standard Megatron-style split, expressed declaratively and applied
# by XLA (no explicit collectives).
# ---------------------------------------------------------------------------

PARAM_SPECS = {
    "embed": P(None, None),
    "final_norm": P(None),
    "lm_head": P(None, "model"),
    "attn_norm": P(None),
    "mlp_norm": P(None),
    "wq": P(None, "model"),
    "wk": P(None, "model"),
    "wv": P(None, "model"),
    "wo": P("model", None),
    "w_gate": P(None, "model"),
    "w_up": P(None, "model"),
    "w_down": P("model", None),
    # MoE family: experts sharded over the same mesh axis (expert
    # parallelism on the tp axis); the router replicates.
    "router": P(None, None),
    "w_in": P("model", None, None),
    "w_out": P("model", None, None),
}


def param_shardings(mesh: Mesh, params: dict):
    """Build a NamedSharding pytree matching ``params``.

    Handles quantized trees too (tpumon.loadgen.quant.QTensor): the int8
    ``q`` array keeps the full weight's layout, and the per-output-channel
    ``scale`` shards like the weight's last axis — so column-parallel
    weights get model-sharded scales and row-parallel weights replicated
    ones, with no resharding inside the dequantizing matmul.
    """

    def leaf_spec(path, _leaf):
        key = getattr(path[-1], "key", None)
        if isinstance(key, str):
            return NamedSharding(mesh, PARAM_SPECS.get(key, P()))
        # Flattened child of a custom node (QTensor): path[-2] names the
        # weight; child 0 is q, child 1 is scale.
        name = getattr(path[-2], "key", None)
        spec = PARAM_SPECS.get(name, P())
        if key == 0 or not len(spec):
            return NamedSharding(mesh, spec)
        return NamedSharding(mesh, P(spec[-1]))

    return jax.tree_util.tree_map_with_path(leaf_spec, params)


# ---------------------------------------------------------------------------
# Forward pass
# ---------------------------------------------------------------------------


def _constrain(x: jax.Array, mesh: Mesh | None, spec: P) -> jax.Array:
    """Apply a sharding constraint when running over a mesh; no-op on a
    single device (entry() compiles the same code mesh-less)."""
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight.astype(x.dtype)


def _rope(x: jax.Array, theta: float,
          positions: jax.Array | None = None) -> jax.Array:
    """Rotary embedding over the last dim; x: [B, T, H, D].

    ``positions`` [T] overrides the default 0..T-1 — sequence-parallel
    shards (loadgen.sp_train) pass each row's GLOBAL position, which for
    the zigzag layout is non-contiguous."""
    _, t, _, d = x.shape
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    if positions is None:
        positions = jnp.arange(t, dtype=jnp.float32)
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]  # [T, D/2]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


_NEG_INF = -1e30


def _chunked_attention_core(
    q: jax.Array, k: jax.Array, v: jax.Array, block_k: int
) -> jax.Array:
    """Causal attention with K/V streamed in blocks (online softmax).

    q/k/v: [B, T, H, D] (RoPE'd, GQA-repeated). A lax.scan over
    block_k-row K/V blocks accumulates through the SAME
    ``_block_attend`` update ring attention uses (one in-repo
    implementation of the online-softmax numerics; ring streams blocks
    across chips over ICI, this streams them through time on one chip).
    Peak transient is one [B, H, T, block_k] score block instead of the
    naive [B, H, T, T]; the body is checkpointed so the backward pass
    recomputes each block instead of storing its probabilities (without
    this the scan's saved residuals would add back the O(T^2) the
    schedule removes). Differentiable end to end — the training-side
    analogue of the inference flash kernel (tpumon.ops.flash_attention,
    forward-only).
    """
    from tpumon.loadgen.ring_attention import _block_attend

    b, t, h, d = q.shape
    dtype = q.dtype
    bk = block_k
    # 2D causal blocking, flash-attention structure in XLA. r03's
    # schedule streamed K/V blocks against the FULL q — every
    # (q row, k block) pair was computed and then causally masked, i.e.
    # T² work where the causal triangle needs T²/2, and the per-block
    # score transient was [B, H, T, block] (268 MB at seq 8192). Here q
    # is split into a few LARGE blocks (a static Python unroll), and
    # each q block's inner lax.scan runs only over the k blocks at or
    # below the diagonal — the trip count is static per q block, so the
    # skipped near-half of the blocks costs nothing, Mosaic pipelines
    # each scan normally (no lax.cond on the hot path — measured: a
    # cond-per-block variant starves the MXU on sub-5µs blocks), and q
    # blocks stay big enough to amortize per-step overheads.
    # Few big q blocks: overhead amortization vs causal skip. Swept on
    # hardware (r04, d2048/L6 seq-8192 training): nq 4/8/16 at bk 512
    # measured 42.3/44.1/43.1% MFU, bk 1024/256 lost — 8 is the knee.
    nq = min(8, -(-t // bk))
    bq = -(-t // (nq * bk)) * bk  # q block rows, a multiple of bk
    nq = -(-t // bq)
    if nq * bq - t:
        q = jnp.pad(q, ((0, 0), (0, nq * bq - t), (0, 0), (0, 0)))
    nk = -(-t // bk)
    if nk * bk - t:
        # Padded K rows have positions >= t > every real q position, so
        # the causal test masks them; padded q rows are sliced off.
        k = jnp.pad(k, ((0, 0), (0, nk * bk - t), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, nk * bk - t), (0, 0), (0, 0)))
    scale = 1.0 / d**0.5

    # Checkpointed per k-block: the backward pass recomputes each
    # block's probabilities instead of storing them (without this the
    # scan's residuals would re-add the O(T²) the schedule removes).
    @jax.checkpoint
    def k_body(q_i, q0, carry, kj):
        j, k_j, v_j = kj
        return _block_attend(q_i, k_j, v_j, q0, j * bk, scale, True,
                             *carry), ()

    outs = []
    for i in range(nq):
        q0 = i * bq
        q_i = q[:, q0:q0 + bq]
        # Causal horizon: rows < q0+bq only ever attend k rows < q0+bq,
        # so this q block's scan covers k blocks [0, nkj) — static.
        nkj = min(nk, -(-(q0 + bq) // bk))
        kb = k[:, :nkj * bk].reshape(b, nkj, bk, h, d).transpose(
            1, 0, 2, 3, 4)
        vb = v[:, :nkj * bk].reshape(b, nkj, bk, h, d).transpose(
            1, 0, 2, 3, 4)
        m0 = jnp.full((b, h, bq), float("-inf"), jnp.float32)
        l0 = jnp.zeros((b, h, bq), jnp.float32)
        o0 = jnp.zeros((b, bq, h, d), jnp.float32)
        (_, el, o), _ = lax.scan(
            partial(k_body, q_i, q0), (m0, l0, o0),
            (jnp.arange(nkj, dtype=jnp.int32), kb, vb))
        l_safe = jnp.where(el == 0.0, 1.0, el)
        outs.append((o / l_safe.swapaxes(1, 2)[..., None]).astype(dtype))
    return jnp.concatenate(outs, axis=1)[:, :t]


def _flash_block(block_k: int, t: int) -> int:
    """Triangle block size: follow attn_block_k (clamped to a 128
    multiple) — per-pair MXU work grows with block^2 while grid-step
    count shrinks with it, and sub-5 us pairs starve the MXU (the same
    knee BENCH_NOTES r04 measured for the jnp schedule). Also clamp
    DOWN to the 128-aligned sequence length: a short sequence must pad
    to one small block, not to a full 512-row pair."""
    blk = max(128, (block_k // 128) * 128)
    return min(blk, -(-t // 128) * 128)


def _flash_fwd(q, k, v, block_k):
    from tpumon.ops.flash_attention import flash_attention_tri_fwd

    b, t, h, d = q.shape
    blk = _flash_block(block_k, t)
    # Pad T up to the kernel's block grid. Safe under the causal mask:
    # padded K rows sit AFTER every real row so no real query attends
    # them; padded query rows produce garbage that is sliced off
    # below. (Training T is seq-1 = 8191 — never aligned.)
    tp = -(-t // blk) * blk
    if tp != t:
        pad = ((0, 0), (0, tp - t), (0, 0), (0, 0))
        q, k, v = jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad)

    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, tp, d)

    # Triangle-grid kernel: only lower-diagonal (q, k) block pairs are
    # iterated or DMA'd — T^2/2 work, matching the causal-skipping jnp
    # schedule's FLOP count (ops/flash_attention module docstring).
    out_p, lse = flash_attention_tri_fwd(
        fold(q), fold(k), fold(v), block=blk,
        interpret=jax.default_backend() != "tpu")
    out = out_p.reshape(b, h, tp, d).transpose(0, 2, 1, 3)[:, :t]
    # Residuals: q/k/v stay FOLDED/PADDED (the backward kernels consume
    # that layout directly), but the attention OUTPUT is saved as the
    # returned `out` — it is already live downstream for the wo-matmul
    # vjp, so saving out_p as well would keep a second full-size copy
    # per layer alive into the backward; bwd re-folds it instead (a
    # transpose is cheaper than ~32 MB/layer of duplicated residency
    # at the no-remat seq-8k shape). Beyond that, only lse (one f32
    # per row) exists.
    return out, (fold(q), fold(k), fold(v), out, lse)


def _flash_bwd(block_k, res, g):
    # Flash backward kernels (ops.flash_attention_tri_bwd): two
    # triangle passes rebuilding P from the saved lse — dQ accumulated
    # row-major, dK/dV column-major. No chunked-core recompute.
    from tpumon.ops.flash_attention import flash_attention_tri_bwd

    qf, kf, vf, out, lse = res
    b, t, h, d = g.shape
    bh, tp, _ = qf.shape

    def refold(x):
        # [B, t, H, D] -> folded/padded [BH, Tp, D]. Zero padding is
        # safe for BOTH re-folded tensors: padded rows of the cotangent
        # are 0 (so dK/dV take no contribution and the padded dQ rows
        # are sliced off), and the padded rows of `out` only enter
        # D_i = rowsum(dO ∘ O), which those zero dO rows annihilate.
        xf = x.transpose(0, 2, 1, 3).reshape(bh, t, d)
        if tp != t:
            xf = jnp.pad(xf, ((0, 0), (0, tp - t), (0, 0)))
        return xf

    dq, dk, dv = flash_attention_tri_bwd(
        qf, kf, vf, refold(out), lse, refold(g),
        block=_flash_block(block_k, t),
        interpret=jax.default_backend() != "tpu")

    def unfold(x):
        return x.reshape(b, h, tp, d).transpose(0, 2, 1, 3)[:, :t]

    return unfold(dq), unfold(dk), unfold(dv)


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_attention_core(q, k, v, block_k):
    """Causal attention via the triangle-grid Pallas kernels: fwd
    through flash_attention_tri_fwd, bwd through the two-pass
    flash_attention_tri_bwd (P rebuilt from the saved lse).
    q/k/v: [B, T, H, D], GQA-widened."""
    return _flash_fwd(q, k, v, block_k)[0]


_flash_attention_core.defvjp(_flash_fwd, _flash_bwd)


def _attention(
    cfg: ModelConfig,
    layer: dict,
    x: jax.Array,
    mesh: Mesh | None = None,
    positions: jax.Array | None = None,
    attn_core=None,
) -> jax.Array:
    """One attention sublayer (projections + RoPE + core + wo).

    ``attn_core(q, k, v) -> [B, T, H, D]`` replaces the built-in
    naive/chunked core and receives the UNREPEATED nkv-head K/V (the
    core owns GQA widening — the sp path repeats locally after each
    ring receive so the ppermute stays narrow)."""
    b, t, _ = x.shape
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    dt = x.dtype
    q = (x @ layer["wq"].astype(dt)).reshape(b, t, nh, hd)
    k = (x @ layer["wk"].astype(dt)).reshape(b, t, nkv, hd)
    v = (x @ layer["wv"].astype(dt)).reshape(b, t, nkv, hd)
    q = _rope(q, cfg.rope_theta, positions=positions)
    k = _rope(k, cfg.rope_theta, positions=positions)
    if attn_core is not None:
        out = attn_core(q, k, v).reshape(b, t, nh * hd)
        return out @ layer["wo"].astype(dt)
    # Grouped-query attention: repeat kv heads.
    if nkv != nh:
        rep = nh // nkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if cfg.attention == "flash":
        out = _flash_attention_core(q, k, v, cfg.attn_block_k)
        out = out.reshape(b, t, nh * hd)
    elif cfg.attention == "chunked" and t > cfg.attn_block_k:
        out = _chunked_attention_core(q, k, v, cfg.attn_block_k)
        out = out.reshape(b, t, nh * hd)
    else:
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / (
            hd**0.5)
        causal = jnp.tril(jnp.ones((t, t), bool))
        scores = jnp.where(causal[None, None], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1).astype(dt)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, t, nh * hd)
    out = _constrain(out, mesh, P("data", None, "model"))
    return out @ layer["wo"].astype(dt)


def _mlp(layer: dict, x: jax.Array, mesh: Mesh | None = None,
         cfg: ModelConfig | None = None) -> jax.Array:
    dt = x.dtype
    if "moe" in layer:
        return _moe_mlp(cfg, layer["moe"], x)
    h = jax.nn.silu(x @ layer["w_gate"].astype(dt)) * (x @ layer["w_up"].astype(dt))
    h = _constrain(h, mesh, P("data", None, "model"))
    return h @ layer["w_down"].astype(dt)


def _moe_mlp(cfg: ModelConfig, moe_params: dict, x: jax.Array,
             full_capacity: bool = False) -> jax.Array:
    """Routed expert FFN over [B, T, D]: flattens tokens, routes
    through loadgen.moe.moe_ffn (top-1, fixed capacity, dropped tokens
    ride the residual), restores shape.
    Sharding is declarative: expert weights carry PARAM_SPECS
    placements and XLA inserts the dispatch/combine all-to-alls."""
    from tpumon.loadgen.moe import MoEConfig, moe_ffn

    if cfg is None:
        raise ValueError(
            "MoE layers need the ModelConfig at the _mlp call site; the "
            "sp_train and pipeline paths run the dense family only "
            "(their callers don't thread cfg — extend them before "
            "training MoE there)")
    b, t, d = x.shape
    mcfg = MoEConfig(d_model=cfg.d_model, d_ff=cfg.d_ff,
                     n_experts=cfg.n_experts,
                     capacity_factor=cfg.moe_capacity_factor)
    dt = x.dtype
    params = {k: v.astype(dt) if k != "router" else v
              for k, v in moe_params.items()}
    out = moe_ffn(mcfg, params, x.reshape(b * t, d).astype(dt),
                  capacity=b * t if full_capacity else None)
    return out.reshape(b, t, d).astype(dt)


def forward(
    cfg: ModelConfig, params: dict, tokens: jax.Array, mesh: Mesh | None = None
) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, vocab] float32."""
    dt = jnp.dtype(cfg.compute_dtype)
    x = params["embed"].astype(dt)[tokens]
    x = _constrain(x, mesh, P("data", None, None))

    def layer_block(x, layer):
        x = x + _attention(cfg, layer, _rms_norm(x, layer["attn_norm"]), mesh)
        return x + _mlp(layer, _rms_norm(x, layer["mlp_norm"]), mesh,
                        cfg=cfg)

    if cfg.remat:
        layer_block = jax.checkpoint(layer_block)
    for layer in params["layers"]:
        x = layer_block(x, layer)
    x = _rms_norm(x, params["final_norm"])
    return (x @ params["lm_head"].astype(dt)).astype(jnp.float32)


def next_token_nll(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean cross-entropy of [B, T, V] logits against [B, T] targets.

    Shared by the sequential (here) and pipelined (pipeline.py) paths so
    the loss definition can't diverge between them."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def loss_fn(
    cfg: ModelConfig, params: dict, tokens: jax.Array, mesh: Mesh | None = None
) -> jax.Array:
    """Next-token cross-entropy over a [B, T] batch."""
    logits = forward(cfg, params, tokens[:, :-1], mesh)
    return next_token_nll(logits, tokens[:, 1:])


def sgd_train_step(
    cfg: ModelConfig,
    params: dict,
    tokens: jax.Array,
    lr: float = 1e-3,
    mesh: Mesh | None = None,
) -> tuple[dict, jax.Array]:
    """One SGD step (kept optimizer-trivial: the workload exists to light
    up MXU/HBM/ICI, not to converge)."""
    loss, grads = jax.value_and_grad(partial(loss_fn, cfg))(params, tokens, mesh)
    new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    return new_params, loss


def _check_moe_tp(cfg: ModelConfig, mesh: Mesh) -> None:
    """Experts shard over the "model" axis (PARAM_SPECS), so the expert
    count must divide it — validate here instead of letting device_put
    raise an opaque low-level dimension error."""
    tp = mesh.shape.get("model", 1) if hasattr(mesh, "shape") else 1
    if cfg.n_experts and cfg.n_experts % tp:
        raise ValueError(
            f"n_experts={cfg.n_experts} must be divisible by the mesh's "
            f"'model' axis ({tp}) — experts shard over it")


def replica_meshes(dp: int, tp: int, dense: bool = False,
                   devices=None) -> list:
    """Carve the device set into ``dp`` disjoint tensor-parallel
    submeshes for mesh serving (one per data-parallel replica). Each
    entry is the replica's Mesh over its own ``tp`` contiguous devices
    — contiguous so a replica's tp ring stays on neighboring chips
    (ICI locality on real slices) — or None when tp == 1 (a plain
    single-device engine needs no mesh at all). ``dense`` picks the
    dense serving path's ("data", "model") axis names (a degenerate
    data axis of 1: data parallelism lives at the replica level here,
    never inside one engine); paged serving is tensor-parallel only
    and uses a bare ("model",) axis. Raises ValueError when dp*tp
    does not tile the device count — the caller's config error, named
    here once so both CLIs report the same text."""
    import numpy as np

    devices = list(jax.devices() if devices is None else devices)
    ndev = len(devices)
    if dp < 1 or tp < 1:
        raise ValueError(f"mesh shape dp×tp must be >= 1x1, got {dp}x{tp}")
    if dp * tp > ndev or ndev % (dp * tp):
        raise ValueError(
            f"mesh shape dp×tp = {dp}x{tp} needs {dp * tp} devices but "
            f"{ndev} are visible — dp*tp must divide the device count")
    out = []
    for d in range(dp):
        devs = devices[d * tp:(d + 1) * tp]
        if tp == 1:
            out.append(None)
        elif dense:
            out.append(Mesh(np.array(devs).reshape(1, tp),
                            ("data", "model")))
        else:
            out.append(Mesh(np.array(devs), ("model",)))
    return out


def make_sharded_train_step(cfg: ModelConfig, mesh: Mesh, params: dict):
    """jit the train step over a dp×tp mesh; returns (step_fn, placed_params).

    Token batches are sharded over "data"; params per PARAM_SPECS. XLA
    derives the psum/all-reduce pattern (gradients over "data", activation
    reductions over "model") and routes them over ICI.
    """
    _check_moe_tp(cfg, mesh)
    shardings = param_shardings(mesh, params)
    placed = jax.device_put(params, shardings)
    token_sharding = NamedSharding(mesh, P("data", None))

    @partial(
        jax.jit,
        in_shardings=(shardings, token_sharding),
        out_shardings=(shardings, NamedSharding(mesh, P())),
    )
    def step(p, tokens):
        return sgd_train_step(cfg, p, tokens, mesh=mesh)

    return step, placed
