"""Workload-side self-reporting: publish HBM footprint + device activity.

Counterpart of ``tpumon.collectors.workload`` (see there for why this
exists and the provenance contract). A workload wraps its device work::

    reporter = WorkloadReporter(name="train")
    reporter.start()
    ...
    with reporter.device_work():
        out = jitted_step(...)   # blocking device execution
    ...
    reporter.stop()

and a background thread writes a report file every ``interval_s``:

- ``hbm_used``: the process's live device buffers (``jax.live_arrays``),
  attributed per device — ground truth for this process's footprint,
  regardless of whether the platform exposes an HBM counter.
- ``busy_frac``: fraction of the last interval spent inside
  ``device_work()`` blocks — the workload's own duty-cycle proxy. On a
  remote-execution tunnel this includes dispatch RTT; it is labeled
  ``source: workload`` downstream precisely because it is the
  workload's *declared* activity, not a hardware counter.
"""

from __future__ import annotations

import contextlib
import threading
import time

from tpumon.collectors.workload import (
    DEFAULT_DIR,
    remove_report,
    write_report,
)


def _device_index(d) -> int:
    """Stable per-host device index, matching accel_jax's chip indexing
    (local_hardware_id when present, else the global id)."""
    idx = getattr(d, "local_hardware_id", None)
    return int(idx if idx is not None else d.id)


def footprint_by_device() -> dict[int, dict]:
    """Live device-buffer bytes per device index for this process.

    Per-device attribution uses ``addressable_shards`` (each shard's
    actual bytes on its device — a replicated array occupies its full
    nbytes on EVERY device, which an even split would undercount by the
    device count); arrays without shard info fall back to an even split.
    """
    import jax

    out: dict[int, dict] = {}

    def charge(idx: int, nbytes: float) -> None:
        ent = out.setdefault(idx, {"hbm_used": 0, "hbm_total": None})
        ent["hbm_used"] = int(ent["hbm_used"] + nbytes)

    for arr in jax.live_arrays():
        try:
            shards = getattr(arr, "addressable_shards", None) or []
            charged = False
            for sh in shards:
                nb = int(getattr(sh.data, "nbytes", 0) or 0)
                if nb:
                    charge(_device_index(sh.device), nb)
                    charged = True
            if not charged:
                devs = list(arr.devices())
                if devs:
                    for d in devs:
                        charge(_device_index(d), int(arr.nbytes) / len(devs))
        except Exception:
            continue
    # Every local device reports, even with zero live buffers — the
    # monitor needs an explicit 0 baseline, not absence (a SKIPped
    # check and a passing one differ exactly here). hbm_total via PJRT
    # where available (absent on tunneled dev chips). Per-device
    # try/except: one raising memory_stats() must not cost the other
    # devices their baseline entries.
    try:
        devices = jax.local_devices()
    except Exception:
        devices = []
    for d in devices:
        try:
            ent = out.setdefault(
                _device_index(d), {"hbm_used": 0, "hbm_total": None}
            )
            stats = d.memory_stats() or {}
            limit = stats.get("bytes_limit")
            if limit:
                ent["hbm_total"] = int(limit)
        except Exception:
            continue
    return out


class WorkloadReporter:
    """Background self-report writer; safe to start/stop repeatedly."""

    def __init__(
        self,
        name: str = "loadgen",
        directory: str | None = None,
        interval_s: float = 1.0,
    ) -> None:
        self.name = name
        self.directory = directory or DEFAULT_DIR
        self.interval_s = interval_s
        self._busy_s = 0.0
        # Open device_work intervals keyed by thread ident: one reporter
        # may be shared by several worker threads (the serving engine's
        # streams), and a single slot would let them overwrite each
        # other's start stamp and undercount busy time.
        self._busy_since: dict[int, float] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ---- activity accounting ----

    @contextlib.contextmanager
    def device_work(self):
        """Mark device-busy time. Concurrent blocks from different
        threads each get their own interval; overlapping intervals sum
        (busy_frac is clamped to 1.0 downstream), matching "any thread
        kept the device busy" semantics."""
        ident = threading.get_ident()
        with self._lock:
            self._busy_since[ident] = time.monotonic()
        try:
            yield
        finally:
            t1 = time.monotonic()
            with self._lock:
                # Charge from the stored stamp, not the block start: a
                # drain mid-block already counted the earlier slice and
                # advanced the stamp (charging from t0 would double-
                # count the whole block on exit).
                since = self._busy_since.pop(ident, None)
                if since is not None:
                    self._busy_s += t1 - since

    def _drain_busy(self, now: float) -> float:
        """Busy seconds accumulated since the last drain, counting a
        still-open device_work block up to ``now`` (a workload inside a
        long fused scan must read busy, not idle, mid-block)."""
        with self._lock:
            busy = self._busy_s
            self._busy_s = 0.0
            for ident, since in self._busy_since.items():
                busy += now - since
                self._busy_since[ident] = now
        return busy

    # ---- report loop ----

    def write_once(self, interval_s: float | None = None) -> str:
        """One report write (also the unit the tests drive directly)."""
        now = time.monotonic()
        interval = interval_s if interval_s is not None else self.interval_s
        busy = self._drain_busy(now)
        frac = max(0.0, min(1.0, busy / interval)) if interval > 0 else 0.0
        devices = []
        for idx, ent in sorted(footprint_by_device().items()):
            devices.append(
                {
                    "index": idx,
                    "hbm_used": ent["hbm_used"],
                    "hbm_total": ent["hbm_total"],
                    "busy_frac": round(frac, 4),
                }
            )
        return write_report(self.directory, self.name, devices)

    def _loop(self) -> None:
        last = time.monotonic()
        while not self._stop.wait(self.interval_s):
            now = time.monotonic()
            try:
                self.write_once(interval_s=max(1e-3, now - last))
            except Exception:
                pass  # reporting must never take down the workload
            last = now

    def start(self) -> "WorkloadReporter":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name=f"tpumon-report-{self.name}", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        remove_report(self.directory, self.name)

    def __enter__(self) -> "WorkloadReporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
