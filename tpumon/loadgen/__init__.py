"""Load-generation / monitoring-validation workloads.

The reference has no way to *exercise* the accelerators it monitors; on
NVIDIA stacks that role is played by out-of-tree tools (dcgmproftester).
tpumon ships an in-tree, TPU-native equivalent: a small Llama-style
transformer (tpumon.loadgen.model) and targeted burn kernels
(tpumon.loadgen.burn) that drive the MXU, HBM and ICI so the monitoring
pipeline can be validated end-to-end on real hardware — and so bench.py
measures scrape→render latency while the chip is actually busy.

Everything here is written jit-first: static shapes, lax control flow,
bfloat16 matmuls for the MXU, sharding via jax.sharding.Mesh +
NamedSharding so the same step runs single-chip or over a multi-host
dp×tp mesh.
"""
