"""Pipeline parallelism: GPipe-style microbatched stages over the mesh.

Completes the loadgen's parallelism coverage alongside dp/tp (model.py),
ep (moe.py) and sp (ring_attention.py): the transformer stack is split
into S stages sharded over a mesh "pipe" axis, and a batch is fed
through as M microbatches. Each tick every stage applies its layers to
the activation it holds and hands the result to the next stage with
``lax.ppermute`` — the activation hand-off rides the ICI ring, exactly
the traffic pattern tpumon's ICI panels monitor for pipelined training
jobs (the reference monitors only flat per-device GPU counters,
monitor_server.js:83-95; slice/pipeline topology is the TPU-native
extension, SURVEY §2.5).

TPU-first notes:
- the schedule is a single ``lax.scan`` over M + S - 1 ticks — static
  trip count, no data-dependent control flow, traced once under jit;
- per-stage layers are stacked leaves scanned with ``lax.scan`` (one
  compiled block body regardless of depth);
- bubble overhead is the standard GPipe (S-1)/(M+S-1) — callers pick
  M >= S to keep MXU duty high, and the monitor's MXU panel is how you
  see it;
- backward needs no hand-written schedule: AD transposes ``ppermute``
  into the reverse ring rotation, so the cooldown phase emerges from
  the same scan.

Composes with data parallelism: the mesh is ("data", "pipe"); the
microbatch batch dim shards over "data", stages over "pipe".
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpumon.loadgen.model import (
    ModelConfig,
    _attention,
    _mlp,
    _rms_norm,
    init_params,
    next_token_nll,
)


@dataclass(frozen=True)
class PipelineConfig:
    model: ModelConfig = ModelConfig()
    n_stages: int = 2
    n_microbatches: int = 4

    def check(self) -> "PipelineConfig":
        assert self.model.n_layers % self.n_stages == 0, (
            f"n_layers={self.model.n_layers} must divide into "
            f"n_stages={self.n_stages}"
        )
        assert self.n_microbatches >= 1
        return self


def stack_pipeline_params(cfg: PipelineConfig, params: dict) -> dict:
    """Regroup a model.init_params tree for the pipeline.

    The per-layer dicts become stacked leaves of shape
    [n_stages, layers_per_stage, ...] so stage s owns layers
    [s*Lps, (s+1)*Lps) and scans them in order. Embed/head/final-norm
    stay top-level (they run outside the shard_map, replicated).
    """
    cfg.check()
    layers = params["layers"]
    lps = cfg.model.n_layers // cfg.n_stages
    stacked = {
        key: jnp.stack(
            [
                jnp.stack([layers[s * lps + j][key] for j in range(lps)])
                for s in range(cfg.n_stages)
            ]
        )
        for key in layers[0]
    }
    return {
        "embed": params["embed"],
        "stages": stacked,
        "final_norm": params["final_norm"],
        "lm_head": params["lm_head"],
    }


def init_pipeline_params(cfg: PipelineConfig, key: jax.Array) -> dict:
    return stack_pipeline_params(cfg, init_params(cfg.model, key))


def pipeline_param_shardings(mesh: Mesh, params: dict):
    """Stage leaves shard over "pipe" on their leading axis; the
    embedding/head ends are replicated (they run on every device)."""

    def spec(path, leaf):
        if getattr(path[0], "key", None) == "stages":
            return NamedSharding(mesh, P("pipe", *([None] * (leaf.ndim - 1))))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(spec, params)


def _stage_apply(cfg: ModelConfig, stage: dict, x: jax.Array) -> jax.Array:
    """Run one stage's stacked layers (leaves [Lps, ...]) over x."""

    def body(h, layer):
        h = h + _attention(cfg, layer, _rms_norm(h, layer["attn_norm"]))
        h = h + _mlp(layer, _rms_norm(h, layer["mlp_norm"]))
        return h, None

    x, _ = jax.lax.scan(body, x, stage)
    return x


def pipeline_forward(
    cfg: PipelineConfig, params: dict, tokens: jax.Array, mesh: Mesh
) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, vocab] float32.

    B must equal n_microbatches * microbatch size, and the microbatch
    size must divide by the mesh's "data" axis.
    """
    cfg.check()
    mcfg = cfg.model
    s_count, m_count = cfg.n_stages, cfg.n_microbatches
    b, t = tokens.shape
    assert b % m_count == 0, f"batch {b} not divisible by M={m_count}"
    mb = b // m_count
    dp = mesh.shape["data"]
    assert mb % dp == 0, f"microbatch size {mb} not divisible by dp={dp}"
    dt = jnp.dtype(mcfg.compute_dtype)

    # Embed outside the pipeline (replicated — it's the stage-0 input
    # producer and tiny next to the stack).
    x = params["embed"].astype(dt)[tokens].reshape(m_count, mb, t, mcfg.d_model)

    stage_specs = jax.tree.map(
        lambda a: P("pipe", *([None] * (a.ndim - 1))), params["stages"]
    )
    x_spec = P(None, "data", None, None)
    perm = [(i, (i + 1) % s_count) for i in range(s_count)]

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(stage_specs, x_spec),
        out_specs=x_spec,
    )
    def run(stages, xs):
        # Local views: stage leaves [1, Lps, ...] -> [Lps, ...];
        # xs [M, mb/dp, T, D].
        stages = jax.tree.map(lambda a: a[0], stages)
        my = jax.lax.axis_index("pipe")
        # The carries become device-varying over "pipe" after one tick;
        # mark the (all-zero) initial values the same way so the scan
        # carry type is stable.
        state = jax.lax.pcast(jnp.zeros_like(xs[0]), ("pipe",), to="varying")
        outbuf = jax.lax.pcast(jnp.zeros_like(xs), ("pipe",), to="varying")

        def tick(carry, i):
            state, outbuf = carry
            # Stage 0 picks up microbatch i during warm-up; later stages
            # consume what the previous stage permuted over last tick.
            fresh = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(i, 0, m_count - 1), 0, keepdims=False
            )
            x_in = jnp.where(my == 0, fresh, state)
            y = _stage_apply(mcfg, stages, x_in)
            # The last stage finishes microbatch i-(S-1) at tick i.
            out_i = i - (s_count - 1)
            slot = jnp.clip(out_i, 0, m_count - 1)
            cur = jax.lax.dynamic_index_in_dim(outbuf, slot, 0, keepdims=False)
            write = jnp.where((my == s_count - 1) & (out_i >= 0), y, cur)
            outbuf = jax.lax.dynamic_update_index_in_dim(outbuf, write, slot, 0)
            # Hand activations to the next stage over the ICI ring.
            state = jax.lax.ppermute(y, "pipe", perm)
            return (state, outbuf), None

        (state, outbuf), _ = jax.lax.scan(
            tick, (state, outbuf), jnp.arange(m_count + s_count - 1)
        )
        # Only the last stage holds real outputs; one masked psum at
        # pipeline flush broadcasts them back to every stage.
        outbuf = jnp.where(my == s_count - 1, outbuf, 0.0)
        return jax.lax.psum(outbuf, "pipe")

    x = run(params["stages"], x).reshape(b, t, mcfg.d_model)
    x = _rms_norm(x, params["final_norm"])
    return (x @ params["lm_head"].astype(dt)).astype(jnp.float32)


def pipeline_loss(
    cfg: PipelineConfig, params: dict, tokens: jax.Array, mesh: Mesh
) -> jax.Array:
    logits = pipeline_forward(cfg, params, tokens[:, :-1], mesh)
    return next_token_nll(logits, tokens[:, 1:])


def make_pipeline_train_step(cfg: PipelineConfig, mesh: Mesh, params: dict):
    """jit one SGD step over a (data, pipe) mesh; returns (step, placed).

    ``params`` is a stacked tree (init_pipeline_params /
    stack_pipeline_params output).
    """
    shardings = pipeline_param_shardings(mesh, params)
    placed = jax.device_put(params, shardings)
    token_sharding = NamedSharding(mesh, P("data", None))

    @partial(
        jax.jit,
        in_shardings=(shardings, token_sharding),
        out_shardings=(shardings, NamedSharding(mesh, P())),
    )
    def step(p, tokens):
        loss, grads = jax.value_and_grad(partial(pipeline_loss, cfg))(
            p, tokens, mesh
        )
        new_p = jax.tree_util.tree_map(lambda w, g: w - 1e-3 * g, p, grads)
        return new_p, loss

    return step, placed
