"""Training loop with checkpoint/resume for the loadgen model.

``python -m tpumon.loadgen.train --steps 200 --ckpt-dir /tmp/ckpt`` runs
the Llama-style model's sharded SGD loop on synthetic data, saving orbax
checkpoints (tpumon.loadgen.checkpoint) every ``--ckpt-every`` steps and
resuming from the latest one on restart — kill it mid-run and rerun the
same command to watch it continue from the saved step. This is the
elastic-recovery loop SURVEY §5.3/§5.4 calls for on the workload side:
a preempted/failed TPU job restarts from its checkpoint, and the monitor
alerts on the pod transition while it happens.

Sharding: on >1 device the step runs over a dp×tp
``jax.sharding.Mesh`` (model.make_sharded_train_step — XLA derives the
gradient psum over "data" and activation reductions over "model");
single-device falls back to a plain jit.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpumon.loadgen.checkpoint import restore_checkpoint, save_checkpoint
from tpumon.loadgen.model import (
    ModelConfig,
    init_params,
    make_sharded_train_step,
    param_shardings,
    sgd_train_step,
)


@dataclass(frozen=True)
class TrainConfig:
    model: ModelConfig
    steps: int = 100
    batch: int = 8
    seq: int = 64
    lr: float = 1e-3
    ckpt_dir: str | None = None
    ckpt_every: int = 20
    seed: int = 0


def _default_mesh() -> Mesh | None:
    """dp×tp mesh over all local devices; None for a single device."""
    devices = jax.devices()
    if len(devices) < 2:
        return None
    tp = 1
    for cand in (4, 8, 2):
        if len(devices) % cand == 0:
            tp = cand
            break
    dp = len(devices) // tp
    return Mesh(np.array(devices).reshape(dp, tp), ("data", "model"))


def synthetic_batch(cfg: TrainConfig, step: int) -> jax.Array:
    """Deterministic per-step token batch — resume reproduces the exact
    data order, so a resumed run's loss curve continues the original's."""
    key = jax.random.fold_in(jax.random.PRNGKey(cfg.seed ^ 0x5EED), step)
    return jax.random.randint(
        key, (cfg.batch, cfg.seq), 0, cfg.model.vocab, dtype=jnp.int32
    )


def run_train(
    cfg: TrainConfig, mesh: Mesh | None = None, log=lambda s: None
) -> dict:
    """Run (or resume) the loop; returns {step, loss, resumed_from, ...}."""
    if mesh is None:
        mesh = _default_mesh()
    params = init_params(cfg.model, jax.random.PRNGKey(cfg.seed))

    if mesh is not None:
        step_fn, placed = make_sharded_train_step(cfg.model, mesh, params)
        token_sharding = NamedSharding(mesh, P("data", None))
        like = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            params,
            param_shardings(mesh, params),
        )
    else:
        step_fn = jax.jit(
            partial(sgd_train_step, cfg.model, lr=cfg.lr)
        )
        placed, token_sharding, like = params, None, params

    start = 0
    resumed_from = None
    if cfg.ckpt_dir:
        restored = restore_checkpoint(cfg.ckpt_dir, like=like, cfg=cfg.model)
        if restored is not None:
            placed, saved_step = restored
            start = resumed_from = saved_step + 1
            log(f"resumed from step {saved_step}")

    loss = None  # stays None when resume lands at/past the final step
    t0 = time.perf_counter()
    tokens_seen = 0
    for step in range(start, cfg.steps):
        tokens = synthetic_batch(cfg, step)
        if token_sharding is not None:
            tokens = jax.device_put(tokens, token_sharding)
        placed, loss_arr = step_fn(placed, tokens)
        tokens_seen += cfg.batch * cfg.seq
        if cfg.ckpt_dir and (
            (step + 1) % cfg.ckpt_every == 0 or step == cfg.steps - 1
        ):
            jax.block_until_ready(placed)
            save_checkpoint(cfg.ckpt_dir, placed, step=step, cfg=cfg.model)
            log(f"step {step}: loss {float(loss_arr):.4f} (checkpointed)")
        loss = loss_arr
    jax.block_until_ready(placed)
    dt = time.perf_counter() - t0
    return {
        "step": cfg.steps - 1,
        "loss": float(loss) if loss is not None else None,
        "resumed_from": resumed_from,
        "tokens_per_sec": round(tokens_seen / dt, 1) if dt > 0 else 0.0,
        "mesh": dict(mesh.shape) if mesh is not None else None,
        "params": placed,
    }


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--ckpt-every", type=int, default=20)
    args = ap.parse_args(argv)

    cfg = TrainConfig(
        model=ModelConfig(
            vocab=2048, d_model=256, n_layers=4, n_heads=8, n_kv_heads=4,
            d_ff=1024, max_seq=max(64, args.seq),
        ),
        steps=args.steps, batch=args.batch, seq=args.seq,
        ckpt_dir=args.ckpt_dir, ckpt_every=args.ckpt_every,
    )
    out = run_train(cfg, log=print)
    out.pop("params")
    print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
