"""Training loop with checkpoint/resume for the loadgen model.

``python -m tpumon.loadgen.train --steps 200 --ckpt-dir /tmp/ckpt`` runs
the Llama-style model's sharded SGD loop on synthetic data, saving orbax
checkpoints (tpumon.loadgen.checkpoint) every ``--ckpt-every`` steps and
resuming from the latest one on restart — kill it mid-run and rerun the
same command to watch it continue from the saved step. This is the
elastic-recovery loop SURVEY §5.3/§5.4 calls for on the workload side:
a preempted/failed TPU job restarts from its checkpoint, and the monitor
alerts on the pod transition while it happens.

Sharding: on >1 device the step runs over a dp×tp
``jax.sharding.Mesh`` (model.make_sharded_train_step — XLA derives the
gradient psum over "data" and activation reductions over "model");
single-device falls back to a plain jit.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpumon.loadgen.checkpoint import restore_checkpoint, save_checkpoint
from tpumon.loadgen.model import (
    ModelConfig,
    init_params,
    loss_fn,
    make_sharded_train_step,
    param_shardings,
    sgd_train_step,
)


@dataclass(frozen=True)
class TrainConfig:
    model: ModelConfig
    steps: int = 100
    batch: int = 8
    seq: int = 64
    lr: float = 1e-3
    ckpt_dir: str | None = None
    ckpt_every: int = 20
    seed: int = 0
    # "auto": dp×tp over the local devices (single-device when alone).
    # "sp" / "sp-ring": sequence parallelism over a 1-D "seq" mesh of
    # all local devices — activations sequence-sharded through zigzag
    # (sp) or plain ring (sp-ring) attention (loadgen.sp_train); needs
    # seq-1 divisible by 2×devices (sp) / devices (sp-ring).
    parallel: str = "auto"

    def __post_init__(self) -> None:
        if self.parallel not in ("auto", "sp", "sp-ring"):
            raise ValueError(f"unknown parallel mode {self.parallel!r}")


def _default_mesh() -> Mesh | None:
    """dp×tp mesh over all local devices; None for a single device."""
    devices = jax.devices()
    if len(devices) < 2:
        return None
    tp = 1
    for cand in (4, 8, 2):
        if len(devices) % cand == 0:
            tp = cand
            break
    dp = len(devices) // tp
    return Mesh(np.array(devices).reshape(dp, tp), ("data", "model"))


def synthetic_batch(cfg: TrainConfig, step: int) -> jax.Array:
    """Deterministic per-step token batch — resume reproduces the exact
    data order, so a resumed run's loss curve continues the original's."""
    key = jax.random.fold_in(jax.random.PRNGKey(cfg.seed ^ 0x5EED), step)
    return jax.random.randint(
        key, (cfg.batch, cfg.seq), 0, cfg.model.vocab, dtype=jnp.int32
    )


# Peak dense bf16 TFLOP/s per chip by device kind (public spec sheets);
# the basis of MFU. Unknown kinds (CPU test meshes) report no MFU unless
# an explicit peak is passed.
PEAK_TFLOPS_BY_KIND = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v5": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


def detect_peak_flops() -> float | None:
    """Total peak FLOP/s across local devices, or None if unknown."""
    try:
        devices = jax.devices()
        kind = getattr(devices[0], "device_kind", "")
    except Exception:
        return None
    for name, tflops in PEAK_TFLOPS_BY_KIND.items():
        if kind.startswith(name):
            return tflops * 1e12 * len(devices)
    return None


def flops_per_token(cfg: ModelConfig, seq: int) -> float:
    """Training FLOPs per token: the standard 6·N (fwd 2N + bwd 4N over
    all parameters) plus the attention term 12·L·s·d (score+value
    matmuls, fwd+bwd, across layers at sequence length s)."""
    # MoE family: FLOPs count ACTIVE parameters per token — the router
    # plus the ONE routed expert (top-1, in+out projections) — not the
    # full expert bank (standard MoE accounting).
    ffn = (cfg.d_model * cfg.n_experts + 2 * cfg.d_model * cfg.d_ff
           if cfg.n_experts else 3 * cfg.d_model * cfg.d_ff)
    n_params = (
        cfg.vocab * cfg.d_model * 2  # embed + untied lm_head
        + cfg.n_layers * (
            cfg.d_model * (cfg.n_heads + 2 * cfg.n_kv_heads)
            * cfg.head_dim  # qkv
            + cfg.n_heads * cfg.head_dim * cfg.d_model  # wo
            + ffn
            + 2 * cfg.d_model  # norms
        )
        + cfg.d_model  # final norm
    )
    return 6.0 * n_params + 12.0 * cfg.n_layers * seq * cfg.d_model


class TrainMetrics:
    """Live training telemetry, exposed as Prometheus text.

    The trainer-side half of the monitor's training panel: step progress,
    loss, amortized step time, token throughput, goodput (productive
    step time over wall time — checkpoint saves and restore stalls are
    the non-productive remainder), and MFU (achieved model FLOP/s over
    the chips' peak — the standard TPU training health number). Updates
    are plain attribute writes from the train loop; the HTTP scrape
    thread only formats them.
    """

    def __init__(self, flops_per_token: float | None = None,
                 peak_flops: float | None = None) -> None:
        self.started = time.time()
        self.step = -1
        self.loss: float | None = None
        self.step_time_ema_s: float | None = None
        self.tokens_total = 0
        self.ckpt_step = -1
        self.productive_s = 0.0
        self.flops_per_token = flops_per_token
        self.peak_flops = peak_flops

    def observe_step(self, step: int, dt_s: float, batch_tokens: int) -> None:
        self.step = step
        self.tokens_total += batch_tokens
        self.productive_s += dt_s
        ema = self.step_time_ema_s
        self.step_time_ema_s = dt_s if ema is None else 0.9 * ema + 0.1 * dt_s

    @property
    def mfu_pct(self) -> float | None:
        """Cumulative MFU: achieved FLOP/s over peak, from totals.

        Cumulative (not per-step EMA) because the train loop is
        dispatch-only under JAX async dispatch: an individual loop dt
        can be ~1 ms while the device step is ~100 ms (queue not yet
        saturated), which would feed absurd per-step MFU samples into
        an EMA. Totals amortize dispatch-time artifacts away.
        """
        if not (self.flops_per_token and self.peak_flops
                and self.productive_s > 0):
            return None
        return 100.0 * (self.tokens_total * self.flops_per_token) / (
            self.productive_s * self.peak_flops)

    def metrics_text(self) -> str:
        wall = max(1e-9, time.time() - self.started)
        lines = [
            "# TYPE tpumon_train_tokens_total counter",
            f"tpumon_train_tokens_total {self.tokens_total}",
            "# TYPE tpumon_train_goodput_pct gauge",
            f"tpumon_train_goodput_pct {100.0 * min(1.0, self.productive_s / wall):.2f}",
        ]
        # -1 sentinels (no step yet / no checkpointing) are not data —
        # omit the gauges so the panel shows its "–" placeholder.
        if self.step >= 0:
            lines += ["# TYPE tpumon_train_step gauge",
                      f"tpumon_train_step {self.step}"]
        if self.ckpt_step >= 0:
            lines += ["# TYPE tpumon_train_checkpoint_step gauge",
                      f"tpumon_train_checkpoint_step {self.ckpt_step}"]
        if self.loss is not None:
            lines += ["# TYPE tpumon_train_loss gauge",
                      f"tpumon_train_loss {self.loss:.6f}"]
        if self.step_time_ema_s is not None:
            lines += ["# TYPE tpumon_train_step_time_seconds gauge",
                      f"tpumon_train_step_time_seconds {self.step_time_ema_s:.6f}"]
        if self.mfu_pct is not None:
            lines += ["# TYPE tpumon_train_mfu_pct gauge",
                      f"tpumon_train_mfu_pct {self.mfu_pct:.2f}"]
        return "\n".join(lines) + "\n"


def start_metrics_server(metrics: TrainMetrics, port: int = 0):
    """Serve ``metrics.metrics_text()`` on /metrics; returns (httpd, url)."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib API name)
            if self.path.rstrip("/") not in ("", "/metrics"):
                self.send_response(404)
                self.end_headers()
                return
            body = metrics.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_port}/metrics"


def fused_train_bench(cfg: TrainConfig, steps: int) -> dict:
    """Measure steady-state train throughput with the WHOLE step loop
    inside one jitted ``lax.scan`` — the idiomatic TPU shape for a
    benchmark, and the only honest one on remote-execution backends
    (the axon tunnel), where a Python-level step loop re-ships the
    params pytree by value every step and a warm ``block_until_ready``
    does not block (see loadgen.burn._sync). Tokens are drawn in-program
    per step; the scalar fetch at the end is the sync point.

    Returns {seconds, tokens_per_sec, mfu_pct (None off-TPU), loss}.
    """
    from tpumon.loadgen.burn import _sync

    params = init_params(cfg.model, jax.random.PRNGKey(cfg.seed))

    @jax.jit
    def run(params, key):
        def body(carry, step_key):
            tokens = jax.random.randint(
                step_key, (cfg.batch, cfg.seq), 0, cfg.model.vocab, jnp.int32
            )
            new_params, loss = sgd_train_step(
                cfg.model, carry, tokens, lr=cfg.lr
            )
            return new_params, loss
        keys = jax.random.split(key, steps)
        final, losses = jax.lax.scan(body, params, keys)
        # Touch the final params so the last update isn't dead code.
        checksum = sum(jnp.sum(x) for x in jax.tree_util.tree_leaves(final))
        return losses[-1] + 0 * checksum

    _sync(run(params, jax.random.PRNGKey(1)))  # compile
    t0 = time.perf_counter()
    loss = _sync(run(params, jax.random.PRNGKey(2)))
    dt = time.perf_counter() - t0
    tokens = steps * cfg.batch * cfg.seq
    peak = detect_peak_flops()
    fpt = flops_per_token(cfg.model, cfg.seq)
    mfu = (
        100.0 * tokens * fpt / (dt * peak) if peak and dt > 0 else None
    )
    return {
        "seconds": dt,
        "tokens_per_sec": tokens / dt,
        "mfu_pct": mfu,
        "loss": float(loss),
    }


def train_induction(model: ModelConfig, steps: int = 2000,
                    period: int = 16, seq: int = 256, batch: int = 16,
                    lr: float = 1e-3, seed: int = 0):
    """Train ``model`` to CONTINUE periodic token sequences (the
    induction/copy task) with Adam, the whole loop fused into one
    jitted ``lax.scan``.

    Exists for workloads that need a target model that genuinely
    copies: bench.py's prompt-lookup speculation benchmark trains the
    serving model here so measured acceptance is a property of real
    target agreement (an untrained target makes any proposer's
    acceptance noise — plain SGD at the loadgen's default lr leaves
    the copy task unlearned, measured r05: 8.79 -> 8.68 after 2k
    steps, vs Adam's 8.79 -> 0.51 which is the irreducible
    first-period entropy, i.e. perfect copying). Returns
    (trained_params, losses [steps]).
    """
    import optax

    opt = optax.adam(lr)
    params = init_params(model, jax.random.PRNGKey(seed))
    state = opt.init(params)
    reps = -(-seq // period)

    @jax.jit
    def fit(params, state, key):
        def body(carry, k):
            p, st = carry
            pat = jax.random.randint(
                k, (batch, period), 1, model.vocab, jnp.int32)
            toks = jnp.tile(pat, (1, reps))[:, :seq]
            loss, grads = jax.value_and_grad(
                partial(loss_fn, model))(p, toks)
            up, st = opt.update(grads, st)
            return (optax.apply_updates(p, up), st), loss

        return jax.lax.scan(
            body, (params, state), jax.random.split(key, steps))

    (params, _), losses = fit(params, state,
                              jax.random.PRNGKey(seed ^ 0xC0FFEE))
    jax.block_until_ready(losses)
    return params, losses


def run_train(
    cfg: TrainConfig,
    mesh: Mesh | None = None,
    log=lambda s: None,
    metrics: TrainMetrics | None = None,
    reporter=None,
) -> dict:
    """Run (or resume) the loop; returns {step, loss, resumed_from, ...}."""
    if cfg.parallel != "auto":
        # Explicit over silent: a 1-device host "running sp" would
        # really be running the dense step, misattributing every number
        # it produces; and a caller-provided dp×tp mesh can't carry the
        # sp step (it builds its own 1-D seq mesh).
        if len(jax.devices()) < 2:
            raise ValueError(
                f"parallel={cfg.parallel!r} needs >1 device "
                f"(have {len(jax.devices())})")
        if mesh is not None:
            raise ValueError(
                "pass either mesh= or parallel=; the sp modes build "
                "their own 1-D 'seq' mesh over all local devices")
    if mesh is None and cfg.parallel == "auto":
        mesh = _default_mesh()
    params = init_params(cfg.model, jax.random.PRNGKey(cfg.seed))

    if cfg.parallel != "auto":
        if cfg.model.n_experts:
            raise ValueError(
                "the MoE family does not compose with parallel="
                f"{cfg.parallel!r} yet (the sp step's layer body runs "
                "the dense family only); train MoE with parallel='auto'")
        # Sequence parallelism: 1-D "seq" mesh over all local devices;
        # each synthetic [B, seq] batch trains on seq-1 tokens, so the
        # shardable length is seq-1.
        from tpumon.loadgen.sp_train import make_sp_train_step

        n = len(jax.devices())
        need = 2 * n if cfg.parallel == "sp" else n
        if (cfg.seq - 1) % need:
            raise ValueError(
                f"parallel={cfg.parallel!r} over {n} devices needs "
                f"seq-1 divisible by {need} (got seq={cfg.seq})")
        sp_mesh = Mesh(np.array(jax.devices()), ("seq",))
        schedule = "zigzag" if cfg.parallel == "sp" else "ring"
        sp_step, placed = make_sp_train_step(
            cfg.model, sp_mesh, params, schedule=schedule, lr=cfg.lr)

        def step_fn(p, tokens):
            return sp_step(p, *sp_step.prep(tokens))

        mesh = sp_mesh
        token_sharding = None  # prep shards per-array via in_shardings
        like = params  # replicated
    elif mesh is not None:
        step_fn, placed = make_sharded_train_step(cfg.model, mesh, params)
        token_sharding = NamedSharding(mesh, P("data", None))
        like = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            params,
            param_shardings(mesh, params),
        )
    else:
        step_fn = jax.jit(
            partial(sgd_train_step, cfg.model, lr=cfg.lr)
        )
        placed, token_sharding, like = params, None, params

    start = 0
    resumed_from = None
    if cfg.ckpt_dir:
        restored = restore_checkpoint(cfg.ckpt_dir, like=like, cfg=cfg.model)
        if restored is not None:
            placed, saved_step = restored
            start = resumed_from = saved_step + 1
            log(f"resumed from step {saved_step}")

    loss = None  # stays None when resume lands at/past the final step
    t0 = time.perf_counter()
    tokens_seen = 0
    # Self-report (tpumon.loadgen.report): the step loop saturates the
    # device queue (async dispatch), so loop wall time is declared
    # device activity — labeled source:workload downstream.
    work_ctx = (
        reporter.device_work() if reporter is not None
        else contextlib.nullcontext()
    )
    with work_ctx:
        return _train_loop(
            cfg, mesh, log, metrics, step_fn, placed, token_sharding,
            start, resumed_from, loss, t0, tokens_seen,
        )


def _train_loop(
    cfg, mesh, log, metrics, step_fn, placed, token_sharding,
    start, resumed_from, loss, t0, tokens_seen,
) -> dict:
    for step in range(start, cfg.steps):
        t_step = time.perf_counter()
        tokens = synthetic_batch(cfg, step)
        if token_sharding is not None:
            tokens = jax.device_put(tokens, token_sharding)
        placed, loss_arr = step_fn(placed, tokens)
        tokens_seen += cfg.batch * cfg.seq
        if metrics is not None:
            # Loop dt amortizes to true step time once async dispatch
            # saturates the device queue; loss syncs only on checkpoint
            # steps below to keep the hot loop dispatch-only.
            metrics.observe_step(
                step, time.perf_counter() - t_step, cfg.batch * cfg.seq
            )
        if cfg.ckpt_dir and (
            (step + 1) % cfg.ckpt_every == 0 or step == cfg.steps - 1
        ):
            jax.block_until_ready(placed)
            save_checkpoint(cfg.ckpt_dir, placed, step=step, cfg=cfg.model)
            if metrics is not None:
                metrics.ckpt_step = step
                metrics.loss = float(loss_arr)
            log(f"step {step}: loss {float(loss_arr):.4f} (checkpointed)")
        loss = loss_arr
    if metrics is not None and loss is not None:
        metrics.loss = float(loss)
    jax.block_until_ready(placed)
    dt = time.perf_counter() - t0
    return {
        "step": cfg.steps - 1,
        "loss": float(loss) if loss is not None else None,
        "resumed_from": resumed_from,
        "tokens_per_sec": round(tokens_seen / dt, 1) if dt > 0 else 0.0,
        "mesh": dict(mesh.shape) if mesh is not None else None,
        "params": placed,
    }


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--ckpt-every", type=int, default=20)
    ap.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        help="expose tpumon_train_* Prometheus metrics on this port "
        "(0 = ephemeral); add the printed URL to tpumon's serving_targets",
    )
    ap.add_argument(
        "--peak-tflops",
        type=float,
        default=None,
        help="per-chip peak dense bf16 TFLOP/s for MFU (default: "
        "auto-detect from the TPU device kind; unknown kinds omit MFU)",
    )
    ap.add_argument(
        "--remat", action="store_true",
        help="per-layer rematerialization (jax.checkpoint): trade ~1/3 "
        "extra forward FLOPs for the activation HBM that otherwise "
        "bounds model size",
    )
    ap.add_argument(
        "--attention", choices=["naive", "chunked", "flash"],
        default="naive",
        help="'chunked' streams K/V blocks with an online softmax "
        "(O(T*block) attention memory); 'flash' runs the triangle-grid "
        "Pallas fwd+bwd kernels — the fastest measured TPU schedule at "
        "every bench shape (BENCH_NOTES r05) and needs no --remat at "
        "long seq (no T^2 transient)",
    )
    ap.add_argument("--attn-block", type=int, default=512,
                    help="K/V block rows for --attention chunked, pair "
                    "block for flash (1024 is the measured seq-8k knee)")
    ap.add_argument("--experts", type=int, default=0,
                    help="MoE model family: replace each layer's dense "
                    "SwiGLU with this many top-1-routed experts "
                    "(0 = dense; GShard capacity-factor routing)")
    ap.add_argument(
        "--parallel", choices=["auto", "sp", "sp-ring"], default="auto",
        help="'auto': dp×tp over local devices; 'sp'/'sp-ring': "
        "sequence parallelism through zigzag/plain ring attention "
        "(long-context mode; needs seq-1 divisible by 2×devices / "
        "devices)")
    ap.add_argument("--no-report", action="store_true",
                    help="disable the workload self-report (HBM "
                         "footprint + activity to the monitor's "
                         "source:workload channel)")
    args = ap.parse_args(argv)

    cfg = TrainConfig(
        model=ModelConfig(
            vocab=2048, d_model=256, n_layers=4, n_heads=8, n_kv_heads=4,
            d_ff=1024, max_seq=max(64, args.seq), remat=args.remat,
            attention=args.attention, attn_block_k=args.attn_block,
            n_experts=args.experts,
        ),
        steps=args.steps, batch=args.batch, seq=args.seq,
        ckpt_dir=args.ckpt_dir, ckpt_every=args.ckpt_every,
        parallel=args.parallel,
    )
    metrics = httpd = None
    if args.metrics_port is not None:
        if args.peak_tflops is None:
            peak = detect_peak_flops()
        elif args.peak_tflops > 0:
            peak = args.peak_tflops * 1e12 * len(jax.devices())
        else:
            peak = None  # explicit 0 disables MFU even on known TPUs
        metrics = TrainMetrics(
            flops_per_token=flops_per_token(cfg.model, cfg.seq),
            peak_flops=peak)
        httpd, url = start_metrics_server(metrics, port=args.metrics_port)
        print(f"train metrics at {url}")
    reporter = None
    if not args.no_report:
        from tpumon.loadgen.report import WorkloadReporter

        reporter = WorkloadReporter(name="train").start()
    try:
        out = run_train(cfg, log=print, metrics=metrics, reporter=reporter)
    finally:
        if reporter is not None:
            reporter.stop()
    out.pop("params")
    print(out)
    if httpd is not None:
        httpd.shutdown()
        httpd.server_close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
