"""Prefix caching: reuse prompt K/V across requests sharing a prefix.

Beyond-reference serving capability (the reference ships no serving
code — SURVEY §5.7): requests in real serving traffic share long system
prompts, so production TPU engines cache the KV of common prompt
prefixes and skip recomputing them. tpumon's engine
(tpumon.loadgen.serving) does the same at **chunk granularity**: after
a prompt is prefilled, the K/V rows of its chunk-aligned prefix are
snapshotted; a later prompt starting with the same tokens restores
those rows with one HBM-to-HBM copy and prefills only the tail.

TPU-first design:
- restore/extract are single ``dynamic_update_slice`` /
  ``dynamic_slice`` ops over ``[layers, rows, kv_heads, head_dim]``
  blocks — pure HBM bandwidth, no MXU work, no per-layer Python loop
  on the hot path. Each distinct chunk count compiles once (row count
  must be static under jit); prompts are already chunked by
  ``prefill_len``, so the shape set is tiny.
- keys are exact token tuples at chunk boundaries, so a restored row
  is bit-identical to the prefill that produced it — greedy decode
  outputs are unchanged by cache hits, which the tests pin.
- entries pin device HBM (the point: trading memory for prefill
  FLOPs), so the store is a bounded LRU; eviction frees the arrays.
- the cached prefix is always strictly shorter than the prompt (the
  chunk containing the last token is recomputed) so the engine still
  gets first-token logits from a real prefill call.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


@partial(jax.jit, donate_argnums=(0,))
def _restore(cache_kv: jax.Array, slot: jax.Array,
             block: jax.Array) -> jax.Array:
    """Write ``block`` [layers, rows, nkv, hd] into rows 0..rows-1 of
    ``slot`` in cache_kv [layers, slots, seq, nkv, hd]. One compile per
    distinct row count (the block's static shape)."""
    return lax.dynamic_update_slice(
        cache_kv, block[:, None], (0, slot, 0, 0, 0))


@partial(jax.jit, static_argnums=(2,))
def _extract(cache_kv: jax.Array, slot: jax.Array, rows: int) -> jax.Array:
    """Read rows 0..rows-1 of ``slot`` → [layers, rows, nkv, hd]."""
    layers, _, _, nkv, hd = cache_kv.shape
    return lax.dynamic_slice(
        cache_kv, (0, slot, 0, 0, 0), (layers, 1, rows, nkv, hd))[:, 0]


@dataclass
class PrefixCache:
    """Bounded LRU of chunk-aligned prompt-prefix K/V blocks.

    ``chunk`` is the engine's prefill_len; keys are
    ``tuple(prompt[:m])`` with m a multiple of chunk.
    """

    chunk: int
    max_entries: int = 16
    _store: OrderedDict = field(default_factory=OrderedDict)
    hits: int = 0
    misses: int = 0
    saved_tokens: int = 0
    _resident: int = 0  # bytes; kept incrementally so /metrics readers
    # in other threads never iterate the live OrderedDict

    def cached_prefix_len(self, prompt: list[int]) -> int:
        """Longest cached chunk-aligned strict prefix of ``prompt``
        (strict: the chunk holding the last token is never served from
        cache so prefill still produces first-token logits)."""
        n = len(prompt)
        m = ((n - 1) // self.chunk) * self.chunk
        while m >= self.chunk:
            if tuple(prompt[:m]) in self._store:
                return m
            m -= self.chunk
        return 0

    def peek(self, prompt: list[int]) -> int:
        """Side-effect-free probe: the cached chunk-aligned strict
        prefix length ``restore`` would serve, without touching LRU
        order or the hit/miss counters (``cached_prefix_len`` is
        already side-effect-free; this is the name the scheduler's
        probe contract uses across both cache kinds)."""
        return self.cached_prefix_len(prompt)

    def restore(self, cache: dict, prompt: list[int], slot) -> int:
        """If a prefix of ``prompt`` is cached, write it into ``slot``
        (mutating ``cache`` in place) and return its length, else 0."""
        m = self.cached_prefix_len(prompt)
        if not m:
            self.misses += 1
            return 0
        key = tuple(prompt[:m])
        blocks = self._store[key]
        self._store.move_to_end(key)  # LRU touch
        for name in ("k", "v"):
            cache[name] = _restore(cache[name], slot, blocks[name])
        self.hits += 1
        self.saved_tokens += m
        return m

    def store(self, cache: dict, prompt: list[int], slot) -> None:
        """Snapshot the chunk-aligned strict prefix of ``prompt`` from
        ``slot`` (a no-op if already cached or shorter than one chunk)."""
        n = len(prompt)
        m = ((n - 1) // self.chunk) * self.chunk
        if m < self.chunk:
            return
        key = tuple(prompt[:m])
        if key in self._store:
            self._store.move_to_end(key)
            return
        blocks = {
            name: _extract(cache[name], slot, m) for name in ("k", "v")
        }
        self._store[key] = blocks
        self._resident += sum(b.nbytes for b in blocks.values())
        while len(self._store) > self.max_entries:
            _, evicted = self._store.popitem(last=False)  # frees the HBM
            self._resident -= sum(b.nbytes for b in evicted.values())

    @property
    def entries(self) -> int:
        return len(self._store)

    def resident_bytes(self) -> int:
        return self._resident
