"""JetStream-style serving engine: KV-cached prefill/decode + /metrics.

The reference can only watch an LLM serving stack from the outside (its
README names vLLM metric collection, README.md:73, but ships no serving
code — SURVEY §5.7). tpumon closes the loop in-tree: this module is a
minimal continuous-batching inference engine over the loadgen model
(tpumon.loadgen.model) that exposes JetStream-compatible Prometheus
metrics — TTFT histogram, token/request counters, queue and slot gauges —
so the serving collector (tpumon/collectors/serving.py) scrapes it with
zero special-casing. That reproduces the north-star deployment
(BASELINE config 4: JetStream serving a Llama-family model on v5e) as a
self-contained demo: tpumon monitoring a real TPU serving job.

TPU-first design:
- prefill and decode are each jitted ONCE with static shapes: prompts pad
  to ``prefill_len``, the KV cache is one preallocated
  ``[layers, slots, max_seq, n_kv, head_dim]`` buffer per K/V, and all
  per-slot writes go through ``lax.dynamic_update_slice`` (vmapped over
  slots in decode) — no retracing as requests come and go;
- decode advances ALL active slots in one fused step (continuous
  batching): one embed + per-layer {QKV matmul, cache append, attention
  over the cache, SwiGLU MLP} for the whole batch — MXU-batched work, no
  per-request Python in the hot path;
- cache buffers are donated to the jitted calls so XLA updates them
  in place on TPU instead of copying ~seq_len × slots of HBM per token;
- sampling defaults to greedy (argmax), keeping the engine deterministic
  for the correctness tests (decode must reproduce full-forward logits);
  per-request temperature / top-k sampling runs on device in the same
  dispatch (``sample_tokens``: top-k mask + categorical, keyed per
  (request id, token index) off one base seed — every request's token
  stream is a pure function of (seed, prompt, params), independent of
  scheduling, slot assignment, and batch composition);
- admission is an interleaved chunked-prefill scheduler
  (``ServeConfig.scheduler``): each step spends at most
  ``prefill_chunk_budget`` prefill chunk dispatches before the decode
  batch, so long prompts admit over many steps while active slots keep
  emitting tokens (``scheduler="sequential"`` keeps the stop-the-world
  baseline the bench's serving_concurrency phase compares against).
"""

from __future__ import annotations

import itertools
import queue
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field, replace as dc_replace
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from tpumon.loadgen.model import ModelConfig, _rms_norm, init_params
from tpumon.metrics_text import MetricsWriter

# TTFT histogram bucket upper bounds, seconds (JetStream buckets are
# seconds; the serving distiller converts quantiles to ms).
TTFT_BUCKETS_S = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)

# Last-resort ceiling on any per-tenant admission-shed fraction
# (set_shed clamps to it): whatever the actuation layer is configured
# to, the engine itself can never be told to shed a whole tenant —
# some live traffic always survives to prove recovery.
SHED_CAP = 0.95


@dataclass(frozen=True)
class ServeConfig:
    model: ModelConfig = field(default_factory=ModelConfig)
    slots: int = 4  # concurrent decode slots (continuous batching)
    prefill_len: int = 64  # static prompt padding length
    # MoE prefill-chunk cap. Serving routes MoE layers at FULL capacity
    # (capacity = the chunk's token count G, decoder_forward) so routing
    # is shape-independent and every decode mode emits identical tokens
    # — but _route then materializes [G, E, G] dispatch/combine tensors,
    # O(G²·E) memory/FLOPs that grow QUADRATICALLY with the prefill
    # chunk. At the 256-token cap with 8 experts that is ~2 MB f32 per
    # MoE layer (fine); at prefill_len 2048 it would be ~134 MB per
    # layer. The engine refuses MoE configs whose prefill_len exceeds
    # this cap (raise the knob only with the quadratic cost in mind, or
    # lower prefill_len — long prompts already run as multiple chunks).
    # Decode paths (step/block/spec-verify) have tiny G and are
    # unaffected.
    moe_prefill_max_chunk: int = 256
    # Weight-only quantization: None (compute dtype) or "int8"
    # (tpumon.loadgen.quant — halves decode's HBM weight traffic vs bf16).
    quantize: str | None = None
    # Speculative decoding (tpumon.loadgen.speculative): propose spec_len
    # draft tokens per round, verify them in one target dispatch (over
    # the dense cache or the paged pool — paged_kv.paged_decode_block).
    # 0 = off. draft_model None = self-speculation (draft shares target
    # weights — 100% acceptance; the correctness/demo mode); a
    # layer-truncated draft_model shares the target's bottom layers.
    # Greedy output matches plain decode regardless of draft quality
    # (see tpumon.loadgen.speculative on bf16 argmax near-ties).
    spec_len: int = 0
    draft_model: ModelConfig | None = None
    # Speculative proposal source: "draft" runs a draft model (above);
    # "prompt" proposes by n-gram prompt lookup
    # (tpumon.loadgen.prompt_lookup) — no draft model/cache/dispatches,
    # proposals copied from the request's own context, the win case
    # being repetitive continuations. Verify step identical either way,
    # so greedy output stays lossless regardless of proposal quality.
    spec_source: str = "draft"
    # Prompt-lookup backward-scan bound: only the most recent
    # spec_ngram_window tokens of each request's context are searched
    # per round (0 = unbounded), so host-side proposal cost stops
    # growing with context length. 1024 comfortably covers the periods
    # of the repetitive workloads the proposer targets.
    spec_ngram_window: int = 1024
    # Prefix caching: LRU entries of chunk-aligned prompt-prefix K/V;
    # 0 = off. Dense layout snapshots+restores rows with an HBM copy
    # (tpumon.loadgen.prefix_cache); paged layout SHARES the prefix's
    # refcounted pages, zero-copy (paged_kv.PagePrefixCache). Each
    # entry pins HBM — the deliberate trade of memory for prefill
    # FLOPs; the paged engine evicts entries under pool pressure.
    prefix_cache_entries: int = 0
    # KV layout: "dense" reserves slots*max_seq rows forever; "paged"
    # (tpumon.loadgen.paged_kv) allocates page_size(=prefill_len) pages
    # from a shared pool per request and frees them on completion, so
    # resident KV scales with admitted work. pool_pages 0 sizes the
    # pool to the dense equivalent (the win comes from setting it
    # lower); exhaustion blocks admission instead of OOMing.
    kv_layout: str = "dense"
    pool_pages: int = 0
    # Paged decode attention read path: "gather" lets XLA fuse the page
    # table gather into the attention einsum; "kernel" routes the decode
    # step through the Pallas paged-attention kernel
    # (tpumon.ops.paged_attention — scalar-prefetched page tables, pages
    # DMA'd straight through VMEM). Which wins is a function of scale,
    # measured both ways on v5e (BENCH_NOTES r05): at PRODUCTION shape
    # (370M params, 16 slots x 4k context, page 128, GQA 4 — KV pool far
    # beyond on-chip memory) the kernel cuts the engine decode step
    # 1.49x (11.0 -> 7.4 ms, bench paged_engine_step_*); at the
    # demo/test shape (page 32, hd 64, pool ~8-135 MB) the pool sits in
    # on-chip memory, the kernel's tiny grid cells starve the MXU, and
    # gather wins ~9x — hence the default. Covers the T=1 hot loop
    # (plain step + decode_block rounds); the speculative verify block
    # (multi-token queries) stays on the gather path. Requires
    # kv_layout="paged" and kv_dtype="compute" (the kernel reads bf16/f32
    # pages, not the int8 pool).
    paged_attn: str = "gather"
    # Fused plain decode: run this many (decode_step -> sample) pairs
    # inside ONE dispatch per engine step (serving.decode_rounds) — the
    # plain-decode analogue of the speculative verify fusion. Cuts
    # per-token dispatch overhead at the cost of up to block-1 wasted
    # tokens past a stop/max_new and block-1 steps of added admission
    # latency. 1 = off. Composes with dense KV, paged KV
    # (paged_kv.paged_decode_rounds), and the tensor-parallel mesh
    # (make_sharded_serving rounds_fn).
    decode_block: int = 1
    # KV cache element type: "compute" stores K/V in compute_dtype;
    # "int8" stores them quantized with a per-(row, kv-head) float scale
    # — halves resident cache HBM and the bytes decode attention streams
    # (decode is KV-bandwidth-bound), at a small accuracy cost (outputs
    # are no longer bit-identical to the bf16 cache). Dense single-
    # device engine; composes with decode_block and int8 weights.
    kv_dtype: str = "compute"
    # Admission scheduler. "interleaved" (default, Sarathi-style chunked
    # prefill): each step() runs at most ``prefill_chunk_budget`` prefill
    # chunk dispatches before the decode batch, so a long prompt admits
    # over many steps while every active slot keeps emitting tokens.
    # "sequential" is the stop-the-world baseline: a request's ENTIRE
    # chunked prefill runs inline at admission, stalling decode for the
    # full prompt length (the prefill/decode interference the bench's
    # serving_concurrency phase measures). Token streams are identical
    # either way — sampling is keyed per (request id, token index), so a
    # request's stream is a pure function of (seed, prompt, params)
    # regardless of scheduling.
    scheduler: str = "interleaved"
    # Prefill chunk dispatches spent per step() under the interleaved
    # scheduler (round-robin over in-prefill slots; draft-model prefill
    # chunks count too). Higher = lower prefill latency, more decode
    # stall per step. Ignored by scheduler="sequential".
    prefill_chunk_budget: int = 1
    # Paged admission lookahead (0 = strict FIFO): when the queue head's
    # page reservation fails, probe up to this many following requests
    # and admit the first whose reservation succeeds — a fully-cached
    # prefix (zero new pages) must not wait behind a page-starved head.
    # Bounded by ``admit_max_skips``: after that many queue-jumps the
    # head is force-next (lookahead suspends) so nothing starves.
    admit_lookahead: int = 0
    admit_max_skips: int = 8
    # dp×tp mesh serving (MeshServingEngine): mesh_dp data-parallel
    # replicas, each a full continuous-batching engine over its own
    # mesh_tp-chip tensor-parallel submesh. Requests are admitted to a
    # replica by the topology- and prefix-affinity-aware router
    # (MeshServingEngine.submit); the PR 10 interleaved scheduler runs
    # per replica unchanged, and every request's sampled stream stays a
    # pure function of (seed, prompt, params) — bit-identical across
    # shard layouts (tests/test_scheduler.py golden matrix). 1×1 = the
    # plain single engine. dp*tp must divide the device count
    # (validated where the config meets devices — MeshServingEngine).
    mesh_dp: int = 1
    mesh_tp: int = 1
    # Ring-attention engine mode (0 = off, >= 2 = stripe count):
    # long-context requests whose KV exceeds one chip's HBM stripe
    # admit into a ring layout — the page table widens to
    # ring_stripes × the flat capacity, stripe s owning page block s,
    # and decode pages KV block-wise around the tp ring during
    # attention (on the fake mesh the page gather IS the collect the
    # ring's ppermute performs). Admission cap rises from max_seq-1 to
    # ring_stripes*max_seq - 1 tokens; the paged kernels are
    # table-width-driven, so the ring engine's math is IDENTICAL to a
    # flat paged engine whose max_seq is the full ring capacity —
    # which is what pins bit-identical streams vs unsharded
    # (tests/test_scheduler.py ring admission test). Requires
    # kv_layout="paged"; speculative decoding (dense draft cache is
    # one stripe wide) and paged_attn="kernel" (geometry pinned to one
    # stripe) do not compose.
    ring_stripes: int = 0


# ---------------------------------------------------------------------------
# Jittable kernels
# ---------------------------------------------------------------------------


def init_cache(cfg: ServeConfig) -> dict:
    m = cfg.model
    shape = (m.n_layers, cfg.slots, m.max_seq, m.n_kv_heads, m.head_dim)
    if cfg.kv_dtype == "int8":
        # Quantized cache: int8 rows + per-(row, kv-head) f32 scales
        # ("ks"/"vs"). The scales add 4/head_dim of the int8 payload
        # (~3% at hd=128) against the 2x saving vs bf16 rows.
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "ks": jnp.zeros(shape[:-1], jnp.float32),
            "vs": jnp.zeros(shape[:-1], jnp.float32),
        }
    dt = jnp.dtype(m.compute_dtype)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def _kv_quant(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-(..., head)-row int8 quantization over head_dim."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1) / 127.0, 1e-8)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def _kv_dequant(q: jax.Array, scale: jax.Array, dt) -> jax.Array:
    return (q.astype(jnp.float32) * scale[..., None]).astype(dt)


def _rope_at(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding at explicit positions; x: [B, T, H, D],
    positions: [B, T] (int)."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [B, T, D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _gqa_repeat(kv: jax.Array, n_heads: int) -> jax.Array:
    nkv = kv.shape[-2]
    return kv if nkv == n_heads else jnp.repeat(kv, n_heads // nkv, axis=-2)


def decoder_forward(cfg: ServeConfig, params: dict, tokens: jax.Array,
                    pos: jax.Array, mask: jax.Array,
                    kv_update, attend=None) -> jax.Array:
    """The ONE transformer body shared by every serving path — dense
    prefill/decode, speculative verify, and paged prefill/decode differ
    only in how K/V is stored and read back, which ``kv_update``
    abstracts; everything else (RoPE, GQA attention, SwiGLU) lives here
    exactly once so the modes cannot drift numerically.

    tokens: [B, T] int32; pos: [B, T] int32 global row positions;
    mask: [B, 1, T, S] over the context rows kv_update returns;
    kv_update(li, k, v): write the block's K/V ([B, T, nkv, hd]) into
    layer li's store and return the full context (ck, cv) as
    [B, S, nkv, hd]. Returns final-norm hidden states [B, T, D]
    (callers apply lm_head to the rows they need).

    attend(li, q, k, v), when given, REPLACES kv_update + the in-body
    attention for every layer: it must write the block's K/V into layer
    li's store and return the attention output [B, T, n_heads, hd]
    directly. This is the ServeConfig.paged_attn="kernel" path — the
    Pallas paged-attention kernel reads pages in-kernel via scalar-
    prefetched tables, so a gathered [B, S] context never exists and
    ``mask`` is unused (the kernel masks by sequence length).
    """
    m = cfg.model
    dt = jnp.dtype(m.compute_dtype)
    nh, nkv, hd = m.n_heads, m.n_kv_heads, m.head_dim
    b, t = tokens.shape
    x = params["embed"].astype(dt)[tokens]  # [B, T, D]
    for li, layer in enumerate(params["layers"]):
        h = _rms_norm(x, layer["attn_norm"])
        q = _rope_at((h @ layer["wq"].astype(dt)).reshape(b, t, nh, hd),
                     pos, m.rope_theta)
        k = _rope_at((h @ layer["wk"].astype(dt)).reshape(b, t, nkv, hd),
                     pos, m.rope_theta)
        v = (h @ layer["wv"].astype(dt)).reshape(b, t, nkv, hd)
        if attend is not None:
            att = attend(li, q, k, v).reshape(b, t, nh * hd)
        else:
            ck, cv = kv_update(li, k, v)
            kr, vr = _gqa_repeat(ck, nh), _gqa_repeat(cv, nh)
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, kr).astype(jnp.float32)
            scores = scores / (hd**0.5)
            scores = jnp.where(mask, scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(dt)
            att = jnp.einsum(
                "bhqk,bkhd->bqhd", probs, vr).reshape(b, t, nh * hd)
        x = x + att @ layer["wo"].astype(dt)
        hm = _rms_norm(x, layer["mlp_norm"])
        if "moe" in layer:
            # MoE family (model._moe_mlp): routed expert FFN at FULL
            # capacity (no drops) — GShard capacity depends on the
            # dispatch batch SHAPE, and serving runs the same sequence
            # through different shapes (chunked prefill, step decode,
            # fused blocks, spec verify); full capacity makes routing
            # shape-independent so every mode emits identical tokens.
            from tpumon.loadgen.model import _moe_mlp

            x = x + _moe_mlp(m, layer["moe"], hm, full_capacity=True)
        else:
            gate = jax.nn.silu(hm @ layer["w_gate"].astype(dt))
            x = x + (gate * (hm @ layer["w_up"].astype(dt))) @ layer[
                "w_down"].astype(dt)
    return _rms_norm(x, params["final_norm"])


def prefill(cfg: ServeConfig, params: dict, cache: dict, tokens: jax.Array,
            length: jax.Array, slot: jax.Array,
            start: jax.Array | int = 0) -> tuple[dict, jax.Array]:
    """Process one padded prompt *chunk* into cache slot ``slot``.

    tokens: [prefill_len] int32 (padded); length: scalar int32 true length
    within this chunk; slot: scalar int32; start: scalar int32 cache row
    the chunk begins at (0 for the first/only chunk). Chunk queries attend
    to every earlier row of the slot's cache plus the causal prefix of the
    chunk itself, so a long prompt runs as ceil(n/prefill_len) fixed-shape
    calls (chunked prefill — no retracing, prompt length bounded by
    max_seq rather than prefill_len). Returns (cache, logits[vocab] at
    local position length-1; meaningful for the final chunk). Padding
    rows hold garbage but are never attended: the row mask stops at the
    causal frontier, and decode appends overwrite them in order.
    """
    m = cfg.model
    p = cfg.prefill_len
    dt = jnp.dtype(m.compute_dtype)
    nkv, hd = m.n_kv_heads, m.head_dim
    pos = start + jnp.arange(p, dtype=jnp.int32)[None]  # [1, P] global rows
    row = jnp.arange(m.max_seq, dtype=jnp.int32)
    # mask[i, row]: row <= start + i — prior chunks + causal within chunk.
    mask = (row[None, :] <= pos[0][:, None])[None, None]  # [1,1,P,S]

    def kv_update(li, k, v):
        # Write the chunk, then attend over the slot's whole cache
        # (earlier chunks are already there). "ks" in the cache dict
        # means the int8 layout (init_cache) — a trace-time branch.
        if "ks" in cache:
            (qk, sk), (qv, sv) = _kv_quant(k), _kv_quant(v)
            cache["k"] = lax.dynamic_update_slice(
                cache["k"], qk[None], (li, slot, start, 0, 0))
            cache["v"] = lax.dynamic_update_slice(
                cache["v"], qv[None], (li, slot, start, 0, 0))
            cache["ks"] = lax.dynamic_update_slice(
                cache["ks"], sk[None], (li, slot, start, 0))
            cache["vs"] = lax.dynamic_update_slice(
                cache["vs"], sv[None], (li, slot, start, 0))
            ck = _kv_dequant(
                lax.dynamic_slice(
                    cache["k"], (li, slot, 0, 0, 0),
                    (1, 1, m.max_seq, nkv, hd))[0],
                lax.dynamic_slice(
                    cache["ks"], (li, slot, 0, 0), (1, 1, m.max_seq, nkv))[0],
                k.dtype)
            cv = _kv_dequant(
                lax.dynamic_slice(
                    cache["v"], (li, slot, 0, 0, 0),
                    (1, 1, m.max_seq, nkv, hd))[0],
                lax.dynamic_slice(
                    cache["vs"], (li, slot, 0, 0), (1, 1, m.max_seq, nkv))[0],
                v.dtype)
            return ck, cv
        cache["k"] = lax.dynamic_update_slice(
            cache["k"], k[None], (li, slot, start, 0, 0))
        cache["v"] = lax.dynamic_update_slice(
            cache["v"], v[None], (li, slot, start, 0, 0))
        ck = lax.dynamic_slice(
            cache["k"], (li, slot, 0, 0, 0), (1, 1, m.max_seq, nkv, hd)
        )[0]
        cv = lax.dynamic_slice(
            cache["v"], (li, slot, 0, 0, 0), (1, 1, m.max_seq, nkv, hd)
        )[0]
        return ck, cv  # [1, S, nkv, hd]

    x = decoder_forward(cfg, params, tokens[None], pos, mask, kv_update)
    last = lax.dynamic_index_in_dim(x[0], length - 1, axis=0, keepdims=False)
    logits = (last @ params["lm_head"].astype(dt)).astype(jnp.float32)
    return cache, logits


def decode_step(cfg: ServeConfig, params: dict, cache: dict,
                last_tokens: jax.Array, positions: jax.Array
                ) -> tuple[dict, jax.Array]:
    """Advance every slot one token.

    last_tokens: [B] int32 (token to feed per slot); positions: [B] int32
    (cache row the new token's K/V is written to == current sequence
    length per slot). Returns (cache, logits [B, vocab]) for the next
    token. Inactive slots compute garbage that the host ignores; their
    cache writes land on a stale row and are rewritten on admit.

    The T == 1 case of ``speculative.decode_block`` — one layer body,
    no drift between the plain and speculative paths.
    """
    from tpumon.loadgen.speculative import decode_block

    cache, logits = decode_block(cfg, params, cache,
                                 last_tokens[:, None], positions)
    return cache, logits[:, 0]


def decode_rounds(cfg: ServeConfig, params: dict, cache: dict,
                  last_tokens: jax.Array, positions: jax.Array,
                  base_key: jax.Array, rids: jax.Array, ctr0: jax.Array,
                  temps: jax.Array, topks: jax.Array, steps: int
                  ) -> tuple[dict, jax.Array, jax.Array, jax.Array]:
    """``steps`` greedy/sampled decode steps fused into ONE dispatch.

    A Python-level decode loop pays dispatch overhead (and on remote-
    execution backends, cache re-shipping) per token; scanning the
    (decode_step -> sample_tokens) pair inside jit pays it once per
    block — the same fusion idea as speculative verify, but for plain
    decode. Sampling matches the per-step path exactly: rids [B] and
    ctr0 [B] carry each request's (id, next token index), the index
    advances by one per in-block step, and the key is a pure function
    of (request, index) — so blocked decode emits the per-step stream
    even when a mid-block completion discards the tail (discarded
    indices are simply never re-used by that request).

    Returns (cache, last_tokens, positions, tokens [B, steps]).
    """

    def body(carry, _):
        cache, last, pos, ctr = carry
        cache, logits = decode_step(cfg, params, cache, last, pos)
        nxt = sample_tokens(logits, base_key, rids, ctr, temps, topks)
        pos = jnp.minimum(pos + 1, cfg.model.max_seq - 1)
        return (cache, nxt, pos, ctr + 1), nxt

    (cache, last, pos, _), toks = jax.lax.scan(
        body, (cache, last_tokens, positions, ctr0), None, length=steps)
    return cache, last, pos, toks.T  # [B, steps] in emission order


# ---------------------------------------------------------------------------
# Sharded (multi-chip) serving: tensor-parallel decode over a mesh
# ---------------------------------------------------------------------------


def make_sharded_serving(cfg: ServeConfig, mesh, params: dict):
    """jit prefill + decode tensor-parallel over mesh axis "model".

    The Megatron-style split from the training path (model.PARAM_SPECS)
    carries over to serving unchanged: QKV projections column-parallel →
    each device owns a contiguous block of KV heads, attention is local
    per head, the output/down projections are row-parallel and XLA
    inserts the psum over ICI. The KV cache is sharded on its head axis
    (``[layers, slots, seq, n_kv, head_dim]`` → n_kv split over "model")
    so per-token cache appends touch only device-local HBM — no
    collective in the append. Logits are replicated for host-side
    sampling (one all-gather over the vocab-sharded lm_head output).

    Requires ``n_kv_heads % mesh.shape["model"] == 0`` and
    ``slots % mesh.shape["data"] == 0`` (slots are data-parallel).
    Returns (prefill_fn, decode_fn, placed_params, placed_cache,
    rounds_fn) — rounds_fn is the fused block-decode twin
    (decode_rounds over the same shardings; ServeConfig.decode_block).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpumon.loadgen.model import param_shardings

    tp = mesh.shape["model"]
    dp = mesh.shape.get("data", 1)
    assert cfg.model.n_kv_heads % tp == 0, (
        f"n_kv_heads={cfg.model.n_kv_heads} not divisible by tp={tp}")
    from tpumon.loadgen.model import _check_moe_tp

    _check_moe_tp(cfg.model, mesh)
    assert cfg.slots % dp == 0, f"slots={cfg.slots} not divisible by dp={dp}"
    shardings = param_shardings(mesh, params)
    placed = jax.device_put(params, shardings)
    cache_sh = {
        "k": NamedSharding(mesh, P(None, "data", None, "model", None)),
        "v": NamedSharding(mesh, P(None, "data", None, "model", None)),
    }
    rep = NamedSharding(mesh, P())
    _pre = jax.jit(
        partial(prefill, cfg),
        in_shardings=(shardings, cache_sh, rep, rep, rep, rep),
        out_shardings=(cache_sh, rep),
        donate_argnums=(1,),
    )
    _dec = jax.jit(
        partial(decode_step, cfg),
        in_shardings=(shardings, cache_sh, rep, rep),
        out_shardings=(cache_sh, rep),
        donate_argnums=(1,),
    )

    _rounds = jax.jit(
        partial(decode_rounds, cfg),
        in_shardings=(shardings, cache_sh, rep, rep, rep, rep, rep, rep,
                      rep),
        out_shardings=(cache_sh, rep, rep, rep),
        # static_argnums, not argnames: pjit with in_shardings rejects
        # kwargs, so steps is passed positionally below.
        static_argnums=(9,),
        donate_argnums=(1,),
    )

    def prefill_fn(cache, tokens, length, slot, start=None):
        if start is None:
            start = jnp.int32(0)
        return _pre(placed, cache, tokens, length, slot, start)

    def decode_fn(cache, last_tokens, positions):
        return _dec(placed, cache, last_tokens, positions)

    def rounds_fn(cache, last_tokens, positions, base_key, rids, ctr0,
                  temps, topks, steps):
        return _rounds(placed, cache, last_tokens, positions,
                       base_key, rids, ctr0, temps, topks, steps)

    placed_cache = jax.device_put(init_cache(cfg), cache_sh)
    return prefill_fn, decode_fn, placed, placed_cache, rounds_fn


# ---------------------------------------------------------------------------
# Host-side engine
# ---------------------------------------------------------------------------


@dataclass
class Request:
    rid: int
    prompt: list[int]
    max_new: int
    enqueued: float
    temperature: float = 0.0  # 0 = greedy (deterministic)
    top_k: int = 0  # 0 = full vocab
    # Multi-tenant attribution (tpumon.loadgen.traffic): the tag rides
    # the request through admission and completion so the engine's
    # per-tenant latency/goodput accounting — and from there the
    # monitor's ``serving.<tenant>.*`` TSDB series — can tell a chat
    # tenant's regression from a batch tenant's backlog. "" = untagged
    # (every pre-tenant caller), excluded from per-tenant metrics.
    tenant: str = ""
    # Terminal status, set exactly once when the request leaves the
    # engine: "completed" | "rejected" | "cancelled" | "shed" ("" while
    # in flight). ``shed`` is the actuation layer's admission shed
    # (tpumon.actuate) — a deliberate remedial drop that must never be
    # distilled into a tenant's error rate (the error rate is what
    # triggered the shed; counting sheds there would latch the SLO).
    status: str = ""
    # dp-replica placement domain this request was attributed to at
    # slot assignment (engine.slices round-robin); None untracked.
    slice: str | None = None
    # Drain-and-requeue accounting: how many times a slice drain
    # aborted this request mid-flight and re-admitted it.
    requeues: int = 0
    # Stream tokens already delivered before a requeue: the re-run
    # regenerates a bit-identical prefix (sampling is keyed per
    # (rid, token index) — docs/perf.md scheduler section), which must
    # not reach the consumer's stream twice.
    _replay_n: int = 0
    ttft_s: float | None = None
    first_tok_t: float | None = None  # monotonic at first emit (TPOT)
    output: list[int] = field(default_factory=list)
    done: threading.Event = field(default_factory=threading.Event)
    # Streaming: tokens are pushed here as they are emitted (None = end
    # of stream), so a consumer sees the first token at TTFT instead of
    # waiting for completion. Created by submit(stream=True).
    stream: "object | None" = None
    # Generation ends early when an emitted token is in stop_tokens
    # (the EOS contract; the stop token is included in output).
    stop_tokens: tuple = ()
    cancelled: threading.Event = field(default_factory=threading.Event)

    def cancel(self) -> None:
        """Ask the engine to drop this request at its next step — frees
        the slot (and paged KV pages) instead of generating for a
        client that went away."""
        self.cancelled.set()

    def emit(self, tokens: list[int]) -> None:
        for t in tokens:
            self.output.append(t)
            # Replay suppression after a drain-requeue: the rebuilt
            # prefix is bit-identical (keyed sampling), so only tokens
            # past the already-delivered count reach the stream.
            if self.stream is not None and len(self.output) > self._replay_n:
                self.stream.put(t)

    def hit_stop(self) -> bool:
        return bool(self.stop_tokens) and bool(self.output) and (
            self.output[-1] in self.stop_tokens)

    def finish_stream(self) -> None:
        if self.stream is not None:
            self.stream.put(None)


@dataclass
class _TenantStats:
    """Per-tenant serving accounting (guarded by the engine lock).

    Latency samples carry their observation time so the quantile
    gauges can be computed over a *recency* window
    (``ServingEngine.tenant_window_s``) rather than a fixed count — a
    tenant whose traffic recovered must see its p95 recover once the
    regression ages out, which is what lets the SLO soak's burn alert
    clear (docs/slo.md)."""

    submitted: int = 0
    completed: int = 0
    rejected: int = 0
    cancelled: int = 0
    # Admission sheds (tpumon.actuate): a distinct terminal status —
    # NOT rejections — so the collector's error-rate distillation can
    # exclude them (a shed is the remedy for an error-rate SLO burn;
    # counting it as an error would re-fire the very SLO that shed).
    shed: int = 0
    tokens: int = 0
    ttft: deque = field(default_factory=lambda: deque(maxlen=512))
    tpot: deque = field(default_factory=lambda: deque(maxlen=512))

    def recent(self, series: deque, window_s: float, now: float) -> list:
        return [v for t, v in series if now - t <= window_s]


@dataclass
class _PrefillWork:
    """Per-slot chunked-prefill progress (the interleaved scheduler's
    unit of preemption): which chunk runs next, how far the draft
    model's own prefill got, and the final chunk's logits once
    produced. A slot holding one is occupied but not yet decoding."""

    req: Request
    n: int                      # prompt length (tokens)
    next_c0: int                # next target chunk's start row
    draft_c0: int = 0           # next draft chunk's start row (spec)
    logits: jax.Array | None = None   # final-chunk logits
    pages: list[int] | None = None    # paged: full reservation
    shared_n: int = 0           # paged: chunks served from shared pages
    table_row: jax.Array | None = None  # paged: this slot's table


@jax.jit
def sample_tokens(logits: jax.Array, base_key: jax.Array, rids: jax.Array,
                  ctrs: jax.Array, temps: jax.Array,
                  topk: jax.Array) -> jax.Array:
    """Per-slot token selection on device, one dispatch for the batch.

    logits [B, V]; rids [B] int32 request ids; ctrs [B] int32 per-request
    token indices; temps [B] (<=0 -> greedy argmax, the default); topk [B]
    (0 -> full vocab). Top-k keeps each row's k highest logits, then
    temperature-scaled categorical sampling.

    Each row's PRNG key folds (request id, token index) into the base
    key — NOT a global step counter — so a request's sampled stream is a
    pure function of (seed, prompt, params): independent of scheduler
    choice, slot assignment, batch composition, and how requests
    interleave. This is the invariant that makes sequential and
    interleaved scheduling token-identical (tests/test_scheduler.py).
    """
    v = logits.shape[-1]
    keys = jax.vmap(
        lambda r, c: jax.random.fold_in(jax.random.fold_in(base_key, r), c)
    )(rids, ctrs)
    sorted_desc = -jnp.sort(-logits, axis=-1)
    k_idx = jnp.clip(jnp.where(topk > 0, topk, v) - 1, 0, v - 1)
    thresh = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)
    masked = jnp.where(logits >= thresh, logits, -1e30)
    scaled = masked / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.vmap(jax.random.categorical)(keys, scaled)
    greedy = jnp.argmax(logits, axis=-1)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


def default_engine_config() -> ServeConfig:
    """The small demo model an engine runs when no config is given."""
    return ServeConfig(
        model=ModelConfig(vocab=512, d_model=128, n_layers=2, n_heads=4,
                          n_kv_heads=2, d_ff=256, max_seq=128),
        slots=4, prefill_len=16,
    )


class ServingEngine:
    """Continuous-batching engine: submit() from any thread, step() (or
    the run loop) drives prefill/decode; /metrics-ready exposition from
    metrics_text()."""

    def __init__(self, cfg: ServeConfig | None = None,
                 params: dict | None = None, seed: int = 0,
                 max_queue: int = 64, ckpt_dir: str | None = None,
                 quantize: str | None = None,
                 draft_params: dict | None = None,
                 mesh=None):
        if cfg is None and ckpt_dir:
            # No explicit config: adopt the checkpoint's own architecture
            # so --loadgen-ckpt serves the trained weights instead of
            # silently falling back to a mismatched default init.
            from tpumon.loadgen.checkpoint import saved_model_config

            saved = saved_model_config(ckpt_dir)
            if saved is not None:
                cfg = ServeConfig(model=saved, slots=4,
                                  prefill_len=min(16, saved.max_seq // 2))
        self.cfg = cfg or default_engine_config()
        if quantize is not None:
            import dataclasses

            self.cfg = dataclasses.replace(self.cfg, quantize=quantize)
        # Validate configuration before any expensive work (param init,
        # device placement, cache allocation).
        if self.cfg.kv_layout not in ("dense", "paged"):
            raise ValueError(f"unknown kv_layout {self.cfg.kv_layout!r}")
        if self.cfg.spec_len < 0:
            raise ValueError(
                f"spec_len must be >= 0, got {self.cfg.spec_len}")
        if self.cfg.spec_source not in ("draft", "prompt"):
            raise ValueError(
                f"unknown spec_source {self.cfg.spec_source!r}")
        if self.cfg.spec_source == "prompt" and self.cfg.draft_model:
            raise ValueError(
                "spec_source='prompt' proposes from the request context "
                "— a draft_model has no role (drop one of the two)")
        if self.cfg.pool_pages and self.cfg.kv_layout != "paged":
            raise ValueError(
                "pool_pages requires kv_layout='paged' (a dense cache "
                "has no page pool to size)")
        if mesh is not None and self.cfg.prefix_cache_entries:
            raise ValueError(
                "a tensor-parallel mesh does not compose with prefix "
                "caching (host-side cache surgery on sharded buffers)")
        if mesh is not None and (
                self.cfg.spec_len and self.cfg.kv_layout != "paged"):
            raise ValueError(
                "over a mesh, speculative decoding composes with the "
                "PAGED layout (r05 _shard_paged_jits); dense-layout "
                "spec is single-device only")
        if mesh is not None and self.cfg.paged_attn == "kernel":
            raise ValueError(
                "paged_attn='kernel' is single-device (the Pallas "
                "kernel is not pjit-partitionable); use the gather "
                "path over a mesh")
        if (
            self.cfg.model.n_experts
            and self.cfg.prefill_len > self.cfg.moe_prefill_max_chunk
        ):
            raise ValueError(
                f"MoE serving at prefill_len={self.cfg.prefill_len} would "
                f"materialize O(G²·E) routing tensors per chunk "
                f"(full-capacity routing, ServeConfig.moe_prefill_max_chunk "
                f"doc): cap is {self.cfg.moe_prefill_max_chunk} tokens — "
                "lower prefill_len (long prompts run as multiple chunks) "
                "or raise moe_prefill_max_chunk knowingly")
        if self.cfg.decode_block < 1:
            raise ValueError(
                f"decode_block must be >= 1, got {self.cfg.decode_block}")
        if self.cfg.scheduler not in ("interleaved", "sequential"):
            raise ValueError(f"unknown scheduler {self.cfg.scheduler!r}")
        if self.cfg.prefill_chunk_budget < 1:
            raise ValueError(
                f"prefill_chunk_budget must be >= 1, got "
                f"{self.cfg.prefill_chunk_budget}")
        if self.cfg.admit_lookahead < 0:
            raise ValueError(
                f"admit_lookahead must be >= 0, got "
                f"{self.cfg.admit_lookahead}")
        if self.cfg.admit_lookahead and self.cfg.kv_layout != "paged":
            raise ValueError(
                "admit_lookahead requires kv_layout='paged' (dense "
                "admission never blocks on pages, so the lookahead "
                "window would silently do nothing)")
        if self.cfg.admit_max_skips < 1:
            raise ValueError(
                f"admit_max_skips must be >= 1, got "
                f"{self.cfg.admit_max_skips}")
        if self.cfg.kv_dtype not in ("compute", "int8"):
            raise ValueError(f"unknown kv_dtype {self.cfg.kv_dtype!r}")
        if self.cfg.paged_attn not in ("gather", "kernel", "ring"):
            raise ValueError(f"unknown paged_attn {self.cfg.paged_attn!r}")
        if self.cfg.paged_attn == "kernel" and (
                self.cfg.kv_layout != "paged"
                or self.cfg.kv_dtype == "int8"):
            raise ValueError(
                "paged_attn='kernel' requires kv_layout='paged' with "
                "kv_dtype='compute' (the Pallas kernel reads bf16/f32 "
                "pages, not the int8 pool)")
        if self.cfg.paged_attn == "ring" and (
                self.cfg.kv_layout != "paged"
                or self.cfg.kv_dtype == "int8"):
            raise ValueError(
                "paged_attn='ring' requires kv_layout='paged' with "
                "kv_dtype='compute' (the blockwise ring accumulator "
                "streams compute-dtype pages, not the int8 pool)")
        if self.cfg.mesh_dp < 1 or self.cfg.mesh_tp < 1:
            raise ValueError(
                f"mesh_dp/mesh_tp must be >= 1, got "
                f"{self.cfg.mesh_dp}x{self.cfg.mesh_tp}")
        if self.cfg.mesh_dp * self.cfg.mesh_tp > 1:
            raise ValueError(
                "ServeConfig.mesh_dp/mesh_tp describe a dp×tp mesh "
                "engine — construct a MeshServingEngine (or pass "
                "--loadgen-mesh dp,tp), not a plain ServingEngine")
        if self.cfg.ring_stripes:
            if self.cfg.ring_stripes < 2:
                raise ValueError(
                    f"ring_stripes must be 0 (off) or >= 2, got "
                    f"{self.cfg.ring_stripes} (one stripe IS the flat "
                    "layout)")
            if self.cfg.kv_layout != "paged":
                raise ValueError(
                    "ring_stripes requires kv_layout='paged' (ring mode "
                    "pages KV block-wise around the tp ring — a dense "
                    "cache has no pages to stripe)")
            if self.cfg.spec_len:
                raise ValueError(
                    "ring_stripes does not compose with speculative "
                    "decoding (the draft cache is one stripe wide; a "
                    "ring-admitted context would overrun it)")
            if self.cfg.paged_attn == "kernel":
                raise ValueError(
                    "ring_stripes does not compose with "
                    "paged_attn='kernel' (the Pallas kernel's geometry "
                    "is pinned to one chip's stripe); use the gather "
                    "or ring read path")
        if self.cfg.kv_dtype == "int8" and (
                mesh is not None
                or ((self.cfg.spec_len or self.cfg.prefix_cache_entries)
                    and self.cfg.kv_layout != "paged")):
            raise ValueError(
                "kv_dtype='int8' composes with the dense engine (with "
                "decode_block and int8 weights) and the full paged "
                "engine (incl. prefix caching and speculative "
                "decoding) — not with a mesh, or with the DENSE "
                "layout's speculative/prefix cache surgery")
        m = self.cfg.model
        # Ring-attention engine mode: the admission/position ceiling.
        # Flat engines cap sequences at max_seq; ring engines stripe
        # ring_stripes × max_seq KV rows around the tp ring, so every
        # completion check, position clamp and the submit() refusal
        # work against _seq_cap instead. The paged kernels derive all
        # geometry from the page-table width, so widening the tables
        # (below) is the ONLY device-side change ring mode needs.
        self._seq_cap = max(1, self.cfg.ring_stripes or 1) * m.max_seq
        self.params = params if params is not None else init_params(
            m, jax.random.PRNGKey(seed))
        self.ckpt_step: int | None = None
        if params is None and ckpt_dir:
            # Serve trained weights: resume from the trainer's orbax
            # checkpoint (tpumon.loadgen.train) when the architecture
            # matches; otherwise keep the fresh init (best-effort, like
            # every other tpumon resume path) — but say so, loudly.
            from tpumon.loadgen.checkpoint import restore_checkpoint

            restored = restore_checkpoint(ckpt_dir, like=self.params, cfg=m)
            if restored is not None:
                self.params, self.ckpt_step = restored
            else:
                print(
                    f"serving: no compatible checkpoint in {ckpt_dir!r}; "
                    "serving FRESH INIT weights",
                    file=sys.stderr,
                )
        if self.cfg.quantize == "int8":
            # Quantize AFTER any checkpoint restore: int8 is a serving-time
            # representation, never what the trainer writes.
            from tpumon.loadgen.quant import quantize_params

            self.params = quantize_params(self.params)
        elif self.cfg.quantize is not None:
            raise ValueError(f"unknown quantize mode {self.cfg.quantize!r}")
        # params stay a traced argument (closure capture would bake the
        # weights into the executable as constants, duplicating them in
        # HBM); only the cache is donated for in-place updates.
        self.mesh = mesh
        if mesh is not None and self.cfg.kv_layout == "paged":
            # Paged over a mesh: the single-device jits below are
            # placeholders — the paged setup block re-points every
            # paged fn (and the spec draft/verify) at tensor-parallel
            # versions via _shard_paged_jits.
            self._prefill = jax.jit(partial(prefill, self.cfg),
                                    donate_argnums=(1,))
            self._decode = jax.jit(partial(decode_step, self.cfg),
                                   donate_argnums=(1,))
            self._decode_rounds = None
        elif mesh is not None:
            # Tensor-parallel engine: the whole continuous-batching loop
            # runs over the mesh — Megatron-split projections, KV cache
            # sharded on its head axis, XLA inserting the psums over ICI
            # (make_sharded_serving). Same call signatures as the
            # single-chip jits (params are pre-placed, so the params
            # argument the engine passes is ignored via the adapters).
            pre_fn, dec_fn, placed, placed_cache, rounds_fn = (
                make_sharded_serving(self.cfg, mesh, self.params))
            self.params = placed
            self.cache = placed_cache  # sharded on the KV-head axis
            self._prefill = (
                lambda _params, cache, toks, ln, slot, start:
                pre_fn(cache, toks, ln, slot, start))
            self._decode = (
                lambda _params, cache, last, positions:
                dec_fn(cache, last, positions))
            self._decode_rounds = (
                (lambda _params, cache, last, positions, key, rids, ctr,
                 temps, topks, steps:
                 rounds_fn(cache, last, positions, key, rids, ctr,
                           temps, topks, steps))
                if self.cfg.decode_block > 1 else None)
        else:
            self._prefill = jax.jit(partial(prefill, self.cfg),
                                    donate_argnums=(1,))
            self._decode = jax.jit(partial(decode_step, self.cfg),
                                   donate_argnums=(1,))
            self._decode_rounds = None
            if self.cfg.decode_block > 1 and self.cfg.kv_layout != "paged":
                self._decode_rounds = jax.jit(
                    partial(decode_rounds, self.cfg),
                    static_argnames=("steps",), donate_argnums=(1,))
        # Speculative decoding state (after quantization so a self-
        # speculating draft shares the quantized weights, not a second
        # f32 copy).
        self.spec_len = self.cfg.spec_len
        if self.spec_len and self.cfg.spec_source == "prompt":
            # Prompt-lookup proposals (loadgen.prompt_lookup): no draft
            # model, no draft cache — only the verify jit is needed.
            from tpumon.loadgen.speculative import decode_block

            self.draft_params = None
            self._draft_pos = [0] * self.cfg.slots  # unused; kept uniform
            self._verify = jax.jit(
                partial(decode_block, self.cfg), donate_argnums=(1,))
        elif self.spec_len:
            import dataclasses as _dc

            from tpumon.loadgen.speculative import decode_block

            dm = self.cfg.draft_model or m
            if dm.vocab != m.vocab or dm.max_seq != m.max_seq:
                raise ValueError(
                    "draft_model must share vocab and max_seq with the "
                    f"target (draft {dm.vocab}/{dm.max_seq} vs "
                    f"target {m.vocab}/{m.max_seq})")
            if self.cfg.draft_model is not None and dm.n_layers >= m.n_layers:
                # As deep as the target = self-speculation with extra
                # steps (and a deeper draft would silently truncate to
                # exactly that while over-allocating its KV cache) —
                # reported acceptance would be the r03 tautology.
                raise ValueError(
                    f"draft_model must be shallower than the target "
                    f"({dm.n_layers} >= {m.n_layers} layers; use "
                    "draft_model=None for self-speculation)")
            self._draft_scfg = ServeConfig(
                model=dm, slots=self.cfg.slots,
                prefill_len=self.cfg.prefill_len)
            if draft_params is not None:
                self.draft_params = draft_params
            elif self.cfg.draft_model is None:
                self.draft_params = self.params  # self-speculation
            elif dm == _dc.replace(m, n_layers=dm.n_layers):
                # Layer-truncated draft (--spec-draft-layers): share the
                # target's first k layers + embed/head instead of random
                # weights — a fresh random draft agrees with the target
                # ~1/vocab of the time, which makes acceptance (and the
                # whole speculative path) meaningless.
                self.draft_params = {
                    "embed": self.params["embed"],
                    "layers": self.params["layers"][:dm.n_layers],
                    "final_norm": self.params["final_norm"],
                    "lm_head": self.params["lm_head"],
                }
            else:
                self.draft_params = init_params(
                    dm, jax.random.PRNGKey(seed + 1))
            self._draft_prefill = jax.jit(
                partial(prefill, self._draft_scfg), donate_argnums=(1,))
            self._draft_decode = jax.jit(
                partial(decode_step, self._draft_scfg), donate_argnums=(1,))
            self._verify = jax.jit(
                partial(decode_block, self.cfg), donate_argnums=(1,))
            self.draft_cache = init_cache(self._draft_scfg)
            # Per-slot draft cache write frontier: rows < _draft_pos[s]
            # hold valid K/V of the true sequence. Falls behind the
            # target position when plain-step fallbacks run (they never
            # touch the draft cache); _spec_round catches it up before
            # proposing so acceptance doesn't silently collapse.
            self._draft_pos = [0] * self.cfg.slots
        self.spec_rounds_total = 0
        self.spec_proposed_total = 0
        self.spec_accepted_total = 0
        self.prefix_cache = None
        self.paged = self.cfg.kv_layout == "paged"
        if self.cfg.prefix_cache_entries and not self.paged:
            from tpumon.loadgen.prefix_cache import PrefixCache

            self.prefix_cache = PrefixCache(
                chunk=self.cfg.prefill_len,
                max_entries=self.cfg.prefix_cache_entries)
        # Paged KV mode (tpumon.loadgen.paged_kv).
        if self.paged:
            from tpumon.loadgen.paged_kv import (
                PageAllocator,
                init_pool,
                paged_decode_block,
                paged_decode_step,
                paged_prefill,
            )

            p = self.cfg.prefill_len
            # Per-slot table width: ring mode widens each slot's table
            # to the full ring capacity (stripe s owns page block s of
            # the row).
            self._max_pages = -(-self._seq_cap // p)
            pool_pages = self.cfg.pool_pages or (
                self.cfg.slots * self._max_pages + 1)
            if pool_pages < 2:
                raise ValueError("pool_pages must be >= 2")
            self.pool = init_pool(self.cfg, pool_pages)
            self.allocator = PageAllocator(pool_pages)
            # Page 0 is the permanent trash page: freed slots' tables
            # point at it so their garbage batched-decode writes can
            # never corrupt pages reallocated to live requests.
            trash = self.allocator.alloc(1)
            assert trash == [0]
            if self.cfg.prefix_cache_entries:
                # Paged prefix caching: page == prefill chunk, so a
                # cached prefix is shared by POINTING new requests'
                # tables at the same pages — no HBM copy at all (the
                # dense cache's restore is a copy). Exposes the same
                # counter surface as the dense PrefixCache, so the
                # /metrics block below serves both unchanged.
                from tpumon.loadgen.paged_kv import PagePrefixCache

                self.prefix_cache = PagePrefixCache(
                    chunk=p, allocator=self.allocator,
                    max_entries=self.cfg.prefix_cache_entries)
                self.prefix_cache.page_bytes = sum(
                    v.nbytes for v in self.pool.values()) // pool_pages
            self._slot_pages: list[list[int]] = [
                [] for _ in range(self.cfg.slots)]
            self._tables_host = [
                [0] * self._max_pages for _ in range(self.cfg.slots)]
            self._tables_dev = jnp.zeros(
                (self.cfg.slots, self._max_pages), jnp.int32)
            self._tables_dirty = False
            self._paged_prefill = jax.jit(
                partial(paged_prefill, self.cfg), donate_argnums=(1,))
            self._paged_decode = jax.jit(
                partial(paged_decode_step, self.cfg), donate_argnums=(1,))
            if self.spec_len:
                # Speculative verify over the pool: re-point the verify
                # jit at the paged twin (same contract — logits[:, t]
                # predicts row positions+t+1; rejected rows overwritten
                # by later true tokens, trash page absorbs overshoot).
                self._verify = jax.jit(
                    partial(paged_decode_block, self.cfg),
                    donate_argnums=(1,))
            if self.cfg.decode_block > 1:
                from tpumon.loadgen.paged_kv import paged_decode_rounds

                self._decode_rounds = jax.jit(
                    partial(paged_decode_rounds, self.cfg,
                            seq_cap=self._seq_cap),
                    static_argnames=("steps",), donate_argnums=(1,))
            if mesh is not None:
                self._shard_paged_jits(mesh)
        if self.paged:
            self.cache = None
        elif mesh is None:
            self.cache = init_cache(self.cfg)
        # (mesh mode set self.cache when the sharded jits were built)
        self.positions = jnp.zeros((self.cfg.slots,), jnp.int32)
        self._host_positions = [0] * self.cfg.slots  # mirror, avoids syncs
        self.last_tokens = jnp.zeros((self.cfg.slots,), jnp.int32)
        self._host_last = [0] * self.cfg.slots  # mirror of last_tokens
        # Per-slot sampling settings (device-resident; updated on admit).
        self.temps = jnp.zeros((self.cfg.slots,), jnp.float32)
        self.topks = jnp.zeros((self.cfg.slots,), jnp.int32)
        self._sample_key = jax.random.PRNGKey(seed ^ 0x7A11)
        # Per-slot sampling identity: the occupying request's id and its
        # next token index (== len(req.output) while decoding). Together
        # with _sample_key these fully determine every sampled token —
        # sample_tokens keys per (rid, index), never per engine step.
        self.rids = jnp.zeros((self.cfg.slots,), jnp.int32)
        self.tok_ctrs = jnp.zeros((self.cfg.slots,), jnp.int32)
        self._slots: list[Request | None] = [None] * self.cfg.slots
        # In-flight chunked-prefill state per slot (interleaved
        # scheduler): a slot with a _PrefillWork is occupied but not yet
        # decoding — excluded from decode batches until its final chunk
        # yields first-token logits.
        self._prefill_work: list[_PrefillWork | None] = (
            [None] * self.cfg.slots)
        self._prefill_rr = 0  # round-robin cursor over in-prefill slots
        # Lookahead aging (guarded by _lock): how often the CURRENT
        # queue head has been jumped. _head_rid pins the count to one
        # request, so a cancelled/purged head can't bequeath its aged
        # state to an innocent successor.
        self._head_skips = 0
        self._head_rid = -1
        self._queue: deque[Request] = deque()
        self.max_queue = max_queue
        self._rid = itertools.count()
        self._lock = threading.Lock()
        # metrics state (guarded by _lock)
        self.tokens_total = 0
        self.requests_total = 0
        self.rejected_total = 0
        self.cancelled_total = 0
        self.completed_total = 0
        self.decode_steps_total = 0
        self._ttft_counts = [0] * len(TTFT_BUCKETS_S)
        self._ttft_inf = 0
        self._ttft_sum = 0.0
        # Recent per-request latency windows for the p50/p95 gauges
        # (tracing.quantiles over a bounded deque — the same single-sort
        # summary the monitor's own SourceStats use). TPOT = decode
        # seconds per output token after the first.
        self._ttft_recent: deque[float] = deque(maxlen=512)
        self._tpot_recent: deque[float] = deque(maxlen=512)
        # Per-tenant accounting (guarded by _lock), keyed by the
        # Request.tenant tag; untagged requests ("") are not tracked.
        # tenant_window_s bounds the recency window the per-tenant
        # quantile gauges are computed over.
        self.tenants: dict[str, _TenantStats] = {}
        self.tenant_window_s = 60.0
        # --- actuation surface (tpumon.actuate, docs/actuation.md) ---
        # Per-tenant admission-shed fractions ("*" = every request) and
        # the deterministic pacing accumulators behind them (fraction
        # 0.5 sheds exactly every 2nd submission — reproducible, no
        # RNG), both guarded by _lock. shed_total/requeued_total feed
        # the tpumon_serving_requests_{shed,requeued} counters.
        self._shed: dict[str, float] = {}
        self._shed_acc: dict[str, float] = {}
        self.shed_total = 0
        self.requeued_total = 0
        # dp-replica placement domains (set_slices): admitted requests
        # are attributed round-robin; drain_slice marks a domain
        # drained — its in-flight requests abort-and-requeue at the
        # next step (the sweep runs on the step thread, like request
        # cancellation) and new placements avoid it until undrained.
        self.slices: tuple[str, ...] = ()
        self._slice_rr = 0
        self._drained: set[str] = set()
        # Optional tpumon.loadgen.report.WorkloadReporter: when attached,
        # step() time counts as declared device activity (source:
        # workload in the monitor's counter chain).
        self.reporter = None

    def _shard_paged_jits(self, mesh) -> None:
        """Tensor-parallel PAGED serving (r05): re-point every paged
        engine fn at a pjit over mesh axis "model".

        The Megatron param split (model.PARAM_SPECS) carries over
        exactly as in make_sharded_serving; the page POOL shards on its
        kv-head axis (``[layers, kv_heads, pages, page, hd]`` →
        "model" on axis 1) so both the batched append scatter and the
        attention gather touch only device-local pages — page tables
        are host-side ints and replicate. Slots are NOT data-parallel
        here (continuous batching is serving's batch axis), so the mesh
        must be tp-only. Speculative decoding composes: the draft's
        dense cache shards on ITS kv-head axis, the layer-truncated
        draft re-slices the PLACED target params (pure aliasing — no
        second copy in HBM), and the paged verify block runs over the
        sharded pool. The Pallas kernel path does not (manual-mode
        kernel; engine init rejects it with a mesh).
        """
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tpumon.loadgen.model import param_shardings
        from tpumon.loadgen.paged_kv import (
            paged_decode_block,
            paged_decode_rounds,
            paged_decode_step,
            paged_prefill,
        )

        tp = mesh.shape["model"]
        dp = mesh.shape.get("data", 1)
        if dp != 1:
            raise ValueError(
                "paged serving over a mesh is tensor-parallel only "
                f"(axis 'model'); got data={dp} — slots batch via "
                "continuous batching, not a data axis")
        if self.cfg.model.n_kv_heads % tp:
            raise ValueError(
                f"n_kv_heads={self.cfg.model.n_kv_heads} not divisible "
                f"by tp={tp}")
        from tpumon.loadgen.model import _check_moe_tp

        _check_moe_tp(self.cfg.model, mesh)
        # Capture draft aliasing BEFORE rebinding self.params: after
        # device_put the old identities are gone.
        draft_is_target = self.spec_len and self.draft_params is self.params
        draft_shares_layers = (
            self.spec_len and not draft_is_target
            and isinstance(self.draft_params, dict)
            and self.draft_params.get("layers")
            and self.draft_params["layers"][0]
            is self.params["layers"][0])
        shardings = param_shardings(mesh, self.params)
        self.params = jax.device_put(self.params, shardings)
        rep = NamedSharding(mesh, P())
        pool_sh = {
            k: NamedSharding(mesh, P(None, "model", None, None, None))
            for k in self.pool
        }
        self.pool = jax.device_put(self.pool, pool_sh)
        self._paged_prefill = jax.jit(
            partial(paged_prefill, self.cfg),
            in_shardings=(shardings, pool_sh, rep, rep, rep, rep, rep),
            out_shardings=(pool_sh, rep), donate_argnums=(1,))
        self._paged_decode = jax.jit(
            partial(paged_decode_step, self.cfg),
            in_shardings=(shardings, pool_sh, rep, rep, rep),
            out_shardings=(pool_sh, rep), donate_argnums=(1,))
        if self.cfg.decode_block > 1:
            _rounds = jax.jit(
                partial(paged_decode_rounds, self.cfg,
                        seq_cap=self._seq_cap),
                in_shardings=(shardings, pool_sh,
                              rep, rep, rep, rep, rep, rep, rep, rep),
                out_shardings=(pool_sh, rep, rep, rep),
                # static_argnums, not argnames: pjit with in_shardings
                # rejects kwargs; the engine passes steps= by keyword,
                # so adapt positionally. steps is arg index 10 after
                # partial(cfg): params, pool, last, positions, tables,
                # key, rids, ctr, temps, topks, steps.
                static_argnums=(10,), donate_argnums=(1,))
            self._decode_rounds = (
                lambda params, pool, last, pos, tables, key, rids, ctr,
                temps, topks, steps:
                _rounds(params, pool, last, pos, tables, key, rids, ctr,
                        temps, topks, steps))
        if self.spec_len and self.cfg.spec_source == "prompt":
            from tpumon.loadgen.paged_kv import paged_decode_block as _pdb

            self._verify = jax.jit(
                partial(_pdb, self.cfg),
                in_shardings=(shardings, pool_sh, rep, rep, rep),
                out_shardings=(pool_sh, rep), donate_argnums=(1,))
            return
        if self.spec_len:
            dm = self._draft_scfg.model
            # Re-derive the draft from the PLACED target so shared
            # leaves stay aliases of the sharded arrays (no second
            # HBM copy); a genuinely distinct draft is placed itself.
            if draft_is_target:
                self.draft_params = self.params  # self-speculation
            elif draft_shares_layers:
                self.draft_params = {
                    "embed": self.params["embed"],
                    "layers": self.params["layers"][:dm.n_layers],
                    "final_norm": self.params["final_norm"],
                    "lm_head": self.params["lm_head"],
                }
            else:
                self.draft_params = jax.device_put(
                    self.draft_params,
                    param_shardings(mesh, self.draft_params))
            d_shard = param_shardings(mesh, self.draft_params)
            dcache_sh = {
                k: NamedSharding(mesh, P(None, None, None, "model", None))
                for k in self.draft_cache
            }
            self.draft_cache = jax.device_put(self.draft_cache, dcache_sh)
            self._draft_prefill = jax.jit(
                partial(prefill, self._draft_scfg),
                in_shardings=(d_shard, dcache_sh, rep, rep, rep, rep),
                out_shardings=(dcache_sh, rep), donate_argnums=(1,))
            self._draft_decode = jax.jit(
                partial(decode_step, self._draft_scfg),
                in_shardings=(d_shard, dcache_sh, rep, rep),
                out_shardings=(dcache_sh, rep), donate_argnums=(1,))
            self._verify = jax.jit(
                partial(paged_decode_block, self.cfg),
                in_shardings=(shardings, pool_sh, rep, rep, rep),
                out_shardings=(pool_sh, rep), donate_argnums=(1,))

    # -- submission ---------------------------------------------------------

    def _tenant_locked(self, req: Request) -> "_TenantStats | None":
        """The request's tenant stats record (caller holds the lock);
        None for untagged requests."""
        if not req.tenant:
            return None
        st = self.tenants.get(req.tenant)
        if st is None:
            st = self.tenants[req.tenant] = _TenantStats()
        return st

    def submit(self, prompt: list[int], max_new: int = 16,
               temperature: float = 0.0, top_k: int = 0,
               stream: bool = False,
               stop_tokens: tuple = (), tenant: str = "",
               rid: int | None = None) -> Request:
        """Enqueue a request. When the queue is full the request is
        rejected immediately (done is set, output stays empty) — the
        backpressure a real serving frontend applies instead of letting
        latency grow without bound. temperature 0 = greedy; top_k 0 =
        full vocab. Prompts may exceed prefill_len — they run as chunked
        prefill — but a prompt over the engine's sequence capacity
        (max_seq-1 rows flat; ring_stripes*max_seq - 1 in ring mode) is
        REFUSED with status="rejected": truncating would silently serve
        a different prompt, and the refusal is exactly the admission
        boundary ring mode exists to move. stream=True attaches a queue
        (req.stream) that receives each token as it is emitted, None at
        end of stream. ``rid`` overrides the engine-local id — the mesh
        router owns the rid namespace so streams stay pure functions of
        (seed, prompt, params) regardless of which replica serves them."""
        m = self.cfg.model
        max_new = max(0, int(max_new))  # negatives would corrupt paged
        # reservation math and mean nothing in any mode
        prompt = [t % m.vocab for t in prompt]
        over_cap = len(prompt) > self._seq_cap - 1
        req = Request(rid=rid if rid is not None else next(self._rid),
                      prompt=prompt or [0],
                      max_new=max_new, enqueued=time.monotonic(),
                      temperature=float(temperature), top_k=int(top_k),
                      stream=queue.Queue() if stream else None,
                      stop_tokens=tuple(int(t) for t in stop_tokens),
                      tenant=str(tenant))
        infeasible = over_cap or (self.paged and self._pages_needed(
            req) > self.allocator.num_pages - 1)
        with self._lock:
            # Cancelled entries must not consume queue capacity.
            self._purge_cancelled_locked()
            tst = self._tenant_locked(req)
            if tst is not None:
                tst.submitted += 1
            # Actuation shed (tpumon.actuate): a per-tenant admission
            # throttle. Deterministic pacing — the fraction accumulates
            # and sheds on overflow, so fraction f drops exactly
            # round(n*f) of n submissions, reproducibly. A shed is its
            # own terminal status, never a rejection (error-rate math).
            frac = (
                self._shed[req.tenant]
                if req.tenant in self._shed
                else self._shed.get("*", 0.0)
            )
            if frac > 0.0:
                acc = self._shed_acc.get(req.tenant, 0.0) + frac
                if acc >= 1.0:
                    acc -= 1.0
                    self._shed_acc[req.tenant] = acc
                    self.shed_total += 1
                    if tst is not None:
                        tst.shed += 1
                    req.status = "shed"
                    req.finish_stream()
                    req.done.set()
                    return req
                self._shed_acc[req.tenant] = acc
            if len(self._queue) >= self.max_queue or infeasible:
                # Queue full, or (paged) the reservation can never be
                # satisfied by the whole pool — rejecting beats wedging
                # the queue head forever.
                self.rejected_total += 1
                if tst is not None:
                    tst.rejected += 1
                req.status = "rejected"
                req.finish_stream()
                req.done.set()
                return req
            self._queue.append(req)
            self.requests_total += 1
        return req

    # -- actuation surface (tpumon.actuate, docs/actuation.md) --------------

    def set_shed(self, tenant: str, fraction: float) -> float:
        """Set the admission-shed fraction for ``tenant`` ("*" = every
        tenant without its own entry); <= 0 removes the throttle.
        Clamped to SHED_CAP — whatever the actuation layer asks for,
        some live traffic always survives to prove recovery. Returns
        the effective fraction."""
        frac = min(float(fraction), SHED_CAP)
        with self._lock:
            if frac <= 0.0:
                self._shed.pop(tenant, None)
                if tenant == "*":
                    # "*"-paced tenants accumulate under their OWN
                    # names: drop every accumulator not owned by a
                    # tenant-specific throttle, so the next episode
                    # starts at a fresh accumulator (deterministic
                    # pacing is per-episode) and nothing leaks.
                    for t in [t for t in self._shed_acc
                              if t not in self._shed]:
                        self._shed_acc.pop(t, None)
                else:
                    self._shed_acc.pop(tenant, None)
                return 0.0
            self._shed[tenant] = frac
            return frac

    def shed_fractions(self) -> dict[str, float]:
        with self._lock:
            return dict(self._shed)

    def nudge_capacity(self, prefill_budget: int | None = None,
                       admit_lookahead: int | None = None) -> dict:
        """Adjust the scheduler knobs live (the capacity-nudge action):
        prefill chunk dispatches per step and — paged engines only —
        the admission lookahead window. Safe to swap mid-flight: the
        jitted kernels closed over the ORIGINAL ServeConfig (the knobs
        never reach a trace), and both fields are read fresh each step.
        Returns the effective values, the actuator's revert baseline."""
        kw = {}
        if prefill_budget is not None:
            kw["prefill_chunk_budget"] = max(1, int(prefill_budget))
        if admit_lookahead is not None and self.paged:
            kw["admit_lookahead"] = max(0, int(admit_lookahead))
        if kw:
            self.cfg = dc_replace(self.cfg, **kw)
        return {"prefill_budget": self.cfg.prefill_chunk_budget,
                "admit_lookahead": self.cfg.admit_lookahead}

    def set_slices(self, names) -> None:
        """Declare the dp-replica placement domains requests are
        attributed to (round-robin at slot assignment). Renaming drops
        drain marks for domains that no longer exist."""
        with self._lock:
            self.slices = tuple(str(n) for n in names)
            self._slice_rr = 0
            self._drained &= set(self.slices)

    def drain_slice(self, name: str) -> None:
        """Mark a placement domain drained: its in-flight requests
        abort-and-requeue at the next step (the sweep runs on the step
        thread, like request cancellation — docs/actuation.md), and new
        placements avoid it until ``undrain_slice``."""
        with self._lock:
            self._drained.add(str(name))

    def undrain_slice(self, name: str) -> None:
        with self._lock:
            self._drained.discard(str(name))

    def drained_slices(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._drained))

    def _requeue_slot(self, slot: int) -> None:
        """Drain-and-requeue one slot: abort the request mid-flight,
        free its slot (and paged pages) and re-admit it at the queue
        HEAD, so the recompute — prefix-cheap when the prompt is in the
        prefix cache — starts ahead of fresh arrivals. The re-run
        regenerates a bit-identical token prefix (sampling is keyed per
        (rid, token index)); ``_replay_n`` keeps already-delivered
        stream tokens from reaching the consumer twice."""
        req = self._slots[slot]
        self._slots[slot] = None
        self._prefill_work[slot] = None
        self._release_slot_pages(slot)
        req.slice = None
        req.requeues += 1
        req._replay_n = max(req._replay_n, len(req.output))
        req.output = []
        with self._lock:
            self.requeued_total += 1
            self._queue.appendleft(req)

    # -- mesh-replica surface (MeshServingEngine) ---------------------------

    def load(self) -> int:
        """Queued + in-flight request count — the mesh router's
        tie-break signal when no replica holds a cached prefix."""
        with self._lock:
            qd = len(self._queue)
        return qd + sum(1 for s in self._slots if s is not None)

    def prefix_hit_len(self, prompt: list[int]) -> int:
        """Longest cached chunk-aligned prefix (tokens) this engine
        already holds for ``prompt`` — side-effect-free (the router's
        affinity probe must not touch hit/miss counters or LRU order).
        0 with no prefix cache. Both cache kinds expose ``peek``; the
        paged one returns (len, pages), the dense one the bare length."""
        if self.prefix_cache is None:
            return 0
        got = self.prefix_cache.peek(prompt)
        return int(got[0] if isinstance(got, tuple) else got)

    def adopt(self, req: Request) -> None:
        """Take ownership of an existing Request at the queue head —
        the mesh drain path moves in-flight work between replicas
        WITHOUT minting a new rid, so the re-run on the new replica
        replays a bit-identical stream (sampling is keyed per
        (rid, token index)). Counters were already charged by the
        original submit/requeue, so adoption charges nothing."""
        with self._lock:
            self._queue.appendleft(req)

    def evict_all(self) -> "list[Request]":
        """Drain this engine for the mesh router: abort-and-requeue
        every in-flight slot (the _requeue_slot replay contract — rid
        and delivered-stream watermark preserved) and hand back the
        whole queue, leaving the engine empty. The router re-routes
        the returned requests to un-drained replicas."""
        for slot in range(self.cfg.slots):
            if self._slots[slot] is not None:
                self._requeue_slot(slot)
        with self._lock:
            out = list(self._queue)
            self._queue.clear()
        return out

    # -- engine loop --------------------------------------------------------

    def _observe_ttft(self, dt_s: float) -> None:
        for i, bound in enumerate(TTFT_BUCKETS_S):
            if dt_s <= bound:
                self._ttft_counts[i] += 1
                break
        else:
            self._ttft_inf += 1
        self._ttft_sum += dt_s
        self._ttft_recent.append(dt_s)

    def _pages_needed(self, req: Request) -> int:
        """Worst-case page reservation: KV rows 0..prompt+max_new-1,
        capped by the max_seq-1 position clamp."""
        rows = len(req.prompt) + req.max_new
        return max(1, min(-(-rows // self.cfg.prefill_len),
                          self._max_pages))

    def _purge_cancelled_locked(self) -> None:
        """Drop cancelled requests anywhere in the queue (caller holds
        the lock): they must not consume capacity or ever run. Counted
        as cancellations, not completions."""
        if not any(r.cancelled.is_set() for r in self._queue):
            return
        kept: deque[Request] = deque()
        for r in self._queue:
            if r.cancelled.is_set():
                self.cancelled_total += 1
                tst = self._tenant_locked(r)
                if tst is not None:
                    tst.cancelled += 1
                r.status = "cancelled"
                r.finish_stream()
                r.done.set()
            else:
                kept.append(r)
        self._queue = kept

    def _sync_tables(self) -> None:
        """Upload the host page tables when admission changed them."""
        if self._tables_dirty:
            self._tables_dev = jnp.asarray(self._tables_host, jnp.int32)
            self._tables_dirty = False

    def _reserve_next_locked(self) -> tuple[Request, list, int] | None:
        """Pick the next admissible queued request (caller holds the
        lock; paged only): probe the head, then — bounded lookahead —
        up to ``admit_lookahead`` requests behind it, admitting the
        first whose page reservation succeeds. Probes use the prefix
        cache's side-effect-free ``peek``; the hit/miss/retain
        accounting (``lookup``) runs only for the request actually
        admitted, so a blocked head re-probed every step leaves no
        counter trace. Aging: every queue-jump past a blocked head
        bumps ``_head_skips``; at ``admit_max_skips`` the lookahead
        window collapses to the head alone until it admits, so
        sustained prefix-hit traffic can't starve it. Returns
        (request, pages, shared_chunks) or None when nothing fits."""
        if self._queue[0].rid != self._head_rid:
            # New head (admitted predecessor, or a cancelled head was
            # purged): its age starts fresh.
            self._head_rid = self._queue[0].rid
            self._head_skips = 0
        aged_out = self._head_skips >= self.cfg.admit_max_skips
        window = 1 if aged_out else 1 + self.cfg.admit_lookahead
        for i, cand in enumerate(self._queue):
            if i >= window:
                break
            shared: list[int] = []
            if self.prefix_cache is not None:
                _, shared = self.prefix_cache.peek(cand.prompt)
            need = self._pages_needed(cand) - len(shared)
            pages = self.allocator.alloc(need)
            if i == 0:
                # Head under pool pressure may evict cache entries
                # (their pinned pages are reclaimable capacity);
                # lookahead candidates must fit WITHOUT eviction —
                # a queue-jumper doesn't get to churn the cache. The
                # head's own peeked prefix is protected from eviction:
                # without that, freeing pages FOR the head could evict
                # the prefix it is about to share and silently turn its
                # hit into a full recompute.
                protect = tuple(cand.prompt[:len(shared)
                                            * self.cfg.prefill_len])
                while pages is None and (
                        self.prefix_cache is not None
                        and self.prefix_cache.evict_one(
                            protect=protect or None)):
                    # The protected key IS the longest cached prefix,
                    # so the peeked (shared, need) pair cannot change
                    # under eviction — only retry the allocation.
                    pages = self.allocator.alloc(need)
            if pages is None:
                continue
            if self.prefix_cache is not None:
                # The real lookup: retains the shared pages, counts the
                # hit/miss, touches LRU — only now that admission is
                # certain.
                _, shared = self.prefix_cache.lookup(cand.prompt)
            if i == 0:
                self._queue.popleft()
                self._head_skips = 0
            else:
                del self._queue[i]
                self._head_skips += 1
            return cand, shared + pages, len(shared)
        return None

    def _admit(self) -> None:
        """Assign queued requests to free slots. Assignment reserves
        resources (pages / dense prefix restore) and creates the slot's
        prefill work state; the prefill chunk dispatches themselves run
        in ``_prefill_tick`` — at most ``prefill_chunk_budget`` per
        step under the interleaved scheduler, exhaustively and inline
        under ``scheduler="sequential"`` (the stop-the-world bench
        baseline)."""
        with self._lock:
            self._purge_cancelled_locked()
        for slot in range(self.cfg.slots):
            if self._slots[slot] is not None:
                continue
            with self._lock:
                if not self._queue:
                    return
                if self.paged:
                    picked = self._reserve_next_locked()
                    if picked is None:
                        return  # head (and window) blocked on pages
                    req, pages, shared_n = picked
                else:
                    req, pages, shared_n = self._queue.popleft(), None, 0
            self._assign_slot(slot, req, pages, shared_n)
            if self.cfg.scheduler == "sequential":
                self._drain_prefill_slot(slot)

    def _assign_slot(self, slot: int, req: Request, pages: list | None,
                     shared_n: int) -> None:
        """Install ``req`` into ``slot`` in the in-prefill state: page
        table / dense prefix restore, prefill work record, and the
        garbage-write parking of the slot's position."""
        n = len(req.prompt)
        p = self.cfg.prefill_len
        # Placement-domain attribution (tpumon.actuate drain-and-
        # requeue): round-robin over the non-drained domains; when
        # every domain is drained, placement proceeds anyway (refusing
        # admission would wedge the queue) and the per-step drain
        # sweep re-homes the request as soon as any domain is
        # undrained while the mark persists.
        if self.slices and req.slice is None:
            avail = [s for s in self.slices if s not in self._drained]
            pool = avail or list(self.slices)
            req.slice = pool[self._slice_rr % len(pool)]
            self._slice_rr += 1
        work = _PrefillWork(req=req, n=n, next_c0=shared_n * p,
                            pages=pages, shared_n=shared_n)
        if self.paged:
            self._slot_pages[slot] = pages
            trow = self._tables_host[slot]
            for i in range(self._max_pages):
                trow[i] = pages[i] if i < len(pages) else 0
            self._tables_dirty = True
            work.table_row = jnp.asarray(trow, jnp.int32)
        elif self.prefix_cache is not None:
            # Dense prefix restore is ONE HBM copy — run it at
            # assignment (hit/miss accounting here IS the admission).
            work.next_c0 = self.prefix_cache.restore(
                self.cache, req.prompt, jnp.int32(slot))
        self._slots[slot] = req
        self._prefill_work[slot] = work
        # Park the slot's position on the last row while prefill is in
        # flight: batched decode dispatches still compute this slot (and
        # write garbage K/V at its position), and a stale position could
        # land that garbage on a row an earlier chunk already filled.
        # The last capacity row (_seq_cap-1; max_seq-1 flat) is never a
        # prompt row (prompts cap one short of capacity) and is
        # legitimately rewritten in the same dispatch that first attends
        # it, so garbage there is dead.
        park = self._seq_cap - 1
        self.positions = self.positions.at[slot].set(park)
        self._host_positions[slot] = park

    def _drain_prefill_slot(self, slot: int) -> None:
        """Run this slot's remaining prefill chunks to completion (the
        sequential scheduler's inline admission)."""
        while self._prefill_work[slot] is not None:
            self._prefill_chunk(slot)

    def _prefill_tick(self) -> None:
        """Interleaved scheduler: spend up to ``prefill_chunk_budget``
        prefill chunk dispatches, round-robin over in-prefill slots so
        a short prompt admitted next to a long one still reaches its
        first token in a handful of steps instead of waiting out the
        long prompt's whole chunk count.

        The budget exists to bound how long the decode batch stalls per
        step — so it only binds while there IS a decode batch. With no
        decodable slot (e.g. the first steps of an arrival burst, when
        every slot is mid-prefill), throttling prefill would starve
        nobody and merely serialize idle steps; instead one full
        round-robin round runs per step so every in-prefill slot
        advances a chunk."""
        if self.cfg.scheduler != "interleaved":
            return
        nslots = self.cfg.slots
        decoding = any(
            self._slots[s] is not None and self._prefill_work[s] is None
            for s in range(nslots))
        budget = self.cfg.prefill_chunk_budget
        if not decoding:
            budget = max(
                budget,
                sum(1 for w in self._prefill_work if w is not None))
        while budget > 0:
            pending = [s for s in range(nslots)
                       if self._prefill_work[s] is not None]
            if not pending:
                return
            # Start from the cursor so budget rotates across slots.
            slot = min(pending,
                       key=lambda s: (s - self._prefill_rr) % nslots)
            self._prefill_chunk(slot)
            self._prefill_rr = (slot + 1) % nslots
            budget -= 1

    def _prefill_chunk(self, slot: int) -> None:
        """One prefill chunk dispatch for ``slot``: target chunks
        first, then (speculative draft mode) the draft model's own
        chunks — the draft cache is unshared, so prefix-shared target
        chunks still need draft K/V. Completing the last chunk samples
        the first token and flips the slot to decoding."""
        work = self._prefill_work[slot]
        req = work.req
        p = self.cfg.prefill_len
        draft_mode = self.spec_len and self.cfg.spec_source != "prompt"
        if work.next_c0 < work.n:
            c0 = work.next_c0
            chunk = req.prompt[c0:c0 + p]
            ln = len(chunk)
            toks = jnp.asarray(chunk + [0] * (p - ln), jnp.int32)
            if self.paged:
                ci = c0 // p
                self.pool, work.logits = self._paged_prefill(
                    self.params, self.pool, toks, jnp.int32(ln),
                    jnp.int32(work.pages[ci]), work.table_row,
                    jnp.int32(c0))
            else:
                self.cache, work.logits = self._prefill(
                    self.params, self.cache, toks, jnp.int32(ln),
                    jnp.int32(slot), jnp.int32(c0))
            work.next_c0 = c0 + p
        elif draft_mode and work.draft_c0 < work.n:
            c0 = work.draft_c0
            chunk = req.prompt[c0:c0 + p]
            ln = len(chunk)
            toks = jnp.asarray(chunk + [0] * (p - ln), jnp.int32)
            self.draft_cache, _ = self._draft_prefill(
                self.draft_params, self.draft_cache, toks,
                jnp.int32(ln), jnp.int32(slot), jnp.int32(c0))
            work.draft_c0 = c0 + p
        if work.next_c0 < work.n or (
                draft_mode and work.draft_c0 < work.n):
            return
        # Prefill complete: pin the prefix for later sharers only now —
        # storing at assignment would share pages whose K/V hasn't been
        # computed yet.
        if self.prefix_cache is not None:
            if self.paged:
                self.prefix_cache.store(req.prompt, work.pages)
            else:
                self.prefix_cache.store(
                    self.cache, req.prompt, jnp.int32(slot))
        if draft_mode:
            self._draft_pos[slot] = work.n
        self._prefill_work[slot] = None
        self._after_prefill(slot, req, work.n, work.logits)

    def _after_prefill(self, slot: int, req: Request, n: int,
                       logits: jax.Array) -> None:
        """Shared admission tail: sample the first token (index 0 of
        the request's stream, keyed by its rid), install the request
        into its slot for decoding."""
        first = int(sample_tokens(
            logits[None], self._sample_key,
            jnp.asarray([req.rid], jnp.int32),
            jnp.zeros((1,), jnp.int32),
            jnp.full((1,), req.temperature, jnp.float32),
            jnp.full((1,), req.top_k, jnp.int32))[0])
        now = time.monotonic()
        with self._lock:
            # A drain-requeued re-run replays its first token: its TTFT
            # was observed on the ORIGINAL admission and must not be
            # counted (or re-timed) again.
            if req.ttft_s is None:
                req.ttft_s = now - req.enqueued
                req.first_tok_t = now
                self._observe_ttft(req.ttft_s)
                tst = self._tenant_locked(req)
                if tst is not None:
                    tst.ttft.append((now, req.ttft_s))
            req.emit([first])
            self.tokens_total += 1
        self._slots[slot] = req
        self.positions = self.positions.at[slot].set(n)
        self._host_positions[slot] = n
        self.last_tokens = self.last_tokens.at[slot].set(first)
        self._host_last[slot] = first
        self.temps = self.temps.at[slot].set(req.temperature)
        self.topks = self.topks.at[slot].set(req.top_k)
        self.rids = self.rids.at[slot].set(req.rid)
        self.tok_ctrs = self.tok_ctrs.at[slot].set(1)  # index 0 spent
        if len(req.output) >= req.max_new + 1 or req.hit_stop():
            self._complete(slot)

    def _release_slot_pages(self, slot: int) -> None:
        if self.paged:
            # Free the pages and park the slot's table on the trash
            # page so its garbage batched-decode writes can't corrupt
            # pages reallocated to live requests.
            self.allocator.release(self._slot_pages[slot])
            self._slot_pages[slot] = []
            self._tables_host[slot] = [0] * self._max_pages
            self._tables_dirty = True

    def _complete(self, slot: int) -> None:
        req = self._slots[slot]
        assert req is not None
        self._slots[slot] = None
        self._release_slot_pages(slot)
        req.status = "completed"
        with self._lock:
            self.completed_total += 1
            tst = self._tenant_locked(req)
            if tst is not None:
                tst.completed += 1
                tst.tokens += len(req.output)
            if req.first_tok_t is not None and len(req.output) > 1:
                tpot = ((time.monotonic() - req.first_tok_t)
                        / (len(req.output) - 1))
                self._tpot_recent.append(tpot)
                if tst is not None:
                    tst.tpot.append((time.monotonic(), tpot))
        req.finish_stream()
        req.done.set()

    def _abort_prefill(self, slot: int) -> None:
        """Cancellation observed while the slot was still prefilling:
        release the reservation and count a cancellation (no token was
        ever emitted — this is not a completion)."""
        req = self._slots[slot]
        self._slots[slot] = None
        self._prefill_work[slot] = None
        self._release_slot_pages(slot)
        req.status = "cancelled"
        with self._lock:
            self.cancelled_total += 1
            tst = self._tenant_locked(req)
            if tst is not None:
                tst.cancelled += 1
        req.finish_stream()
        req.done.set()

    def step(self) -> bool:
        """Admit + one decode step (plain or speculative round);
        returns True if any work remains."""
        if self.reporter is not None:
            with self.reporter.device_work():
                return self._step_inner()
        return self._step_inner()

    def _step_inner(self) -> bool:
        self._admit()
        # Cancelled mid-flight requests free their slot (and paged
        # pages) instead of decoding — or prefilling: the sweep runs
        # BEFORE the prefill tick so a dead request's chunks never
        # consume the step's budget.
        for slot in range(self.cfg.slots):
            req = self._slots[slot]
            if req is not None and req.cancelled.is_set():
                if self._prefill_work[slot] is not None:
                    self._abort_prefill(slot)
                else:
                    self._complete(slot)
        # Drain sweep (tpumon.actuate): requests attributed to a domain
        # marked drained abort-and-requeue — same step-thread seam as
        # cancellation. The sweep runs EVERY step while marks persist,
        # so a request the all-drained placement fallback parked on a
        # drained domain re-homes as soon as any domain is undrained.
        # With no un-drained domain to requeue TO, nothing is swept
        # (a requeue would just be re-parked: an abort/re-prefill
        # thrash loop that never completes) — liveness beats placement
        # purity, matching the fallback's contract.
        if self._drained:
            with self._lock:
                drained = set(self._drained)
                has_home = any(s not in drained for s in self.slices)
            if drained and has_home:
                for slot in range(self.cfg.slots):
                    req = self._slots[slot]
                    if req is not None and req.slice in drained:
                        self._requeue_slot(slot)
        self._prefill_tick()
        # Decode batch: slots still mid-prefill are excluded (their
        # first token doesn't exist yet; the batched dispatch computes
        # them as garbage the host ignores, like free slots).
        in_prefill = any(w is not None for w in self._prefill_work)
        active = [s for s in range(self.cfg.slots)
                  if self._slots[s] is not None
                  and self._prefill_work[s] is None]
        if active:
            # Speculative round needs room for spec_len+1 cache rows in
            # every active slot, at least one greedy slot to profit
            # (temperature slots accept zero drafts — a spec round for
            # them alone is strictly slower than plain decode), and —
            # DENSE layout only — no slot mid-prefill: the dense
            # verify's clamped [T]-row block write could land on rows a
            # parked slot's prefill already filled. (Paged verify
            # writes per-token through the page table, where a parked
            # slot's rows resolve to the trash page or dead tail rows,
            # so paged spec rounds run right through prefill.) Deferred
            # rounds fall back to the plain step; the draft catch-up
            # loop re-syncs afterwards.
            if (
                self.spec_len
                and (self.paged or not in_prefill)
                and any(self._slots[s].temperature <= 0 for s in active)
                and all(
                    self._host_positions[s]
                    <= self._seq_cap - 2 - self.spec_len
                    for s in active
                )
            ):
                self._spec_round(active)
            else:
                self._plain_step(active)
        with self._lock:
            pending = bool(self._queue)
        return pending or any(s is not None for s in self._slots)

    def _plain_step(self, active: list[int]) -> None:
        # Fused block decode when configured and every active slot has
        # cache room for the whole block (else fall through to the
        # single-step path, same boundary rule as speculative rounds).
        n = self.cfg.decode_block
        if (
            self._decode_rounds is not None
            and n > 1
            and all(
                self._host_positions[s] <= self._seq_cap - 1 - n
                for s in active
            )
        ):
            self._block_step(active, n)
            return
        if self.paged:
            self._sync_tables()
            self.pool, logits = self._paged_decode(
                self.params, self.pool, self.last_tokens, self.positions,
                self._tables_dev)
        else:
            self.cache, logits = self._decode(
                self.params, self.cache, self.last_tokens, self.positions)
        nxt = sample_tokens(logits, self._sample_key,
                            self.rids, self.tok_ctrs,
                            self.temps, self.topks)
        self.tok_ctrs = self.tok_ctrs + 1
        self.last_tokens = nxt
        self.positions = jnp.minimum(
            self.positions + 1, self._seq_cap - 1)
        # ONE host-device sync per step; positions tracked host-side.
        nxt_host = jax.device_get(nxt).tolist()
        self._host_last = list(nxt_host)
        with self._lock:
            self.decode_steps_total += 1
            self.tokens_total += len(active)
        for slot in active:
            req = self._slots[slot]
            req.emit([nxt_host[slot]])
            self._host_positions[slot] = min(
                self._host_positions[slot] + 1,
                self._seq_cap - 1)
            if (len(req.output) >= req.max_new + 1
                    or req.hit_stop()
                    or self._host_positions[slot]
                    >= self._seq_cap - 1):
                self._complete(slot)

    def _block_step(self, active: list[int], n: int) -> None:
        """One fused decode_rounds dispatch: n tokens per active slot,
        ONE host-device sync. Per-slot emission replays the block in
        order and stops at each request's own completion condition —
        tokens generated past it are discarded (bounded waste, the
        block-decode trade). Paged mode scans paged_decode_rounds with
        the (loop-invariant) page tables; overshoot rows land on
        reserved pages or the trash page."""
        if self.paged:
            self._sync_tables()
            self.pool, self.last_tokens, self.positions, toks = (
                self._decode_rounds(
                    self.params, self.pool, self.last_tokens,
                    self.positions, self._tables_dev,
                    self._sample_key, self.rids, self.tok_ctrs,
                    self.temps, self.topks, steps=n,
                )
            )
        else:
            self.cache, self.last_tokens, self.positions, toks = (
                self._decode_rounds(
                    self.params, self.cache, self.last_tokens,
                    self.positions,
                    self._sample_key, self.rids, self.tok_ctrs,
                    self.temps, self.topks, steps=n,
                )
            )
        self.tok_ctrs = self.tok_ctrs + n
        toks_host = jax.device_get(toks).tolist()  # [B, n]
        emitted = 0
        with self._lock:
            self.decode_steps_total += n
        for slot in active:
            req = self._slots[slot]
            for tok in toks_host[slot]:
                req.emit([tok])
                emitted += 1
                self._host_positions[slot] = min(
                    self._host_positions[slot] + 1,
                    self._seq_cap - 1)
                if (len(req.output) >= req.max_new + 1
                        or req.hit_stop()
                        or self._host_positions[slot]
                        >= self._seq_cap - 1):
                    self._complete(slot)
                    break
        self._host_last = [row[-1] for row in toks_host]
        with self._lock:
            self.tokens_total += emitted

    def _seq_token(self, req: Request, i: int) -> int:
        """Token at sequence index ``i``: prompt, then emitted output."""
        n = len(req.prompt)
        return req.prompt[i] if i < n else req.output[i - n]

    def _spec_round(self, active: list[int]) -> None:
        """One speculative round: spec_len draft steps + one verify
        dispatch; accept the longest agreed prefix per greedy slot plus
        the target's bonus token. Temperature>0 slots emit one sampled
        token from the verified logits (== plain decode for them)."""
        g = self.spec_len
        if self.cfg.spec_source == "prompt":
            self._spec_round_prompt(active)
            return
        # Catch the draft cache up to the target frontier first:
        # plain-step fallbacks advance the sequence without touching the
        # draft cache, and proposing over those K/V holes would degrade
        # acceptance for the rest of the request.
        deficit = max(
            self._host_positions[s] - self._draft_pos[s] for s in active)
        for d in range(deficit):
            toks, rows = [], []
            for s in range(self.cfg.slots):
                req = self._slots[s]
                p_s = self._host_positions[s]
                f = self._draft_pos[s] + d
                if (req is not None and self._prefill_work[s] is None
                        and f < p_s):
                    toks.append(self._seq_token(req, f))
                    rows.append(f)
                else:
                    # Caught-up, empty, or mid-prefill slot (parked
                    # position, stale _draft_pos — its own chunked
                    # draft prefill owns that cache region): rewrite
                    # the row the proposal loop writes first anyway —
                    # idempotent.
                    toks.append(self._host_last[s])
                    rows.append(p_s)
            self.draft_cache, _ = self._draft_decode(
                self.draft_params, self.draft_cache,
                jnp.asarray(toks, jnp.int32), jnp.asarray(rows, jnp.int32))
        dt_tok = self.last_tokens
        dpos = self.positions
        drafts = []
        for _ in range(g):
            self.draft_cache, dlogits = self._draft_decode(
                self.draft_params, self.draft_cache, dt_tok, dpos)
            dt_tok = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
            drafts.append(dt_tok)
            dpos = dpos + 1
        # One extra draft step feeding the last proposal: when all g
        # drafts are accepted the sequence includes d_g, whose K/V the
        # proposal loop never wrote — without this the draft cache has a
        # hole at row p+g and every later draft round degrades. The
        # proposal it returns is discarded; if acceptance stops short the
        # row is stale-but-masked like any rejected row.
        self.draft_cache, _ = self._draft_decode(
            self.draft_params, self.draft_cache, dt_tok, dpos)
        proposed = jnp.stack(drafts, axis=1)  # [B, g]
        self._spec_verify_emit(active, proposed, prop_h=None)

    def _spec_round_prompt(self, active: list[int]) -> None:
        """Prompt-lookup speculative round: proposals are host-side
        n-gram copies from each request's own context
        (loadgen.prompt_lookup.ngram_propose) — zero draft dispatches;
        the verify/accept path is the shared one, so greedy output is
        lossless regardless of guess quality."""
        from tpumon.loadgen.prompt_lookup import ngram_propose

        g = self.spec_len
        prop_rows = []
        for s in range(self.cfg.slots):
            req = self._slots[s]
            if req is None:
                prop_rows.append([0] * g)
            else:
                prop_rows.append(
                    ngram_propose(req.prompt + req.output, g,
                                  window=self.cfg.spec_ngram_window))
        proposed = jnp.asarray(prop_rows, jnp.int32)  # [B, g]
        self._spec_verify_emit(active, proposed, prop_h=prop_rows)

    def _spec_verify_emit(self, active: list[int], proposed,
                          prop_h: list | None) -> None:
        """Shared speculative tail: one target verify dispatch over
        [feed, proposals], greedy-prefix acceptance + bonus token,
        temperature slots sampled from the verified logits. prop_h is
        the host copy of ``proposed`` when the proposer already has one
        (prompt lookup); None fetches it with the verify results in the
        single per-round device sync."""
        g = self.spec_len
        ver_in = jnp.concatenate(
            [self.last_tokens[:, None], proposed], axis=1)  # [B, g+1]
        if self.paged:
            self._sync_tables()
            self.pool, vlogits = self._verify(
                self.params, self.pool, ver_in, self.positions,
                self._tables_dev)
        else:
            self.cache, vlogits = self._verify(
                self.params, self.cache, ver_in, self.positions)
        tgt = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)  # [B, g+1]
        # The sampling dispatch (full-vocab sort for top-k) only pays
        # off when a temperature slot shares the batch; all-greedy
        # rounds take tgt_h directly.
        any_temp = any(self._slots[s].temperature > 0 for s in active)
        if any_temp:
            samp0 = sample_tokens(vlogits[:, 0], self._sample_key,
                                  self.rids, self.tok_ctrs,
                                  self.temps, self.topks)
            # ONE host-device sync per round.
            if prop_h is None:
                prop_h, tgt_h, samp_h = (
                    a.tolist()
                    for a in jax.device_get((proposed, tgt, samp0)))
            else:
                tgt_h, samp_h = (
                    a.tolist() for a in jax.device_get((tgt, samp0)))
        else:
            if prop_h is None:
                prop_h, tgt_h = (
                    a.tolist() for a in jax.device_get((proposed, tgt)))
            else:
                tgt_h = jax.device_get(tgt).tolist()
            samp_h = None
        from tpumon.loadgen.speculative import greedy_accept_len

        emitted_n = 0
        accepted_n = 0
        proposed_n = 0  # greedy slots only: temp slots can't accept
        for slot in active:
            req = self._slots[slot]
            if req.temperature > 0:
                a = 0
                emitted = [samp_h[slot]]
            else:
                a = greedy_accept_len(prop_h[slot], tgt_h[slot])
                emitted = prop_h[slot][:a] + [tgt_h[slot][a]]
                proposed_n += g
            accepted_n += a
            room = req.max_new + 1 - len(req.output)
            emitted = emitted[:room]  # room >= 1: full slots completed
            if req.stop_tokens:
                for si, tok in enumerate(emitted):
                    if tok in req.stop_tokens:
                        emitted = emitted[:si + 1]
                        break
            req.emit(emitted)
            self._host_positions[slot] += len(emitted)
            self._host_last[slot] = emitted[-1]
            self._draft_pos[slot] = self._host_positions[slot]
            emitted_n += len(emitted)
            if (len(req.output) >= req.max_new + 1
                    or req.hit_stop()
                    or self._host_positions[slot]
                    >= self._seq_cap - 1):
                self._complete(slot)
        self.positions = jnp.asarray(self._host_positions, jnp.int32)
        self.last_tokens = jnp.asarray(self._host_last, jnp.int32)
        # Re-sync per-slot token indices from the host truth: greedy
        # slots advanced by their accepted length, temperature slots by
        # one — len(output) IS the next sample index either way.
        self.tok_ctrs = jnp.asarray(
            [len(r.output) if (r := self._slots[s]) is not None else 0
             for s in range(self.cfg.slots)], jnp.int32)
        with self._lock:
            self.decode_steps_total += 1
            self.spec_rounds_total += 1
            self.spec_proposed_total += proposed_n
            self.spec_accepted_total += accepted_n
            self.tokens_total += emitted_n

    def drain(self, max_steps: int = 10_000) -> None:
        for _ in range(max_steps):
            if not self.step():
                return

    # -- metrics ------------------------------------------------------------

    def _stats_snapshot(self) -> dict:
        """Raw metrics state as one mergeable dict — counters under the
        lock, latency windows as plain lists, per-tenant series with
        their observation times intact. ``metrics_text`` renders one
        snapshot; MeshServingEngine sums its replicas' snapshots
        (_merge_serving_snapshots) and renders ONCE, so the federation
        of dp replicas exposes a single coherent /metrics page plus the
        per-replica gauge family."""
        with self._lock:
            snap = {
                "tokens": self.tokens_total,
                "requests": self.requests_total,
                "completed": self.completed_total,
                "steps": self.decode_steps_total,
                "queue": len(self._queue),
                "rejected": self.rejected_total,
                "cancelled": self.cancelled_total,
                "shed": self.shed_total,
                "requeued": self.requeued_total,
                "ttft_counts": list(self._ttft_counts),
                "ttft_inf": self._ttft_inf,
                "ttft_sum": self._ttft_sum,
                "free": sum(1 for s in self._slots if s is None),
                "in_prefill": sum(
                    1 for w in self._prefill_work if w is not None),
                "ttft_recent": list(self._ttft_recent),
                "tpot_recent": list(self._tpot_recent),
                "spec_rounds": self.spec_rounds_total,
                "spec_proposed": self.spec_proposed_total,
                "spec_accepted": self.spec_accepted_total,
                "tenant_window_s": self.tenant_window_s,
                "tenants": {
                    name: {
                        "submitted": st.submitted,
                        "completed": st.completed,
                        "rejected": st.rejected,
                        "cancelled": st.cancelled,
                        "shed": st.shed,
                        "tokens": st.tokens,
                        "ttft": list(st.ttft),
                        "tpot": list(st.tpot),
                    }
                    for name, st in self.tenants.items()
                },
            }
        from tpumon.loadgen.quant import QTensor, param_bytes

        weight_bytes = param_bytes(self.params)
        if self.spec_len and self.draft_params is not self.params:
            # A distinct draft model's weights are resident too — but
            # only the leaves that are actually separate arrays: the
            # layer-truncated draft (engine init) aliases the target's
            # arrays leaf-for-leaf, so counting it wholesale would
            # report HBM that is not separately resident.
            _is_q = lambda x: isinstance(x, QTensor)  # noqa: E731
            target_ids = {
                id(x) for x in jax.tree.leaves(self.params, is_leaf=_is_q)}
            weight_bytes += sum(
                x.nbytes
                for x in jax.tree.leaves(self.draft_params, is_leaf=_is_q)
                if id(x) not in target_ids)
        snap["weight_bytes"] = weight_bytes
        if self.paged:
            snap["kv_pages_total"] = self.allocator.num_pages - 1
            snap["kv_pages_free"] = self.allocator.free_pages
        else:
            snap["kv_pages_total"] = snap["kv_pages_free"] = None
        if self.prefix_cache is not None:
            pc = self.prefix_cache
            snap["prefix"] = {
                "hits": pc.hits, "misses": pc.misses,
                "saved_tokens": pc.saved_tokens,
                "bytes": pc.resident_bytes(),
            }
        else:
            snap["prefix"] = None
        return snap

    def metrics_text(self) -> str:
        return _render_serving_metrics(self._stats_snapshot())


def _merge_serving_snapshots(snaps: "list[dict]") -> dict:
    """Sum dp-replica snapshots into one fleet snapshot: counters and
    gauge counts add, latency windows concatenate (quantiles are
    order-independent), per-tenant series merge with observation times
    intact so the recency window still applies."""
    out = dict(snaps[0])
    out["tenants"] = {
        name: dict(row, ttft=list(row["ttft"]), tpot=list(row["tpot"]))
        for name, row in snaps[0]["tenants"].items()
    }
    for s in snaps[1:]:
        for k in ("tokens", "requests", "completed", "steps", "queue",
                  "rejected", "cancelled", "shed", "requeued", "ttft_inf",
                  "ttft_sum", "free", "in_prefill", "spec_rounds",
                  "spec_proposed", "spec_accepted", "weight_bytes"):
            out[k] += s[k]
        out["ttft_counts"] = [
            a + b for a, b in zip(out["ttft_counts"], s["ttft_counts"])]
        for k in ("kv_pages_total", "kv_pages_free"):
            if s[k] is not None:
                out[k] = (out[k] or 0) + s[k]
        out["ttft_recent"] = out["ttft_recent"] + s["ttft_recent"]
        out["tpot_recent"] = out["tpot_recent"] + s["tpot_recent"]
        if s["prefix"] is not None:
            if out["prefix"] is None:
                out["prefix"] = dict(s["prefix"])
            else:
                out["prefix"] = {
                    k: out["prefix"][k] + v for k, v in s["prefix"].items()}
        for name, row in s["tenants"].items():
            mine = out["tenants"].get(name)
            if mine is None:
                out["tenants"][name] = dict(
                    row, ttft=list(row["ttft"]), tpot=list(row["tpot"]))
                continue
            for k in ("submitted", "completed", "rejected", "cancelled",
                      "shed", "tokens"):
                mine[k] += row[k]
            mine["ttft"] = list(mine["ttft"]) + list(row["ttft"])
            mine["tpot"] = list(mine["tpot"]) + list(row["tpot"])
    return out


def _render_serving_metrics(snap: dict,
                            replica_rows: "list[tuple] | None" = None
                            ) -> str:
    """Render one (possibly merged) stats snapshot as the /metrics
    exposition. ``replica_rows`` — (replica, slots_free, queue,
    ttft_p95_ms, tpot_p95_ms) per dp replica — adds the
    ``tpumon_serving_replica_*`` gauge family the mesh engine exposes
    (docs/perf.md "Mesh serving"); None omits the family entirely."""
    tokens = snap["tokens"]
    requests = snap["requests"]
    completed = snap["completed"]
    steps = snap["steps"]
    queue = snap["queue"]
    rejected = snap["rejected"]
    cancelled = snap["cancelled"]
    shed = snap["shed"]
    requeued = snap["requeued"]
    counts = snap["ttft_counts"]
    inf = snap["ttft_inf"]
    ttft_sum = snap["ttft_sum"]
    free = snap["free"]
    in_prefill = snap["in_prefill"]
    ttft_recent = snap["ttft_recent"]
    tpot_recent = snap["tpot_recent"]
    spec_rounds = snap["spec_rounds"]
    spec_proposed = snap["spec_proposed"]
    spec_accepted = snap["spec_accepted"]
    now_mono = time.monotonic()
    tw = snap["tenant_window_s"]
    tenant_rows = [
        (
            name,
            row["submitted"], row["completed"], row["rejected"],
            row["cancelled"], row["shed"], row["tokens"],
            [v for t, v in row["ttft"] if now_mono - t <= tw],
            [v for t, v in row["tpot"] if now_mono - t <= tw],
        )
        for name, row in sorted(snap["tenants"].items())
    ]
    w = MetricsWriter()
    w.counter("jetstream_generate_tokens",
              "tokens generated (prefill first-token + decode)"
              ).add(value=tokens)
    w.counter("jetstream_request_count", "requests submitted"
              ).add(value=requests)
    w.counter("tpumon_serving_requests_completed", "requests finished"
              ).add(value=completed)
    w.counter("tpumon_serving_requests_rejected",
              "requests dropped by queue backpressure"
              ).add(value=rejected)
    w.counter("tpumon_serving_requests_cancelled",
              "requests cancelled before their first token "
              "(while queued or mid-prefill)"
              ).add(value=cancelled)
    w.counter("tpumon_serving_requests_shed",
              "requests shed at admission by the actuation layer "
              "(tpumon.actuate; a remedial drop, never an error)"
              ).add(value=shed)
    w.counter("tpumon_serving_requests_requeued",
              "in-flight requests aborted and re-admitted by a "
              "slice drain (tpumon.actuate)"
              ).add(value=requeued)
    w.counter("tpumon_serving_decode_steps", "fused decode steps"
              ).add(value=steps)
    w.gauge("jetstream_queue_size", "requests waiting for a slot"
            ).add(value=queue)
    w.gauge("jetstream_slots_available", "free decode slots"
            ).add(value=free)
    w.gauge("tpumon_serving_slots_prefill",
            "slots mid-chunked-prefill (admitted, not yet decoding)"
            ).add(value=in_prefill)
    # Per-request latency quantiles over a recent window
    # (tracing.quantiles — one sort per render): TTFT from enqueue
    # to first token, TPOT decode seconds per token after it.
    from tpumon.tracing import quantiles

    for fam, series, unit in (
        ("tpumon_serving_ttft", ttft_recent, 1e3),
        ("tpumon_serving_tpot", tpot_recent, 1e3),
    ):
        q = quantiles(series)
        if q is not None:
            w.gauge(fam + "_p50_ms",
                    "recent-window per-request p50"
                    ).add(value=round(q[0] * unit, 3))
            w.gauge(fam + "_p95_ms",
                    "recent-window per-request p95"
                    ).add(value=round(q[1] * unit, 3))
    if tenant_rows:
        # Per-tenant serving signals (tpumon.loadgen.traffic): the
        # SLO engine's inputs. Counters are lifetime (the collector
        # derives windowed goodput/error rates from scrape deltas);
        # latency quantiles cover the tenant_window_s recency
        # window, so a recovered tenant's p95 actually recovers.
        reqs = w.counter("tpumon_serving_tenant_requests",
                         "requests submitted per tenant")
        comp = w.counter("tpumon_serving_tenant_completed",
                         "requests finished per tenant")
        rej = w.counter("tpumon_serving_tenant_rejected",
                        "requests dropped by backpressure per tenant")
        canc = w.counter("tpumon_serving_tenant_cancelled",
                         "requests cancelled per tenant")
        shd = w.counter("tpumon_serving_tenant_shed",
                        "requests shed at admission per tenant "
                        "(excluded from error-rate math — a shed "
                        "is the remedy, not the fault)")
        toks = w.counter("tpumon_serving_tenant_tokens",
                         "tokens emitted per tenant")
        tg: dict[str, object] = {}
        for fam in ("tpumon_serving_tenant_ttft_p50_ms",
                    "tpumon_serving_tenant_ttft_p95_ms",
                    "tpumon_serving_tenant_tpot_p50_ms",
                    "tpumon_serving_tenant_tpot_p95_ms"):
            tg[fam] = w.gauge(
                fam, "recent-window per-tenant latency quantile")
        for (name, sub, done, rj, cn, sh, tk, ttfts, tpots) in tenant_rows:
            labels = {"tenant": name}
            reqs.add(labels, sub)
            comp.add(labels, done)
            rej.add(labels, rj)
            canc.add(labels, cn)
            shd.add(labels, sh)
            toks.add(labels, tk)
            for fam_base, series in (
                ("tpumon_serving_tenant_ttft", ttfts),
                ("tpumon_serving_tenant_tpot", tpots),
            ):
                q = quantiles(series)
                if q is not None:
                    tg[fam_base + "_p50_ms"].add(
                        labels, round(q[0] * 1e3, 3))
                    tg[fam_base + "_p95_ms"].add(
                        labels, round(q[1] * 1e3, 3))
    w.gauge("tpumon_serving_weight_bytes",
            "resident model weight bytes (int8 when quantized)"
            ).add(value=snap["weight_bytes"])
    w.counter("tpumon_serving_spec_rounds",
              "speculative decode rounds (0 when disabled)"
              ).add(value=spec_rounds)
    w.counter("tpumon_serving_spec_proposed",
              "draft tokens proposed").add(value=spec_proposed)
    w.counter("tpumon_serving_spec_accepted",
              "draft tokens the target verify accepted"
              ).add(value=spec_accepted)
    if snap["kv_pages_total"] is not None:
        w.gauge("tpumon_serving_kv_pages_total",
                "shared KV pool pages (excl. the trash page)"
                ).add(value=snap["kv_pages_total"])
        w.gauge("tpumon_serving_kv_pages_free",
                "KV pool pages not reserved by admitted requests"
                ).add(value=snap["kv_pages_free"])
    if snap["prefix"] is not None:
        pc = snap["prefix"]
        w.counter("tpumon_serving_prefix_hits",
                  "admissions served a cached prompt prefix"
                  ).add(value=pc["hits"])
        w.counter("tpumon_serving_prefix_misses",
                  "admissions with no cached prefix").add(value=pc["misses"])
        w.counter("tpumon_serving_prefix_saved_tokens",
                  "prompt tokens whose prefill was skipped"
                  ).add(value=pc["saved_tokens"])
        w.gauge("tpumon_serving_prefix_bytes",
                "HBM pinned by cached prefix K/V"
                ).add(value=pc["bytes"])
    if replica_rows is not None:
        # Mesh-engine per-replica gauge family (docs/perf.md "Mesh
        # serving"): the collector distills these into per-replica
        # TSDB series so the SLO engine can target one dp replica.
        rg = {}
        for fam, help_ in (
            ("tpumon_serving_replica_slots_available",
             "free decode slots per dp replica"),
            ("tpumon_serving_replica_queue_size",
             "requests waiting per dp replica (router-assigned)"),
            ("tpumon_serving_replica_ttft_p95_ms",
             "recent-window TTFT p95 per dp replica"),
            ("tpumon_serving_replica_tpot_p95_ms",
             "recent-window TPOT p95 per dp replica"),
        ):
            rg[fam] = w.gauge(fam, help_)
        for (replica, slots_free, rq, ttft_p95, tpot_p95) in replica_rows:
            labels = {"replica": replica}
            rg["tpumon_serving_replica_slots_available"].add(
                labels, slots_free)
            rg["tpumon_serving_replica_queue_size"].add(labels, rq)
            if ttft_p95 is not None:
                rg["tpumon_serving_replica_ttft_p95_ms"].add(
                    labels, round(ttft_p95, 3))
            if tpot_p95 is not None:
                rg["tpumon_serving_replica_tpot_p95_ms"].add(
                    labels, round(tpot_p95, 3))
    lines = [w.render().rstrip("\n")]
    lines.append("# TYPE jetstream_time_to_first_token histogram")
    cum = 0
    for bound, c in zip(TTFT_BUCKETS_S, counts):
        cum += c
        lines.append(
            f'jetstream_time_to_first_token_bucket{{le="{bound}"}} {cum}')
    total = cum + inf
    lines.append(
        f'jetstream_time_to_first_token_bucket{{le="+Inf"}} {total}')
    lines.append(f"jetstream_time_to_first_token_sum {ttft_sum:.6f}")
    lines.append(f"jetstream_time_to_first_token_count {total}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# dp×tp mesh serving: replicated engines behind an affinity router
# ---------------------------------------------------------------------------


class MeshServingEngine:
    """Production-shape sharded serving: ``mesh_dp`` data-parallel
    replicas — each a plain ServingEngine running the PR 10 interleaved
    scheduler UNCHANGED over its own ``mesh_tp``-chip tensor-parallel
    submesh (model.replica_meshes) — behind a topology- and
    prefix-affinity-aware router.

    Routing policy (docs/perf.md "Mesh serving"): a request goes to the
    replica with the LONGEST cached prefix for its prompt (the replica
    already holding those KV pages skips that prefill), ties broken by
    least load (queued + in-flight), then lowest replica index — the
    index order is ICI-locality order, replica_meshes carves contiguous
    device ranges. The router owns the rid namespace (children take
    ``submit(rid=...)``), so every request's sampled stream stays a
    pure function of (seed, prompt, params): sampling is keyed per
    (rid, token index) and all replicas share seed and params —
    dp=1/tp=1, dp=2/tp=2 and dp=4/tp=1 produce bit-identical streams
    (the golden matrix in tests/test_scheduler.py pins this).

    Placement-domain surface (tpumon.actuate): the dp replica ids
    ("r0".."r<dp-1>") ARE the placement domains — ``drain_slice("r1")``
    stops admission to that replica and moves its queued + in-flight
    work to live replicas via the PR 14 requeue path (rid and
    delivered-stream watermark preserved, so re-runs replay
    bit-identically). With every replica drained the router REJECTS new
    work — backpressure a client can see and retry beats silently
    un-draining a replica an operator just drained."""

    def __init__(self, cfg: ServeConfig | None = None,
                 params: dict | None = None, seed: int = 0,
                 max_queue: int = 64, ckpt_dir: str | None = None,
                 quantize: str | None = None,
                 draft_params: dict | None = None,
                 devices=None):
        from tpumon.loadgen.model import replica_meshes

        self.cfg = cfg or default_engine_config()
        dp, tp = self.cfg.mesh_dp, self.cfg.mesh_tp
        # replica_meshes validates the shape against the device count
        # (the satellite-6 ValueError both CLIs surface verbatim).
        meshes = replica_meshes(dp, tp, dense=self.cfg.kv_layout != "paged",
                                devices=devices)
        child_cfg = dc_replace(self.cfg, mesh_dp=1, mesh_tp=1)
        self.replica_ids: tuple[str, ...] = tuple(
            f"r{d}" for d in range(dp))
        # Children share (seed, params): identical weights on every
        # replica is the bit-identical-stream precondition. With
        # params=None each child re-inits from the SAME PRNG seed, so
        # the replicas still agree leaf-for-leaf.
        self.replicas: list[ServingEngine] = [
            ServingEngine(cfg=child_cfg, params=params, seed=seed,
                          max_queue=max_queue, ckpt_dir=ckpt_dir,
                          quantize=quantize, draft_params=draft_params,
                          mesh=meshes[d])
            for d in range(dp)
        ]
        self._rid = itertools.count()
        self._lock = threading.Lock()
        self._drained: set[str] = set()
        self.slices: tuple[str, ...] = self.replica_ids
        self.router_rejected = 0

    # -- admission / routing ------------------------------------------------

    def _live(self) -> "list[int]":
        with self._lock:
            drained = set(self._drained)
        return [i for i, rid_ in enumerate(self.replica_ids)
                if rid_ not in drained]

    def _route(self, prompt: list[int], live: "list[int]") -> ServingEngine:
        best_i = live[0]
        best = (-self.replicas[best_i].prefix_hit_len(prompt),
                self.replicas[best_i].load())
        for i in live[1:]:
            eng = self.replicas[i]
            key = (-eng.prefix_hit_len(prompt), eng.load())
            if key < best:
                best, best_i = key, i
        return self.replicas[best_i]

    def submit(self, prompt: list[int], max_new: int = 16,
               temperature: float = 0.0, top_k: int = 0,
               stream: bool = False, stop_tokens=(),
               tenant: str = "", rid: int | None = None) -> Request:
        """Route one request to a dp replica (affinity → load → index)
        and submit it there with a router-minted rid. Same contract as
        ServingEngine.submit; with every replica drained the request is
        rejected here (visible backpressure, never a silent admit to a
        drained replica)."""
        live = self._live()
        if not live:
            req = Request(
                rid=rid if rid is not None else next(self._rid),
                prompt=[t % self.cfg.model.vocab for t in prompt] or [0],
                max_new=max(0, int(max_new)), enqueued=time.monotonic(),
                temperature=float(temperature), top_k=int(top_k),
                stream=queue.Queue() if stream else None,
                stop_tokens=tuple(int(t) for t in stop_tokens),
                tenant=str(tenant))
            with self._lock:
                self.router_rejected += 1
            req.status = "rejected"
            req.finish_stream()
            req.done.set()
            return req
        eng = self._route(list(prompt), live)
        return eng.submit(prompt, max_new=max_new, temperature=temperature,
                          top_k=top_k, stream=stream,
                          stop_tokens=stop_tokens, tenant=tenant,
                          rid=rid if rid is not None else next(self._rid))

    # -- engine loop --------------------------------------------------------

    def step(self) -> bool:
        """One scheduler step on every replica (drained replicas
        included: their remaining in-flight work — the evict below is
        best-effort when no live replica exists — must still finish).
        True if any replica made progress."""
        progressed = False
        for eng in self.replicas:
            progressed = eng.step() or progressed
        return progressed

    def drain(self, max_steps: int = 10_000) -> None:
        for _ in range(max_steps):
            if not self.step():
                return

    # -- actuation surface (tpumon.actuate) ---------------------------------

    def set_shed(self, tenant: str, fraction: float) -> float:
        got = 0.0
        for eng in self.replicas:
            got = eng.set_shed(tenant, fraction)
        return got

    def shed_fractions(self) -> dict[str, float]:
        return self.replicas[0].shed_fractions()

    def nudge_capacity(self, prefill_budget: int | None = None,
                       admit_lookahead: int | None = None) -> dict:
        out: dict = {}
        for eng in self.replicas:
            out = eng.nudge_capacity(prefill_budget=prefill_budget,
                                     admit_lookahead=admit_lookahead)
        return out

    def set_slices(self, names) -> None:
        """The placement-domain namespace here is the replica ids —
        fixed at construction. A sync (tpumon.actuate._sync_domains,
        fed replica ids by the sampler when a mesh engine is bound)
        only prunes drain marks for names that no longer exist, exactly
        like ServingEngine.set_slices."""
        with self._lock:
            self.slices = tuple(str(n) for n in names)
            self._drained &= set(self.slices)

    def drain_slice(self, name: str) -> None:
        """Drain one dp replica: the router stops admitting to it and
        its queued + in-flight requests move to live replicas via the
        PR 14 requeue path (abort, re-admit with rid and stream
        watermark preserved — the re-run replays bit-identically).
        With no live replica left the work stays put (and finishes
        where it is): liveness beats placement purity."""
        name = str(name)
        with self._lock:
            self._drained.add(name)
        if name not in self.replica_ids:
            return
        live = self._live()
        if not live:
            return
        evicted = self.replicas[self.replica_ids.index(name)].evict_all()
        # adopt() pushes at the queue HEAD; reversed iteration keeps
        # the evicted order (requeued in-flight first, then the queue)
        # intact on each receiving replica.
        for req in reversed(evicted):
            target = min(live, key=lambda i: self.replicas[i].load())
            self.replicas[target].adopt(req)

    def undrain_slice(self, name: str) -> None:
        with self._lock:
            self._drained.discard(str(name))

    def drained_slices(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._drained))

    # -- shared-surface passthroughs ----------------------------------------

    @property
    def paged(self) -> bool:
        return self.replicas[0].paged

    @property
    def params(self) -> dict:
        return self.replicas[0].params

    @property
    def prefix_cache(self):
        return self.replicas[0].prefix_cache

    @property
    def reporter(self):
        return self.replicas[0].reporter

    @reporter.setter
    def reporter(self, value) -> None:
        for eng in self.replicas:
            eng.reporter = value

    @property
    def tokens_total(self) -> int:
        return sum(e.tokens_total for e in self.replicas)

    @property
    def requests_total(self) -> int:
        return sum(e.requests_total for e in self.replicas)

    @property
    def completed_total(self) -> int:
        return sum(e.completed_total for e in self.replicas)

    @property
    def rejected_total(self) -> int:
        return self.router_rejected + sum(
            e.rejected_total for e in self.replicas)

    @property
    def requeued_total(self) -> int:
        return sum(e.requeued_total for e in self.replicas)

    # -- metrics ------------------------------------------------------------

    def metrics_text(self) -> str:
        """One merged /metrics page for the whole mesh — fleet counters
        are sums, latency quantiles pool every replica's recent window
        — plus the tpumon_serving_replica_* per-replica gauge family
        the collector distills into serving.<replica>.* TSDB series."""
        from tpumon.tracing import quantiles

        snaps = [eng._stats_snapshot() for eng in self.replicas]
        rows = []
        for rid_, snap in zip(self.replica_ids, snaps):
            tq = quantiles(snap["ttft_recent"])
            pq = quantiles(snap["tpot_recent"])
            rows.append((rid_, snap["free"], snap["queue"],
                         None if tq is None else tq[1] * 1e3,
                         None if pq is None else pq[1] * 1e3))
        merged = _merge_serving_snapshots(snaps)
        with self._lock:
            merged["rejected"] += self.router_rejected
        return _render_serving_metrics(merged, replica_rows=rows)


def make_serving_engine(cfg: ServeConfig | None = None, **kw):
    """Build the engine the config asks for: a MeshServingEngine when
    mesh_dp×mesh_tp describes a real mesh, a plain ServingEngine
    otherwise. One seam so both CLIs (and tests) pick the engine shape
    from ServeConfig alone."""
    cfg = cfg or default_engine_config()
    if cfg.mesh_dp * cfg.mesh_tp > 1:
        return MeshServingEngine(cfg=cfg, **kw)
    kw.pop("devices", None)
    return ServingEngine(cfg=cfg, **kw)


# ---------------------------------------------------------------------------
# /metrics HTTP endpoint + demo loop
# ---------------------------------------------------------------------------


def start_metrics_server(engine: ServingEngine, port: int = 0,
                         host: str = "127.0.0.1"):
    """Serve /metrics and /generate; returns (server, port).

    /generate is the inference API (the engine loop must be running —
    the arrival loop or any thread calling step()):
      GET /generate?prompt=1,2,3&max_new=8            → JSON when done
      GET /generate?prompt=1,2,3&max_new=8&stream=1   → SSE, one
          ``data: <token>`` event per token as it is emitted, then
          ``event: done``. First event arrives at TTFT, not completion.
    Runs in a daemon thread; call server.shutdown() THEN
    server.server_close() to stop — shutdown alone leaks the
    listening socket."""
    import json as _json
    import urllib.parse
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib API name)
            path, _, query = self.path.partition("?")
            if path == "/metrics":
                self._send(200, engine.metrics_text().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/generate":
                self._generate(urllib.parse.parse_qs(query))
            else:
                self.send_error(404)

        def _send(self, code, body, ctype):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _generate(self, q):
            try:
                prompt = [int(t) for t in q["prompt"][0].split(",") if t]
                max_new = int(q.get("max_new", ["16"])[0])
                temp = float(q.get("temperature", ["0"])[0])
                top_k = int(q.get("top_k", ["0"])[0])
                stops = tuple(
                    int(t) for t in q.get("stop", [""])[0].split(",") if t)
            except (KeyError, ValueError):
                self._send(400, b'{"error": "bad prompt/max_new"}',
                           "application/json")
                return
            streaming = q.get("stream", ["0"])[0] not in ("0", "")
            req = engine.submit(prompt, max_new=max_new, temperature=temp,
                                top_k=top_k, stream=streaming,
                                stop_tokens=stops)
            if req.done.is_set() and not req.output:
                # Queue-full backpressure must be visible to clients
                # (retry logic keys off the status code, not the body).
                self._send(429, b'{"error": "queue full"}',
                           "application/json")
                return
            if not streaming:
                if not req.done.wait(timeout=60):
                    req.cancel()  # stop generating for a timed-out call
                    self._send(504, b'{"error": "timeout"}',
                               "application/json")
                    return
                body = _json.dumps({
                    "rid": req.rid, "tokens": req.output,
                    "ttft_ms": None if req.ttft_s is None
                    else req.ttft_s * 1e3,
                }).encode()
                self._send(200, body, "application/json")
                return
            # SSE: stream tokens as the engine emits them.
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.end_headers()
            try:
                while True:
                    try:
                        tok = req.stream.get(timeout=60)
                    except queue.Empty:
                        # Engine stalled: terminate explicitly so SSE
                        # clients don't auto-reconnect and enqueue a
                        # duplicate generation.
                        self.wfile.write(
                            b'event: error\ndata: {"error": "stalled"}'
                            b"\n\n")
                        self.wfile.flush()
                        req.cancel()  # connection is being abandoned
                        return
                    if tok is None:
                        self.wfile.write(b"event: done\ndata: {}\n\n")
                        self.wfile.flush()
                        return
                    self.wfile.write(f"data: {tok}\n\n".encode())
                    self.wfile.flush()
            except Exception:
                # Client went away: cancel so the engine frees the slot
                # instead of generating into a dead socket.
                req.cancel()
                return

        def log_message(self, *a):  # quiet
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, server.server_address[1]


@dataclass
class ArrivalSource:
    """One Poisson arrival process for ``ArrivalPump``.

    ``rate(rel_t)`` returns the source's current arrivals/sec at
    ``rel_t`` seconds into the run (<= 0 pauses the source);
    ``fire(rel_t)`` submits one request; ``interval(rate)`` draws the
    next inter-arrival gap in seconds. The caller owns the RNG behind
    ``fire``/``interval``, so the draw order — and with it seeded
    replayability — is the caller's contract, not the pump's.
    """

    rate: object  # Callable[[float], float]
    fire: object  # Callable[[float], None]
    interval: object  # Callable[[float], float]
    next_at: float = 0.0  # absolute monotonic due time (pump-owned)
    paused: bool = False  # rate() was <= 0 last pass (pump-owned)


class ArrivalPump:
    """The arrival/step pump shared by the demo ``_arrival_loop`` and
    the multi-tenant traffic driver (tpumon.loadgen.traffic): drain
    every source's due arrivals, step the engine, and sleep only while
    idle. Extracted from the old inline Poisson loop so traffic.py
    composes it instead of copy-pasting; with a single constant-rate
    source the scheduling (RNG draw order, catch-up semantics, idle
    sleep policy) is bit-compatible with the pre-extraction loop.

    ``step`` replaces ``engine.step`` when given — the traffic driver
    routes its scheduler-degradation knob through this seam.
    """

    def __init__(self, engine: "ServingEngine",
                 sources: "list[ArrivalSource]", step=None):
        self.engine = engine
        self.sources = list(sources)
        self.step = step if step is not None else engine.step

    def run(self, stop: threading.Event, duration: float = 0.0) -> None:
        t0 = time.monotonic()
        for s in self.sources:
            s.next_at = t0
        while not stop.is_set():
            now = time.monotonic()
            rel = now - t0
            if duration and rel >= duration:
                return
            for s in self.sources:
                # Catch-up against one ``now``: a burst due in the past
                # all fires this pass, exactly like the old loop.
                while True:
                    rate = s.rate(rel)
                    if rate <= 0:
                        s.paused = True
                        break
                    if s.paused:
                        # Pause -> active transition: re-anchor the
                        # clock so the pause produced ZERO arrivals —
                        # without this, next_at stays frozen in the
                        # past and this pass would fire a synthetic
                        # catch-up burst covering the whole pause.
                        s.paused = False
                        s.next_at = max(s.next_at, now)
                    if now < s.next_at:
                        break
                    s.fire(rel)
                    s.next_at += s.interval(rate)
            if not self.step():
                waits = [
                    max(0.0, s.next_at - now)
                    for s in self.sources if s.rate(rel) > 0
                ]
                time.sleep(0.05 if not waits else min(0.05, min(waits)))


def _arrival_loop(engine: ServingEngine, rps: float, max_new: int,
                  stop: threading.Event, duration: float = 0.0,
                  seed: int = 0, temperature: float = 0.0,
                  top_k: int = 0) -> None:
    """Poisson-ish synthetic request arrivals + engine stepping until
    ``stop`` is set (or ``duration`` seconds elapse, if nonzero).

    When the engine has a prefix cache, arrivals model real traffic's
    shared system prompt: every request starts with the same
    two-chunk prefix plus a random tail, so the cache actually hits.

    One ``ArrivalSource`` over the shared pump; the RNG draw order per
    arrival (prompt length, tail tokens, then the exponential gap) is
    the pre-extraction loop's, so seeded runs replay identically.
    """
    import random

    rng = random.Random(seed)
    shared: list[int] = []
    if engine.prefix_cache is not None:
        srng = random.Random(seed ^ 0x5A5)
        shared = [srng.randrange(engine.cfg.model.vocab)
                  for _ in range(2 * engine.cfg.prefill_len)]

    def fire(_rel: float) -> None:
        n = rng.randint(2, engine.cfg.prefill_len)
        tail = [rng.randrange(engine.cfg.model.vocab)
                for _ in range(n)]
        engine.submit(shared + tail, max_new=max_new,
                      temperature=temperature, top_k=top_k)

    src = ArrivalSource(rate=lambda _t: rps, fire=fire,
                        interval=rng.expovariate)
    ArrivalPump(engine, [src]).run(stop, duration=duration)


def start_background(rps: float = 0.5, max_new: int = 16,
                     cfg: ServeConfig | None = None, port: int = 0,
                     seed: int = 0, ckpt_dir: str | None = None,
                     quantize: str | None = None,
                     spec_len: int = 0, prefix_cache: int = 0,
                     kv_layout: str = "dense", pool_pages: int = 0,
                     decode_block: int = 1, kv_dtype: str = "compute",
                     paged_attn: str = "gather",
                     spec_source: str = "draft",
                     scheduler: str = "interleaved",
                     prefill_budget: int = 1,
                     admit_lookahead: int = 0,
                     mesh_dp: int = 1, mesh_tp: int = 1,
                     ring_stripes: int = 0):
    """Run the serving loadgen inside this process: engine loop in a
    daemon thread + /metrics endpoint. Returns (engine, url, stop_event).
    Used by ``python -m tpumon --serve-loadgen`` so one command runs the
    whole north-star loop: a live TPU serving job AND the monitor
    scraping it."""
    if cfg is None and (spec_len or prefix_cache or pool_pages
                        or kv_layout != "dense" or decode_block != 1
                        or kv_dtype != "compute"
                        or paged_attn != "gather"
                        or spec_source != "draft"
                        or scheduler != "interleaved"
                        or prefill_budget != 1
                        or admit_lookahead != 0
                        or mesh_dp != 1 or mesh_tp != 1
                        or ring_stripes != 0):
        import dataclasses

        # Keep the checkpoint-architecture adoption the engine would do
        # for a bare ckpt_dir: engine options must not silently swap the
        # served model back to the demo default.
        base = None
        if ckpt_dir:
            from tpumon.loadgen.checkpoint import saved_model_config

            saved = saved_model_config(ckpt_dir)
            if saved is not None:
                base = ServeConfig(model=saved, slots=4,
                                   prefill_len=min(16, saved.max_seq // 2))
        cfg = dataclasses.replace(
            base or default_engine_config(), spec_len=spec_len,
            prefix_cache_entries=prefix_cache,
            kv_layout=kv_layout, pool_pages=pool_pages,
            decode_block=decode_block, kv_dtype=kv_dtype,
            paged_attn=paged_attn, spec_source=spec_source,
            scheduler=scheduler, prefill_chunk_budget=prefill_budget,
            admit_lookahead=admit_lookahead,
            mesh_dp=mesh_dp, mesh_tp=mesh_tp, ring_stripes=ring_stripes)
    engine = make_serving_engine(cfg=cfg, ckpt_dir=ckpt_dir,
                                 quantize=quantize)
    server, bound = start_metrics_server(engine, port=port)
    stop = threading.Event()

    def _run():
        try:
            _arrival_loop(engine, rps, max_new, stop, seed=seed)
        finally:
            # shutdown() alone stops the accept loop but LEAKS the
            # listening socket — every start/stop cycle would pin an fd
            # (found by tpulint's serve-forever-unclosed pass, PR 8).
            server.shutdown()
            server.server_close()

    threading.Thread(target=_run, daemon=True).start()
    return engine, f"http://127.0.0.1:{bound}/metrics", stop


def main(argv: list[str] | None = None) -> int:
    """``python -m tpumon.loadgen.serving`` — run the serving loadgen:
    synthetic request arrivals + /metrics for tpumon to scrape."""
    import argparse

    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("--port", type=int, default=9105)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--quant", choices=["int8"], default=None,
                    help="weight-only quantization (tpumon.loadgen.quant)")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="sampling temperature (0 = greedy)")
    ap.add_argument("--top-k", type=int, default=0,
                    help="top-k sampling cutoff (0 = full vocab)")
    ap.add_argument("--rps", type=float, default=2.0,
                    help="synthetic request arrival rate")
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--duration", type=float, default=0.0,
                    help="seconds to run; 0 = forever")
    ap.add_argument("--spec-len", type=int, default=0,
                    help="speculative decoding: draft tokens per round "
                         "(0 = off)")
    ap.add_argument("--spec-draft-layers", type=int, default=0,
                    help="draft model layer count (0 = self-speculation: "
                         "draft shares the target weights)")
    ap.add_argument("--spec-source", choices=["draft", "prompt"],
                    default="draft",
                    help="'prompt': n-gram prompt-lookup proposals from "
                         "the request's own context — no draft model "
                         "(tpumon.loadgen.prompt_lookup)")
    ap.add_argument("--prefix-cache", type=int, default=0,
                    help="prompt-prefix KV cache LRU entries (0 = off)")
    ap.add_argument("--kv-dtype", choices=["compute", "int8"],
                    default="compute",
                    help="KV cache element type; int8 halves resident "
                         "cache HBM (dense engine)")
    ap.add_argument("--decode-block", type=int, default=1,
                    help="fuse N plain-decode steps into one dispatch "
                         "(dense or paged KV; 1 = off)")
    ap.add_argument("--kv-layout", choices=["dense", "paged"],
                    default="dense",
                    help="paged: per-request page reservation from a "
                         "shared pool instead of slots*max_seq rows")
    ap.add_argument("--pool-pages", type=int, default=0,
                    help="paged pool size in pages (0 = dense "
                         "equivalent; smaller = real memory savings "
                         "with admission backpressure)")
    ap.add_argument("--paged-attn", choices=["gather", "kernel", "ring"],
                    default="gather",
                    help="paged decode read path: XLA fused gather, "
                         "the Pallas paged-attention kernel (regime "
                         "map in ops/paged_attention), or blockwise "
                         "ring attention paging KV page-by-page "
                         "(long-context ring layouts)")
    ap.add_argument("--mesh", default=None, metavar="DP,TP",
                    help="serve over a dp×tp device mesh: DP "
                         "data-parallel replicas behind the affinity "
                         "router, each tensor-parallel over TP chips "
                         "(docs/perf.md 'Mesh serving')")
    ap.add_argument("--ring-attn", type=int, default=0, metavar="N",
                    help="ring-attention engine mode: admit prompts up "
                         "to N x max_seq by paging KV block-wise "
                         "around the tp ring (requires --kv-layout "
                         "paged; 0 = off)")
    ap.add_argument("--scheduler", choices=["interleaved", "sequential"],
                    default="interleaved",
                    help="admission scheduler: interleaved chunked "
                         "prefill (decode keeps flowing while long "
                         "prompts admit) or the sequential "
                         "stop-the-world baseline")
    ap.add_argument("--prefill-budget", type=int, default=1,
                    help="prefill chunk dispatches per engine step "
                         "under the interleaved scheduler")
    ap.add_argument("--admit-lookahead", type=int, default=0,
                    help="paged admission: probe this many requests "
                         "behind a page-blocked queue head (0 = strict "
                         "FIFO; aging-bounded, see ServeConfig)")
    ap.add_argument("--experts", type=int, default=0,
                    help="serve the MoE model family: this many "
                         "top-1-routed experts per layer (0 = dense; "
                         "full-capacity routing in serving so every "
                         "decode mode stays token-identical)")
    ap.add_argument("--no-report", action="store_true",
                    help="disable the workload self-report (HBM "
                         "footprint + activity to the monitor's "
                         "source:workload channel)")
    args = ap.parse_args(argv)
    if args.spec_draft_layers and not args.spec_len:
        ap.error("--spec-draft-layers requires --spec-len > 0")
    if args.spec_source == "prompt" and args.spec_draft_layers:
        ap.error("--spec-source prompt proposes from context; drop "
                 "--spec-draft-layers")
    if args.spec_source == "prompt" and not args.spec_len:
        ap.error("--spec-source prompt requires --spec-len > 0 "
                 "(speculation is otherwise off and the flag would "
                 "silently do nothing)")
    if args.spec_draft_layers >= 4:  # the CLI model's n_layers below
        ap.error("--spec-draft-layers must be < 4 (the target's depth)")
    if args.spec_len < 0:
        ap.error("--spec-len must be >= 0")
    if args.pool_pages and args.kv_layout != "paged":
        ap.error("--pool-pages requires --kv-layout paged")
    if args.prefill_budget < 1:
        ap.error("--prefill-budget must be >= 1")
    if args.admit_lookahead and args.kv_layout != "paged":
        ap.error("--admit-lookahead requires --kv-layout paged (dense "
                 "admission never blocks on pages)")
    if args.paged_attn == "kernel" and (
            args.kv_layout != "paged" or args.kv_dtype == "int8"):
        ap.error("--paged-attn kernel requires --kv-layout paged with "
                 "--kv-dtype compute (the kernel reads bf16/f32 pages)")
    mesh_dp = mesh_tp = 1
    if args.mesh is not None:
        try:
            mesh_dp, mesh_tp = (int(x) for x in args.mesh.split(","))
        except ValueError:
            ap.error(f"--mesh wants DP,TP (two integers), got "
                     f"{args.mesh!r}")
        if mesh_dp < 1 or mesh_tp < 1:
            ap.error(f"--mesh shape must be >= 1,1, got {args.mesh}")
    if args.ring_attn and args.ring_attn < 2:
        ap.error("--ring-attn N needs N >= 2 stripes (1 stripe IS the "
                 "flat layout; pass 0 to disable)")
    if args.ring_attn and args.kv_layout != "paged":
        ap.error("--ring-attn requires --kv-layout paged (the ring "
                 "pages KV block-wise; a dense cache has no pages)")

    import dataclasses

    model = ModelConfig(vocab=2048, d_model=256, n_layers=4, n_heads=8,
                        n_kv_heads=4, d_ff=1024, max_seq=256,
                        n_experts=args.experts)
    draft = (dataclasses.replace(model, n_layers=args.spec_draft_layers)
             if args.spec_draft_layers else None)
    try:
        engine = make_serving_engine(cfg=ServeConfig(
            model=model, slots=args.slots, prefill_len=32,
            quantize=args.quant,
            spec_len=args.spec_len, draft_model=draft,
            spec_source=args.spec_source,
            prefix_cache_entries=args.prefix_cache,
            kv_layout=args.kv_layout, pool_pages=args.pool_pages,
            decode_block=args.decode_block, kv_dtype=args.kv_dtype,
            paged_attn=args.paged_attn, scheduler=args.scheduler,
            prefill_chunk_budget=args.prefill_budget,
            admit_lookahead=args.admit_lookahead,
            mesh_dp=mesh_dp, mesh_tp=mesh_tp,
            ring_stripes=args.ring_attn,
        ))
    except ValueError as e:
        # Mesh shapes that don't divide the device count, ring modes
        # that don't compose — config errors, reported as such.
        ap.error(str(e))
    server, port = start_metrics_server(engine, args.port)
    print(f"serving loadgen: /metrics on :{port} "
          f"(point TPUMON_SERVING_TARGETS=http://127.0.0.1:{port}/metrics)")
    reporter = None
    if not args.no_report:
        from tpumon.loadgen.report import WorkloadReporter

        reporter = WorkloadReporter(name="serve").start()
        engine.reporter = reporter
    try:
        _arrival_loop(engine, args.rps, args.max_new, threading.Event(),
                      duration=args.duration, temperature=args.temperature,
                      top_k=args.top_k)
    except KeyboardInterrupt:
        pass
    finally:
        if reporter is not None:
            reporter.stop()
        server.shutdown()
        server.server_close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
