"""Typed configuration for tpumon.

The reference has zero configurability: two hardcoded constants
(``PORT = 8888``, ``PROMETHEUS_URL``, monitor_server.js:10-11), a hardcoded
8-core CPU divisor (monitor_server.js:76) and magic-number alert thresholds
(monitor_server.js:163-184). tpumon replaces that with a small typed config
loaded from defaults <- optional JSON/TOML file <- TPUMON_* environment
variables, covering everything SURVEY.md §5.6 calls for: port, Prometheus
URL, core count (auto-detected), thresholds, enabled collectors and
topology expectations.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Mapping

from tpumon.collectors.workload import DEFAULT_DIR as _WORKLOAD_DEFAULT_DIR

_DURATION_RE = re.compile(r"^(\d+)([smhd])$")
_DURATION_UNITS = {"s": 1, "m": 60, "h": 3600, "d": 86400}


def parse_duration(text: str | int | float, default: float | None = None) -> float:
    """Parse ``"30m"``-style durations into seconds.

    Same grammar as the reference's parseDuration (monitor_server.js:54-63:
    regex ``(\\d+)([smhd])``), but a bad input raises (or returns an explicit
    caller-provided default) instead of silently becoming 1800.
    """
    if isinstance(text, (int, float)):
        return float(text)
    m = _DURATION_RE.match(text.strip())
    if not m:
        if default is not None:
            return default
        raise ValueError(f"invalid duration {text!r} (want e.g. '30s', '30m', '1h')")
    return float(int(m.group(1)) * _DURATION_UNITS[m.group(2)])


@dataclass(frozen=True)
class TriLevel:
    """A minor/serious/critical threshold triple.

    Mirrors the reference's three severity buckets (monitor_server.js:159-238,
    README.md:58-64). ``minor`` may be None for signals that only have
    serious/critical levels (e.g. temperature, monitor_server.js:183-184).
    """

    minor: float | None
    serious: float
    critical: float

    def severity(self, value: float) -> str | None:
        """Classify a value; returns 'minor' | 'serious' | 'critical' | None."""
        if value > self.critical:
            return "critical"
        if value > self.serious:
            return "serious"
        if self.minor is not None and value > self.minor:
            return "minor"
        return None


@dataclass(frozen=True)
class Thresholds:
    """Alert thresholds, re-keyed for TPU per SURVEY.md §2.2.

    cpu/memory/disk keep the reference's 70/85/95 (monitor_server.js:163-175).
    GPU-mem% becomes per-chip HBM% (reference checked device 0 only,
    monitor_server.js:178-182); GPU temp becomes chip temp 75/85
    (monitor_server.js:183-184). MXU duty-cycle gets *idle* rules instead of
    high-usage rules (a busy MXU is healthy; a claimed-busy job on an idle
    MXU is not) plus the TPU-only ICI/slice rules.
    """

    cpu_pct: TriLevel = TriLevel(70, 85, 95)
    memory_pct: TriLevel = TriLevel(70, 85, 95)
    disk_pct: TriLevel = TriLevel(70, 85, 95)
    hbm_pct: TriLevel = TriLevel(70, 85, 95)
    temp_c: TriLevel = TriLevel(None, 75, 85)
    # A chip whose HBM is heavily committed but whose MXU duty-cycle sits
    # below this for the whole observation window is likely a wedged/stalled
    # job (serious).
    mxu_idle_pct: float = 5.0
    mxu_idle_hbm_gate_pct: float = 50.0
    # A training target whose step counter hasn't advanced for this long
    # is stalled (serious) — wedged collective, input starvation, or a
    # checkpoint write that never returns. 0 disables.
    train_stall_s: float = 120.0
    # Paged-serving KV pool occupancy (reserved pages / pool): high
    # occupancy means admissions are about to queue on KV memory.
    kv_pool_pct: TriLevel = TriLevel(None, 85, 95)
    # libtpu SDK per-chip scores, both scaled 0-10 (PROBE_libtpu.md).
    # ICI link health: 1-5 transient problem (minor), 6-9 persistent
    # minor problem (serious); 10 = unusable, covered by the critical
    # link-down rule. Throttle: score N means throttled by N*10%.
    ici_health_score: TriLevel = TriLevel(0, 5, 9)
    throttle_score: TriLevel = TriLevel(0, 4, 7)
    # Anti-flap holds (Prometheus "for" / "keep_firing_for" semantics):
    # a condition must hold fire_hold_s before the alert fires, and must
    # stay clear resolve_hold_s before it resolves. 0/0 = the reference's
    # instant per-evaluation behavior (its 1-sample alerts flap at every
    # threshold crossing).
    fire_hold_s: float = 0.0
    resolve_hold_s: float = 0.0


@dataclass(frozen=True)
class Config:
    # --- serving ---
    port: int = 8888  # same default as the reference (monitor_server.js:10)
    host: str = "0.0.0.0"
    # Server-side TLS (the PR 7 follow-up): PEM certificate chain + key
    # terminating HTTPS on the listener, so the SLO/alerting surface —
    # and eventually the actuation routes — isn't plaintext on the pod
    # network. tls_key defaults to tls_cert (single combined PEM).
    # Uplinks already speak https:// in federate_up; with these set the
    # server side can terminate them.
    tls_cert: str | None = None
    tls_key: str | None = None

    # --- history (reference: 30m window / 30s step, monitor_server.js:38) ---
    # DEPRECATED: the external-Prometheus history path is retired — the
    # in-process TSDB + query engine (tpumon.query, docs/query.md)
    # serve /api/history and /api/query. Accepted so old configs load;
    # a deprecation warning is printed, nothing is queried.
    prometheus_url: str | None = None
    history_window_s: float = 30 * 60
    history_step_s: float = 30
    # Long-range tier: /api/history?window= up to this span, served from
    # coarse (bucket-mean) ring data when Prometheus is absent.
    history_long_window_s: float = 24 * 3600
    history_coarse_step_s: float = 60
    # Mid retention tier (tpumon.tsdb): bucket means between the fine
    # ring and the coarse tier, so multi-hour windows render at 30 s
    # resolution instead of the coarse step. 0 disables.
    history_mid_step_s: float = 30
    history_mid_window_s: float = 6 * 3600
    # Per-chip history: the sampler records chip.<id>.{mxu,hbm,temp,link}
    # series for up to this many chips (drill-down curves via
    # /api/history?series=chip.* — holds at v5p-256 thanks to the
    # columnar store). 0 disables per-chip history entirely; chips
    # beyond the cap are counted, not silently dropped (/api/health).
    history_per_chip: int = 256
    # On-disk format for history_snapshot_path writes: "binary" (the v2
    # chunk-verbatim format, ~10x cheaper) or "json" (the v1 format).
    # Restore reads either, whatever this is set to.
    history_snapshot_format: str = "binary"

    # --- sampling (replaces per-request execSync collection, SURVEY §3.2) ---
    sample_interval_s: float = 1.0
    pods_interval_s: float = 5.0
    serving_interval_s: float = 5.0

    # --- resilience (tpumon.resilience; SURVEY §7 hardened) ---
    # Wall-clock bound on any one collect(): a hung collector (stuck
    # kubectl, wedged gRPC channel) degrades to a deadline-exceeded
    # Sample instead of freezing the sampler loop. 0 disables.
    collect_deadline_s: float = 10.0
    # Per-source overrides, e.g. {"k8s": 30, "host": 2}.
    collect_deadlines: Mapping[str, float] = field(default_factory=dict)
    # Circuit breaker: after this many consecutive failures a source is
    # probed on an exponential-backoff cadence (base..max, ±20% jitter)
    # instead of at full rate. breaker_failures=0 disables breaking.
    breaker_failures: int = 3
    breaker_backoff_s: float = 5.0
    breaker_backoff_max_s: float = 300.0
    # --- self-tracing (tpumon.tracing; docs/observability.md) ---
    # Bounded span-ring capacity for the always-on data-plane tracer
    # behind /api/trace, /api/trace/export and the
    # tpumon_stage_duration_seconds histograms. 0 disables tracing
    # entirely (the bench's overhead baseline).
    trace_ring: int = 4096

    # --- structured event journal (tpumon.events; docs/events.md) ---
    # Bounded ring of lifecycle events (alert fired/resolved, breaker
    # transitions, chaos injections, anomaly fires, ...) behind
    # /api/events, the SSE event feed and tpumon_events_total. Values
    # below 16 clamp up — a ring too small for one alert lifecycle
    # would break the timeline.
    events_ring: int = 4096
    # JSONL persistence path for the journal (crash-safe atomic
    # rewrites on events_interval_s, restored at startup so cursors and
    # the incident record survive restarts). None disables.
    events_path: str | None = None
    events_interval_s: float = 30.0

    # --- EWMA anomaly detection (tpumon.anomaly; docs/events.md) ---
    # Per-series drift detectors over fleet duty/HBM, tick duration and
    # per-source scrape p95: z-score gate with hysteresis, emitting
    # ``anomaly`` journal events and a minor ``anomaly.<series>`` alert.
    anomaly_detect: bool = True
    anomaly_alpha: float = 0.05
    anomaly_z_fire: float = 4.0
    anomaly_z_clear: float = 1.5
    anomaly_warmup: int = 30

    # Chaos fault injection ("mode:source:param,..." —
    # tpumon.collectors.chaos; "" = no faults). Example:
    # "hang:accel:0.1,err:k8s:0.3,slow:host:200".
    chaos: str = ""
    # Optional seed for reproducible chaos soaks.
    chaos_seed: int | None = None

    # --- crash-safe history (tpumon.history.HistorySnapshotter) ---
    # Path for the periodic ring+coarse history snapshot; restored at
    # startup so a monitor restart doesn't erase the recent past. None
    # disables (state_path already covers history when configured).
    history_snapshot_path: str | None = None
    history_snapshot_interval_s: float = 30.0

    # --- collectors ---
    collectors: tuple[str, ...] = ("host", "accel", "k8s", "serving")
    # accel backend: "auto" | "jax" | "fake:<topology>" | "none", plus
    # the GPU family (ISSUE 15): "gpufake:<topology>" (dgx-a100-8 /
    # dgx-h100-8 / superpod-32), "nvidia-smi[:<path>]" (CSV shell-out),
    # "dcgm:<url>" (DCGM-exporter scrape) — all normalize into the same
    # ChipSample schema with accel_kind="gpu".
    accel_backend: str = "auto"
    # host cpu count: 0 => auto-detect (reference hardcoded 8, monitor_server.js:76)
    cpu_count: int = 0
    disk_mounts: tuple[str, ...] = ("/",)
    # k8s: "auto" tries in-cluster API then kubectl; "api" | "watch"
    # (live watch stream — catches sub-sample pod flaps) | "kubectl" |
    # "fake" | "none"
    k8s_mode: str = "auto"
    k8s_api_url: str | None = None
    # JetStream / MaxText /metrics scrape targets (SURVEY §5.7)
    serving_targets: tuple[str, ...] = ()
    # Peer tpumon instances whose chips are merged into this one's view
    # (realtime multi-host federation, BASELINE config 5)
    peers: tuple[str, ...] = ()
    # Federation fan-out bound: at most this many peer fetches in
    # flight at once (a 64-peer fleet must not spawn 64 worker threads
    # per tick) — see tpumon.collectors.accel_peers.
    peer_fanout: int = 16
    # Per-peer HTTP timeout for federation fetches.
    peer_timeout_s: float = 3.0
    # Binary peer wire (docs/perf.md "ingest spine"): serve and request
    # the columnar binary frame on /api/accel/wire (negotiated by
    # Accept header; JSON remains the default representation so
    # pre-binary peers keep federating). Off = JSON-only, both ways.
    wire_binary: bool = True
    # --- hierarchical federation (tpumon.federation, docs/federation.md) ---
    # Role in the aggregator tree: "" standalone (the default — no tree
    # behavior at all), "leaf" (pushes chip-level delta frames to
    # federate_up), "aggregator" (ingests downstream frames on
    # /api/federation/ingest, computes slice rollups, pushes SLICE-level
    # rows to federate_up), "root" (ingest + rollups only, the fleet
    # view). federate_up set with no role implies "leaf".
    federation_role: str = ""
    # Upstream aggregator base URL this instance pushes delta frames to
    # (long-lived chunked POST — push-based, the upstream never polls).
    # Dual-homed HA: a comma-separated second address is the standby
    # upstream — the uplink rotates to it on any stream failure and the
    # reconnect keyframe rebuilds the new upstream's fan-in state.
    federate_up: str | None = None
    # Node identity in upstream views/events; default = hostname.
    federation_node: str | None = None
    # Uplink keyframe cadence (the sse_keyframe_every idea applied to
    # the federation wire): a full keyframe every N frames bounds how
    # long a silently-desynced aggregator can stay wrong. Reconnects
    # always start with a keyframe regardless.
    federation_keyframe_every: int = 30
    # A downstream node whose stream has been silent this long is
    # marked dark: its slices flip to health="dark" in the fleet view
    # and a serious ``federation`` event fires.
    federation_dark_after_s: float = 5.0
    # --- root HA (tpumon.leader, docs/federation.md "Root HA") ---
    # Base URL of this root's peer root. Set on BOTH roots (each points
    # at the other); enables the leadership lease + heartbeat poll +
    # journal reconciliation. Leaves/aggregators reach both roots via a
    # comma-separated dual-homed federate_up instead.
    federation_peer: str = ""
    # Leadership lease length: a root whose event loop stops renewing
    # for this long self-fences (refuses to actuate); the standby
    # promotes after 2x this of peer silence.
    federation_lease_s: float = 2.0
    # Bootstrap asymmetry: exactly one root sets this, and it claims
    # generation 1 on its first peer probe instead of waiting out a
    # silence window. A restarting root always defers to an observed
    # leader regardless.
    federation_initial_leader: bool = False
    # Native TSDB append/downsample kernel (tpumon/native/tsdbkern.cpp):
    # off forces the bit-exact pure-Python ingest path even when the
    # shared library is built.
    ingest_kernel: bool = True

    # --- in-tree query engine (tpumon.query; docs/query.md) ---
    # Recording rules: ``family[window]`` range selectors (e.g.
    # "chip.mxu[5m]") whose count/sum/min/max/rate/quantile aggregates
    # are maintained incrementally AT APPEND TIME — an instant
    # *_over_time / rate read over a registered (family, window) is an
    # O(1) head-state merge, never a point walk.
    recording_rules: tuple[str, ...] = ()
    # Default window when a range function omits [w]: rate(chip.hbm)
    # reads the last query_default_range.
    query_default_range_s: float = 60.0
    # Instant-selector staleness bound: a series with no point newer
    # than this is absent from instant vectors (Prometheus lookback).
    query_lookback_s: float = 300.0
    # Wall budget for one distributed (fleet=1) query across the
    # federation tree; silent/dark nodes past it degrade the answer to
    # an explicit partial instead of an error.
    query_fleet_timeout_s: float = 2.0

    # --- SLO objectives (tpumon.slo; docs/slo.md) ---
    # Each entry: {"name", "expr", "target", "window", "tenant"?,
    # "fast"?/"slow"? window pairs, "fast_burn"?/"slow_burn"?/
    # "clear_ratio"?}. ``expr`` is the bad-event condition in the query
    # language; the engine records slo.<name>.bad per tick and serves
    # multi-window burn-rate alerts from it (GET /api/slo,
    # tpumon_slo_* gauges, `tpumon slo`). As an env/CLI value the list
    # is JSON (TPUMON_SLOS='[{"name": ...}]').
    slos: tuple = ()

    # --- SLO-driven actuation (tpumon.actuate; docs/actuation.md) ---
    # Each entry: {"name", "when", "action": "shed"|"capacity"|"drain",
    # per-action params, "clear"?, "cooldown_s"?, "fire_hold"?,
    # "clear_hold"?, "dry_run"?}. ``when`` is a query-language
    # condition (like the SLO bad-event expressions); the engine
    # evaluates every policy once per fast tick and drives the bound
    # actuator through journaled, guarded transitions. As an env/CLI
    # value the list is JSON (TPUMON_ACTUATIONS='[{"name": ...}]').
    actuations: tuple = ()
    # Global dry-run: every policy journals intent without acting
    # (per-policy "dry_run" does the same for one policy).
    actuate_dry_run: bool = False
    # Global guard: at most this many performed actions per
    # actuate_window_s across ALL policies — a misconfigured policy set
    # cannot thrash the serving engine. Reverts are never rate-limited.
    actuate_max_actions: int = 10
    actuate_window_s: float = 60.0
    # Hard cap any shed policy's fraction is clamped to — a
    # misconfigured policy can never shed a whole tenant (the serving
    # engine holds its own last-resort ceiling on top).
    shed_max_fraction: float = 0.5

    # --- SSE delta stream (tpumon.server, docs/perf.md) ---
    # The /api/stream push emits delta frames (only changed fields,
    # keyed by snapshot epoch); a full keyframe recurs every this many
    # frames so a desynced client is bounded. 1 = keyframe-only (the
    # pre-delta wire behavior, at full-payload cost per frame).
    sse_keyframe_every: int = 30
    # Directory where workloads self-report HBM/activity
    # (tpumon.collectors.workload) — the explicitly-labeled fallback
    # counter source when every platform source is dark. "" disables.
    # Default is uid-suffixed and ownership-checked (multi-user /tmp).
    workload_dir: str = _WORKLOAD_DEFAULT_DIR

    # --- topology expectations (for slice-failure alerting, SURVEY §2.2) ---
    # e.g. {"slice-0": 8} => alert critical if fewer chips report
    expected_slice_chips: Mapping[str, int] = field(default_factory=dict)

    # --- checkpoint/resume (SURVEY §5.4; tpumon.state) ---
    # Path for the monitor-state snapshot (ring history, alert timeline,
    # pod-transition baseline). None => reference behavior: state dies
    # with the process (monitor_server.js:157).
    state_path: str | None = None
    state_interval_s: float = 60.0

    # --- alert webhook sinks (tpumon.notify; reference has no alert
    # delivery — alerts live only as long as a browser polls) ---
    # URLs receive fired/resolved events as JSON POSTs; prefix "slack+"
    # (or use a hooks.slack.com URL) for Slack-message payloads.
    alert_webhooks: tuple[str, ...] = ()
    webhook_min_severity: str = "minor"  # minor | serious | critical
    webhook_timeout_s: float = 5.0

    # Per-request access logging (method path status ms) — SURVEY §5.1.
    access_log: bool = False

    # Bearer token gating the mutating/expensive routes (POST
    # /api/silence, /api/unsilence; GET /api/profile; GET
    # /api/query?fleet=1 — a distributed query fans sub-queries across
    # the whole federation tree per request). None (default)
    # keeps those routes open — reference parity (monitor_server.js:
    # 244-248 serves everything unauthenticated) — but the reference has
    # no mutating routes, so deployments that page off tpumon alerts
    # should set a token (TPUMON_AUTH_TOKEN) so network reach doesn't
    # equal silence-my-pager.
    auth_token: str | None = None

    thresholds: Thresholds = field(default_factory=Thresholds)

    def effective_cpu_count(self) -> int:
        return self.cpu_count or os.cpu_count() or 1


# Keys accepted from file / env and how to coerce them.
_SCALAR_FIELDS: dict[str, type] = {
    "port": int,
    "host": str,
    "prometheus_url": str,
    "sample_interval_s": float,
    "pods_interval_s": float,
    "serving_interval_s": float,
    "accel_backend": str,
    "cpu_count": int,
    "k8s_mode": str,
    "k8s_api_url": str,
    "state_path": str,
    "state_interval_s": float,
    "collect_deadline_s": float,
    "breaker_failures": int,
    "breaker_backoff_s": float,
    "breaker_backoff_max_s": float,
    "trace_ring": int,
    "events_ring": int,
    "events_path": str,
    "events_interval_s": float,
    "anomaly_detect": lambda v: str(v).lower() in ("1", "true", "yes", "on"),
    "anomaly_alpha": float,
    "anomaly_z_fire": float,
    "anomaly_z_clear": float,
    "anomaly_warmup": int,
    "chaos": str,
    "chaos_seed": int,
    "history_snapshot_path": str,
    "history_snapshot_interval_s": float,
    "history_snapshot_format": str,
    "history_per_chip": int,
    "peer_fanout": int,
    "peer_timeout_s": float,
    "wire_binary": lambda v: str(v).lower() in ("1", "true", "yes", "on"),
    "federation_role": str,
    "federate_up": str,
    "federation_node": str,
    "federation_keyframe_every": int,
    "federation_dark_after_s": float,
    "federation_peer": str,
    "federation_lease_s": float,
    "federation_initial_leader":
        lambda v: str(v).lower() in ("1", "true", "yes", "on"),
    "ingest_kernel": lambda v: str(v).lower() in ("1", "true", "yes", "on"),
    "query_fleet_timeout_s": float,
    "sse_keyframe_every": int,
    "actuate_dry_run": lambda v: str(v).lower() in ("1", "true", "yes", "on"),
    "actuate_max_actions": int,
    "actuate_window_s": float,
    "shed_max_fraction": float,
    "webhook_min_severity": str,
    "webhook_timeout_s": float,
    "access_log": lambda v: str(v).lower() in ("1", "true", "yes", "on"),
    "auth_token": str,
    "workload_dir": str,
    "tls_cert": str,
    "tls_key": str,
}
# Config-file/env key -> Config field for duration-valued settings
# ("30m"-style strings accepted via parse_duration).
_DURATION_KEYS = {
    "history_window": "history_window_s",
    "history_step": "history_step_s",
    "history_long_window": "history_long_window_s",
    "history_coarse_step": "history_coarse_step_s",
    "history_mid_step": "history_mid_step_s",
    "history_mid_window": "history_mid_window_s",
    "query_default_range": "query_default_range_s",
    "query_lookback": "query_lookback_s",
}
_LIST_FIELDS = {
    "collectors", "disk_mounts", "serving_targets", "peers",
    "alert_webhooks", "recording_rules",
}


def _coerce_thresholds(raw: Mapping[str, Any], base: Thresholds) -> Thresholds:
    kw: dict[str, Any] = {}
    for f in dataclasses.fields(Thresholds):
        if f.name not in raw:
            continue
        v = raw[f.name]
        is_trilevel = f.type in ("TriLevel", TriLevel)
        if isinstance(v, (list, tuple)):
            if not is_trilevel:
                raise ValueError(f"threshold {f.name}: want a single number, got {v!r}")
            if len(v) == 3:
                kw[f.name] = TriLevel(v[0], v[1], v[2])
            elif len(v) == 2:
                kw[f.name] = TriLevel(None, v[0], v[1])
            else:
                raise ValueError(f"threshold {f.name}: want 2 or 3 values, got {v!r}")
        elif is_trilevel:
            raise ValueError(
                f"threshold {f.name}: want [minor, serious, critical] or "
                f"[serious, critical], got {v!r}"
            )
        else:
            kw[f.name] = float(v)
    return dataclasses.replace(base, **kw) if kw else base


def _apply_mapping(cfg_kw: dict[str, Any], raw: Mapping[str, Any]) -> None:
    for key, value in raw.items():
        if key.startswith("_"):  # comment keys in config files
            continue
        if key in _SCALAR_FIELDS:
            cfg_kw[key] = None if value is None else _SCALAR_FIELDS[key](value)
        elif key in _DURATION_KEYS:
            cfg_kw[_DURATION_KEYS[key]] = parse_duration(value)
        elif key in _LIST_FIELDS:
            if isinstance(value, str):
                value = [v.strip() for v in value.split(",") if v.strip()]
            cfg_kw[key] = tuple(value)
        elif key == "expected_slice_chips":
            cfg_kw[key] = {str(k): int(v) for k, v in value.items()}
        elif key == "collect_deadlines":
            cfg_kw[key] = {str(k): float(v) for k, v in value.items()}
        elif key in ("slos", "actuations"):
            # SLO objectives (tpumon.slo, docs/slo.md) and actuation
            # policies (tpumon.actuate, docs/actuation.md): lists of
            # objects in config files; env/CLI pass the list as JSON.
            # Structural validation happens in slo.parse_slos /
            # actuate.parse_actuations at startup (per-entry,
            # journaled) — here we only coerce.
            if isinstance(value, str):
                value = json.loads(value) if value.strip() else []
            if not isinstance(value, (list, tuple)):
                raise ValueError(
                    f"{key}: want a list of objects, got {value!r}")
            cfg_kw[key] = tuple(value)
        elif key == "thresholds":
            cfg_kw["_thresholds_raw"] = value
        else:
            raise ValueError(f"unknown config key {key!r}")


def load_config(
    path: str | None = None,
    env: Mapping[str, str] | None = None,
    overrides: Mapping[str, Any] | None = None,
) -> Config:
    """Build a Config from defaults <- file <- env <- explicit overrides."""
    env = os.environ if env is None else env
    kw: dict[str, Any] = {}

    path = path or env.get("TPUMON_CONFIG")
    if path:
        with open(path, "rb") as f:
            if path.endswith(".toml"):
                import tomllib

                raw = tomllib.load(f)
            else:
                raw = json.load(f)
        _apply_mapping(kw, raw)

    env_raw: dict[str, Any] = {}
    for env_key, value in env.items():
        if not env_key.startswith("TPUMON_") or env_key == "TPUMON_CONFIG":
            continue
        key = env_key[len("TPUMON_") :].lower()
        env_raw[key] = value
    if env_raw:
        # Env values arrive as strings; mapping-valued keys as JSON.
        if "expected_slice_chips" in env_raw:
            env_raw["expected_slice_chips"] = json.loads(env_raw["expected_slice_chips"])
        if "collect_deadlines" in env_raw:
            env_raw["collect_deadlines"] = json.loads(env_raw["collect_deadlines"])
        if "thresholds" in env_raw:
            env_raw["thresholds"] = json.loads(env_raw["thresholds"])
        _apply_mapping(kw, env_raw)

    if overrides:
        _apply_mapping(kw, overrides)

    thresholds_raw = kw.pop("_thresholds_raw", None)
    cfg = Config(**kw)
    if thresholds_raw:
        cfg = dataclasses.replace(
            cfg, thresholds=_coerce_thresholds(thresholds_raw, cfg.thresholds)
        )
    return cfg
