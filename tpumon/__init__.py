"""tpumon — a TPU-native cluster monitoring framework.

Re-implements, TPU-first, the capabilities of the reference dashboard
``fuqiangfeng96-web/k8s-llm-monitor`` (a Node.js + browser K8s LLM monitor,
see /root/reference/monitor_server.js and monitor.html):

- live host metric cards           (reference: monitor_server.js:66-81)
- live accelerator metric cards    (reference: monitor_server.js:83-95, nvidia-smi)
- Kubernetes pod table             (reference: monitor_server.js:97-114, kubectl)
- 30-min history charts            (reference: monitor_server.js:117-154, PromQL)
- three-tier alert engine          (reference: monitor_server.js:156-238)
- single self-contained dashboard  (reference: monitor.html)

The NVIDIA data path (nvidia-smi shell-out, DCGM exporter, DCGM_FI_DEV_*
series) is replaced by a TPU-native one: per-chip MXU duty cycle, HBM
usage and ICI link traffic read in-process, exported as tpu_* Prometheus
series by an in-tree exporter, with chip->host->slice topology as a
first-class data model and JetStream/MaxText serving-metrics ingest.

Architectural divergences from the reference (deliberate, per SURVEY.md):
- async collectors + a single background sampler own all state; HTTP
  handlers only read snapshots (fixes the reference's event-loop blocking
  execSync calls and its lastPodStates data race, monitor_server.js:157,235).
- per-chip alerting (the reference only inspects device 0,
  monitor_server.js:178).
- in-process ring-buffer history as a degraded mode so the dashboard works
  without Prometheus.
- explicit per-source health instead of indistinguishable empty payloads.
"""

__version__ = "0.1.0"

from tpumon.config import Config, load_config  # noqa: F401
