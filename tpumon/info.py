"""Alias so ``python -m tpumon.info`` works like the tpu-info CLI."""

from tpumon.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
