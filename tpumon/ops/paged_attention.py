"""Paged-attention decode as a Pallas TPU kernel.

The building block for vLLM-style paged KV serving (beyond-reference:
the reference ships no serving code — SURVEY §5.7). Instead of one
contiguous ``[max_seq]`` KV region per slot, sequences own lists of
fixed-size pages from a shared pool; the per-slot page table is the
indirection. Decode attention then has a data-dependent gather the
plain XLA path would materialize in HBM every step — the page table
says *which* page to read only at runtime.

That gather is exactly what TPU scalar prefetch is for: the page table
and sequence lengths ride in SMEM ahead of the kernel body
(``pltpu.PrefetchScalarGridSpec``), so the BlockSpec index map can
route each grid step's HBM→VMEM DMA to ``table[b, p]`` directly — pages
stream through VMEM once, nothing is re-materialized.

Schedule: grid = (B, kv_heads, max_pages), pages innermost
("arbitrary") so each (sequence, kv-head) keeps online-softmax state —
running max m, denominator l, f32 accumulator over the GQA query group
— in VMEM scratch across page steps. Pages past a sequence's length are
skipped with ``pl.when`` (their DMA may fetch an arbitrary valid page;
its values are never read into the accumulator), and the final partial
page is masked by position.

Measured on v5e (slope-timed; full regime map in BENCH_NOTES r05):

- Isolated op, B=16, 32/8 heads, hd=128, 4k context, bf16, 268 MB pool,
  RANDOM-permutation table (the layout a churned pool converges to):
  this kernel streams KV at **149.3 GB/s vs 75.3 for the XLA
  dense-gather path** (``paged_attention_reference`` under jit) —
  1.98x (BENCH_r04). An earlier round claimed ~555 GB/s parity for
  both; that run predated the noise-floor/roofline guards
  (BENCH_NOTES.md "r02 -> r03 correction") and is superseded.
- Full ENGINE decode step (the kernel consumed via
  ``ServeConfig.paged_attn="kernel"`` in
  loadgen/paged_kv.paged_decode_step) at production shape — 370M
  params, 16 slots x 4k context, page 128, GQA 4, 537 MB of KV
  streamed per step: **11.0 -> 7.4 ms/step (1.49x)** — bench
  ``paged_engine_step_*``.
- Same engine step at the demo/test shape (page 32, hd 64, group 1,
  pool 8-135 MB): gather WINS ~9x — the small pool sits in on-chip
  memory and the kernel's (1, group, hd) grid cells are too small to
  feed the MXU; and end-to-end through the axon tunnel at that shape
  both paths tie (dispatch-bound). Hence the engine default is
  "gather"; production long-context configs should select "kernel".

Known headroom: ``pl.when`` skips compute but not the pipeline's page
DMA, so short sequences in a mixed batch still pay max_pages of
traffic in both paths — compacting the grid by prefetched page counts
is the next step if that mix dominates.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpumon.ops.flash_attention import _NEG_INF, online_softmax_update


def _paged_kernel(
    table_ref, len_ref, q_ref, k_ref, v_ref, out_ref, m_ref, l_ref, acc_ref,
    *, page_size: int, pages: int, scale: float,
):
    b = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = len_ref[b]

    @pl.when(p * page_size < length)
    def _attend():
        q = q_ref[0, 0]  # [group, hd]
        k = k_ref[0, 0]  # [page_size, hd]
        v = v_ref[0, 0]  # [page_size, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [group, page_size]
        kpos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        s = jnp.where(kpos < length, s, _NEG_INF)
        online_softmax_update(s, v, m_ref, l_ref, acc_ref)

    @pl.when(p == pages - 1)
    def _store():
        l_final = l_ref[:, 0]
        l_safe = jnp.where(l_final == 0.0, 1.0, l_final)
        out_ref[0, 0] = (acc_ref[:] / l_safe[:, None]).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_table: jax.Array,
    lengths: jax.Array,
    interpret: bool = False,
) -> jax.Array:
    """Decode-step attention over paged KV.

    q: [B, n_heads, hd] (one query token per sequence);
    k_pages/v_pages: [n_kv_heads, num_pages, page_size, hd] shared pool
    (head-major: the TPU lowering requires the last two block dims to
    be full/aligned, so the head axis must come first — it also makes
    each page's rows one contiguous DMA);
    page_table: [B, max_pages] int32 — page ids per sequence in order
    (entries past the sequence's pages may be any valid id);
    lengths: [B] int32 context lengths. Returns [B, n_heads, hd].
    GQA handled in-kernel: each grid cell attends one kv head's query
    group. Entirely masked sequences (length 0) return zeros.
    """
    b, nh, hd = q.shape
    nkv, num_pages, page_size, hd2 = k_pages.shape
    assert hd2 == hd and v_pages.shape == k_pages.shape
    assert nh % nkv == 0, (nh, nkv)
    group = nh // nkv
    _, max_pages = page_table.shape
    scale = 1.0 / hd**0.5
    qg = q.reshape(b, nkv, group, hd)

    kernel = functools.partial(
        _paged_kernel, page_size=page_size, pages=max_pages, scale=scale,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, lengths
        grid=(b, nkv, max_pages),
        in_specs=[
            pl.BlockSpec((1, 1, group, hd),
                         lambda bb, h, p, table, lens: (bb, h, 0, 0)),
            pl.BlockSpec((1, 1, page_size, hd),
                         lambda bb, h, p, table, lens:
                         (h, table[bb, p], 0, 0)),
            pl.BlockSpec((1, 1, page_size, hd),
                         lambda bb, h, p, table, lens:
                         (h, table[bb, p], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, group, hd),
                               lambda bb, h, p, table, lens: (bb, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, 128), jnp.float32),  # running max m
            pltpu.VMEM((group, 128), jnp.float32),  # running denom l
            pltpu.VMEM((group, hd), jnp.float32),  # output accumulator
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nkv, group, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(page_table, lengths, qg, k_pages, v_pages)
    return out.reshape(b, nh, hd)


def paged_attention_reference(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_table: jax.Array,
    lengths: jax.Array,
) -> jax.Array:
    """Dense oracle: gather pages per sequence, plain softmax attention.

    Under jit this is also the engine's ``paged_attn="gather"`` read
    path: XLA fuses the leading-axis gather into the attention consumer
    instead of materializing it — competitive while page tables stay
    near-contiguous, ~2x slower than the kernel once the pool fragments
    (see module docstring for the measured numbers).
    """
    b, nh, hd = q.shape
    nkv, _, page_size, _ = k_pages.shape
    _, max_pages = page_table.shape
    s_max = max_pages * page_size
    # [nkv, B, max_pages, page_size, hd] -> [B, S, nkv, hd]
    k = k_pages[:, page_table].reshape(
        nkv, b, s_max, hd).transpose(1, 2, 0, 3)
    v = v_pages[:, page_table].reshape(
        nkv, b, s_max, hd).transpose(1, 2, 0, 3)
    group = nh // nkv
    if group > 1:
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    s = jnp.einsum("bhd,bkhd->bhk", q, k).astype(jnp.float32) / hd**0.5
    kpos = jnp.arange(s_max, dtype=jnp.int32)
    mask = kpos[None, None] < lengths[:, None, None]
    s = jnp.where(mask, s, _NEG_INF)
    # Fully-masked rows (length 0) produce uniform probs; zero them.
    probs = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    probs = jnp.where(mask, probs, 0.0)
    return jnp.einsum("bhk,bkhd->bhd", probs, v)
