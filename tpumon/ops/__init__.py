"""Pallas TPU kernels used by the loadgen/burn workloads.

The framework's compute path is the loadgen subsystem (the monitor itself
runs no XLA programs); these kernels are its hot ops, written the TPU way:
MXU-shaped bf16 tiles, float32 VMEM accumulation, grid semantics that let
Mosaic pipeline HBM→VMEM copies. They run in interpret mode on CPU for
tests and compiled on real TPUs.
"""
