"""Fused causal flash attention as a Pallas TPU kernel.

The single-chip hot op of the loadgen transformer (its multi-chip
counterpart is tpumon.loadgen.ring_attention, which rotates K/V blocks
across chips; this kernel is what each chip would run on its local
blocks). Standard flash-attention schedule:

  grid = (batch*heads, Tq/block_q, Tk/block_k), K innermost ("arbitrary")
  so each (bh, iq) output tile keeps its online-softmax state — running
  max m, denominator l, and the f32 accumulator — in VMEM scratch across
  K steps; HBM sees each block exactly once.

TPU specifics: m/l live in (block_q, 128) VMEM tiles (min lane width)
with the statistic broadcast across lanes; causal block skipping uses
pl.when so fully-masked K blocks cost no MXU work; the in-block mask is
built from broadcasted iotas (2D, as TPU requires).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def online_softmax_update(s, v, m_ref, l_ref, acc_ref):
    """One flash-attention block update, shared by the flash and paged
    kernels so their numerics stay provably identical.

    s: [rows, cols] f32 scores (already scaled/masked); v: [cols, d]
    values; m/l: (rows, 128) VMEM stat tiles (statistic broadcast
    across lanes — min TPU lane width); acc: (rows, d) f32 accumulator.
    """
    m_prev = m_ref[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
    p = jnp.exp(s - m_new[:, None])  # masked entries underflow to 0
    corr = jnp.exp(m_prev - m_new)
    l_ref[:] = (l_ref[:, 0] * corr + jnp.sum(p, axis=1))[
        :, None] + jnp.zeros_like(l_ref)
    m_ref[:] = m_new[:, None] + jnp.zeros_like(m_ref)
    acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _flash_kernel(
    q_ref, k_ref, v_ref, out_ref, m_ref, l_ref, acc_ref,
    *, block_q: int, block_k: int, k_steps: int, scale: float, causal: bool,
):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _attend():
        q = q_ref[0]  # [block_q, d]
        k = k_ref[0]  # [block_k, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        online_softmax_update(s, v_ref[0], m_ref, l_ref, acc_ref)

    if causal:
        # Skip K blocks entirely above the diagonal: with equal block
        # sizes, block (iq, ik) is all-masked iff ik > iq.
        pl.when(ik * block_k <= iq * block_q + (block_q - 1))(_attend)
    else:
        _attend()

    @pl.when(ik == k_steps - 1)
    def _store():
        l_final = l_ref[:, 0]
        l_safe = jnp.where(l_final == 0.0, 1.0, l_final)
        out_ref[0] = (acc_ref[:] / l_safe[:, None]).astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """q/k/v: [BH, T, D] -> [BH, T, D] (fold batch*heads before calling)."""
    bh, t, d = q.shape
    assert k.shape == v.shape == (bh, t, d)
    assert t % block_q == 0 and t % block_k == 0, (t, block_q, block_k)
    k_steps = t // block_k
    scale = 1.0 / d**0.5
    kernel = functools.partial(
        _flash_kernel,
        block_q=block_q,
        block_k=block_k,
        k_steps=k_steps,
        scale=scale,
        causal=causal,
    )
    return pl.pallas_call(
        kernel,
        grid=(bh, t // block_q, k_steps),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max m
            pltpu.VMEM((block_q, 128), jnp.float32),  # running denom l
            pltpu.VMEM((block_q, d), jnp.float32),  # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
