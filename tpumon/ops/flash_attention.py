"""Fused causal flash attention as a Pallas TPU kernel.

The single-chip hot op of the loadgen transformer (its multi-chip
counterpart is tpumon.loadgen.ring_attention, which rotates K/V blocks
across chips; this kernel is what each chip would run on its local
blocks). Standard flash-attention schedule:

  grid = (batch*heads, Tq/block_q, Tk/block_k), K innermost ("arbitrary")
  so each (bh, iq) output tile keeps its online-softmax state — running
  max m, denominator l, and the f32 accumulator — in VMEM scratch across
  K steps; HBM sees each block exactly once.

TPU specifics: m/l live in (block_q, 128) VMEM tiles (min lane width)
with the statistic broadcast across lanes; causal block skipping uses
pl.when so fully-masked K blocks cost no MXU work; the in-block mask is
built from broadcasted iotas (2D, as TPU requires).

``pl.when`` skips the MXU work above the diagonal but NOT the
pipeline's K/V DMA or the grid step itself — the rectangular causal
grid still pays ~2x the triangle's traffic and iterations. r05 adds
``flash_attention_tri``: the grid enumerates ONLY the lower-triangle
(q block, k block) pairs, with the pair -> (iq, ik) decoding shipped
as scalar-prefetched index arrays (pltpu.PrefetchScalarGridSpec) that
the BlockSpec index maps read — T^2/2 work AND T^2/2 DMA. The
training schedule (loadgen.model attention="flash") uses the triangle
kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def online_softmax_update(s, v, m_ref, l_ref, acc_ref):
    """One flash-attention block update, shared by the flash and paged
    kernels so their numerics stay provably identical.

    s: [rows, cols] f32 scores (already scaled/masked); v: [cols, d]
    values; m/l: (rows, 128) VMEM stat tiles (statistic broadcast
    across lanes — min TPU lane width); acc: (rows, d) f32 accumulator.
    """
    m_prev = m_ref[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
    p = jnp.exp(s - m_new[:, None])  # masked entries underflow to 0
    corr = jnp.exp(m_prev - m_new)
    l_ref[:] = (l_ref[:, 0] * corr + jnp.sum(p, axis=1))[
        :, None] + jnp.zeros_like(l_ref)
    m_ref[:] = m_new[:, None] + jnp.zeros_like(m_ref)
    acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _flash_kernel(
    q_ref, k_ref, v_ref, out_ref, m_ref, l_ref, acc_ref,
    *, block_q: int, block_k: int, k_steps: int, scale: float, causal: bool,
):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _attend():
        q = q_ref[0]  # [block_q, d]
        k = k_ref[0]  # [block_k, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        online_softmax_update(s, v_ref[0], m_ref, l_ref, acc_ref)

    if causal:
        # Skip K blocks entirely above the diagonal: with equal block
        # sizes, block (iq, ik) is all-masked iff ik > iq.
        pl.when(ik * block_k <= iq * block_q + (block_q - 1))(_attend)
    else:
        _attend()

    @pl.when(ik == k_steps - 1)
    def _store():
        l_final = l_ref[:, 0]
        l_safe = jnp.where(l_final == 0.0, 1.0, l_final)
        out_ref[0] = (acc_ref[:] / l_safe[:, None]).astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """q/k/v: [BH, T, D] -> [BH, T, D] (fold batch*heads before calling)."""
    bh, t, d = q.shape
    assert k.shape == v.shape == (bh, t, d)
    assert t % block_q == 0 and t % block_k == 0, (t, block_q, block_k)
    k_steps = t // block_k
    scale = 1.0 / d**0.5
    kernel = functools.partial(
        _flash_kernel,
        block_q=block_q,
        block_k=block_k,
        k_steps=k_steps,
        scale=scale,
        causal=causal,
    )
    return pl.pallas_call(
        kernel,
        grid=(bh, t // block_q, k_steps),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max m
            pltpu.VMEM((block_q, 128), jnp.float32),  # running denom l
            pltpu.VMEM((block_q, d), jnp.float32),  # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)


def _tri_masked_scores(q, k, qi, kj, block: int, scale: float):
    """Scaled, causally-masked scores for triangle pair (qi, kj) —
    the ONE implementation shared by the fwd kernel and both backward
    passes (same spirit as online_softmax_update: shared numerics are
    provably identical numerics)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [block, block]
    qpos = qi * block + jax.lax.broadcasted_iota(
        jnp.int32, (block, block), 0)
    kpos = kj * block + jax.lax.broadcasted_iota(
        jnp.int32, (block, block), 1)
    return jnp.where(qpos >= kpos, s, _NEG_INF)


def _flash_tri_kernel(
    qi_ref, kj_ref, q_ref, k_ref, v_ref, out_ref, lse_ref,
    m_ref, l_ref, acc_ref,
    *, block: int, scale: float,
):
    p = pl.program_id(1)
    qi = qi_ref[p]
    kj = kj_ref[p]

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Only the diagonal block needs the in-block causal mask, but the
    # where() is cheap relative to the dot and a data-independent mask
    # keeps the body branch-free.
    s = _tri_masked_scores(q_ref[0], k_ref[0], qi, kj, block, scale)
    online_softmax_update(s, v_ref[0], m_ref, l_ref, acc_ref)

    @pl.when(kj == qi)
    def _store():
        # The diagonal is each q row's LAST pair (row-major pair order),
        # so the row's online-softmax state is complete here.
        l_final = l_ref[:, 0]
        l_safe = jnp.where(l_final == 0.0, 1.0, l_final)
        out_ref[0] = (acc_ref[:] / l_safe[:, None]).astype(out_ref.dtype)
        # Per-row logsumexp of the scaled scores — the residual the
        # backward kernels rebuild P from (P = exp(s - lse)); rows with
        # an empty denominator keep lse = m (=-inf rows give P = 0).
        # The lse ref is the WHOLE [1, 1, T] row (a 2-D per-q-row block
        # would have a second-minor dim of 1, which the TPU lowering
        # rejects); each diagonal stores its block's slice.
        lse_ref[0, 0, pl.dslice(qi * block, block)] = (
            m_ref[:, 0] + jnp.log(l_safe))


def _tri_pairs(nb: int, order: str):
    """(qi_of, kj_of) prefetch arrays for the lower-triangle grid.

    order="row": (0,0) (1,0) (1,1) ... — each q row's pairs contiguous,
    diagonal last (fwd + dq accumulate per q row).
    order="col": (0,0) (1,0) (2,0) ... — each k column's pairs
    contiguous, bottom row last (dk/dv accumulate per k column).
    """
    if order == "row":
        pairs = [(i, j) for i in range(nb) for j in range(i + 1)]
    else:
        pairs = [(i, j) for j in range(nb) for i in range(j, nb)]
    qi_of = jnp.asarray([i for i, _ in pairs], jnp.int32)
    kj_of = jnp.asarray([j for _, j in pairs], jnp.int32)
    return qi_of, kj_of, len(pairs)


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def flash_attention_tri_fwd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block: int = 128,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Triangle-grid causal flash forward returning (out, lse).

    lse: [BH, T] float32 per-row logsumexp of the scaled scores — the
    residual flash_attention_tri_bwd rebuilds P from.
    """
    bh, t, d = q.shape
    assert k.shape == v.shape == (bh, t, d)
    assert t % block == 0, (t, block)
    nb = t // block
    qi_of, kj_of, n_pairs = _tri_pairs(nb, "row")
    kernel = functools.partial(
        _flash_tri_kernel, block=block, scale=1.0 / d**0.5)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # qi_of, kj_of
        grid=(bh, n_pairs),
        in_specs=[
            pl.BlockSpec((1, block, d),
                         lambda b, p, qi, kj: (b, qi[p], 0)),
            pl.BlockSpec((1, block, d),
                         lambda b, p, qi, kj: (b, kj[p], 0)),
            pl.BlockSpec((1, block, d),
                         lambda b, p, qi, kj: (b, kj[p], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, d),
                         lambda b, p, qi, kj: (b, qi[p], 0)),
            pl.BlockSpec((1, 1, t),
                         lambda b, p, qi, kj: (b, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, 128), jnp.float32),  # running max m
            pltpu.VMEM((block, 128), jnp.float32),  # running denom l
            pltpu.VMEM((block, d), jnp.float32),  # output accumulator
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, t), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qi_of, kj_of, q, k, v)
    return out, lse[:, 0]


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def flash_attention_tri(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Causal flash attention over a LOWER-TRIANGLE-ONLY grid.

    q/k/v: [BH, T, D] -> [BH, T, D], T % block == 0 (callers pad — the
    extra K rows sit above every real query's diagonal and mask out).
    grid = (BH, T/block * (T/block + 1) / 2): pair p decodes to
    (qi_of[p], kj_of[p]) via scalar-prefetched arrays read by the
    BlockSpec index maps, so blocks above the causal diagonal are never
    DMA'd at all (the rectangular kernel above skips their compute but
    still streams them). Equal q/k block size by construction — the
    diagonal pair is square. Forward-only view of
    flash_attention_tri_fwd; the differentiable training path is
    loadgen.model's custom-vjp (tri fwd + tri bwd kernels).
    """
    return flash_attention_tri_fwd(q, k, v, block=block,
                                   interpret=interpret)[0]


def _flash_tri_bwd_dq_kernel(
    qi_ref, kj_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dvec_ref,
    dq_ref, dq_acc,
    *, block: int, scale: float,
):
    p = pl.program_id(1)
    qi = qi_ref[p]
    kj = kj_ref[p]

    @pl.when(kj == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    k = k_ref[0]
    s = _tri_masked_scores(q_ref[0], k_ref[0], qi, kj, block, scale)
    lse_i = lse_ref[0, 0, pl.dslice(qi * block, block)]
    pmat = jnp.exp(s - lse_i[:, None])  # [block, block]
    dp = jax.lax.dot_general(
        do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [block, block] = dO @ V^T
    d_i = dvec_ref[0, 0, pl.dslice(qi * block, block)]
    ds = pmat * (dp - d_i[:, None]) * scale
    dq_acc[:] += jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(kj == qi)
    def _store():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_tri_bwd_dkv_kernel(
    qi_ref, kj_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dvec_ref,
    dk_ref, dv_ref, dk_acc, dv_acc,
    *, block: int, scale: float, nb: int,
):
    p = pl.program_id(1)
    qi = qi_ref[p]
    kj = kj_ref[p]

    @pl.when(qi == kj)
    def _init():
        # Column-major pair order: (kj, kj) is the column's FIRST pair.
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q = q_ref[0]
    s = _tri_masked_scores(q, k_ref[0], qi, kj, block, scale)
    lse_i = lse_ref[0, 0, pl.dslice(qi * block, block)]
    pmat = jnp.exp(s - lse_i[:, None])
    do = do_ref[0]
    # dV_j += P^T dO
    dv_acc[:] += jax.lax.dot_general(
        pmat.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    d_i = dvec_ref[0, 0, pl.dslice(qi * block, block)]
    dp = jax.lax.dot_general(
        do, v_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = pmat * (dp - d_i[:, None]) * scale
    # dK_j += dS^T Q
    dk_acc[:] += jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(qi == nb - 1)
    def _store():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def flash_attention_tri_bwd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    out: jax.Array,
    lse: jax.Array,
    dout: jax.Array,
    block: int = 128,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Backward of the triangle-grid causal flash attention.

    Two lower-triangle passes over the same pair set: a ROW-major pass
    accumulating dQ per q row, and a COLUMN-major pass accumulating
    dK/dV per k column. P is rebuilt from the forward's saved lse and
    D_i = rowsum(dO ∘ O) is precomputed once outside the kernels (both
    ride as resident [BH, 1, T] f32 rows — `out` itself is never
    streamed into the grid); both passes skip above-diagonal blocks
    entirely, like the forward.
    """
    bh, t, d = q.shape
    assert t % block == 0, (t, block)
    nb = t // block
    scale = 1.0 / d**0.5
    lse3 = lse.reshape(bh, 1, t)
    # D_i = rowsum(dO ∘ O) is constant per q row: precompute it ONCE
    # in plain jnp and ship it like lse (a resident [BH, 1, T] f32 row
    # per bh) instead of streaming the full `out` tensor into both
    # kernels and recomputing the rowsum at every pair (~nb^2/2 times).
    dvec = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                   axis=-1).reshape(bh, 1, t)

    def qrow(b, p, qi, kj):
        return (b, qi[p], 0)

    def kcol(b, p, qi, kj):
        return (b, kj[p], 0)

    def whole_row(b, p, qi, kj):
        return (b, 0, 0)

    in_specs = [
        pl.BlockSpec((1, block, d), qrow),      # q
        pl.BlockSpec((1, block, d), kcol),      # k
        pl.BlockSpec((1, block, d), kcol),      # v
        pl.BlockSpec((1, block, d), qrow),      # dout
        pl.BlockSpec((1, 1, t), whole_row),     # lse
        pl.BlockSpec((1, 1, t), whole_row),     # dvec
    ]
    operands = (q, k, v, dout, lse3, dvec)

    qi_r, kj_r, n_pairs = _tri_pairs(nb, "row")
    dq = pl.pallas_call(
        functools.partial(_flash_tri_bwd_dq_kernel, block=block,
                          scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, n_pairs),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, block, d), qrow),
            scratch_shapes=[
                pltpu.VMEM((block, d), jnp.float32),  # dq accumulator
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qi_r, kj_r, *operands)

    qi_c, kj_c, _ = _tri_pairs(nb, "col")
    dk, dv = pl.pallas_call(
        functools.partial(_flash_tri_bwd_dkv_kernel, block=block,
                          scale=scale, nb=nb),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, n_pairs),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, block, d), kcol),
                pl.BlockSpec((1, block, d), kcol),
            ],
            scratch_shapes=[
                pltpu.VMEM((block, d), jnp.float32),  # dk accumulator
                pltpu.VMEM((block, d), jnp.float32),  # dv accumulator
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qi_c, kj_c, *operands)
    return dq, dk, dv
