"""Tiled bf16 matmul Pallas kernel (the MXU burn hot op).

C[M,N] = A[M,K] @ B[K,N] with a (M/bm, N/bn, K/bk) grid: the K axis is the
innermost ("arbitrary") grid dimension so each (i, j) output tile stays
resident in a float32 VMEM scratch accumulator across K steps, written
back once on the last step — the canonical Pallas TPU matmul schedule
(double-buffered HBM→VMEM pipelining is handled by Mosaic from the
BlockSpecs).

Block defaults are MXU/VMEM-friendly and swept on hardware (r04, v5e,
4096³ bf16, slope-timed): (1024, 1024, 512) measured 172.8 TFLOP/s vs
XLA's 194.9 (0.89×) — the best of 13 candidates; r03's (512, 512, 512)
default measured 153 (0.79×), and every larger tiling (bk 1024+,
bm/bn 2048) fails Mosaic compilation on the ~16 MB VMEM budget
(A 2 MB + B 1 MB double-buffered + 4 MB f32 accumulator + 2 MB out ≈
12 MB). See BENCH_NOTES.md for why the remaining ~11% belongs to XLA's
native scheduler.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _matmul_kernel(a_ref, b_ref, out_ref, acc_ref, *, k_steps: int):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(
        a_ref[:], b_ref[:], preferred_element_type=jnp.float32
    )

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _store():
        out_ref[:] = acc_ref[:].astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_m", "block_n", "block_k", "interpret")
)
def matmul(
    a: jax.Array,
    b: jax.Array,
    block_m: int = 1024,
    block_n: int = 1024,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """bf16 matmul via Pallas; shapes must divide the block sizes."""
    m, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    assert m % block_m == 0 and n % block_n == 0 and k % block_k == 0, (
        f"shapes {(m, k, n)} must divide blocks {(block_m, block_k, block_n)}"
    )
    k_steps = k // block_k
    return pl.pallas_call(
        functools.partial(_matmul_kernel, k_steps=k_steps),
        grid=(m // block_m, n // block_n, k_steps),
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((block_k, block_n), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), a.dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(a, b)
