"""Int8 weight-only matmul Pallas kernel.

C[M,N] = A[M,K] @ (Q[K,N].astype * scale[N]) — the serving-engine hot op
when weights are quantized (tpumon.loadgen.quant): activations stay
bf16/f32, weights stream from HBM as int8 and are widened in VMEM, and
the per-output-channel scale is applied ONCE to the f32 accumulator at
store time (scale depends only on N, so it commutes past the K sum).
That keeps HBM traffic at 1 byte/weight — the whole point of int8 on a
bandwidth-bound decode — while the MXU still sees its preferred wide
dtype.

Same schedule as tpumon.ops.matmul: (M/bm, N/bn, K/bk) grid, K
innermost/"arbitrary", f32 VMEM scratch accumulator written back on the
last K step. ``quantized_matmul`` falls back to the fused XLA path for
shapes that don't tile (tiny decode batches), so callers can use it
unconditionally.

Measured on v5e (4096³, slope-timed, r04 sweep): (1024, 1024, 512)
blocks are the best tiling at 177.6 TOP/s — ~15% over r03's 512³
default (154.4) — hence the defaults; sub-512 M/N tiles lose badly
(sub-MXU-height), larger ones overflow VMEM. XLA's fused dequant path
remains at or slightly above this kernel (r02 slope timing; r01's
"3.4×" claim was a timing artifact — BENCH_NOTES.md), so the serving
engine streams quantized weights through plain ``x @ q.astype(dt)``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _q_matmul_kernel(a_ref, q_ref, s_ref, out_ref, acc_ref, *, k_steps: int):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(
        a_ref[:], q_ref[:].astype(a_ref.dtype), preferred_element_type=jnp.float32
    )

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _store():
        # scale[1, bn] broadcasts over the M rows of the accumulator.
        out_ref[:] = (acc_ref[:] * s_ref[:].astype(jnp.float32)).astype(
            out_ref.dtype
        )


@functools.partial(
    jax.jit, static_argnames=("block_m", "block_n", "block_k", "interpret")
)
def quantized_matmul_pallas(
    a: jax.Array,
    q: jax.Array,
    scale: jax.Array,
    block_m: int = 1024,
    block_n: int = 1024,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """A[M,K] @ dequant(Q[K,N], scale[N]); shapes must divide the blocks."""
    m, k = a.shape
    k2, n = q.shape
    assert k == k2 and scale.shape == (n,), (a.shape, q.shape, scale.shape)
    assert q.dtype == jnp.int8, q.dtype
    assert m % block_m == 0 and n % block_n == 0 and k % block_k == 0, (
        f"shapes {(m, k, n)} must divide blocks {(block_m, block_k, block_n)}"
    )
    k_steps = k // block_k
    return pl.pallas_call(
        functools.partial(_q_matmul_kernel, k_steps=k_steps),
        grid=(m // block_m, n // block_n, k_steps),
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((block_k, block_n), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, block_n), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), a.dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(a, q, scale.reshape(1, n))


def quantized_matmul(
    a: jax.Array,
    q: jax.Array,
    scale: jax.Array,
    block_m: int = 1024,
    block_n: int = 1024,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Pallas int8 matmul when the shapes tile, fused XLA path otherwise
    (decode-sized M is far below a useful MXU tile).

    Perf note (slope-timed r02, BENCH_NOTES.md): XLA's fused-dequant
    matmul measures at or slightly above this kernel on v5e, so the
    serving engine streams quantized weights through plain
    ``x @ q.astype(dt)`` and this entry point exists for explicit
    control of the tiling/dequant schedule (and as the tested Pallas
    building block the paged/flash kernels share patterns with), not
    as a speedup."""
    m, k = a.shape
    n = q.shape[1]
    if m % block_m == 0 and n % block_n == 0 and k % block_k == 0:
        return quantized_matmul_pallas(
            a, q, scale,
            block_m=block_m, block_n=block_n, block_k=block_k,
            interpret=interpret,
        )
    return a @ (q.astype(a.dtype) * scale.astype(a.dtype))
