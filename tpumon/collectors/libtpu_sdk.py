"""libtpu in-process monitoring SDK source (``libtpu.sdk.tpumonitoring``).

Second real counter path next to the gRPC metrics service
(tpumon.collectors.libtpu_grpc). Newer libtpu builds ship an in-process
SDK that exposes strictly more than the gRPC service's three gauges —
probed on real hardware 2026-07-31 (see PROBE_libtpu.md at the repo
root for the committed probe log):

    tensorcore_util, ici_link_health, tpu_throttle_score, duty_cycle_pct,
    buffer_transfer_latency, collective_e2e_latency, hbm_capacity_total,
    hbm_capacity_usage, hlo_execution_timing, hlo_queue_size, tcp_min_rtt,
    tcp_delivery_rate, host_to_device_transfer_latency,
    device_to_host_transfer_latency

``ici_link_health`` is the TPU-native communication-observability signal
SURVEY §5.8 keys the north star on (the analogue of the reference's DCGM
series, monitor_server.js:128-134): per-ICI-link health scored 0-10
(0 healthy, 1-5 transient, 6-9 persistent minor, 10 unusable).
``tpu_throttle_score`` (0-10 = throttled by 0-100%) stands in for the
thermal signal the platform does not export directly (no temperature
metric exists in the SDK list, no hwmon node on TPU VMs — PROBE_libtpu.md).

The SDK returns every metric as a list of *strings* whose grammar is
only specified by each metric's description. All parsing lives in pure
module-level functions so golden tests can pin the documented formats
without a TPU (tests/test_libtpu_sdk.py).
"""

from __future__ import annotations

import asyncio
import re
from dataclasses import dataclass, field

# Metric names as listed by list_supported_metrics() on real hardware.
METRIC_DUTY = "duty_cycle_pct"
METRIC_TC_UTIL = "tensorcore_util"
METRIC_HBM_USAGE = "hbm_capacity_usage"
METRIC_HBM_TOTAL = "hbm_capacity_total"
METRIC_ICI_HEALTH = "ici_link_health"
METRIC_THROTTLE = "tpu_throttle_score"
METRIC_HLO_QUEUE = "hlo_queue_size"
METRIC_HLO_TIMING = "hlo_execution_timing"
METRIC_BUFFER_LATENCY = "buffer_transfer_latency"
METRIC_COLLECTIVE_LATENCY = "collective_e2e_latency"

# Slice-level percentile metrics surfaced verbatim under /api/accel/metrics
# "runtime" -> each parses as {label: {mean,p50,p90,p95,p999}}.
PERCENTILE_METRICS = (
    METRIC_BUFFER_LATENCY,
    METRIC_COLLECTIVE_LATENCY,
    METRIC_HLO_TIMING,
    "host_to_device_transfer_latency",
    "device_to_host_transfer_latency",
)


# ---------------------------------------------------------------------------
# Pure parsers for the SDK's stringly-typed payloads. Each grammar comes
# from the metric's own description() (captured in PROBE_libtpu.md).
# All tolerate junk entries by skipping them — a monitor must not crash on
# a runtime that evolves its exposition.
# ---------------------------------------------------------------------------


def parse_float_list(data: list[str]) -> dict[int, float]:
    """``["0.00", "20.00", ...]`` -> {index: value}.

    Grammar of duty_cycle_pct / tensorcore_util / tcp_* metrics: one
    bare decimal per device, index-ordered.
    """
    out: dict[int, float] = {}
    for i, s in enumerate(data):
        try:
            out[i] = float(str(s).strip().rstrip("%"))
        except ValueError:
            continue
    return out


def parse_int_list(data: list[str]) -> dict[int, int]:
    """``["33550229504", ...]`` -> {index: value} (hbm_capacity_*)."""
    out: dict[int, int] = {}
    for i, s in enumerate(data):
        try:
            out[i] = int(float(str(s).strip()))
        except ValueError:
            continue
    return out


@dataclass(frozen=True)
class IciLink:
    """One ICI link's health reading.

    Location grammar (from the metric description):
    ``tray1.chip3.ici0.int: 0`` -> tray 1, chip 3, port 0, scope "int",
    score 0. Score scale: 0 healthy / 1-5 transient / 6-9 persistent
    minor / 10 unusable.
    """

    location: str
    chip: int | None
    port: int | None
    score: int


def parse_ici_link_health(data: list[str]) -> list[IciLink]:
    links: list[IciLink] = []
    for entry in data:
        loc, sep, score_s = str(entry).rpartition(":")
        if not sep:
            continue
        try:
            score = int(float(score_s.strip()))
        except ValueError:
            continue
        loc = loc.strip().strip("'\"")
        chip_m = re.search(r"chip(\d+)", loc)
        port_m = re.search(r"ici(\d+)", loc)
        links.append(
            IciLink(
                location=loc,
                chip=int(chip_m.group(1)) if chip_m else None,
                port=int(port_m.group(1)) if port_m else None,
                score=score,
            )
        )
    return links


def ici_health_by_chip(links: list[IciLink]) -> dict[int, int]:
    """Worst (max) link score per chip; links with unknown chip -> key -1."""
    out: dict[int, int] = {}
    for ln in links:
        key = ln.chip if ln.chip is not None else -1
        out[key] = max(out.get(key, 0), ln.score)
    return out


def parse_throttle_scores(data: list[str]) -> dict[int, int]:
    """``["0-0", "1-1", ...]`` -> {chip_id: score} (0=none .. 10=100%)."""
    out: dict[int, int] = {}
    for entry in data:
        left, sep, right = str(entry).strip().strip("'\"").partition("-")
        if not sep:
            continue
        try:
            out[int(left)] = int(right)
        except ValueError:
            continue
    return out


def parse_labeled_percentiles(data: list[str]) -> dict[str, dict[str, float]]:
    """``["8MB+, 100.00, 200.00, 300.00, 400.00, 500.00", ...]`` ->
    {label: {mean,p50,p90,p95,p999}}. Shared by the buffer/collective/HLO
    latency metrics; the label is everything before the first comma
    (e.g. "2MB+-ALL_REDUCE", "tensorcore_0")."""
    keys = ("mean", "p50", "p90", "p95", "p999")
    out: dict[str, dict[str, float]] = {}
    for entry in data:
        parts = [p.strip() for p in str(entry).strip().strip("[]'\"").split(",")]
        if len(parts) < 2:
            continue
        label, vals = parts[0], parts[1:]
        try:
            floats = [float(v) for v in vals]
        except ValueError:
            continue
        out[label] = dict(zip(keys, floats))
    return out


def parse_queue_sizes(data: list[str]) -> dict[str, int]:
    """``["tensorcore_0: 0", "tensorcore_1: 10", ...]`` -> {core: size}."""
    out: dict[str, int] = {}
    for entry in data:
        left, sep, right = str(entry).strip().strip("'\"").partition(":")
        if not sep:
            continue
        try:
            out[left.strip()] = int(float(right))
        except ValueError:
            continue
    return out


# ---------------------------------------------------------------------------
# Snapshot source
# ---------------------------------------------------------------------------


@dataclass
class SdkSnapshot:
    """Per-chip maps (index-keyed, merged into ChipSample) + slice extras."""

    duty_pct: dict[int, float] = field(default_factory=dict)
    hbm_used: dict[int, int] = field(default_factory=dict)
    hbm_total: dict[int, int] = field(default_factory=dict)
    ici_health: dict[int, int] = field(default_factory=dict)  # worst per chip
    ici_links: list[IciLink] = field(default_factory=list)
    throttle: dict[int, int] = field(default_factory=dict)
    extras: dict[str, object] = field(default_factory=dict)  # slice-level

    def empty(self) -> bool:
        return not (
            self.duty_pct
            or self.hbm_used
            or self.hbm_total
            or self.ici_health
            or self.throttle
        )


class LibtpuSdkSource:
    """Reads ``libtpu.sdk.tpumonitoring`` off-thread.

    ``snapshot()`` returns None when the SDK is missing or (as on
    axon-tunneled dev chips, PROBE_libtpu.md) present but answering every
    metric with ``[]`` — callers treat None exactly like an absent gRPC
    service and fall through to the next counter source.
    """

    def __init__(self) -> None:
        self._mod = None
        self._import_failed = False
        self._supported: list[str] | None = None
        #: Why the source is dark (validate.py provenance).
        self.last_error: str | None = None

    def _api(self):
        if self._mod is None and not self._import_failed:
            try:
                from libtpu.sdk import tpumonitoring  # type: ignore

                self._mod = tpumonitoring
                self._supported = list(tpumonitoring.list_supported_metrics())
            except Exception as e:
                self._import_failed = True
                self.last_error = (
                    f"libtpu.sdk import: {type(e).__name__}: {str(e)[:160]}")
        return self._mod

    def _get(self, name: str) -> list[str]:
        mod = self._api()
        if mod is None or (self._supported and name not in self._supported):
            return []
        try:
            return list(mod.get_metric(name).data())
        except Exception as e:
            self.last_error = f"{name}: {type(e).__name__}: {str(e)[:160]}"
            return []

    def _snapshot_blocking(self) -> SdkSnapshot | None:
        if self._api() is None:
            return None
        # Fresh provenance per attempt: last_error must describe THIS
        # snapshot, not a transient failure from hours ago (the import
        # error above persists naturally — _api() won't retry).
        self.last_error = None
        snap = SdkSnapshot()
        snap.duty_pct = parse_float_list(self._get(METRIC_DUTY))
        if not snap.duty_pct:
            # Per-core fallback; on single-core-per-chip parts (v5e/v6e)
            # the index mapping is 1:1.
            snap.duty_pct = parse_float_list(self._get(METRIC_TC_UTIL))
        snap.hbm_used = parse_int_list(self._get(METRIC_HBM_USAGE))
        snap.hbm_total = parse_int_list(self._get(METRIC_HBM_TOTAL))
        snap.ici_links = parse_ici_link_health(self._get(METRIC_ICI_HEALTH))
        snap.ici_health = ici_health_by_chip(snap.ici_links)
        snap.throttle = parse_throttle_scores(self._get(METRIC_THROTTLE))
        queue = parse_queue_sizes(self._get(METRIC_HLO_QUEUE))
        if queue:
            snap.extras["hlo_queue_size"] = queue
        for name in PERCENTILE_METRICS:
            pct = parse_labeled_percentiles(self._get(name))
            if pct:
                snap.extras[name] = pct
        if snap.empty():
            if self.last_error is None:
                sup = len(self._supported or [])
                self.last_error = (
                    f"sdk imported ({sup} supported metrics) but every "
                    "queried family answered empty")
            return None
        return snap

    async def snapshot(self) -> SdkSnapshot | None:
        return await asyncio.to_thread(self._snapshot_blocking)
