"""Chaos-injection wrapper collector.

A monitor's degraded modes are claims until something exercises them
against the *live* server — the reference had no way to make kubectl
hang or nvidia-smi lie on demand, so its failure handling shipped
untested (SURVEY §7). ``ChaosCollector`` wraps any real collector and
injects configurable faults:

  hang     collect() never returns (sleeps far past any deadline) —
           exercises the resilience deadline + orphan reaping
  err      collect() raises — exercises degraded Samples + the breaker
  slow     fixed added latency (param = milliseconds, always applied)
  corrupt  the real Sample's payload is truncated / has keys dropped —
           exercises partial-payload tolerance downstream
  flap     a two-state Markov toggle between healthy and erroring
           (param = per-collect switch probability) — exercises the
           breaker's open → half-open → closed lifecycle
  partition  blackholes a federation LINK (param = per-frame drop
           probability; 1.0 = total blackhole): frames are consumed
           and silently dropped while the socket stays open, so the
           remote side sees *silence* — dark marking and lease expiry
           — rather than a clean disconnect. Targets the link sources
           ``uplink`` (federation push stream) and ``leader`` (root HA
           heartbeat, tpumon.leader), not a collector.

Spec grammar (config key ``chaos`` / CLI ``--chaos``), comma-separated
``mode:source:param`` clauses::

    --chaos hang:accel:0.1,err:k8s:0.3,slow:host:200,flap:serving:0.5
    --chaos partition:uplink:1.0,partition:leader:1.0

Probabilistic faults (hang/err/corrupt) roll an injected seeded RNG per
collect, so soak tests are reproducible. Faults are mutable at runtime
(``set_faults`` / clearing the list) so tests lift them mid-run and
assert recovery.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field

from tpumon.collectors import Collector, Sample

FAULT_MODES = ("hang", "err", "slow", "corrupt", "flap", "partition")

# Link (non-collector) chaos targets: `partition` applies to these, and
# only `partition` does — app.build routes their faults to the
# FederationUplink / LeaderLease instead of a ChaosCollector wrap.
LINK_SOURCES = ("uplink", "leader")

# How long a "hang" sleeps: effectively forever relative to any sane
# deadline, but finite so an un-deadlined test can't wedge the suite.
HANG_S = 3600.0


class ChaosError(Exception):
    """The injected failure (distinguishable from real collector errors
    in degraded Samples: ``ChaosError: injected error``)."""


@dataclass
class Fault:
    mode: str  # one of FAULT_MODES
    param: float  # probability (hang/err/corrupt/flap) or ms (slow)

    def __post_init__(self) -> None:
        if self.mode not in FAULT_MODES:
            raise ValueError(
                f"unknown chaos mode {self.mode!r}; known: {FAULT_MODES}"
            )
        if self.param < 0:
            raise ValueError(f"chaos {self.mode}: negative param {self.param}")
        if self.mode != "slow" and self.param > 1:
            raise ValueError(
                f"chaos {self.mode}: param is a probability, got {self.param}"
            )


def parse_chaos_spec(spec: str) -> dict[str, list[Fault]]:
    """``"hang:accel:0.1,err:k8s:0.3"`` -> {"accel": [Fault(hang, .1)],
    "k8s": [Fault(err, .3)]}. Raises ValueError on malformed clauses so
    a typo'd --chaos fails at startup, not silently no-ops."""
    out: dict[str, list[Fault]] = {}
    for clause in (c.strip() for c in spec.split(",") if c.strip()):
        parts = clause.split(":")
        if len(parts) != 3:
            raise ValueError(
                f"bad chaos clause {clause!r} (want mode:source:param)"
            )
        mode, source, param = parts
        try:
            value = float(param)
        except ValueError:
            raise ValueError(f"bad chaos param {param!r} in {clause!r}")
        out.setdefault(source, []).append(Fault(mode=mode, param=value))
    return out


def split_link_faults(spec: str) -> tuple[dict[str, list[Fault]], dict[str, list[Fault]]]:
    """Partition a parsed --chaos spec into (collector faults, link
    faults). Link sources (``uplink``, ``leader``) accept only the
    ``partition`` mode, and ``partition`` only applies to link sources
    — either mismatch raises, so a typo'd clause fails at startup
    instead of silently injecting nothing."""
    by_source = parse_chaos_spec(spec)
    coll: dict[str, list[Fault]] = {}
    link: dict[str, list[Fault]] = {}
    for source, faults in by_source.items():
        if source in LINK_SOURCES:
            bad = [f.mode for f in faults if f.mode != "partition"]
            if bad:
                raise ValueError(
                    f"chaos {bad[0]!r} cannot target link source "
                    f"{source!r} (links take only 'partition')"
                )
            link[source] = faults
        else:
            bad = [f.mode for f in faults if f.mode == "partition"]
            if bad:
                raise ValueError(
                    f"chaos 'partition' targets a federation link "
                    f"({', '.join(LINK_SOURCES)}), not collector "
                    f"{source!r}"
                )
            coll[source] = faults
    return coll, link


def _corrupt(data, rng: random.Random):
    """Mangle a payload the way real half-broken sources do: drop items
    from lists, drop keys from dicts — never invent values. Downstream
    must treat what remains as truth and what's missing as absent."""
    if isinstance(data, list) and data:
        keep = [d for d in data if rng.random() < 0.5]
        return [
            _corrupt(d, rng) if isinstance(d, dict) else d for d in keep
        ]
    if isinstance(data, dict) and data:
        dropped = rng.choice(sorted(data, key=str))
        return {k: v for k, v in data.items() if k != dropped}
    return data


@dataclass
class ChaosCollector:
    """Wraps ``inner`` and injects the listed faults into its collects."""

    inner: Collector
    faults: list[Fault] = field(default_factory=list)
    seed: int | None = None
    rng: random.Random = field(default=None)  # injectable for tests
    # Event journal (tpumon.events): injections are recorded so a chaos
    # soak's /api/events replay shows WHAT was injected next to the
    # degraded samples it caused. Wired by the sampler (set_journal).
    journal: object = field(default=None, repr=False)
    # flap state: True while the toggle is in its erroring phase
    _flap_down: bool = field(default=False, repr=False)

    def __post_init__(self) -> None:
        if self.rng is None:
            self.rng = random.Random(self.seed)

    @property
    def name(self) -> str:
        return self.inner.name

    def set_journal(self, journal) -> None:
        self.journal = journal
        inner_set = getattr(self.inner, "set_journal", None)
        if inner_set is not None:  # chaos may wrap a peer federation
            inner_set(journal)

    def stop(self) -> None:
        """Forward owner-stop to the wrapped collector (the k8s watch
        thread must stop even when its collector is chaos-wrapped)."""
        inner_stop = getattr(self.inner, "stop", None)
        if inner_stop is not None:
            inner_stop()

    def _note(self, msg: str, **attrs) -> None:
        if self.journal is not None:
            self.journal.record("chaos", "minor", self.name, msg, **attrs)

    def set_faults(self, faults: list[Fault]) -> None:
        """Replace the active fault set (tests lift faults mid-soak)."""
        self.faults = list(faults)

    def _fault(self, mode: str) -> Fault | None:
        for f in self.faults:
            if f.mode == mode:
                return f
        return None

    async def collect(self) -> Sample:
        f = self._fault("flap")
        if f is not None:
            if self.rng.random() < f.param:
                self._flap_down = not self._flap_down
                # Journal only the TRANSITION: a flap held down for 30
                # collects is one event, not 30.
                self._note(
                    f"flap toggled {'down' if self._flap_down else 'up'}",
                    mode="flap",
                )
            if self._flap_down:
                raise ChaosError("injected flap error")
        f = self._fault("hang")
        if f is not None and self.rng.random() < f.param:
            self._note("injected hang (collect will ride out its deadline)",
                       mode="hang")
            await asyncio.sleep(HANG_S)
            raise ChaosError("injected hang expired")  # un-deadlined runs
        f = self._fault("err")
        if f is not None and self.rng.random() < f.param:
            self._note("injected collect error", mode="err")
            raise ChaosError("injected error")
        f = self._fault("slow")
        if f is not None:
            await asyncio.sleep(f.param / 1e3)
        s = await self.inner.collect()
        f = self._fault("corrupt")
        if f is not None and self.rng.random() < f.param:
            self._note("injected payload corruption", mode="corrupt")
            s = Sample(
                source=s.source,
                ok=s.ok,
                data=_corrupt(s.data, self.rng),
                error=s.error,
                ts=s.ts,
                latency_ms=s.latency_ms,
                notes=[*s.notes, "chaos: payload corrupted"],
            )
        return s


def wrap_collectors(
    collectors: dict[str, Collector | None],
    spec: str | dict[str, list[Fault]],
    seed: int | None = None,
) -> dict[str, Collector | None]:
    """Wrap each named collector that the spec targets; unknown source
    names raise (a typo'd --chaos must not silently test nothing).
    ``spec`` is the raw grammar string or an already-split fault dict
    (app.build splits link faults off first — split_link_faults)."""
    faults_by_source = (
        dict(spec) if isinstance(spec, dict) else parse_chaos_spec(spec)
    )
    unknown = set(faults_by_source) - set(collectors)
    if unknown:
        raise ValueError(
            f"chaos spec targets unknown source(s) {sorted(unknown)}; "
            f"known: {sorted(collectors)}"
        )
    disabled = sorted(
        n for n in faults_by_source if collectors.get(n) is None
    )
    if disabled:
        raise ValueError(
            f"chaos spec targets disabled source(s) {disabled} — the "
            f"collector isn't configured, so the fault would inject "
            f"nothing"
        )
    out: dict[str, Collector | None] = {}
    for name, c in collectors.items():
        faults = faults_by_source.get(name)
        if c is not None and faults:
            out[name] = ChaosCollector(inner=c, faults=faults, seed=seed)
        else:
            out[name] = c
    return out
