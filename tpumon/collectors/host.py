"""Host metrics collector: CPU, memory, disk.

Reference parity (monitor_server.js:66-81 ``getHostMetrics``):
- CPU: the reference divides 1-min loadavg by a hardcoded 8 cores
  (monitor_server.js:76). tpumon reports both a real utilization percent
  computed from /proc/stat jiffy deltas between samples *and* the loadavg,
  with the core count auto-detected (SURVEY §5.6).
- Memory: /proc/meminfo MemTotal/MemAvailable (monitor_server.js:69-71).
- Disk: the reference shells out ``df -B1 /`` (monitor_server.js:72);
  tpumon uses os.statvfs directly — no subprocess.

Shape of the returned data matches the reference contract (SURVEY §2.3
/api/host/metrics) with numbers, not stringified floats: the reference
returns percent fields as toFixed(1) strings (monitor_server.js:76-78), a
quirk SURVEY §2.1 says to fix.

Fast path: when the native shim (tpumon/native/hostmon.cpp) is built, the
raw /proc reads + parses happen in C++ in a single call; the Python layer
only computes deltas and percentages. Each sub-source still degrades
independently, and the pure-Python reader remains the fallback.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from tpumon.collectors import Sample


def _read_proc_stat_cpu(text: str) -> tuple[int, int]:
    """Return (busy_jiffies, total_jiffies) from the aggregate 'cpu ' line."""
    for line in text.splitlines():
        if line.startswith("cpu "):
            parts = [int(x) for x in line.split()[1:]]
            # user nice system idle iowait irq softirq steal [guest guest_nice]
            idle = parts[3] + (parts[4] if len(parts) > 4 else 0)
            total = sum(parts[:8])
            return total - idle, total
    raise ValueError("no aggregate 'cpu' line in /proc/stat")


def parse_meminfo(text: str) -> dict[str, int]:
    """Parse /proc/meminfo into {key: bytes}."""
    out: dict[str, int] = {}
    for line in text.splitlines():
        if ":" not in line:
            continue
        key, _, rest = line.partition(":")
        fields = rest.split()
        if not fields:
            continue
        val = int(fields[0])
        if len(fields) > 1 and fields[1] == "kB":
            val *= 1024
        out[key.strip()] = val
    return out


def parse_net_dev(text: str) -> dict[str, tuple[int, int]]:
    """Parse /proc/net/dev into {iface: (rx_bytes, tx_bytes)}.

    The loopback interface is excluded: for a multi-host TPU deployment
    the NIC counters are the host's DCN-traffic proxy (SURVEY §5.8 —
    ICI within a slice, DCN across hosts), and lo traffic would swamp
    the signal with scrape-loop chatter."""
    out: dict[str, tuple[int, int]] = {}
    for line in text.splitlines():
        if ":" not in line:
            continue
        iface, _, rest = line.partition(":")
        iface = iface.strip()
        if iface == "lo":
            continue
        fields = rest.split()
        if len(fields) < 10:
            continue
        try:
            out[iface] = (int(fields[0]), int(fields[8]))
        except ValueError:
            continue
    return out


@dataclass
class HostCollector:
    name: str = "host"
    cpu_count: int = 0
    disk_mounts: tuple[str, ...] = ("/",)
    proc_root: str = "/proc"  # overridable for golden-input tests
    use_native: bool = True

    _last_cpu: tuple[int, int] | None = None
    _native: object = field(default=None, repr=False)
    native_active: bool = field(default=False, repr=False)

    def __post_init__(self) -> None:
        self.cpu_count = self.cpu_count or os.cpu_count() or 1
        if self.use_native:
            try:
                from tpumon.native import make_reader

                self._native = make_reader(
                    proc_root=self.proc_root, mount=self.disk_mounts[0]
                )
            except Exception:
                self._native = None
            self.native_active = self._native is not None

    # -- sub-collectors; each degrades independently (monitor_server.js:80) --

    def _cpu_pct_from_jiffies(self, busy: int, total: int, load1: float) -> float:
        pct = None
        if self._last_cpu is not None:
            dbusy = busy - self._last_cpu[0]
            dtotal = total - self._last_cpu[1]
            if dtotal > 0:
                pct = 100.0 * dbusy / dtotal
        self._last_cpu = (busy, total)
        if pct is None:
            # First sample: fall back to the reference's load-based estimate,
            # but with the detected core count (monitor_server.js:76).
            pct = min(100.0, 100.0 * load1 / self.cpu_count)
        return pct

    def _cpu(self, ns: dict | None) -> dict:
        if ns is not None and ns["ok_cpu"]:
            load1 = ns["load1"]
            busy, total = ns["cpu_busy_jiffies"], ns["cpu_total_jiffies"]
        else:
            with open(os.path.join(self.proc_root, "loadavg")) as f:
                load1 = float(f.read().split()[0])
            with open(os.path.join(self.proc_root, "stat")) as f:
                busy, total = _read_proc_stat_cpu(f.read())
        return {
            "load_1min": load1,
            "cores": self.cpu_count,
            "percent": round(self._cpu_pct_from_jiffies(busy, total, load1), 1),
        }

    def _memory(self, ns: dict | None) -> dict:
        if ns is not None and ns["ok_mem"]:
            total, avail = ns["mem_total"], ns["mem_available"]
        else:
            with open(os.path.join(self.proc_root, "meminfo")) as f:
                mi = parse_meminfo(f.read())
            total = mi["MemTotal"]
            avail = mi.get("MemAvailable", mi.get("MemFree", 0))
        used = total - avail
        return {
            "total": total,
            "used": used,
            "available": avail,
            "percent": round(100.0 * used / total, 1) if total else None,
        }

    def _disk_one(self, mount: str) -> dict:
        st = os.statvfs(mount)
        total = st.f_blocks * st.f_frsize
        used = total - st.f_bfree * st.f_frsize
        return {
            "total": total,
            "used": used,
            "percent": round(100.0 * used / total, 1) if total else None,
        }

    def _disk(self, ns: dict | None) -> dict:
        mounts: dict[str, dict] = {}
        if ns is not None and ns["ok_disk"]:
            total, used = ns["disk_total"], ns["disk_used"]
            mounts[self.disk_mounts[0]] = {
                "total": total,
                "used": used,
                "percent": round(100.0 * used / total, 1) if total else None,
            }
            rest = self.disk_mounts[1:]
        else:
            rest = self.disk_mounts
        for mount in rest:
            mounts[mount] = self._disk_one(mount)
        primary = mounts[self.disk_mounts[0]]
        return {**primary, "mounts": mounts}

    def _net(self, ns: dict | None) -> dict:
        with open(os.path.join(self.proc_root, "net", "dev")) as f:
            ifaces = parse_net_dev(f.read())
        return {
            "rx_bytes": sum(rx for rx, _ in ifaces.values()),
            "tx_bytes": sum(tx for _, tx in ifaces.values()),
            "interfaces": {
                name: {"rx_bytes": rx, "tx_bytes": tx}
                for name, (rx, tx) in sorted(ifaces.items())
            },
        }

    async def collect(self) -> Sample:
        ns = None
        if self._native is not None:
            try:
                ns = self._native.sample()
            except Exception:
                ns = None
        data: dict = {}
        errors: list[str] = []
        for key, fn in (("cpu", self._cpu), ("memory", self._memory),
                        ("disk", self._disk), ("net", self._net)):
            try:
                data[key] = fn(ns)
            except Exception as e:
                data[key] = {}
                errors.append(f"{key}: {type(e).__name__}: {e}")
        return Sample(
            source=self.name,
            ok=not errors,
            data=data,
            error="; ".join(errors) or None,
        )
