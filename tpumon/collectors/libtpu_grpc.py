"""libtpu runtime-metrics gRPC client.

This is the TPU-native replacement for the reference's accelerator data
path — ``execSync('nvidia-smi --query-gpu=...')`` + CSV parsing
(monitor_server.js:83-95) and the out-of-tree DCGM exporter
(README.md:135). On Cloud TPU VMs, libtpu serves runtime metrics over
gRPC on localhost (default port 8431, the same service the ``tpu-info``
CLI reads): per-device HBM usage/capacity and TensorCore duty cycle.

We speak the wire protocol directly via tpumon.protowire — the request is
a single-string message and responses are decoded structurally — so no
generated proto stubs are needed and minor proto evolution doesn't break
us. The client degrades to ``available=False`` when the service is absent
(e.g. non-TPU hosts, or tunneled single-chip dev environments), in which
case the accel collector still reports chip identity from JAX with
metric fields None (SURVEY §7: honest degraded modes).

Metric names as exposed by libtpu (verified against tpu-info's public
metric list; re-verify on hardware per SURVEY §5.8):
  tpu.runtime.hbm.memory.usage.bytes
  tpu.runtime.hbm.memory.total.bytes
  tpu.runtime.tensorcore.dutycycle.percent
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

from tpumon import protowire as pw

METRIC_HBM_USAGE = "tpu.runtime.hbm.memory.usage.bytes"
METRIC_HBM_TOTAL = "tpu.runtime.hbm.memory.total.bytes"
METRIC_DUTY_CYCLE = "tpu.runtime.tensorcore.dutycycle.percent"
METRIC_UPTIME = "tpu.runtime.uptime"

GRPC_METHOD = "/tpu.monitoring.runtime.MetricService/GetRuntimeMetric"
DEFAULT_ADDR = "localhost:8431"


def encode_metric_request(metric_name: str) -> bytes:
    """MetricRequest { string metric_name = 1; }"""
    return pw.encode_string(1, metric_name)


def extract_gauges(response: bytes) -> dict[int, float]:
    """Structurally extract {device_index: value} from a MetricResponse.

    The response nests TPUMetric -> repeated Metric { Attribute, Gauge }.
    Rather than depending on exact field numbers below the top level, we
    walk the decoded tree: a per-device entry is a Message that contains
    (a) an attribute submessage holding an int (the device index) and
    (b) a gauge submessage holding an int or double (the value).
    """
    msg = pw.decode_message(response)
    out: dict[int, float] = {}
    for f in msg.walk():
        if not isinstance(f.value, pw.Message):
            continue
        entry = f.value
        device_idx: int | None = None
        gauge_val: float | None = None
        for sub in entry.fields:
            if not isinstance(sub.value, pw.Message):
                continue
            ints = [
                g.value
                for g in sub.value.walk()
                if isinstance(g.value, int) and g.wire_type == pw.WT_VARINT
            ]
            doubles = [
                g.value for g in sub.value.walk() if isinstance(g.value, float)
            ]
            # Attribute submessage: holds the (small) device index.
            # Gauge submessage: holds the measured value (int64 or double).
            if doubles and gauge_val is None:
                gauge_val = doubles[0]
            elif ints:
                if device_idx is None and 0 <= ints[0] < 4096:
                    device_idx = ints[0]
                elif gauge_val is None:
                    gauge_val = float(ints[0])
        if device_idx is not None and gauge_val is not None:
            out[device_idx] = gauge_val
    return out


@dataclass
class LibtpuMetricsClient:
    addr: str = DEFAULT_ADDR
    timeout_s: float = 2.0
    _channel: object = field(default=None, repr=False)
    #: Why the last get_metric returned None (validate.py provenance).
    last_error: str | None = field(default=None, repr=False)

    def _get_channel(self):
        if self._channel is None:
            import grpc

            self._channel = grpc.aio.insecure_channel(self.addr)
        return self._channel

    async def get_metric(self, metric_name: str) -> dict[int, float] | None:
        """Fetch one metric for all local devices; None if unavailable."""
        try:
            import grpc

            channel = self._get_channel()
            call = channel.unary_unary(
                GRPC_METHOD,
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            resp: bytes = await asyncio.wait_for(
                call(encode_metric_request(metric_name)), timeout=self.timeout_s
            )
            return extract_gauges(resp)
        except Exception as e:
            self.last_error = f"{type(e).__name__}: {str(e)[:160]}"
            return None

    async def snapshot(self) -> dict[str, dict[int, float]] | None:
        """Fetch HBM usage/total and duty cycle; None if service absent."""
        results = await asyncio.gather(
            self.get_metric(METRIC_HBM_USAGE),
            self.get_metric(METRIC_HBM_TOTAL),
            self.get_metric(METRIC_DUTY_CYCLE),
        )
        usage, total, duty = results
        if usage is None and total is None and duty is None:
            return None
        # Some metric answered: the source is live, so a per-metric
        # failure recorded above must not linger as the "why dark".
        self.last_error = None
        return {
            "hbm_used": usage or {},
            "hbm_total": total or {},
            "duty_pct": duty or {},
        }

    async def close(self) -> None:
        if self._channel is not None:
            await self._channel.close()
            self._channel = None
