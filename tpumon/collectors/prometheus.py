"""Async Prometheus query client.

Reference parity: httpGet + queryPrometheus + queryRangePrometheus
(monitor_server.js:14-52) — instant and range queries, resolving to null /
[] on any failure. Differences (deliberate, SURVEY §3.3):

- Queries are issued **in parallel** by callers via asyncio.gather; the
  reference awaited its six history queries sequentially
  (monitor_server.js:119-134).
- Range results keep **all** series (the reference kept only the first,
  monitor_server.js:138) — per-chip tpu_* series need them all.
- Failures still degrade to None/[] but the error is recorded on the
  client for source-health reporting.
"""

from __future__ import annotations

import asyncio
import json
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass, field


@dataclass
class Series:
    labels: dict[str, str]
    times: list[float]  # unix seconds
    values: list[float]


@dataclass
class PrometheusClient:
    base_url: str
    timeout_s: float = 5.0
    last_error: str | None = field(default=None, repr=False)

    def _get(self, path: str, params: dict) -> dict | None:
        url = f"{self.base_url}{path}?{urllib.parse.urlencode(params)}"
        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
                body = json.load(r)
            if body.get("status") != "success":
                raise ValueError(f"prometheus status={body.get('status')}")
            self.last_error = None
            return body
        except Exception as e:
            self.last_error = f"{type(e).__name__}: {e}"
            return None

    async def query(self, promql: str, ts: float | None = None) -> float | None:
        """Instant query; first sample's value or None (monitor_server.js:27-36)."""
        params = {"query": promql}
        if ts is not None:
            params["time"] = ts
        body = await asyncio.to_thread(self._get, "/api/v1/query", params)
        if not body:
            return None
        result = body.get("data", {}).get("result", [])
        if not result:
            return None
        try:
            return float(result[0]["value"][1])
        except (KeyError, IndexError, ValueError):
            return None

    async def query_range(
        self,
        promql: str,
        window_s: float = 1800,
        step_s: float = 30,
        end: float | None = None,
    ) -> list[Series]:
        """Range query over the trailing window (monitor_server.js:38-52)."""
        end = time.time() if end is None else end
        body = await asyncio.to_thread(
            self._get,
            "/api/v1/query_range",
            {
                "query": promql,
                "start": end - window_s,
                "end": end,
                "step": step_s,
            },
        )
        if not body:
            return []
        out: list[Series] = []
        for series in body.get("data", {}).get("result", []):
            times: list[float] = []
            values: list[float] = []
            for t, v in series.get("values", []):
                try:
                    fv = float(v)
                except ValueError:
                    continue
                # Prometheus emits "NaN"/"Inf" strings for 0/0-style
                # expressions; float() accepts them, but they would
                # serialize as invalid JSON downstream — drop to a gap.
                if fv != fv or fv in (float("inf"), float("-inf")):
                    continue
                values.append(fv)
                times.append(float(t))
            out.append(Series(labels=series.get("metric", {}), times=times, values=values))
        return out
