"""Fake TPU accelerator source.

SURVEY.md §7 step 2: a synthetic per-chip source producing v5e-1 / v5e-8 /
v5p-64 shapes so the whole pipeline (API, exporter, alerts, UI, multi-host
aggregation) is testable with zero accelerators — the TPU analogue of the
reference's implicit "no nvidia-smi present" mode (monitor_server.js:94),
but generative instead of empty.

Deterministic given (topology, time): values are smooth functions of t so
history charts look plausible, and per-chip phase offsets make chips
distinguishable. Supports fault injection (``kill_host`` /
``set_override``) for the §4.4 multi-node simulation tests.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field

from tpumon.collectors import Sample
from tpumon.topology import HBM_BYTES_BY_KIND, ChipSample

# topology name -> (kind, n_hosts, chips_per_host, hosts_per_slice)
# hosts_per_slice == n_hosts => the whole topology is one slice (the
# original shapes); smaller => a pod-of-pods: chips carry per-slice
# labels (slice-0.0, slice-0.1, ...) so group-by-slice rollups — the
# federation tree's aggregation keys (tpumon.federation) — have real
# values to group on.
FAKE_TOPOLOGIES: dict[str, tuple[str, int, int, int]] = {
    "v5e-1": ("v5e", 1, 1, 1),
    "v5e-4": ("v5e", 1, 4, 1),
    "v5e-8": ("v5e", 1, 8, 1),
    "v5p-8": ("v5p", 2, 4, 2),
    "v5p-64": ("v5p", 16, 4, 16),  # v5p: 4 chips per host VM
    # Production-scale shapes for the data-plane fast-path benchmarks
    # (bench.py fastpath/federation phases, docs/perf.md): the render
    # and delta-SSE costs are O(chips), so these pin 128/256-chip costs.
    "v5p-128": ("v5p", 32, 4, 32),
    "v5p-256": ("v5p", 64, 4, 64),
    # Pod-of-pods shapes (ROADMAP item 2 / docs/federation.md): 2 and 8
    # v5p-256 slices — the fake fleet geometry behind the federation
    # tree bench and soak (a leaf monitor usually runs one 256-chip
    # slice; v5p-2048 in ONE instance is the degenerate flat baseline).
    "v5p-512": ("v5p", 128, 4, 64),
    "v5p-2048": ("v5p", 512, 4, 64),
}


@dataclass
class FakeTpuCollector:
    """Synthetic TPU chip metrics for a named topology."""

    topology: str = "v5e-8"
    slice_id: str = "slice-0"
    host_prefix: str = "tpu-host"
    name: str = "accel"
    clock: object = time.time  # injectable for deterministic tests
    dead_hosts: set[str] = field(default_factory=set)
    overrides: dict[str, dict] = field(default_factory=dict)  # chip_id -> field overrides
    # Periodic fault episodes (demo mode, `fake:<topo>+faults`): one
    # chip's ICI link degrades for ~60s every ~8 min and another
    # throttles for ~45s every ~11 min, so the degradation UI and the
    # fire->resolve alert lifecycle exercise themselves continuously.
    fault_episodes: bool = False

    def __post_init__(self) -> None:
        if self.topology not in FAKE_TOPOLOGIES:
            raise ValueError(
                f"unknown fake topology {self.topology!r}; "
                f"known: {sorted(FAKE_TOPOLOGIES)}"
            )

    # -- fault injection -------------------------------------------------
    def kill_host(self, host: str) -> None:
        self.dead_hosts.add(host)

    def revive_host(self, host: str) -> None:
        self.dead_hosts.discard(host)

    def set_override(self, chip_id: str, **fields) -> None:
        self.overrides.setdefault(chip_id, {}).update(fields)

    # --------------------------------------------------------------------
    def chips(self) -> list[ChipSample]:
        kind, n_hosts, per_host, hosts_per_slice = FAKE_TOPOLOGIES[self.topology]
        multi_slice = hosts_per_slice < n_hosts
        hbm_total = HBM_BYTES_BY_KIND[kind]
        t = self.clock()
        out: list[ChipSample] = []
        for h in range(n_hosts):
            host = f"{self.host_prefix}-{h}"
            if host in self.dead_hosts:
                continue
            # Pod-of-pods: each hosts_per_slice-host group is its own
            # slice (slice labels are the federation rollup keys);
            # single-slice topologies keep the configured slice_id
            # verbatim (back-compat with every existing test/config).
            slice_id = (
                f"{self.slice_id}.{h // hosts_per_slice}"
                if multi_slice
                else self.slice_id
            )
            for i in range(per_host):
                g = h * per_host + i  # global index => phase offset
                phase = 0.7 * g
                duty = 55 + 35 * math.sin(t / 37 + phase) + 5 * math.sin(t / 5 + g)
                hbm_frac = 0.55 + 0.25 * math.sin(t / 53 + phase / 2)
                temp = 45 + 18 * (duty / 100) + 2 * math.sin(t / 71 + g)
                # Cumulative ICI counters: closed-form integral of a smooth
                # ~2 GB/s rate ∫2e9·(1+sin(t/41+φ))dt so deltas are consistent
                # between successive samples.
                cumulative = int(2e9 * (t + 41 * (1 - math.cos(t / 41 + phase))))
                link_health = 0
                throttle = 0
                if self.fault_episodes:
                    if g == 3 and (t % 480) < 60:
                        link_health = 7  # persistent problem -> serious
                    if g == 5 and (t % 660) < 45:
                        # Thresholds.throttle_score = TriLevel(0, 4, 7) uses
                        # strict '>', so 5 is the lowest serious-severity score.
                        throttle = 5  # ~50% throttled -> serious
                sample = ChipSample(
                    chip_id=f"{host}/chip-{i}",
                    host=host,
                    slice_id=slice_id,
                    index=i,
                    kind=kind,
                    coords=(g % 4, g // 4, 0),
                    mxu_duty_pct=max(0.0, min(100.0, duty)),
                    hbm_used=int(hbm_total * max(0.02, min(0.98, hbm_frac))),
                    hbm_total=hbm_total,
                    temp_c=round(temp, 1),
                    ici_tx_bytes=cumulative,
                    ici_rx_bytes=int(cumulative * 0.97),
                    ici_link_up=True,
                    # Healthy outside episodes; tests/demos also inject
                    # degradation via set_override (PROBE_libtpu.md scale).
                    ici_link_health=link_health,
                    throttle_score=throttle,
                )
                ov = self.overrides.get(sample.chip_id)
                if ov:
                    sample = ChipSample(**{**sample.__dict__, **ov})
                out.append(sample)
        return out

    async def collect(self) -> Sample:
        return Sample(source=self.name, ok=True, data=self.chips())
