"""GPU collector family (ISSUE 15 / ROADMAP item 5).

The reference paper's accelerator path is NVIDIA-native: its L1b
collector shells out to ``nvidia-smi --query-gpu=... --format=csv``
(monitor_server.js:83-95) and its L0 deployment scrapes a DCGM exporter
(:9400, DCGM_FI_DEV_* series). tpumon replaced that wholesale with TPU
collectors; this module re-admits it as *diversity* — both sources
normalize into the same accelerator-generic ``ChipSample`` the TPU
collectors produce, so GPU nodes federate into the same tree, answer
the same queries and render in the same dashboard:

    SM util %                  -> mxu_duty_pct
    framebuffer (VRAM) used    -> hbm_used / hbm_total
    NVLink tx/rx byte counters -> ici_tx_bytes / ici_rx_bytes
    XID errors / link state    -> ici_link_health / ici_link_up
    (provenance)               -> counter_source "nvidia-smi" | "dcgm"
    (family)                   -> accel_kind "gpu"

Both collectors are honest-degraded like every existing source: a
missing binary / unreachable exporter is a ``Sample(ok=False, error=…)``
— never a crash, never a silent empty list (the reference's
"nvidia-smi absent => []" mode, but with the reason recorded).
"""

from __future__ import annotations

import asyncio
import re
import socket
import urllib.request
from dataclasses import dataclass

from tpumon.collectors import Sample
from tpumon.metrics_text import parse_metrics_text
from tpumon.topology import ChipSample

# The query columns (order is the CSV parse contract below) — a
# superset of the reference's ``name, utilization.gpu, memory.used,
# memory.total, temperature.gpu`` (monitor_server.js:85).
SMI_QUERY_FIELDS = (
    "index",
    "name",
    "utilization.gpu",
    "memory.used",
    "memory.total",
    "temperature.gpu",
)
SMI_ARGS = (
    f"--query-gpu={','.join(SMI_QUERY_FIELDS)}",
    "--format=csv,noheader,nounits",
)


_GPU_KIND_RE = re.compile(
    r"(?<![a-z0-9])"
    r"(h200|h100|a100|l40s|l40|a10g|a10|v100|t4|l4)"
    r"(?![a-z0-9])"
)


def normalize_gpu_kind(name: str) -> str:
    """Map an nvidia-smi/DCGM product string ("NVIDIA A100-SXM4-80GB",
    "NVIDIA H100 80GB HBM3") to a short kind — the GPU analogue of
    topology.normalize_chip_kind. Token-bounded match so "L40S" never
    reads as "l4" (and longer parts are tried first)."""
    m = _GPU_KIND_RE.search(name.lower())
    if m:
        return m.group(1)
    return name.strip() or "gpu"


def _maybe_float(s: str) -> float | None:
    """nvidia-smi prints "[N/A]" / "N/A" for unsupported fields —
    that is an honest None, not a zero."""
    s = s.strip()
    if not s or "n/a" in s.lower():
        return None
    try:
        return float(s)
    except ValueError:
        return None


def parse_nvidia_smi_csv(
    text: str, host: str, slice_id: str = "gpu-0"
) -> list[ChipSample]:
    """Parse ``nvidia-smi --query-gpu=… --format=csv,noheader,nounits``
    output (SMI_QUERY_FIELDS order) into ChipSamples. Memory comes back
    in MiB (nounits); rows that don't parse are skipped rather than
    poisoning the sample — the reference's CSV parse did the same by
    construction (monitor_server.js:88-93)."""
    out: list[ChipSample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        parts = [p.strip() for p in line.split(",")]
        if len(parts) < len(SMI_QUERY_FIELDS):
            continue
        idx_f = _maybe_float(parts[0])
        if idx_f is None:
            continue
        index = int(idx_f)
        kind = normalize_gpu_kind(parts[1])
        util = _maybe_float(parts[2])
        mem_used = _maybe_float(parts[3])
        mem_total = _maybe_float(parts[4])
        temp = _maybe_float(parts[5])
        out.append(
            ChipSample(
                chip_id=f"{host}/gpu-{index}",
                host=host,
                slice_id=slice_id,
                index=index,
                kind=kind,
                mxu_duty_pct=util,
                hbm_used=int(mem_used * 2**20) if mem_used is not None else None,
                hbm_total=(
                    int(mem_total * 2**20) if mem_total is not None else None
                ),
                temp_c=temp,
                counter_source="nvidia-smi",
                accel_kind="gpu",
            )
        )
    return out


@dataclass
class NvidiaSmiCollector:
    """Shells out to nvidia-smi per tick (async subprocess — the
    reference did this with a blocking execSync on its event loop,
    monitor_server.js:85). The host identity is this node's hostname so
    federated GPU chips are globally unique, like every TPU source."""

    name: str = "accel"
    smi_path: str = "nvidia-smi"
    # Default slice namespace is the GPU family's own ("gpu-0", like
    # gpu_fake) — NOT the TPU default "slice-0": a peer-merged view
    # holding both families must never collapse them into one mixed
    # SliceView (topology.SliceView.accel_kind assumes one family per
    # slice).
    slice_id: str = "gpu-0"
    host: str = ""

    def __post_init__(self) -> None:
        self.host = self.host or socket.gethostname()

    async def collect(self) -> Sample:
        try:
            proc = await asyncio.create_subprocess_exec(
                self.smi_path,
                *SMI_ARGS,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE,
            )
            stdout, stderr = await proc.communicate()
        except FileNotFoundError:
            return Sample(
                source=self.name, ok=False, data=[],
                error=f"{self.smi_path} not found (no NVIDIA driver?)",
            )
        except OSError as e:
            return Sample(
                source=self.name, ok=False, data=[],
                error=f"{self.smi_path}: {type(e).__name__}: {e}",
            )
        if proc.returncode != 0:
            msg = (stderr or stdout).decode("utf-8", "replace").strip()
            return Sample(
                source=self.name, ok=False, data=[],
                error=f"{self.smi_path} exit {proc.returncode}: {msg[:160]}",
            )
        chips = parse_nvidia_smi_csv(
            stdout.decode("utf-8", "replace"), self.host, self.slice_id
        )
        return Sample(source=self.name, ok=True, data=chips)


# ------------------------------ DCGM -----------------------------------

# DCGM exporter family names (the L0 deployment's :9400 scrape,
# README.md:130-136 of the reference) -> ChipSample normalization.
_DCGM_UTIL = "DCGM_FI_DEV_GPU_UTIL"            # SM util %
_DCGM_FB_USED = "DCGM_FI_DEV_FB_USED"          # MiB
_DCGM_FB_FREE = "DCGM_FI_DEV_FB_FREE"          # MiB
_DCGM_TEMP = "DCGM_FI_DEV_GPU_TEMP"            # °C
_DCGM_NVLINK_TX = "DCGM_FI_PROF_NVLINK_TX_BYTES"  # cumulative bytes
_DCGM_NVLINK_RX = "DCGM_FI_PROF_NVLINK_RX_BYTES"
_DCGM_XID = "DCGM_FI_DEV_XID_ERRORS"           # last XID code (0 = none)

# XID codes that indicate interconnect/bus hardware trouble — the only
# ones mapped onto the ici_link_health score. DCGM reports the LAST
# XID observed (it persists until driver reload), and most codes are
# benign application-level events (13/31/43: a user process crashed),
# so mapping every non-zero XID would raise a perpetual serious alert
# on a healthy GPU. Finer per-code taxonomy is a ROADMAP follow-up.
_XID_LINK_CODES = frozenset({62, 74, 79})  # NVLink errors, GPU off bus


def parse_dcgm_text(
    text: str, default_host: str = "", slice_id: str = "gpu-0"
) -> list[ChipSample]:
    """Parse DCGM-exporter Prometheus exposition into ChipSamples, one
    per distinct ``gpu`` label per host. Host identity prefers the
    exporter's ``Hostname`` label (multi-node scrapes) and falls back
    to ``default_host``. An NVLink/bus XID error (_XID_LINK_CODES)
    degrades the link health score — the nearest NVLink-health
    analogue DCGM exports; other XIDs (mostly application-level) leave
    it healthy rather than paging forever on the last crashed job."""
    per: dict[tuple[str, str], dict] = {}
    for s in parse_metrics_text(text):
        gpu = s.labels.get("gpu")
        if gpu is None:
            continue
        host = s.labels.get("Hostname") or default_host
        d = per.setdefault((host, gpu), {})
        if "model" not in d and s.labels.get("modelName"):
            d["model"] = s.labels["modelName"]
        d.setdefault(s.name, s.value)
    out: list[ChipSample] = []
    for (host, gpu), d in sorted(per.items()):
        fb_used = d.get(_DCGM_FB_USED)
        fb_free = d.get(_DCGM_FB_FREE)
        fb_total = (
            fb_used + fb_free
            if fb_used is not None and fb_free is not None
            else None
        )
        xid = d.get(_DCGM_XID)
        out.append(
            ChipSample(
                chip_id=f"{host}/gpu-{gpu}" if host else f"gpu-{gpu}",
                host=host,
                slice_id=slice_id,
                index=int(gpu) if gpu.isdigit() else 0,
                kind=normalize_gpu_kind(d.get("model", "gpu")),
                mxu_duty_pct=d.get(_DCGM_UTIL),
                hbm_used=int(fb_used * 2**20) if fb_used is not None else None,
                hbm_total=int(fb_total * 2**20) if fb_total is not None else None,
                temp_c=d.get(_DCGM_TEMP),
                ici_tx_bytes=(
                    int(d[_DCGM_NVLINK_TX]) if _DCGM_NVLINK_TX in d else None
                ),
                ici_rx_bytes=(
                    int(d[_DCGM_NVLINK_RX]) if _DCGM_NVLINK_RX in d else None
                ),
                ici_link_health=(
                    None
                    if xid is None
                    else (7 if int(xid) in _XID_LINK_CODES else 0)
                ),
                counter_source="dcgm",
                accel_kind="gpu",
            )
        )
    return out


@dataclass
class DcgmCollector:
    """Scrapes a DCGM exporter's /metrics (the reference's L0 data
    path) and normalizes into ChipSamples. The fetch runs on a worker
    thread (urllib is blocking), same idiom as the serving collector."""

    url: str = "http://127.0.0.1:9400/metrics"
    name: str = "accel"
    slice_id: str = "gpu-0"  # GPU-family namespace, like NvidiaSmiCollector
    timeout_s: float = 3.0
    host: str = ""

    def __post_init__(self) -> None:
        self.host = self.host or socket.gethostname()
        if not self.url.startswith(("http://", "https://")):
            self.url = f"http://{self.url}"
        if not self.url.rstrip("/").endswith("/metrics"):
            self.url = self.url.rstrip("/") + "/metrics"

    def _fetch(self) -> str:
        with urllib.request.urlopen(self.url, timeout=self.timeout_s) as r:
            return r.read().decode("utf-8", "replace")

    async def collect(self) -> Sample:
        try:
            text = await asyncio.to_thread(self._fetch)
        except Exception as e:
            return Sample(
                source=self.name, ok=False, data=[],
                error=f"dcgm {self.url}: {type(e).__name__}: {e}",
            )
        chips = parse_dcgm_text(text, self.host, self.slice_id)
        if not chips:
            return Sample(
                source=self.name, ok=False, data=[],
                error=f"dcgm {self.url}: no DCGM_FI_* gpu series in scrape",
            )
        return Sample(source=self.name, ok=True, data=chips)
