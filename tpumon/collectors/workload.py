"""Workload self-report counter source (``source: workload``).

Every platform counter source can be dark on a dev chip — on the
axon-tunneled v5e this repo validates against, the libtpu SDK answers
``[]``, the gRPC metrics service refuses connections, and PJRT
``memory_stats()`` is ``{}`` (PROBE_libtpu.md finding #3). The reference
faces no such gap: ``nvidia-smi`` always answers
(``/root/reference/monitor_server.js:83-95``). The TPU-native fallback
is the workload itself: a JAX process *knows* its own HBM footprint
(its live device buffers) and its device activity (the fraction of wall
time it spends blocked on device execution), so it can publish them.

Channel: one small JSON file per workload process in a shared directory
(default ``/tmp/tpumon-workload``), written atomically (tmp + rename)
every ~1 s by ``tpumon.loadgen.report.WorkloadReporter`` and merged here
by the collector. Entries older than ``MAX_AGE_S`` are ignored, so a
killed workload disappears from the monitor within seconds.

Provenance is explicit end-to-end: chips whose counters came from this
source carry ``counter_source: "workload"`` in ``/api/accel/metrics``,
and the sample note (surfaced in ``/api/health`` and the dashboard
health strip) says self-reported — these are *workload-declared*
values, deliberately ranked below the SDK/gRPC/PJRT platform sources in
``accel_jax``'s chain (VERDICT r02 item #2).

File format (version 1)::

    {"v": 1, "name": "train", "pid": 1234, "ts": 1753900000.0,
     "devices": [{"index": 0, "hbm_used": 2147483648,
                  "hbm_total": 17179869184, "busy_frac": 0.93}]}
"""

from __future__ import annotations

import json
import os
import stat
import time
from dataclasses import dataclass, field

#: Default shared directory for workload report files, uid-suffixed so a
#: multi-user host's users can't collide or squat each other's channel.
#: Overridable via Config.workload_dir (TPUMON_WORKLOAD_DIR).
DEFAULT_DIR = f"/tmp/tpumon-workload-{os.getuid()}" if hasattr(os, "getuid") \
    else "/tmp/tpumon-workload"


def _owned_by_us(path: str, want_dir: bool = False) -> bool:
    """True iff ``path`` is a real file/directory (never a symlink)
    owned by this process's uid — the trust boundary for the
    self-report channel (a monitor must not publish counters another
    local user planted).

    lstat, not stat: /tmp is world-writable, so another user can
    pre-create the predictable uid-suffixed path as a symlink into a
    victim-owned tree; following it would pass an os.stat ownership
    check while redirecting writes and reads to an attacker-chosen
    location. ``want_dir`` additionally requires a directory (the
    channel root); otherwise a regular file (one report)."""
    if not hasattr(os, "getuid"):
        return True  # no POSIX ownership model; nothing to check
    try:
        st = os.lstat(path)
    except OSError:
        return False
    kind_ok = stat.S_ISDIR(st.st_mode) if want_dir else stat.S_ISREG(st.st_mode)
    return kind_ok and st.st_uid == os.getuid()

#: Reports older than this are a dead/stalled workload and are ignored.
MAX_AGE_S = 10.0

REPORT_VERSION = 1


def write_report(
    directory: str,
    name: str,
    devices: list[dict],
    pid: int | None = None,
    now: float | None = None,
) -> str:
    """Atomically write one workload's report; returns the file path.

    Atomic (tmp + rename on the same filesystem) so the collector never
    reads a half-written JSON.
    """
    pid = os.getpid() if pid is None else pid
    now = time.time() if now is None else now
    os.makedirs(directory, mode=0o700, exist_ok=True)
    if not _owned_by_us(directory, want_dir=True):
        raise PermissionError(
            f"workload report dir {directory!r} is not owned by this "
            "user — refusing to write into a squattable channel"
        )
    path = os.path.join(directory, f"{name}-{pid}.json")
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(
            {
                "v": REPORT_VERSION,
                "name": name,
                "pid": pid,
                "ts": now,
                "devices": devices,
            },
            f,
        )
    os.replace(tmp, path)
    return path


def remove_report(directory: str, name: str, pid: int | None = None) -> None:
    """Best-effort cleanup on workload shutdown (staleness also covers
    an unclean exit)."""
    pid = os.getpid() if pid is None else pid
    try:
        os.unlink(os.path.join(directory, f"{name}-{pid}.json"))
    except OSError:
        pass


def read_reports(
    directory: str, now: float | None = None, max_age_s: float = MAX_AGE_S
) -> list[dict]:
    """All fresh, well-formed reports in the directory. Corrupt or stale
    files are skipped (a monitor must not crash on a torn write or a
    dead workload's leftovers)."""
    now = time.time() if now is None else now
    out: list[dict] = []
    if not _owned_by_us(directory, want_dir=True):
        return out  # absent, or another user's dir: no trusted reports
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for fname in sorted(names):
        if not fname.endswith(".json"):
            continue
        fpath = os.path.join(directory, fname)
        if not _owned_by_us(fpath):
            continue
        try:
            with open(fpath) as f:
                rep = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(rep, dict) or rep.get("v") != REPORT_VERSION:
            continue
        ts = rep.get("ts")
        if not isinstance(ts, (int, float)) or now - ts > max_age_s:
            continue
        if not isinstance(rep.get("devices"), list):
            continue
        out.append(rep)
    return out


def merge_reports(reports: list[dict]) -> dict[int, dict]:
    """Merge per-process reports into one view per device index.

    Several workloads can share a chip (e.g. a trainer and the serving
    engine): HBM footprints add; busy fractions add but cap at 1.0 (two
    processes can't make one chip more than fully busy).
    """
    merged: dict[int, dict] = {}
    for rep in reports:
        for dev in rep["devices"]:
            idx = dev.get("index")
            if not isinstance(idx, int):
                continue
            m = merged.setdefault(
                idx,
                {"hbm_used": None, "hbm_total": None, "busy_frac": None,
                 "workloads": []},
            )
            hbm = dev.get("hbm_used")
            if isinstance(hbm, (int, float)):
                m["hbm_used"] = int((m["hbm_used"] or 0) + hbm)
            total = dev.get("hbm_total")
            if isinstance(total, (int, float)):
                m["hbm_total"] = max(int(total), m["hbm_total"] or 0)
            busy = dev.get("busy_frac")
            if isinstance(busy, (int, float)):
                m["busy_frac"] = min(1.0, (m["busy_frac"] or 0.0) + busy)
            wname = str(rep.get("name", "?"))
            if wname not in m["workloads"]:
                m["workloads"].append(wname)
    return merged


@dataclass
class WorkloadFileSource:
    """Collector-side reader. ``snapshot()`` is synchronous — a handful
    of tiny local file stats is cheaper than a thread hop, and the tick
    path must stay lean (BENCH_r02 sampler-rate lesson). Parsed reports
    are cached per (path, mtime, size) so an unchanged file costs one
    stat per tick, not a JSON parse."""

    directory: str = DEFAULT_DIR
    max_age_s: float = MAX_AGE_S
    clock: object = field(default=time.time, repr=False)
    _cache: dict = field(default_factory=dict, repr=False)

    def _read_cached(self, fpath: str) -> dict | None:
        # lstat, same trust boundary as read_reports: a symlink planted
        # in the channel must not let the collector ingest (or cache)
        # some other user-owned JSON it points at.
        try:
            st = os.lstat(fpath)
        except OSError:
            self._cache.pop(fpath, None)
            return None
        if not stat.S_ISREG(st.st_mode) or (
            hasattr(os, "getuid") and st.st_uid != os.getuid()
        ):
            return None
        key = (st.st_mtime_ns, st.st_size)
        hit = self._cache.get(fpath)
        if hit is not None and hit[0] == key:
            return hit[1]
        try:
            with open(fpath) as f:
                rep = json.load(f)
        except (OSError, ValueError):
            rep = None
        if not (
            isinstance(rep, dict)
            and rep.get("v") == REPORT_VERSION
            and isinstance(rep.get("ts"), (int, float))
            and isinstance(rep.get("devices"), list)
        ):
            rep = None
        self._cache[fpath] = (key, rep)
        return rep

    def snapshot(self) -> dict[int, dict]:
        now = self.clock()
        if not _owned_by_us(self.directory, want_dir=True):
            return {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return {}
        live = set()
        fresh: list[dict] = []
        for fname in sorted(names):
            if not fname.endswith(".json"):
                continue
            fpath = os.path.join(self.directory, fname)
            live.add(fpath)
            rep = self._read_cached(fpath)
            if rep is not None and now - rep["ts"] <= self.max_age_s:
                fresh.append(rep)
        for gone in [p for p in self._cache if p not in live]:
            del self._cache[gone]
        return merge_reports(fresh)
