"""LLM serving-metrics ingest (JetStream / MaxText).

The reference's tech-stack README names vLLM metric collection
(README.md:73) but ships no code for it (SURVEY §5.7); serving is only
visible as pods + GPU counters. tpumon makes serving ingest real and
TPU-native: scrape the Prometheus ``/metrics`` endpoints of JetStream /
MaxText JAX-serving processes and distill the panels the dashboard needs
— TTFT, token throughput, queue depth, request rate (BASELINE config 4).

Metric-name mapping is table-driven because serving stacks drift; each
target is matched against known families with sensible fallbacks, and
unknown deployments degrade to "target reachable, no recognized metrics"
rather than erroring.
"""

from __future__ import annotations

import asyncio
import time
import urllib.request
from dataclasses import dataclass, field

from tpumon.collectors import Sample
from tpumon.metrics_text import (
    histogram_quantile,
    parse_metrics_text,
    samples_by_name,
)

# Known metric families, in preference order per signal.
# JetStream server: https://github.com/AI-Hypercomputer/JetStream (public
# metric names); vLLM names kept as a compatibility fallback.
TTFT_HISTOGRAMS = (
    "jetstream_time_to_first_token",
    "jetstream_time_to_first_token_seconds",
    "vllm:time_to_first_token_seconds",
)
TPOT_HISTOGRAMS = (
    "jetstream_time_per_output_token",
    "vllm:time_per_output_token_seconds",
)
TOKEN_COUNTERS = (
    "jetstream_total_tokens_in_current_batch",
    "jetstream_generate_tokens",
    "jetstream_total_output_tokens",
    "vllm:generation_tokens",
)
QUEUE_GAUGES = (
    "jetstream_queue_size",
    "jetstream_transfer_backlog",
    "jetstream_prefill_backlog_size",
    "vllm:num_requests_waiting",
)
REQUEST_COUNTERS = (
    "jetstream_request_count",
    "jetstream_num_requests",
    "jetstream_request_success_count",
    "vllm:request_success",
)
SLOTS_GAUGES = (
    "jetstream_slots_used_percentage",
    "jetstream_slots_available",
)

# Training-job telemetry (tpumon.loadgen.train publishes these; any
# trainer exporting the same families joins the training panel).
TRAIN_GAUGES = {
    "train_step": "tpumon_train_step",
    "train_loss": "tpumon_train_loss",
    "train_goodput_pct": "tpumon_train_goodput_pct",
    "train_ckpt_step": "tpumon_train_checkpoint_step",
    "train_mfu_pct": "tpumon_train_mfu_pct",
}
TRAIN_STEP_TIME = "tpumon_train_step_time_seconds"
TRAIN_TOKEN_COUNTER = "tpumon_train_tokens_total"


def _sum_samples(by_name: dict, names: tuple[str, ...]) -> tuple[str, float] | None:
    """Sum a family's samples (all label sets), trying each known name
    and its prometheus-client counter form ``<name>_total`` — real
    JetStream/vLLM deployments expose counters with the _total suffix
    (pinned by the golden fixtures in tests/fixtures/)."""
    for name in names:
        for candidate in (name, name + "_total"):
            if candidate in by_name:
                return candidate, sum(s.value for s in by_name[candidate])
    return None


def _histogram_p(by_name: dict, names: tuple[str, ...], q: float):
    for name in names:
        bucket = by_name.get(name + "_bucket")
        if bucket:
            val = histogram_quantile(bucket, q)
            if val is not None:
                return name, val
    return None


def distill_serving_metrics(
    text: str, prev: dict | None = None, now: float | None = None
) -> dict:
    """Distill one target's exposition text into dashboard-ready fields.

    ``prev`` is the previous distilled dict (for counter-rate computation
    between scrapes).
    """
    now = time.time() if now is None else now
    by_name = samples_by_name(parse_metrics_text(text))
    out: dict = {"ts": now, "raw_families": len(by_name)}

    ttft = _histogram_p(by_name, TTFT_HISTOGRAMS, 0.5)
    if ttft:
        name, val = ttft
        # JetStream buckets are seconds; report ms.
        out["ttft_p50_ms"] = val * 1e3
        p99 = _histogram_p(by_name, TTFT_HISTOGRAMS, 0.99)
        if p99:
            out["ttft_p99_ms"] = p99[1] * 1e3
    tpot = _histogram_p(by_name, TPOT_HISTOGRAMS, 0.5)
    if tpot:
        out["tpot_p50_ms"] = tpot[1] * 1e3
    # Engine-native per-request quantile gauges (tpumon.loadgen.serving
    # metrics_text): recent-window TTFT/TPOT p50/p95 plus the scheduler
    # state — queue depth is above via QUEUE_GAUGES; in-prefill slots
    # are the interleaved scheduler's "admitted, not yet decoding"
    # count. Gauges win over the histogram-derived quantiles when both
    # are present (exact per-request sorts beat bucket interpolation).
    for metric, field_name in (
        ("tpumon_serving_ttft_p50_ms", "ttft_p50_ms"),
        ("tpumon_serving_ttft_p95_ms", "ttft_p95_ms"),
        ("tpumon_serving_tpot_p50_ms", "tpot_p50_ms"),
        ("tpumon_serving_tpot_p95_ms", "tpot_p95_ms"),
        ("tpumon_serving_slots_prefill", "slots_prefill"),
    ):
        got = _sum_samples(by_name, (metric,))
        if got:
            out[field_name] = got[1]

    tokens = _sum_samples(by_name, TOKEN_COUNTERS)
    if tokens:
        out["tokens_total"] = tokens[1]
        if prev and "tokens_total" in prev and prev["ts"] < now:
            delta = tokens[1] - prev["tokens_total"]
            if delta >= 0:
                out["tokens_per_sec"] = delta / (now - prev["ts"])

    requests = _sum_samples(by_name, REQUEST_COUNTERS)
    if requests:
        out["requests_total"] = requests[1]
        if prev and "requests_total" in prev and prev["ts"] < now:
            delta = requests[1] - prev["requests_total"]
            if delta >= 0:
                out["requests_per_sec"] = delta / (now - prev["ts"])

    queue = _sum_samples(by_name, QUEUE_GAUGES)
    if queue:
        out["queue_depth"] = queue[1]
    slots = _sum_samples(by_name, SLOTS_GAUGES)
    if slots:
        out["slots"] = slots[1]
    weights = _sum_samples(by_name, ("tpumon_serving_weight_bytes",))
    if weights:
        out["weight_bytes"] = weights[1]  # drops ~4x when served int8
    # Speculative decoding acceptance (tpumon.loadgen.speculative):
    # windowed between scrapes via counter deltas (so the value tracks
    # CURRENT acceptance, matching the PromQL rate-ratio semantics of
    # the history series); lifetime ratio on the first scrape. Idle
    # windows (no new proposals) omit the field rather than repeat a
    # stale number.
    spec_prop = _sum_samples(by_name, ("tpumon_serving_spec_proposed",))
    spec_acc = _sum_samples(by_name, ("tpumon_serving_spec_accepted",))
    if spec_prop and spec_acc:
        out["spec_proposed_total"] = spec_prop[1]
        out["spec_accepted_total"] = spec_acc[1]
        if prev and "spec_proposed_total" in prev:
            dp = spec_prop[1] - prev["spec_proposed_total"]
            da = spec_acc[1] - prev["spec_accepted_total"]
            if dp > 0 and 0 <= da <= dp:
                out["spec_accept_pct"] = 100.0 * da / dp
        elif spec_prop[1] > 0:
            out["spec_accept_pct"] = 100.0 * spec_acc[1] / spec_prop[1]
    # Prefix-cache hit rate (tpumon.loadgen.prefix_cache / the paged
    # page-sharing cache): windowed like spec acceptance — the value
    # tracks CURRENT traffic, not the lifetime average.
    pf_hits = _sum_samples(by_name, ("tpumon_serving_prefix_hits",))
    pf_miss = _sum_samples(by_name, ("tpumon_serving_prefix_misses",))
    if pf_hits and pf_miss:
        out["prefix_hits_total"] = pf_hits[1]
        out["prefix_misses_total"] = pf_miss[1]
        if prev and "prefix_hits_total" in prev:
            dh = pf_hits[1] - prev["prefix_hits_total"]
            dm = pf_miss[1] - prev["prefix_misses_total"]
            if dh >= 0 and dm >= 0 and dh + dm > 0:
                out["prefix_hit_pct"] = 100.0 * dh / (dh + dm)
        elif pf_hits[1] + pf_miss[1] > 0:
            out["prefix_hit_pct"] = (
                100.0 * pf_hits[1] / (pf_hits[1] + pf_miss[1]))
    # Paged KV pool occupancy (tpumon.loadgen.paged_kv): reserved pages
    # over the pool — the engine's KV-memory pressure signal.
    pg_total = _sum_samples(by_name, ("tpumon_serving_kv_pages_total",))
    pg_free = _sum_samples(by_name, ("tpumon_serving_kv_pages_free",))
    if pg_total and pg_total[1] > 0 and pg_free:
        out["kv_pages_total"] = pg_total[1]
        out["kv_pages_used_pct"] = (
            100.0 * (pg_total[1] - pg_free[1]) / pg_total[1])

    # Per-tenant serving signals (tpumon.loadgen.traffic / ServingEngine
    # tenant accounting): the SLO engine's raw material. Latency
    # quantiles copy through; goodput (completed req/s) and error rate
    # (rejected / submitted) are windowed between scrapes via counter
    # deltas, so they track CURRENT traffic like the other rates here.
    tenants: dict[str, dict] = {}
    for metric, field_name in (
        ("tpumon_serving_tenant_ttft_p50_ms", "ttft_p50_ms"),
        ("tpumon_serving_tenant_ttft_p95_ms", "ttft_p95_ms"),
        ("tpumon_serving_tenant_tpot_p50_ms", "tpot_p50_ms"),
        ("tpumon_serving_tenant_tpot_p95_ms", "tpot_p95_ms"),
        ("tpumon_serving_tenant_requests", "requests_total"),
        ("tpumon_serving_tenant_completed", "completed_total"),
        ("tpumon_serving_tenant_rejected", "rejected_total"),
        ("tpumon_serving_tenant_shed", "shed_total"),
    ):
        for candidate in (metric, metric + "_total"):
            for s in by_name.get(candidate, ()):
                tenant = s.labels.get("tenant")
                if tenant:
                    tenants.setdefault(tenant, {})[field_name] = s.value
            if candidate in by_name:
                break
    if tenants:
        prev_tenants = (prev or {}).get("tenants") or {}
        for tenant, row in tenants.items():
            was = prev_tenants.get(tenant)
            dt = (now - prev["ts"]) if prev and prev.get("ts") else 0.0
            if was and dt > 0 and "completed_total" in row and (
                    "completed_total" in was):
                dc = row["completed_total"] - was["completed_total"]
                if dc >= 0:
                    row["goodput_rps"] = dc / dt
            if was and "requests_total" in row and "requests_total" in was:
                dreq = row["requests_total"] - was["requests_total"]
                drej = (row.get("rejected_total", 0)
                        - was.get("rejected_total", 0))
                # Sheds leave BOTH sides of the error-rate fraction
                # (tpumon.actuate): a shed is the remedy for an SLO
                # burn — counting it as an error would re-fire the
                # very SLO that triggered the shed, and leaving it in
                # the denominator would dilute the real error rate of
                # the traffic that actually ran.
                dshed = (row.get("shed_total", 0)
                         - was.get("shed_total", 0))
                deff = dreq - max(0, dshed)
                if deff > 0 and 0 <= drej <= deff:
                    row["error_rate"] = drej / deff
                elif deff == 0 and drej == 0:
                    # Idle window: no submissions, nothing erred.
                    row["error_rate"] = 0.0
        out["tenants"] = tenants

    # Per-replica mesh-serving gauges (tpumon.loadgen.serving
    # MeshServingEngine, docs/perf.md "Mesh serving"): one row per dp
    # replica — free slots, router-assigned queue depth and the
    # recent-window latency p95s — distilled verbatim so the sampler
    # can land serving.<replica>.* TSDB series for per-replica SLOs
    # and the actuation drain verbs.
    replicas: dict[str, dict] = {}
    for metric, field_name in (
        ("tpumon_serving_replica_slots_available", "slots_available"),
        ("tpumon_serving_replica_queue_size", "queue_depth"),
        ("tpumon_serving_replica_ttft_p95_ms", "ttft_p95_ms"),
        ("tpumon_serving_replica_tpot_p95_ms", "tpot_p95_ms"),
    ):
        for s in by_name.get(metric, ()):
            replica = s.labels.get("replica")
            if replica:
                replicas.setdefault(replica, {})[field_name] = s.value
    if replicas:
        out["replicas"] = replicas

    # Training targets (tpumon_train_* families).
    for field_name, metric in TRAIN_GAUGES.items():
        got = _sum_samples(by_name, (metric,))
        if got:
            out[field_name] = got[1]
    step_time = _sum_samples(by_name, (TRAIN_STEP_TIME,))
    if step_time:
        out["train_step_time_ms"] = step_time[1] * 1e3
    train_tokens = _sum_samples(by_name, (TRAIN_TOKEN_COUNTER,))
    if train_tokens:
        out["train_tokens_total"] = train_tokens[1]
        if prev and "train_tokens_total" in prev and prev["ts"] < now:
            delta = train_tokens[1] - prev["train_tokens_total"]
            if delta >= 0:
                out["train_tokens_per_sec"] = delta / (now - prev["ts"])
    return out


def _fake_exposition(now: float | None = None) -> str:
    """Synthetic JetStream /metrics for demo mode: counters advance with
    wall time so rates and quantiles look alive (exercises the same
    distillation path as a real target)."""
    import math

    t = time.time() if now is None else now
    tokens = int(900 * t + 4000 * math.sin(t / 60))  # ~900 tok/s ± wobble
    requests = int(t / 2)
    queue = max(0, int(6 + 5 * math.sin(t / 45)))
    # TTFT histogram drifting between ~40 and ~90 ms p50
    shift = (math.sin(t / 120) + 1) / 2  # 0..1
    b1 = int(2000 + 500 * (1 - shift))
    b2 = int(5500 + 1500 * (1 - shift))
    total = 8000
    return f"""\
# TYPE jetstream_time_to_first_token histogram
jetstream_time_to_first_token_bucket{{le="0.025"}} {b1}
jetstream_time_to_first_token_bucket{{le="0.05"}} {b2}
jetstream_time_to_first_token_bucket{{le="0.1"}} {int(total * 0.97)}
jetstream_time_to_first_token_bucket{{le="0.5"}} {total}
jetstream_time_to_first_token_bucket{{le="+Inf"}} {total}
# TYPE jetstream_generate_tokens counter
jetstream_generate_tokens {tokens}
# TYPE jetstream_request_count counter
jetstream_request_count {requests}
# TYPE jetstream_queue_size gauge
jetstream_queue_size {queue}
# accepted integrates its wobbling rate so the counter stays monotonic
# (rate()-safe); kv_pages_free floors at 15/96 so demo occupancy never
# crosses the 85% pressure alert threshold (no demo alert flapping).
# TYPE tpumon_serving_spec_proposed counter
tpumon_serving_spec_proposed {int(t * 40)}
# TYPE tpumon_serving_spec_accepted counter
tpumon_serving_spec_accepted {int(35.2 * t - 180 * math.cos(t / 75))}
# TYPE tpumon_serving_kv_pages_total gauge
tpumon_serving_kv_pages_total 96
# TYPE tpumon_serving_kv_pages_free gauge
tpumon_serving_kv_pages_free {max(15, int(45 + 28 * math.sin(t / 50)))}
"""


def _fake_train_exposition(now: float | None = None) -> str:
    """Synthetic trainer /metrics for demo mode: a 2k-step epoch loop
    with decaying loss, steady step time, periodic checkpoints."""
    import math

    t = time.time() if now is None else now
    step = int(t / 0.4) % 2000  # ~2.5 steps/s, "epoch" wraps
    loss = 6.0 * math.exp(-step / 600) + 1.8 + 0.05 * math.sin(t / 7)
    tokens = int(t * 1280)  # batch*seq per step at the same cadence
    return f"""\
# TYPE tpumon_train_step gauge
tpumon_train_step {step}
# TYPE tpumon_train_loss gauge
tpumon_train_loss {loss:.4f}
# TYPE tpumon_train_step_time_seconds gauge
tpumon_train_step_time_seconds {0.4 + 0.02 * math.sin(t / 11):.4f}
# TYPE tpumon_train_tokens_total counter
tpumon_train_tokens_total {tokens}
# TYPE tpumon_train_goodput_pct gauge
tpumon_train_goodput_pct {92 + 4 * math.sin(t / 90):.2f}
# TYPE tpumon_train_mfu_pct gauge
tpumon_train_mfu_pct {46 + 3 * math.sin(t / 60):.2f}
# TYPE tpumon_train_checkpoint_step gauge
tpumon_train_checkpoint_step {max(0, (step // 100) * 100)}
"""


@dataclass
class ServingCollector:
    targets: tuple[str, ...] = ()
    name: str = "serving"
    timeout_s: float = 3.0
    _prev: dict[str, dict] = field(default_factory=dict)

    def _fetch(self, url: str) -> str:
        if url == "fake:trainer":
            return _fake_train_exposition()
        if url.startswith("fake:"):
            return _fake_exposition()
        if not url.startswith(("http://", "https://")):
            url = f"http://{url}"
        if not url.rstrip("/").endswith("/metrics"):
            url = url.rstrip("/") + "/metrics"
        with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
            return r.read().decode("utf-8", errors="replace")

    async def _collect_one(self, target: str) -> dict:
        try:
            text = await asyncio.to_thread(self._fetch, target)
            distilled = distill_serving_metrics(text, prev=self._prev.get(target))
            self._prev[target] = distilled
            return {"target": target, "ok": True, **distilled}
        except Exception as e:
            return {
                "target": target,
                "ok": False,
                "error": f"{type(e).__name__}: {e}",
            }

    async def collect(self) -> Sample:
        if not self.targets:
            return Sample(
                source=self.name, ok=True, data=[], error="no serving targets configured"
            )
        results = await asyncio.gather(*(self._collect_one(t) for t in self.targets))
        ok = all(r.get("ok") for r in results)
        errors = "; ".join(
            f"{r['target']}: {r['error']}" for r in results if not r.get("ok")
        )
        return Sample(source=self.name, ok=ok, data=list(results), error=errors or None)
