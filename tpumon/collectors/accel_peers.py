"""Peer federation: merge chips from other tpumon instances.

The reference is strictly single-host for realtime metrics — multi-node
visibility exists only through Prometheus aggregation of per-node
exporters (SURVEY §2.5). tpumon keeps that path (PromQL over per-host
`tpu_*` series) **and** adds a realtime one: an instance configured with
``peers`` fetches each peer's chip snapshot in parallel and merges their
chips with its own, so one dashboard shows a whole v5p slice live with
per-chip resolution and no Prometheus in the loop (BASELINE config 5).

Scaling (docs/perf.md): the fan-out is bounded (``fanout`` worker
threads in flight at once — a 64-peer fleet must not spawn 64 threads
per tick), each peer is fetched over the compact columnar wire format
(``/api/accel/wire``, tpumon.topology.chips_to_wire — positional rows
instead of per-chip key/value dicts), and the merge is incremental
per-peer: parsed chips are kept per peer and each tick revalidates them
with ``If-None-Match`` against the peer's epoch ETag, so a peer whose
accel section did not change between ticks costs a 304 and zero
re-parsing instead of a full payload. Peers predating the wire route
are detected once (404) and fetched via ``/api/accel/metrics`` forever
after — mixed-version fleets federate fine.

Fan-out budgeting: ``peer_timeout_s`` is the whole fan-out's wall
budget, and every peer gets an **independent deadline slice** of it
(budget / number-of-waves, clamped to what remains of the budget when
its turn comes) — one hung peer burns only its own slice, never the
window the peers queued behind it needed. Fetches also reuse
**keep-alive connections** across ticks (the tpumon server honors
``Connection: keep-alive``): the steady-state revalidation poll costs
one request on a warm socket, not a TCP handshake per peer per tick;
a stale socket (server restarted, idle timeout) retries once on a
fresh connection before the peer counts as down.

Peer chips keep their original chip_id/host/slice identity; cumulative
ICI counters survive the merge, so the local sampler computes peer ICI
rates exactly as it does for local chips. An unreachable peer degrades
that peer only (its chips drop out, which is precisely what slice-failure
alerting should see).
"""

from __future__ import annotations

import asyncio
import http.client
import json
import random
import urllib.parse
from dataclasses import dataclass, field

from tpumon.collectors import Collector, Sample
from tpumon.resilience import decorrelated_jitter
from tpumon.protowire import (
    WIRE_FRAME_CTYPE,
    WIRE_FRAME_MAGIC,
    decode_wire_frame,
)
from tpumon.topology import (
    WIRE_VERSION,
    ChipSample,
    chips_from_columns,
    wire_columns,
)
from tpumon.tracing import current_ctx_header


# Down-peer retry pacing (decorrelated jitter, tpumon.resilience): a
# failed peer is NOT re-fetched every tick — each failure schedules the
# next attempt uniform-at-random up to 3x the previous delay, capped
# fleet-safe so a recovered peer is rediscovered within ~PEER_RETRY_CAP_S
# worst case. Without this, 64 monitors polling a restarted peer hammer
# it in lockstep on its first healthy tick (the reconnect stampede).
PEER_RETRY_BASE_S = 0.5
PEER_RETRY_CAP_S = 8.0


def normalize_base_url(url: str) -> str:
    """`host:port` or full URL → scheme-qualified base with no trailing slash."""
    base = url if url.startswith(("http://", "https://")) else f"http://{url}"
    return base.rstrip("/")


def chip_from_json(d: dict) -> ChipSample:
    """Inverse of ChipSample.to_json (hbm_pct and rates are derived)."""
    return ChipSample(
        chip_id=d["chip"],
        host=d.get("host", ""),
        slice_id=d.get("slice", "slice-0"),
        index=int(d.get("index", 0)),
        kind=d.get("kind", "unknown"),
        coords=tuple(d.get("coords") or ()),
        mxu_duty_pct=d.get("mxu_duty_pct"),
        hbm_used=d.get("hbm_used"),
        hbm_total=d.get("hbm_total"),
        temp_c=d.get("temp_c"),
        ici_tx_bytes=d.get("ici_tx_bytes"),
        ici_rx_bytes=d.get("ici_rx_bytes"),
        ici_link_up=d.get("ici_link_up"),
        ici_link_health=d.get("ici_link_health"),
        throttle_score=d.get("throttle_score"),
        counter_source=d.get("counter_source"),
        # Pre-accel_kind peers omit the key: their chips read as TPU
        # (the pre-upgrade meaning of every chip in the fleet).
        accel_kind=d.get("accel_kind") or "tpu",
    )


@dataclass
class PeerFederatedCollector:
    """Wraps a local accel collector and merges peer instances' chips."""

    local: Collector | None
    peers: tuple[str, ...] = ()
    name: str = "accel"
    timeout_s: float = 3.0
    # At most this many peer fetches (worker threads) in flight at once
    # (Config.peer_fanout).
    fanout: int = 16
    # Ask peers for the columnar binary frame (Accept:
    # application/x-tpumon-wire). The response is sniffed, not assumed:
    # a pre-binary peer ignores the Accept header and answers JSON,
    # which parses exactly as before — negotiation costs nothing.
    wire_binary: bool = True
    last_peer_status: dict[str, str] = field(default_factory=dict)
    # Event journal (tpumon.events), wired by the sampler: peer up/down
    # and wire-fallback transitions become durable ``peer`` events.
    journal: object = field(default=None, repr=False)

    def set_journal(self, journal) -> None:
        self.journal = journal

    def _state(self) -> dict:
        """Per-peer incremental-merge state, created lazily so tests
        that build the collector without __init__ still work:
        etags (last seen epoch ETag), chips (last parsed list, reused
        verbatim on 304), wire (peer speaks /api/accel/wire), conns
        (keep-alive HTTP connections reused across ticks)."""
        st = self.__dict__.get("_peer_state")
        if st is None:
            st = self.__dict__["_peer_state"] = {
                "etags": {},
                "chips": {},
                "wire": {},
                "conns": {},
                # journal-transition tracking: last ok/err per peer and
                # which peers' wire-fallback has already been recorded
                "ok": {},
                "wire_logged": set(),
                # down-peer retry gates: url -> (loop.time to retry at,
                # previous backoff delay) — decorrelated jitter
                "retry": {},
                "rng": random.Random(),
            }
        return st

    def _drop_conn(self, url: str) -> None:
        conn = self._state()["conns"].pop(url, None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    def _request(
        self, url: str, path: str, headers: dict, timeout_s: float
    ) -> tuple[int, bytes, object]:
        """One GET on the peer's keep-alive connection; returns
        (status, body, response headers). A REUSED socket that fails
        before any response (peer restarted, idle-closed) retries once
        on a fresh connection — a cold-connection failure or a timeout
        propagates immediately (retrying a timeout would double the
        peer's deadline slice)."""
        conns = self._state()["conns"]
        base = normalize_base_url(url)
        parts = urllib.parse.urlsplit(base)
        for attempt in (0, 1):
            conn = conns.get(url)
            if conn is None:
                cls = (
                    http.client.HTTPSConnection
                    if parts.scheme == "https"
                    else http.client.HTTPConnection
                )
                conn = conns[url] = cls(
                    parts.hostname, parts.port, timeout=timeout_s
                )
            reused = conn.sock is not None
            if reused:
                conn.sock.settimeout(timeout_s)
            else:
                conn.timeout = timeout_s
            try:
                conn.request(
                    "GET", path, headers={"Connection": "keep-alive", **headers}
                )
                r = conn.getresponse()
                body = r.read()
            except (TimeoutError, OSError, http.client.HTTPException) as e:
                self._drop_conn(url)
                stale = reused and isinstance(
                    e,
                    (
                        http.client.BadStatusLine,
                        http.client.CannotSendRequest,
                        ConnectionResetError,
                        BrokenPipeError,
                    ),
                )
                if attempt == 0 and stale:
                    continue  # stale keep-alive socket: one fresh retry
                raise
            if r.will_close:
                self._drop_conn(url)
            return r.status, body, r.headers
        raise RuntimeError("unreachable")  # pragma: no cover

    def _fetch_peer(self, url: str, timeout_s: float | None = None) -> list[ChipSample]:
        """Blocking fetch+parse of one peer (runs on a worker thread)
        within its deadline slice. 304 returns the peer's cached parsed
        chips untouched. Wire fetches ask for the binary frame via
        Accept and sniff the response — binary-speaking peers answer
        the columnar frame (decoded straight to columns, zero per-chip
        dicts), JSON-only peers answer JSON and parse exactly as
        before."""
        timeout_s = self.timeout_s if timeout_s is None else timeout_s
        st = self._state()
        use_wire = st["wire"].get(url, True)
        path = "/api/accel/wire" if use_wire else "/api/accel/metrics"
        headers = {}
        etag = st["etags"].get(url)
        if etag:
            headers["If-None-Match"] = etag
        if use_wire and self.wire_binary:
            headers["Accept"] = WIRE_FRAME_CTYPE
        # Fleet tracing: when this fetch runs inside a fleet-traced
        # span, the peer joins the same trace (its http span remote-
        # parents onto ours). Absent otherwise — no bytes added.
        trace_hdr = current_ctx_header()
        if trace_hdr:
            headers["X-Tpumon-Trace"] = trace_hdr
        status, body, rheaders = self._request(url, path, headers, timeout_s)
        if status == 304:
            return st["chips"].get(url, [])
        if status == 404 and use_wire:
            # Pre-wire peer: remember and fall back to the dict route.
            st["wire"][url] = False
            st["etags"].pop(url, None)
            return self._fetch_peer(url, timeout_s)
        if status != 200:
            raise RuntimeError(f"peer answered HTTP {status}")
        new_etag = rheaders.get("ETag")
        if use_wire:
            try:
                if body[: len(WIRE_FRAME_MAGIC)] == WIRE_FRAME_MAGIC:
                    v, fields, cols = decode_wire_frame(body)
                    if v != WIRE_VERSION:
                        raise ValueError(f"wire version {v}")
                    chips = chips_from_columns(fields, cols)
                else:
                    chips = chips_from_columns(*wire_columns(json.loads(body)))
            except ValueError:
                # Incompatible WIRE_VERSION from a future peer: fall
                # back to the stable dict route, like the 404 path.
                st["wire"][url] = False
                st["etags"].pop(url, None)
                return self._fetch_peer(url, timeout_s)
        else:
            chips = [
                chip_from_json(d) for d in json.loads(body).get("chips", [])
            ]
        if new_etag:
            st["etags"][url] = new_etag
        st["chips"][url] = chips
        return chips

    def _journal_peer(self, url: str, ok: bool, st: dict) -> None:
        """Record peer up/down + wire-fallback TRANSITIONS (never the
        steady state) — runs on the event loop after the fan-out, so
        journal appends don't happen from fetch worker threads."""
        if self.journal is None:
            st["ok"][url] = ok
            return
        was = st["ok"].get(url)
        if not ok and was is not False:
            self.journal.record(
                "peer", "serious", url,
                f"peer down: {self.last_peer_status.get(url, 'unreachable')}"
                + (" (its chips drop from the merged view)" if was else ""),
            )
        elif ok and was is False:
            self.journal.record("peer", "info", url, "peer recovered")
        st["ok"][url] = ok
        if st["wire"].get(url) is False and url not in st["wire_logged"]:
            st["wire_logged"].add(url)
            self.journal.record(
                "peer", "minor", url,
                "pre-wire peer: fell back to /api/accel/metrics "
                "(full-dict fetches from now on)",
            )

    async def _peer_chips(
        self, url: str, timeout_s: float | None = None
    ) -> tuple[str, list[ChipSample] | None]:
        try:
            return url, await asyncio.to_thread(self._fetch_peer, url, timeout_s)
        except Exception as e:
            self.last_peer_status[url] = f"{type(e).__name__}: {e}"
            return url, None

    async def collect(self) -> Sample:
        fanout = max(1, getattr(self, "fanout", 16))
        sem = asyncio.Semaphore(fanout)
        # Independent deadline slices: timeout_s is the WHOLE fan-out's
        # wall budget. With W waves of `fanout` concurrent fetches each
        # peer's slice is budget/W, clamped to what's left of the
        # budget when its slot frees up — a hung peer eats only its own
        # slice, and a backlogged tick fails the stragglers fast
        # instead of letting the fan-out overhang into the next tick.
        budget = max(0.1, self.timeout_s)
        waves = max(1, -(-len(self.peers) // fanout))
        slice_s = budget / waves
        loop = asyncio.get_running_loop()
        t_deadline = loop.time() + budget
        st_retry = self._state()["retry"]
        rng = self._state()["rng"]

        async def bounded(url: str) -> tuple[str, list[ChipSample] | None]:
            gate = st_retry.get(url)
            if gate is not None and loop.time() < gate[0]:
                # Down peer inside its jittered retry window: skip the
                # fetch entirely (its last error stands) — the herd
                # control that keeps a fleet from re-polling a dead
                # peer in lockstep every tick.
                return url, None
            async with sem:
                remaining = t_deadline - loop.time()
                if remaining <= 0.01:
                    self.last_peer_status[url] = "fan-out budget exhausted"
                    return url, None
                res = await self._peer_chips(url, min(slice_s, remaining))
                if res[1] is None:
                    prev = gate[1] if gate is not None else 0.0
                    delay = decorrelated_jitter(
                        prev, base_s=PEER_RETRY_BASE_S,
                        cap_s=PEER_RETRY_CAP_S, rng=rng,
                    )
                    st_retry[url] = (loop.time() + delay, delay)
                else:
                    st_retry.pop(url, None)
                return res

        tasks = [asyncio.ensure_future(bounded(u)) for u in self.peers]
        local_sample = None
        if self.local is not None:
            local_sample = await self.local.collect()

        # Fetch AND parse run inside each worker thread, so peers'
        # parse work already overlaps; gather just collects the
        # (url, chips) results.
        by_url: dict[str, list[ChipSample] | None] = dict(
            await asyncio.gather(*tasks)
        )

        chips: list[ChipSample] = []
        errors: list[str] = []
        if local_sample is not None:
            chips.extend(local_sample.data or [])
            if local_sample.error:
                errors.append(f"local: {local_sample.error}")
        seen = {c.chip_id for c in chips}
        st = self._state()
        # Assemble in configured peer order (stable chip ordering keeps
        # the SSE delta stream's positional list patches small).
        for url in self.peers:
            peer_chips = by_url.get(url)
            self._journal_peer(url, peer_chips is not None, st)
            if peer_chips is None:
                errors.append(f"peer {url}: {self.last_peer_status.get(url)}")
                continue
            self.last_peer_status[url] = "ok"
            for c in peer_chips:
                if c.chip_id not in seen:  # local identity wins on overlap
                    chips.append(c)
                    seen.add(c.chip_id)
        return Sample(
            source=self.name,
            ok=not errors,
            data=chips,
            error="; ".join(errors) or None,
        )
