"""Peer federation: merge chips from other tpumon instances.

The reference is strictly single-host for realtime metrics — multi-node
visibility exists only through Prometheus aggregation of per-node
exporters (SURVEY §2.5). tpumon keeps that path (PromQL over per-host
`tpu_*` series) **and** adds a realtime one: an instance configured with
``peers`` fetches each peer's ``/api/accel/metrics`` in parallel and
merges their chips with its own, so one dashboard shows a whole v5p
slice live with per-chip resolution and no Prometheus in the loop
(BASELINE config 5).

Peer chips keep their original chip_id/host/slice identity; cumulative
ICI counters survive the merge, so the local sampler computes peer ICI
rates exactly as it does for local chips. An unreachable peer degrades
that peer only (its chips drop out, which is precisely what slice-failure
alerting should see).
"""

from __future__ import annotations

import asyncio
import json
import urllib.request
from dataclasses import dataclass, field

from tpumon.collectors import Collector, Sample
from tpumon.topology import ChipSample


def normalize_base_url(url: str) -> str:
    """`host:port` or full URL → scheme-qualified base with no trailing slash."""
    base = url if url.startswith(("http://", "https://")) else f"http://{url}"
    return base.rstrip("/")


def chip_from_json(d: dict) -> ChipSample:
    """Inverse of ChipSample.to_json (hbm_pct and rates are derived)."""
    return ChipSample(
        chip_id=d["chip"],
        host=d.get("host", ""),
        slice_id=d.get("slice", "slice-0"),
        index=int(d.get("index", 0)),
        kind=d.get("kind", "unknown"),
        coords=tuple(d.get("coords") or ()),
        mxu_duty_pct=d.get("mxu_duty_pct"),
        hbm_used=d.get("hbm_used"),
        hbm_total=d.get("hbm_total"),
        temp_c=d.get("temp_c"),
        ici_tx_bytes=d.get("ici_tx_bytes"),
        ici_rx_bytes=d.get("ici_rx_bytes"),
        ici_link_up=d.get("ici_link_up"),
        ici_link_health=d.get("ici_link_health"),
        throttle_score=d.get("throttle_score"),
    )


@dataclass
class PeerFederatedCollector:
    """Wraps a local accel collector and merges peer instances' chips."""

    local: Collector | None
    peers: tuple[str, ...] = ()
    name: str = "accel"
    timeout_s: float = 3.0
    last_peer_status: dict[str, str] = field(default_factory=dict)

    def _fetch_peer(self, url: str) -> list[dict]:
        base = normalize_base_url(url)
        with urllib.request.urlopen(
            f"{base}/api/accel/metrics", timeout=self.timeout_s
        ) as r:
            return json.load(r).get("chips", [])

    async def _peer_chips(self, url: str) -> tuple[str, list[ChipSample] | None]:
        try:
            raw = await asyncio.to_thread(self._fetch_peer, url)
            return url, [chip_from_json(d) for d in raw]
        except Exception as e:
            self.last_peer_status[url] = f"{type(e).__name__}: {e}"
            return url, None

    async def collect(self) -> Sample:
        tasks = [self._peer_chips(u) for u in self.peers]
        local_sample = None
        if self.local is not None:
            local_sample = await self.local.collect()
        peer_results = await asyncio.gather(*tasks)

        chips: list[ChipSample] = []
        errors: list[str] = []
        if local_sample is not None:
            chips.extend(local_sample.data or [])
            if local_sample.error:
                errors.append(f"local: {local_sample.error}")
        seen = {c.chip_id for c in chips}
        for url, peer_chips in peer_results:
            if peer_chips is None:
                errors.append(f"peer {url}: {self.last_peer_status.get(url)}")
                continue
            self.last_peer_status[url] = "ok"
            for c in peer_chips:
                if c.chip_id not in seen:  # local identity wins on overlap
                    chips.append(c)
                    seen.add(c.chip_id)
        return Sample(
            source=self.name,
            ok=not errors,
            data=chips,
            error="; ".join(errors) or None,
        )
