"""Fake GPU accelerator source (ISSUE 15 / ROADMAP item 5).

The GPU twin of tpumon.collectors.accel_fake: synthetic per-GPU
ChipSamples in DGX-node shapes (single-node ``dgx-a100-8`` /
``dgx-h100-8``, multi-node ``superpod-32``) so the whole
accelerator-generic pipeline — wire, federation, queries `by (accel)`,
exporter `accel` label, dashboard — is testable with zero GPUs. This is
the reference's own scenario (an NVIDIA host fleet,
monitor_server.js:83-95) readmitted as the second accelerator family
behind the same ChipSample normalization:

    SM util %        -> mxu_duty_pct
    VRAM used/total  -> hbm_used / hbm_total
    NVLink tx/rx     -> ici_tx_bytes / ici_rx_bytes
    NVLink/XID state -> ici_link_up / ici_link_health

Deterministic given (topology, time), same fault-injection hooks
(``kill_host`` / ``set_override`` / ``fault_episodes``) as the TPU
fake, so every existing soak pattern ports over unchanged.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field

from tpumon.collectors import Sample
from tpumon.topology import ChipSample

# topology name -> (kind, n_hosts, gpus_per_host, hosts_per_slice).
# Same tuple contract as accel_fake.FAKE_TOPOLOGIES; a "slice" for the
# GPU family is the scheduling partition (the node for a single DGX,
# a rail-aligned node group in a SuperPOD) — the federation rollup key.
GPU_FAKE_TOPOLOGIES: dict[str, tuple[str, int, int, int]] = {
    "dgx-a100-8": ("a100", 1, 8, 1),
    "dgx-h100-8": ("h100", 1, 8, 1),
    # Multi-node shape: 4 DGX H100 nodes, 2-node partitions — two
    # slices (slice-0.0 / slice-0.1) so group-by-slice rollups and the
    # dark-node soak have real GPU values to chew on.
    "superpod-32": ("h100", 4, 8, 2),
}

# VRAM bytes per GPU by kind (SXM parts: A100 80 GiB, H100 80 GiB).
VRAM_BYTES_BY_KIND: dict[str, int] = {
    "a100": 80 * 1024**3,
    "h100": 80 * 1024**3,
}


@dataclass
class FakeGpuCollector:
    """Synthetic GPU metrics for a named DGX/SuperPOD topology."""

    topology: str = "dgx-a100-8"
    # Distinct default namespace from the TPU fake's "slice-0": a GPU
    # partition is not part of a TPU slice, and an aggregator merging
    # both families' chips into its local view must not collapse them
    # into one mixed rollup.
    slice_id: str = "gpu-0"
    host_prefix: str = "gpu-node"
    name: str = "accel"
    clock: object = time.time  # injectable for deterministic tests
    dead_hosts: set[str] = field(default_factory=set)
    overrides: dict[str, dict] = field(default_factory=dict)
    # Periodic fault episodes (demo mode, `gpufake:<topo>+faults`):
    # one GPU's NVLink degrades for ~60s every ~8 min — the same
    # cadence as the TPU fake so mixed demos degrade in both families.
    fault_episodes: bool = False

    def __post_init__(self) -> None:
        if self.topology not in GPU_FAKE_TOPOLOGIES:
            raise ValueError(
                f"unknown fake GPU topology {self.topology!r}; "
                f"known: {sorted(GPU_FAKE_TOPOLOGIES)}"
            )

    # -- fault injection -------------------------------------------------
    def kill_host(self, host: str) -> None:
        self.dead_hosts.add(host)

    def revive_host(self, host: str) -> None:
        self.dead_hosts.discard(host)

    def set_override(self, chip_id: str, **fields) -> None:
        self.overrides.setdefault(chip_id, {}).update(fields)

    # --------------------------------------------------------------------
    def chips(self) -> list[ChipSample]:
        kind, n_hosts, per_host, hosts_per_slice = GPU_FAKE_TOPOLOGIES[
            self.topology
        ]
        multi_slice = hosts_per_slice < n_hosts
        vram_total = VRAM_BYTES_BY_KIND[kind]
        t = self.clock()
        out: list[ChipSample] = []
        for h in range(n_hosts):
            host = f"{self.host_prefix}-{h}"
            if host in self.dead_hosts:
                continue
            slice_id = (
                f"{self.slice_id}.{h // hosts_per_slice}"
                if multi_slice
                else self.slice_id
            )
            for i in range(per_host):
                g = h * per_host + i
                phase = 0.9 * g
                # GPU workloads swing harder than TPU pods (per-node
                # jobs come and go); different periods keep mixed
                # fleets visually distinguishable in demos.
                duty = 60 + 30 * math.sin(t / 29 + phase) + 5 * math.sin(t / 7 + g)
                vram_frac = 0.6 + 0.3 * math.sin(t / 47 + phase / 2)
                temp = 40 + 25 * (duty / 100) + 2 * math.sin(t / 61 + g)
                # Cumulative NVLink counters: closed-form integral of a
                # smooth ~1.5 GB/s rate, consistent between samples.
                cumulative = int(1.5e9 * (t + 37 * (1 - math.cos(t / 37 + phase))))
                link_health = 0
                if self.fault_episodes and g == 3 and (t % 480) < 60:
                    link_health = 7  # persistent NVLink problem -> serious
                sample = ChipSample(
                    chip_id=f"{host}/gpu-{i}",
                    host=host,
                    slice_id=slice_id,
                    index=i,
                    kind=kind,
                    coords=(i, h, 0),
                    mxu_duty_pct=max(0.0, min(100.0, duty)),
                    hbm_used=int(vram_total * max(0.02, min(0.98, vram_frac))),
                    hbm_total=vram_total,
                    temp_c=round(temp, 1),
                    ici_tx_bytes=cumulative,
                    ici_rx_bytes=int(cumulative * 0.95),
                    ici_link_up=True,
                    ici_link_health=link_health,
                    accel_kind="gpu",
                )
                ov = self.overrides.get(sample.chip_id)
                if ov:
                    sample = ChipSample(**{**sample.__dict__, **ov})
                out.append(sample)
        return out

    async def collect(self) -> Sample:
        return Sample(source=self.name, ok=True, data=self.chips())
