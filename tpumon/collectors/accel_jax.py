"""Real TPU accelerator collector: JAX device enumeration + libtpu metrics.

Replaces the reference's GPU collector (``nvidia-smi`` shell-out +
CSV parse, monitor_server.js:83-95) with two in-process sources merged
per chip:

1. **Identity & topology** — ``jax.local_devices()``: chip kind, index,
   coords, process/slice membership. Always available when JAX can see
   the chip.
2. **Counters** — in preference order:
   a. in-process libtpu SDK (tpumon.collectors.libtpu_sdk): duty cycle,
      HBM, **ICI link health**, throttle score, HLO-queue/latency extras
      — richest source, verified available on real hardware
      (PROBE_libtpu.md).
   b. libtpu runtime-metrics gRPC (tpumon.collectors.libtpu_grpc): HBM
      used/total + TensorCore duty cycle — the tpu-info data path.
   c. ``device.memory_stats()`` (PJRT): HBM bytes_in_use / bytes_limit.
   d. nothing — fields stay None and the sample is marked degraded.

Temperature: no TPU platform surface exposes it (no SDK metric, no
hwmon — PROBE_libtpu.md finding #4), so ``temp_c`` is None here and the
absence is declared via the sample's note (surfaced in /api/health and
the dashboard health strip). Throttle score is the thermal proxy.

JAX import and device enumeration happen lazily on first collect (in a
thread, since backend init can take seconds) and are cached; per-sample
work is the gRPC round-trip / memory_stats call only.
"""

from __future__ import annotations

import asyncio
import os
import socket
from dataclasses import dataclass, field

from tpumon.collectors import Sample
from tpumon.collectors.libtpu_grpc import LibtpuMetricsClient
from tpumon.collectors.libtpu_sdk import LibtpuSdkSource, SdkSnapshot
from tpumon.collectors.workload import WorkloadFileSource
from tpumon.topology import HBM_BYTES_BY_KIND, ChipSample, normalize_chip_kind

#: Health-strip note attached to every real-hardware accel sample: the
#: platform exposes no temperature metric (PROBE_libtpu.md finding #4).
TEMP_UNAVAILABLE_NOTE = (
    "temp_c unavailable: no TPU platform temperature source "
    "(PROBE_libtpu.md); throttle_score is the thermal proxy"
)


@dataclass
class JaxTpuCollector:
    name: str = "accel"
    slice_id: str | None = None  # default: derived from env / "slice-0"
    hostname: str | None = None
    libtpu_addr: str = "localhost:8431"
    # Directory workloads self-report into (tpumon.collectors.workload);
    # None disables the source.
    workload_dir: str | None = None
    # JAX backend init can hang indefinitely when the device runtime is
    # wedged (e.g. a lost remote-device grant); a monitor must degrade,
    # not hang with it.
    init_timeout_s: float = 60.0

    _devices: list | None = field(default=None, repr=False)
    _client: LibtpuMetricsClient | None = field(default=None, repr=False)
    _sdk: LibtpuSdkSource | None = field(default=None, repr=False)
    _libtpu_ok: bool | None = field(default=None, repr=False)
    _sdk_ok: bool | None = field(default=None, repr=False)
    _init_error: str | None = field(default=None, repr=False)
    _collects: int = field(default=0, repr=False)
    _reprobe_task: object | None = field(default=None, repr=False)
    #: Slice-level SDK extras (HLO queue sizes, transfer/collective
    #: latency percentiles) from the last successful SDK snapshot;
    #: the server surfaces these under /api/accel/metrics -> "runtime".
    last_extras: dict = field(default_factory=dict, repr=False)

    # Re-probe a missing libtpu metrics service every N collects: the
    # service only exists once a workload initializes libtpu, which may
    # happen long after the monitor starts.
    LIBTPU_REPROBE_EVERY: int = 30

    def __post_init__(self) -> None:
        self.hostname = self.hostname or socket.gethostname()
        if self.slice_id is None:
            # GKE TPU podslice pods carry these; fall back to a stable default.
            self.slice_id = (
                os.environ.get("TPU_SLICE_NAME")
                or os.environ.get("MEGASCALE_SLICE_ID")
                or "slice-0"
            )
        self._client = LibtpuMetricsClient(addr=self.libtpu_addr)
        self._sdk = LibtpuSdkSource()
        self._workload = (
            WorkloadFileSource(directory=self.workload_dir)
            if self.workload_dir
            else None
        )

    def _init_devices(self) -> list:
        """Blocking JAX init; run in a thread."""
        import jax

        return [d for d in jax.local_devices() if d.platform == "tpu"]

    async def _devices_cached(self) -> list:
        if self._devices is None and self._init_error is None:
            try:
                self._devices = await asyncio.wait_for(
                    asyncio.to_thread(self._init_devices),
                    timeout=self.init_timeout_s,
                )
            except asyncio.TimeoutError:
                # The init thread may never return; record the wedge and
                # stop waiting (the thread is daemonic via executor).
                self._init_error = (
                    f"JAX backend init hung >{self.init_timeout_s:.0f}s "
                    "(wedged device runtime?)"
                )
                self._devices = []
            except Exception as e:
                self._init_error = f"{type(e).__name__}: {e}"
                self._devices = []
        return self._devices or []

    def _kick_reprobe(self) -> None:
        """Re-probe dark counter sources off the tick path. The probe
        runs as a fire-and-forget task; if a source answers, its ok-flag
        resets to None so the next tick adopts it inline."""
        task = self._reprobe_task
        if task is not None and not task.done():
            return

        async def probe() -> None:
            if self._sdk_ok is False:
                if await self._sdk.snapshot() is not None:
                    self._sdk_ok = None
            if self._libtpu_ok is False:
                if await self._client.snapshot() is not None:
                    self._libtpu_ok = None

        self._reprobe_task = asyncio.create_task(probe())

    async def probe_sources(self) -> dict[str, dict]:
        """Actively probe every counter source once and report, per
        source, whether it answered and why not (validate.py provenance
        — VERDICT r03 item #8: a future host with live libtpu counters
        must upgrade the evidence chain visibly, and a dark host must
        say per source WHY it is dark)."""
        out: dict[str, dict] = {}
        devices = await self._devices_cached()

        snap = await self._sdk.snapshot()
        out["sdk"] = {
            "live": snap is not None,
            "detail": (
                f"duty×{len(snap.duty_pct)} hbm×{len(snap.hbm_used)} "
                f"extras={sorted(snap.extras)}" if snap is not None
                else getattr(self._sdk, "last_error", None) or "no data"),
        }

        gsnap = await self._client.snapshot()
        out["grpc"] = {
            "live": gsnap is not None,
            "detail": (
                f"{getattr(self._client, 'addr', '?')}: "
                f"hbm×{len(gsnap['hbm_used'])} "
                f"duty×{len(gsnap['duty_pct'])}" if gsnap is not None
                else f"{getattr(self._client, 'addr', '?')}: "
                     f"{getattr(self._client, 'last_error', None) or 'no data'}"),
        }

        if not devices:
            out["pjrt"] = {"live": False,
                           "detail": self._init_error or "no devices"}
        else:
            stats = None
            err = None
            try:
                stats = await asyncio.to_thread(devices[0].memory_stats)
            except Exception as e:
                err = f"memory_stats: {type(e).__name__}: {str(e)[:120]}"
            live = bool(stats) and stats.get("bytes_in_use") is not None
            out["pjrt"] = {
                "live": live,
                "detail": (
                    f"{len(devices)} device(s); memory_stats keys: "
                    f"{sorted(stats)[:6]}" if live else
                    err or f"{len(devices)} device(s); memory_stats "
                           f"{'empty' if not stats else 'lacks bytes_in_use'}"),
            }

        if self._workload is None:
            out["workload"] = {"live": False,
                               "detail": "disabled (no workload_dir)"}
        else:
            wsnap = await asyncio.to_thread(self._workload.snapshot)
            out["workload"] = {
                "live": bool(wsnap),
                "detail": (
                    f"{self._workload.directory}: {len(wsnap)} device "
                    f"entr{'y' if len(wsnap) == 1 else 'ies'}" if wsnap
                    else f"{self._workload.directory}: no fresh reports"),
            }
        return out

    async def collect(self) -> Sample:
        devices = await self._devices_cached()
        if not devices:
            return Sample(
                source=self.name,
                ok=False,
                data=[],
                error=self._init_error or "no local TPU devices visible to JAX",
            )

        # Counter sources, preference order (a) SDK, (b) gRPC. On a miss,
        # skip on the tick path but keep re-probing in a *background* task
        # — either service appears when a workload initializes libtpu
        # in-process / on-host, but a dark source's probe cost (thread
        # hop + 12 SDK metric reads / a refused connect, all riding the
        # tunnel) must not land on the sampler tick (BENCH_r02's 3.6x
        # sampler-rate regression traced to exactly this).
        self._collects += 1
        if self._collects % self.LIBTPU_REPROBE_EVERY == 0:
            self._kick_reprobe()
        sdk_snap: SdkSnapshot | None = None
        if self._sdk_ok is not False:
            sdk_snap = await self._sdk.snapshot()
            self._sdk_ok = sdk_snap is not None
            # Extras mirror the *probed* state: cleared when the SDK stops
            # reporting so /api/accel "runtime" never serves a dead
            # workload's queue depths as current.
            self.last_extras = sdk_snap.extras if sdk_snap is not None else {}
        # The SDK may report only some families (empty duty/HBM maps) or
        # only some chips (gaps in a non-empty map); either way fall
        # through to the gRPC source per-field rather than gating the
        # whole probe on sdk_snap is None.
        local_idxs = [
            int(
                d.id
                if getattr(d, "local_hardware_id", None) is None
                else d.local_hardware_id
            )
            for d in devices
        ]
        sdk_partial = sdk_snap is not None and any(
            i not in sdk_snap.duty_pct or i not in sdk_snap.hbm_used
            for i in local_idxs
        )
        libtpu_snap = None
        if (sdk_snap is None or sdk_partial) and self._libtpu_ok is not False:
            libtpu_snap = await self._client.snapshot()
            self._libtpu_ok = libtpu_snap is not None

        # Counter source (d): workload self-reports, ranked below every
        # platform source. Read lazily — the directory is listed only if
        # some chip actually has a gap after the platform sources, so a
        # fully healthy SDK keeps the tick path file-IO-free.
        workload_snap: dict[int, dict] | None = None

        def workload_lookup(idx: int) -> dict | None:
            nonlocal workload_snap
            if self._workload is None:
                return None
            if workload_snap is None:
                workload_snap = self._workload.snapshot()
            return workload_snap.get(idx)

        chips: list[ChipSample] = []
        degraded: list[str] = []
        workload_names: list[str] = []
        for d, local_idx in zip(devices, local_idxs):
            kind = normalize_chip_kind(d.device_kind)
            hbm_used = hbm_total = None
            duty = None
            ici_health = throttle = None
            sources: list[str] = []  # provenance, in fill order
            if sdk_snap is not None:
                duty = sdk_snap.duty_pct.get(local_idx)
                hbm_used = sdk_snap.hbm_used.get(local_idx)
                hbm_total = sdk_snap.hbm_total.get(local_idx)
                ici_health = sdk_snap.ici_health.get(local_idx)
                # Links whose location string didn't carry a chipN token
                # roll up under -1; attribute that worst score to every
                # chip on this host (a bad link *somewhere* in the host's
                # ICI fabric degrades the whole slice's collectives) so
                # it can never be silently dropped.
                unattributed = sdk_snap.ici_health.get(-1)
                if unattributed is not None:
                    ici_health = max(ici_health or 0, unattributed)
                throttle = sdk_snap.throttle.get(local_idx)
                if duty is not None or hbm_used is not None:
                    sources.append("sdk")
            if libtpu_snap is not None:
                grpc_used = False
                if hbm_used is None:
                    hbm_used = libtpu_snap["hbm_used"].get(local_idx)
                    grpc_used = hbm_used is not None
                if hbm_total is None:
                    hbm_total = libtpu_snap["hbm_total"].get(local_idx)
                if duty is None:
                    duty = libtpu_snap["duty_pct"].get(local_idx)
                    grpc_used = grpc_used or duty is not None
                if grpc_used:
                    sources.append("grpc")
            if hbm_used is None:
                # Counter source (c): PJRT memory stats (process-local view).
                try:
                    ms = d.memory_stats()
                except Exception:
                    ms = None
                if ms:
                    hbm_used = ms.get("bytes_in_use")
                    hbm_total = ms.get("bytes_limit") or hbm_total
                    if hbm_used is not None:
                        sources.append("pjrt")
            wl = (
                workload_lookup(int(local_idx))
                if (hbm_used is None or duty is None)
                else None
            )
            if wl is not None:
                wl_used = False
                if hbm_used is None and wl["hbm_used"] is not None:
                    hbm_used = wl["hbm_used"]
                    wl_used = True
                if hbm_total is None and wl["hbm_total"] is not None:
                    hbm_total = wl["hbm_total"]
                if duty is None and wl["busy_frac"] is not None:
                    duty = round(100.0 * wl["busy_frac"], 1)
                    wl_used = True
                if wl_used:
                    sources.append("workload")
                    for name in wl.get("workloads", []):
                        if name not in workload_names:
                            workload_names.append(name)
            if hbm_total is None:
                hbm_total = HBM_BYTES_BY_KIND.get(kind)
            if hbm_used is None and duty is None:
                degraded.append(f"chip {local_idx}: no counter source")
            chips.append(
                ChipSample(
                    chip_id=f"{self.hostname}/chip-{local_idx}",
                    host=self.hostname,
                    slice_id=self.slice_id,
                    index=int(local_idx),
                    kind=kind,
                    coords=tuple(getattr(d, "coords", ()) or ()),
                    mxu_duty_pct=duty,
                    hbm_used=int(hbm_used) if hbm_used is not None else None,
                    hbm_total=int(hbm_total) if hbm_total is not None else None,
                    temp_c=None,  # no platform source (PROBE_libtpu.md #4)
                    ici_link_health=ici_health,
                    throttle_score=throttle,
                    # A chip's ICI is down iff any of its links scores 10
                    # ("link is not usable" per the SDK metric description).
                    ici_link_up=(ici_health < 10) if ici_health is not None else None,
                    counter_source="+".join(sources) or None,
                )
            )
        notes = [TEMP_UNAVAILABLE_NOTE]
        if workload_names:
            notes.append(
                "duty/HBM include workload self-reports "
                f"(source: workload — {', '.join(sorted(workload_names))}); "
                "no platform counter source covers these fields on this host"
            )
        return Sample(
            source=self.name,
            ok=not degraded,
            data=chips,
            error=("; ".join(degraded) or None),
            notes=notes,
        )

    async def close(self) -> None:
        # Stop a pending background reprobe before closing the client it
        # may be about to use (and retrieve its exception, if any).
        task = self._reprobe_task
        if task is not None and not task.done():
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        if self._client is not None:
            await self._client.close()
