"""Collector layer (the reference's L1, SURVEY.md §1).

The reference collects synchronously per HTTP request — three blocking
``execSync`` shell-outs on the Node event loop (monitor_server.js:72,85,99).
tpumon collectors are instead invoked by a background sampler
(tpumon.sampler) on fixed cadences; each returns a Sample envelope that
carries explicit health (ok / error / latency) so degraded sources are
distinguishable from genuinely-empty data (SURVEY §7 "honest degraded
modes").

Collectors expose an async ``collect()``; anything that must block (file
IO is cheap enough inline; subprocess fallbacks use asyncio subprocesses)
must not stall the event loop.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Protocol, runtime_checkable

from tpumon.resilience import DEADLINE_ERROR, DeadlineExceeded, collect_bounded


@dataclass
class Sample:
    """Result envelope for one collection attempt."""

    source: str
    ok: bool
    data: Any
    error: str | None = None
    ts: float = field(default_factory=time.time)
    latency_ms: float = 0.0
    # Non-error caveats about the source (e.g. "temp_c unavailable on
    # this platform") — shown in /api/health and the dashboard health
    # strip without flipping ok to False.
    notes: list[str] = field(default_factory=list)

    def health_json(self) -> dict:
        return {
            "source": self.source,
            "ok": self.ok,
            "error": self.error,
            "ts": self.ts,
            "latency_ms": round(self.latency_ms, 3),
            "notes": self.notes,
        }


@runtime_checkable
class Collector(Protocol):
    name: str

    async def collect(self) -> Sample: ...


async def run_collector(
    c: Collector, deadline_s: float | None = None, orphans: dict | None = None
) -> Sample:
    """Invoke a collector, timing it and converting exceptions to a
    degraded Sample (the reference's silent-degradation contract,
    monitor_server.js:80,94,113 — but with the error recorded).

    With ``deadline_s``, the collect is wall-clock bounded
    (tpumon.resilience.collect_bounded): a hung collector degrades to an
    ``error="deadline exceeded"`` Sample at the deadline instead of
    blocking the sampler loop forever, and the orphaned task is
    cancelled/reaped so it cannot leak. ``orphans`` (caller-owned) caps
    a wedged source at one outstanding orphan — see collect_bounded.
    """
    t0 = time.monotonic()
    try:
        if deadline_s is not None and deadline_s > 0:
            s = await collect_bounded(c, deadline_s, orphans=orphans)
        else:
            s = await c.collect()
    except DeadlineExceeded as e:
        s = Sample(source=c.name, ok=False, data=None, error=f"{DEADLINE_ERROR}: {e}")
    except Exception as e:  # degrade, never crash the sampler
        s = Sample(source=c.name, ok=False, data=None, error=f"{type(e).__name__}: {e}")
    s.latency_ms = (time.monotonic() - t0) * 1e3
    return s
