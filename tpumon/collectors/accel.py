"""Accelerator collector factory.

Backend selection (Config.accel_backend):
- "auto": JaxTpuCollector if JAX reports TPU devices, else a disabled
  placeholder that reports no chips (the host-only config — the
  reference's "nvidia-smi absent => []" mode, monitor_server.js:94, but
  with the reason recorded).
- "jax": force the real collector.
- "fake:<topology>[@<host_prefix>][+faults]": synthetic chips (v5e-1 /
  v5e-8 / v5p-64 ...). The optional host prefix disambiguates chip
  identities when several fake-backed instances federate (real
  deployments get distinct identities from their hostnames); "+faults"
  enables periodic ICI-degradation/throttle episodes (demo mode).
- "gpufake:<topology>[@<host_prefix>][+faults]": synthetic GPU nodes
  (dgx-a100-8 / dgx-h100-8 / superpod-32) — the second accelerator
  family (ISSUE 15), same ChipSample normalization, accel_kind="gpu".
- "nvidia-smi[:<path>]": real GPU chips via the nvidia-smi CSV
  shell-out (the reference's L1b path, monitor_server.js:83-95).
- "dcgm:<url>": real GPU chips scraped from a DCGM exporter (the
  reference's L0 deployment path).
- "none": disabled.
"""

from __future__ import annotations

from dataclasses import dataclass

from tpumon.collectors import Collector, Sample
from tpumon.collectors.accel_fake import FakeTpuCollector
from tpumon.collectors.accel_jax import JaxTpuCollector
from tpumon.config import Config


@dataclass
class NullAccelCollector:
    name: str = "accel"
    reason: str = "accel collector disabled"

    async def collect(self) -> Sample:
        return Sample(source=self.name, ok=True, data=[], error=self.reason)


def make_accel_collector(cfg: Config) -> Collector:
    backend = cfg.accel_backend
    if backend == "none":
        local: Collector | None = None
    elif backend.startswith(("fake:", "gpufake:")):
        kind, spec = backend.split(":", 1)
        kw = {}
        if spec.endswith("+faults"):
            spec = spec[: -len("+faults")]
            kw["fault_episodes"] = True
        topology, _, prefix = spec.partition("@")
        if prefix:
            kw["host_prefix"] = prefix
        if kind == "gpufake":
            from tpumon.collectors.gpu_fake import FakeGpuCollector

            local = FakeGpuCollector(topology=topology, **kw)
        else:
            local = FakeTpuCollector(topology=topology, **kw)
    elif backend == "nvidia-smi" or backend.startswith("nvidia-smi:"):
        from tpumon.collectors.gpu import NvidiaSmiCollector

        _, _, smi_path = backend.partition(":")
        local = NvidiaSmiCollector(
            **({"smi_path": smi_path} if smi_path else {})
        )
    elif backend.startswith("dcgm:"):
        from tpumon.collectors.gpu import DcgmCollector

        local = DcgmCollector(url=backend.split(":", 1)[1])
    elif backend in ("auto", "jax"):
        local = JaxTpuCollector(workload_dir=cfg.workload_dir or None)
    else:
        raise ValueError(f"unknown accel backend {backend!r}")
    if cfg.peers:
        from tpumon.collectors.accel_peers import PeerFederatedCollector

        return PeerFederatedCollector(
            local=local,
            peers=cfg.peers,
            timeout_s=cfg.peer_timeout_s,
            fanout=cfg.peer_fanout,
            wire_binary=cfg.wire_binary,
        )
    if local is None:
        return NullAccelCollector(reason="accel backend 'none' configured")
    return local
