"""Kubernetes pod collector.

Reference parity (monitor_server.js:97-114 ``getK8sPods``): per pod
namespace, name, phase, restart count summed over containerStatuses
(:104), humanized age from status.startTime (:106-110). The reference
shells out ``execSync('kubectl get pods -A -o json')`` on the event loop
(:99) — SURVEY §2.1 flags a hung kubectl freezing the whole server.

tpumon talks to the Kubernetes API directly (in-cluster service-account
auth, or any configured API URL), with an *async subprocess* kubectl
fallback for dev boxes. Parsing is a pure function over the PodList JSON
so golden-input tests (SURVEY §4.1) cover containerStatuses edge cases.

TPU additions: each pod record carries slice/topology metadata when
present (GKE TPU nodeSelectors, JobSet labels) so the alert engine can
map pods -> slices (SURVEY §2.5 "pod-slice topology awareness").
"""

from __future__ import annotations

import asyncio
import datetime as dt
import json
import os
import ssl
import urllib.request
from dataclasses import dataclass

from tpumon.collectors import Sample

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# GKE TPU-related pod/node metadata keys (labels & nodeSelector).
TPU_TOPOLOGY_KEY = "cloud.google.com/gke-tpu-topology"
TPU_ACCEL_KEY = "cloud.google.com/gke-tpu-accelerator"
JOBSET_NAME_KEY = "jobset.sigs.k8s.io/jobset-name"
JOB_INDEX_KEY = "batch.kubernetes.io/job-completion-index"


def humanize_age(seconds: float) -> str:
    """Humanize like the reference (monitor_server.js:106-110): days if
    >=1d, hours if >=1h, else minutes."""
    if seconds >= 86400:
        return f"{int(seconds // 86400)}d"
    if seconds >= 3600:
        return f"{int(seconds // 3600)}h"
    return f"{max(0, int(seconds // 60))}m"


def _parse_k8s_time(text: str) -> float | None:
    try:
        return dt.datetime.fromisoformat(text.replace("Z", "+00:00")).timestamp()
    except (ValueError, AttributeError):
        return None


def parse_pod_list(obj: dict, now: float | None = None) -> list[dict]:
    """Pure parser over a K8s PodList JSON document."""
    now = dt.datetime.now(dt.timezone.utc).timestamp() if now is None else now
    pods: list[dict] = []
    for item in obj.get("items", []):
        meta = item.get("metadata", {}) or {}
        status = item.get("status", {}) or {}
        spec = item.get("spec", {}) or {}
        # Restarts summed over containerStatuses (monitor_server.js:104);
        # containerStatuses may be absent for Pending pods.
        restarts = sum(
            cs.get("restartCount", 0) for cs in status.get("containerStatuses") or []
        )
        start = _parse_k8s_time(status.get("startTime"))
        age_s = max(0.0, now - start) if start is not None else None
        labels = meta.get("labels") or {}
        node_selector = spec.get("nodeSelector") or {}
        phase = status.get("phase", "Unknown")
        # TPU chips requested (google.com/tpu), summed over containers —
        # the basis for pod->chip attribution in the accel view.
        tpu_request = 0
        for ctr in spec.get("containers") or []:
            res = ctr.get("resources") or {}
            v = (res.get("requests") or {}).get("google.com/tpu") or (
                res.get("limits") or {}
            ).get("google.com/tpu")
            try:
                tpu_request += int(v)
            except (TypeError, ValueError):
                pass
        # Surface container-level waiting/terminated reasons (CrashLoopBackOff,
        # OOMKilled, ...) the reference can't see — it only looks at phase.
        reason = status.get("reason")
        for cs in status.get("containerStatuses") or []:
            state = cs.get("state") or {}
            last_state = cs.get("lastState") or {}
            waiting = state.get("waiting") or {}
            terminated = state.get("terminated") or last_state.get("terminated") or {}
            if waiting.get("reason"):
                reason = waiting["reason"]
                break
            term_reason = terminated.get("reason")
            if term_reason and term_reason != "Completed":
                reason = term_reason
                break
        pods.append(
            {
                "namespace": meta.get("namespace", ""),
                "name": meta.get("name", ""),
                "status": phase,
                "reason": reason,
                "restarts": restarts,
                "age": humanize_age(age_s) if age_s is not None else "",
                "age_s": age_s,
                "node": spec.get("nodeName"),
                "tpu_request": tpu_request,
                "tpu_topology": node_selector.get(TPU_TOPOLOGY_KEY),
                "tpu_accelerator": node_selector.get(TPU_ACCEL_KEY),
                "jobset": labels.get(JOBSET_NAME_KEY),
                "job_index": labels.get(JOB_INDEX_KEY),
            }
        )
    return pods


# --------------------------------------------------------------------------
# Pod sources
# --------------------------------------------------------------------------


@dataclass
class ApiPodSource:
    """Reads /api/v1/pods from a Kubernetes API server.

    In-cluster: uses the mounted service-account token + CA. Out of
    cluster: any api_url (e.g. a `kubectl proxy` or a test fake) works
    unauthenticated over http.
    """

    api_url: str | None = None
    timeout_s: float = 5.0

    def _resolve(self) -> tuple[str, dict[str, str], ssl.SSLContext | None]:
        if self.api_url:
            return self.api_url, {}, None
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            raise RuntimeError("not in-cluster (KUBERNETES_SERVICE_HOST unset)")
        headers = {}
        token_path = os.path.join(SA_DIR, "token")
        if os.path.exists(token_path):
            with open(token_path) as f:
                headers["Authorization"] = f"Bearer {f.read().strip()}"
        ctx = None
        ca_path = os.path.join(SA_DIR, "ca.crt")
        if os.path.exists(ca_path):
            ctx = ssl.create_default_context(cafile=ca_path)
        return f"https://{host}:{port}", headers, ctx

    def _fetch(self) -> dict:
        base, headers, ctx = self._resolve()
        req = urllib.request.Request(f"{base}/api/v1/pods", headers=headers)
        with urllib.request.urlopen(req, timeout=self.timeout_s, context=ctx) as r:
            return json.load(r)

    async def fetch_pod_list(self) -> dict:
        return await asyncio.to_thread(self._fetch)


@dataclass
class KubectlPodSource:
    """Async-subprocess kubectl fallback (never blocks the event loop,
    unlike the reference's execSync at monitor_server.js:99)."""

    timeout_s: float = 10.0

    async def fetch_pod_list(self) -> dict:
        proc = await asyncio.create_subprocess_exec(
            "kubectl",
            "get",
            "pods",
            "-A",
            "-o",
            "json",
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        try:
            stdout, stderr = await asyncio.wait_for(
                proc.communicate(), timeout=self.timeout_s
            )
        except asyncio.TimeoutError:
            proc.kill()
            raise RuntimeError(f"kubectl timed out after {self.timeout_s}s")
        if proc.returncode != 0:
            raise RuntimeError(f"kubectl failed: {stderr.decode(errors='replace')[:200]}")
        return json.loads(stdout)


@dataclass
class FakePodSource:
    """Synthetic PodList for demo mode and tests: a JetStream serving set,
    system pods, a perpetually-Pending pod and a slow crash-looper whose
    restart count climbs — enough to exercise every pod alert rule."""

    clock: object = None

    def _start(self, now: float, age_s: float) -> str:
        t = dt.datetime.fromtimestamp(now - age_s, dt.timezone.utc)
        return t.isoformat().replace("+00:00", "Z")

    async def fetch_pod_list(self) -> dict:
        import time as _time

        now = self.clock() if self.clock else _time.time()
        restarts = int(now // 300) % 50  # climbs every 5 min
        crashing = (now % 600) < 120  # crash-looping 2 min of every 10
        items = [
            {
                "metadata": {
                    "namespace": "serving",
                    "name": f"jetstream-llama3-8b-{i}",
                    "labels": {JOBSET_NAME_KEY: "jetstream-llama3"},
                },
                "spec": {
                    "nodeName": f"tpu-host-{i}",
                    "nodeSelector": {
                        TPU_TOPOLOGY_KEY: "2x4",
                        TPU_ACCEL_KEY: "tpu-v5-lite-podslice",
                    },
                    "containers": [
                        {
                            "resources": {
                                "requests": {"google.com/tpu": "8"},
                                "limits": {"google.com/tpu": "8"},
                            }
                        }
                    ],
                },
                "status": {
                    "phase": "Running",
                    "startTime": self._start(now, 86400 * 2 + i * 3600),
                    "containerStatuses": [{"restartCount": 0, "state": {"running": {}}}],
                },
            }
            for i in range(2)
        ]
        items.append(
            {
                "metadata": {"namespace": "kube-system", "name": "kube-dns-7c9", "labels": {}},
                "spec": {"nodeName": "cpu-node-0", "nodeSelector": {}},
                "status": {
                    "phase": "Running",
                    "startTime": self._start(now, 86400 * 14),
                    "containerStatuses": [{"restartCount": 1, "state": {"running": {}}}],
                },
            }
        )
        items.append(
            {
                "metadata": {"namespace": "ml", "name": "maxtext-eval-7b", "labels": {}},
                "spec": {"nodeSelector": {TPU_TOPOLOGY_KEY: "4x4"}},
                "status": {"phase": "Pending", "reason": "Unschedulable"},
            }
        )
        items.append(
            {
                "metadata": {"namespace": "ml", "name": "dataprep-worker", "labels": {}},
                "spec": {"nodeName": "cpu-node-1", "nodeSelector": {}},
                "status": {
                    "phase": "Running",
                    "startTime": self._start(now, 7200),
                    "containerStatuses": [
                        {
                            "restartCount": restarts,
                            "state": (
                                {"waiting": {"reason": "CrashLoopBackOff"}}
                                if crashing
                                else {"running": {}}
                            ),
                        }
                    ],
                },
            }
        )
        return {"kind": "PodList", "items": items}


@dataclass
class K8sCollector:
    name: str = "k8s"
    mode: str = "auto"  # "auto" | "api" | "kubectl" | "fake" | "none"
    api_url: str | None = None

    def _sources(self):
        if self.mode == "api":
            return [ApiPodSource(api_url=self.api_url)]
        if self.mode == "kubectl":
            return [KubectlPodSource()]
        if self.mode == "fake":
            return [FakePodSource()]
        if self.mode == "none":
            return []
        return [ApiPodSource(api_url=self.api_url), KubectlPodSource()]

    async def collect(self) -> Sample:
        errors: list[str] = []
        for source in self._sources():
            try:
                pod_list = await source.fetch_pod_list()
                return Sample(source=self.name, ok=True, data=parse_pod_list(pod_list))
            except Exception as e:
                errors.append(f"{type(source).__name__}: {type(e).__name__}: {e}")
        return Sample(
            source=self.name,
            ok=False,
            data=[],
            error="; ".join(errors) or "k8s collection disabled",
        )
