"""Kubernetes pod collector.

Reference parity (monitor_server.js:97-114 ``getK8sPods``): per pod
namespace, name, phase, restart count summed over containerStatuses
(:104), humanized age from status.startTime (:106-110). The reference
shells out ``execSync('kubectl get pods -A -o json')`` on the event loop
(:99) — SURVEY §2.1 flags a hung kubectl freezing the whole server.

tpumon talks to the Kubernetes API directly (in-cluster service-account
auth, or any configured API URL), with an *async subprocess* kubectl
fallback for dev boxes. Parsing is a pure function over the PodList JSON
so golden-input tests (SURVEY §4.1) cover containerStatuses edge cases.

TPU additions: each pod record carries slice/topology metadata when
present (GKE TPU nodeSelectors, JobSet labels) so the alert engine can
map pods -> slices (SURVEY §2.5 "pod-slice topology awareness").
"""

from __future__ import annotations

import asyncio
import datetime as dt
import json
import os
import ssl
import urllib.request
from dataclasses import dataclass

from tpumon.collectors import Sample

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# GKE TPU-related pod/node metadata keys (labels & nodeSelector).
TPU_TOPOLOGY_KEY = "cloud.google.com/gke-tpu-topology"
TPU_ACCEL_KEY = "cloud.google.com/gke-tpu-accelerator"
JOBSET_NAME_KEY = "jobset.sigs.k8s.io/jobset-name"
JOB_INDEX_KEY = "batch.kubernetes.io/job-completion-index"


def humanize_age(seconds: float) -> str:
    """Humanize like the reference (monitor_server.js:106-110): days if
    >=1d, hours if >=1h, else minutes."""
    if seconds >= 86400:
        return f"{int(seconds // 86400)}d"
    if seconds >= 3600:
        return f"{int(seconds // 3600)}h"
    return f"{max(0, int(seconds // 60))}m"


def _parse_k8s_time(text: str) -> float | None:
    try:
        return dt.datetime.fromisoformat(text.replace("Z", "+00:00")).timestamp()
    except (ValueError, AttributeError):
        return None


def parse_pod_list(obj: dict, now: float | None = None) -> list[dict]:
    """Pure parser over a K8s PodList JSON document."""
    now = dt.datetime.now(dt.timezone.utc).timestamp() if now is None else now
    pods: list[dict] = []
    for item in obj.get("items", []):
        meta = item.get("metadata", {}) or {}
        status = item.get("status", {}) or {}
        spec = item.get("spec", {}) or {}
        # Restarts summed over containerStatuses (monitor_server.js:104);
        # containerStatuses may be absent for Pending pods.
        restarts = sum(
            cs.get("restartCount", 0) for cs in status.get("containerStatuses") or []
        )
        start = _parse_k8s_time(status.get("startTime"))
        age_s = max(0.0, now - start) if start is not None else None
        labels = meta.get("labels") or {}
        node_selector = spec.get("nodeSelector") or {}
        phase = status.get("phase", "Unknown")
        # TPU chips requested (google.com/tpu), summed over containers —
        # the basis for pod->chip attribution in the accel view.
        tpu_request = 0
        for ctr in spec.get("containers") or []:
            res = ctr.get("resources") or {}
            v = (res.get("requests") or {}).get("google.com/tpu") or (
                res.get("limits") or {}
            ).get("google.com/tpu")
            try:
                tpu_request += int(v)
            except (TypeError, ValueError):
                pass
        # Surface container-level waiting/terminated reasons (CrashLoopBackOff,
        # OOMKilled, ...) the reference can't see — it only looks at phase.
        reason = status.get("reason")
        for cs in status.get("containerStatuses") or []:
            state = cs.get("state") or {}
            last_state = cs.get("lastState") or {}
            waiting = state.get("waiting") or {}
            terminated = state.get("terminated") or last_state.get("terminated") or {}
            if waiting.get("reason"):
                reason = waiting["reason"]
                break
            term_reason = terminated.get("reason")
            if term_reason and term_reason != "Completed":
                reason = term_reason
                break
        pods.append(
            {
                "namespace": meta.get("namespace", ""),
                "name": meta.get("name", ""),
                "status": phase,
                "reason": reason,
                "restarts": restarts,
                "age": humanize_age(age_s) if age_s is not None else "",
                "age_s": age_s,
                "node": spec.get("nodeName"),
                "tpu_request": tpu_request,
                "tpu_topology": node_selector.get(TPU_TOPOLOGY_KEY),
                "tpu_accelerator": node_selector.get(TPU_ACCEL_KEY),
                "jobset": labels.get(JOBSET_NAME_KEY),
                "job_index": labels.get(JOB_INDEX_KEY),
            }
        )
    return pods


# --------------------------------------------------------------------------
# Pod sources
# --------------------------------------------------------------------------


@dataclass
class ApiPodSource:
    """Reads /api/v1/pods from a Kubernetes API server.

    In-cluster: uses the mounted service-account token + CA. Out of
    cluster: any api_url (e.g. a `kubectl proxy` or a test fake) works
    unauthenticated over http.
    """

    api_url: str | None = None
    timeout_s: float = 5.0

    def _resolve(self) -> tuple[str, dict[str, str], ssl.SSLContext | None]:
        if self.api_url:
            return self.api_url, {}, None
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            raise RuntimeError("not in-cluster (KUBERNETES_SERVICE_HOST unset)")
        headers = {}
        token_path = os.path.join(SA_DIR, "token")
        if os.path.exists(token_path):
            with open(token_path) as f:
                headers["Authorization"] = f"Bearer {f.read().strip()}"
        ctx = None
        ca_path = os.path.join(SA_DIR, "ca.crt")
        if os.path.exists(ca_path):
            ctx = ssl.create_default_context(cafile=ca_path)
        return f"https://{host}:{port}", headers, ctx

    def _fetch(self) -> dict:
        base, headers, ctx = self._resolve()
        req = urllib.request.Request(f"{base}/api/v1/pods", headers=headers)
        with urllib.request.urlopen(req, timeout=self.timeout_s, context=ctx) as r:
            return json.load(r)

    async def fetch_pod_list(self) -> dict:
        return await asyncio.to_thread(self._fetch)


class PodWatcher:
    """Live pod map via the Kubernetes watch API (chunked event stream).

    Poll-based collection — the reference's model and our ApiPodSource —
    sees only poll-boundary states: a pod that fails and recovers inside
    one sample interval is invisible (SURVEY §2.2 calls this out). The
    watcher holds one long-lived ``?watch=1`` stream, applies
    ADDED/MODIFIED/DELETED events to an in-memory pod map, and records
    every phase a pod passes through between collector samples; the
    collector surfaces those as ``interim_phases`` so the alert engine
    can flag a pod that flapped through Failed even though it is Running
    again by sample time. Reconnects with backoff on stream drop,
    re-listing to resync (last_error says why the previous stream died).
    """

    def __init__(self, api_url: str | None = None,
                 reconnect_delay_s: float = 1.0):
        import threading

        self.api_url = api_url
        self.reconnect_delay_s = reconnect_delay_s
        self._lock = threading.Lock()
        self._pods: dict[str, dict] = {}
        self._interim: dict[str, list[str]] = {}
        self._synced = False
        self.last_error: str | None = None
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self.reconnects = 0

    # -- stream plumbing ---------------------------------------------------

    def _resolve(self):
        return ApiPodSource(api_url=self.api_url)._resolve()

    def _list_once(self) -> str:
        # Delegates to the poll source so the /api/v1/pods request path
        # (auth, TLS, timeouts) exists exactly once.
        doc = ApiPodSource(api_url=self.api_url)._fetch()
        with self._lock:
            self._pods = {self._key(p): p for p in doc.get("items", [])}
            self._synced = True
        return doc.get("metadata", {}).get("resourceVersion", "0")

    @staticmethod
    def _key(item: dict) -> str:
        md = item.get("metadata", {})
        return f"{md.get('namespace', 'default')}/{md.get('name', '?')}"

    def _apply(self, event: dict) -> str | None:
        """Apply one watch event; returns its resourceVersion (for
        resume), or raises on ERROR (forces a re-list — the standard
        410 Gone / expired-resourceVersion protocol)."""
        kind = event.get("type")
        item = event.get("object") or {}
        if kind == "ERROR":
            raise RuntimeError(
                f"watch ERROR event: {json.dumps(item)[:120]}")
        if kind not in ("ADDED", "MODIFIED", "DELETED"):
            return None  # BOOKMARK etc.: nothing to apply
        key = self._key(item)
        with self._lock:
            if kind == "DELETED":
                self._pods.pop(key, None)
                self._interim.setdefault(key, []).append("Deleted")
            else:
                prev_phase = (self._pods.get(key) or {}).get(
                    "status", {}).get("phase")
                self._pods[key] = item
                phase = item.get("status", {}).get("phase")
                if phase and phase != prev_phase:
                    self._interim.setdefault(key, []).append(phase)
        return item.get("metadata", {}).get("resourceVersion")

    def _watch_stream(self, rv: str) -> str:
        """One watch connection; returns the last event's rv (resume
        point) on clean server-side timeout."""
        base, headers, ctx = self._resolve()
        # Server-side timeoutSeconds ends quiet streams cleanly so an
        # idle cluster doesn't register as an error; the client timeout
        # is just the backstop for a hung connection.
        url = (f"{base}/api/v1/pods?watch=1&resourceVersion={rv}"
               "&timeoutSeconds=300")
        req = urllib.request.Request(url, headers=headers)
        with urllib.request.urlopen(req, timeout=330, context=ctx) as r:
            for line in r:
                if self._stop.is_set():
                    return rv
                line = line.strip()
                if line:
                    rv = self._apply(json.loads(line)) or rv
        return rv

    def _run(self) -> None:
        rv: str | None = None
        while not self._stop.is_set():
            try:
                if rv is None:
                    rv = self._list_once()
                self.last_error = None
                rv = self._watch_stream(rv)
                # Clean stream end: resume from the last seen rv with
                # no re-list and no error/backoff.
                continue
            except Exception as e:
                self.last_error = f"{type(e).__name__}: {e}"
                rv = None  # full resync on reconnect
            if self._stop.is_set():
                return
            self.reconnects += 1
            self._stop.wait(self.reconnect_delay_s)

    # -- public API --------------------------------------------------------

    def start(self) -> None:
        import threading

        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="tpumon-pod-watch")
        self._thread.start()

    def stop(self) -> None:
        """Stop the watch thread and wait (bounded) for it to exit.
        Terminal: a stopped watcher stays stopped — the collector
        builds a fresh one if watching resumes. The join timeout is
        deliberate: a thread blocked inside the watch read can't be
        interrupted mid-``urlopen`` (it notices the stop event at the
        next line/reconnect), so the wait is bounded and the daemon
        flag guarantees the stragglers can't pin process exit."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)

    @property
    def synced(self) -> bool:
        return self._synced

    def snapshot(self) -> tuple[dict, dict[str, list[str]]]:
        """Current PodList document + drained interim phase excursions
        (phases each pod passed through since the previous snapshot)."""
        with self._lock:
            doc = {"kind": "PodList",
                   "items": [dict(p) for p in self._pods.values()]}
            interim, self._interim = self._interim, {}
        return doc, interim


@dataclass
class KubectlPodSource:
    """Async-subprocess kubectl fallback (never blocks the event loop,
    unlike the reference's execSync at monitor_server.js:99)."""

    timeout_s: float = 10.0

    async def fetch_pod_list(self) -> dict:
        proc = await asyncio.create_subprocess_exec(
            "kubectl",
            "get",
            "pods",
            "-A",
            "-o",
            "json",
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        try:
            stdout, stderr = await asyncio.wait_for(
                proc.communicate(), timeout=self.timeout_s
            )
        except asyncio.TimeoutError:
            proc.kill()
            raise RuntimeError(f"kubectl timed out after {self.timeout_s}s")
        if proc.returncode != 0:
            raise RuntimeError(f"kubectl failed: {stderr.decode(errors='replace')[:200]}")
        return json.loads(stdout)


@dataclass
class FakePodSource:
    """Synthetic PodList for demo mode and tests: a JetStream serving set,
    system pods, a perpetually-Pending pod and a slow crash-looper whose
    restart count climbs — enough to exercise every pod alert rule."""

    clock: object = None

    def _start(self, now: float, age_s: float) -> str:
        t = dt.datetime.fromtimestamp(now - age_s, dt.timezone.utc)
        return t.isoformat().replace("+00:00", "Z")

    async def fetch_pod_list(self) -> dict:
        import time as _time

        now = self.clock() if self.clock else _time.time()
        restarts = int(now // 300) % 50  # climbs every 5 min
        crashing = (now % 600) < 120  # crash-looping 2 min of every 10
        items = [
            {
                "metadata": {
                    "namespace": "serving",
                    "name": f"jetstream-llama3-8b-{i}",
                    "labels": {JOBSET_NAME_KEY: "jetstream-llama3"},
                },
                "spec": {
                    "nodeName": f"tpu-host-{i}",
                    "nodeSelector": {
                        TPU_TOPOLOGY_KEY: "2x4",
                        TPU_ACCEL_KEY: "tpu-v5-lite-podslice",
                    },
                    "containers": [
                        {
                            "resources": {
                                "requests": {"google.com/tpu": "8"},
                                "limits": {"google.com/tpu": "8"},
                            }
                        }
                    ],
                },
                "status": {
                    "phase": "Running",
                    "startTime": self._start(now, 86400 * 2 + i * 3600),
                    "containerStatuses": [{"restartCount": 0, "state": {"running": {}}}],
                },
            }
            for i in range(2)
        ]
        items.append(
            {
                "metadata": {"namespace": "kube-system", "name": "kube-dns-7c9", "labels": {}},
                "spec": {"nodeName": "cpu-node-0", "nodeSelector": {}},
                "status": {
                    "phase": "Running",
                    "startTime": self._start(now, 86400 * 14),
                    "containerStatuses": [{"restartCount": 1, "state": {"running": {}}}],
                },
            }
        )
        items.append(
            {
                "metadata": {"namespace": "ml", "name": "maxtext-eval-7b", "labels": {}},
                "spec": {"nodeSelector": {TPU_TOPOLOGY_KEY: "4x4"}},
                "status": {"phase": "Pending", "reason": "Unschedulable"},
            }
        )
        items.append(
            {
                "metadata": {"namespace": "ml", "name": "dataprep-worker", "labels": {}},
                "spec": {"nodeName": "cpu-node-1", "nodeSelector": {}},
                "status": {
                    "phase": "Running",
                    "startTime": self._start(now, 7200),
                    "containerStatuses": [
                        {
                            "restartCount": restarts,
                            "state": (
                                {"waiting": {"reason": "CrashLoopBackOff"}}
                                if crashing
                                else {"running": {}}
                            ),
                        }
                    ],
                },
            }
        )
        return {"kind": "PodList", "items": items}


@dataclass
class K8sCollector:
    name: str = "k8s"
    # "auto" | "api" | "watch" | "kubectl" | "fake" | "none"
    mode: str = "auto"
    api_url: str | None = None

    def __post_init__(self):
        self._watcher: PodWatcher | None = None

    def _sources(self):
        if self.mode == "api":
            return [ApiPodSource(api_url=self.api_url)]
        if self.mode == "kubectl":
            return [KubectlPodSource()]
        if self.mode == "fake":
            return [FakePodSource()]
        if self.mode == "none":
            return []
        return [ApiPodSource(api_url=self.api_url), KubectlPodSource()]

    def stop(self) -> None:
        """Release background resources: the watch mode's PodWatcher
        holds a thread and a live HTTP stream that would otherwise
        outlive the sampler (found by tpulint's stoppable-not-stopped
        pass, PR 8). Poll modes hold nothing. A later collect() builds
        a fresh watcher, so stop→collect still works."""
        if self._watcher is not None:
            self._watcher.stop()
            self._watcher = None

    def _watch_sample(self) -> Sample | None:
        """Watch mode: serve from the live watcher map, annotating each
        pod with the phases it passed through since the last sample."""
        if self._watcher is None:
            self._watcher = PodWatcher(api_url=self.api_url)
            self._watcher.start()
        w = self._watcher
        if not w.synced:
            return Sample(
                source=self.name, ok=False, data=[],
                error="pod watch not synced yet"
                + (f" ({w.last_error})" if w.last_error else ""),
            )
        doc, interim = w.snapshot()
        pods = parse_pod_list(doc)
        seen = set()
        for p in pods:
            key = f"{p['namespace']}/{p['name']}"
            seen.add(key)
            phases = interim.get(key)
            if phases:
                p["interim_phases"] = phases
        # Pods that vanished between samples still report their final
        # excursions (a Job pod that fails and is deleted inside one
        # interval is exactly the event this mode exists to catch).
        for key, phases in interim.items():
            if key in seen:
                continue
            ns, _, name = key.partition("/")
            pods.append({
                "namespace": ns, "name": name, "status": "Deleted",
                "reason": None, "restarts": 0, "age": "-",
                "interim_phases": phases,
            })
        if w.last_error:
            # The stream is broken: serve the last-synced state but say
            # so — a frozen map must not masquerade as healthy.
            return Sample(
                source=self.name, ok=False, data=pods,
                error=f"pod watch degraded, serving last-synced state "
                f"({w.last_error})",
            )
        return Sample(source=self.name, ok=True, data=pods)

    async def collect(self) -> Sample:
        if self.mode == "watch":
            return await asyncio.to_thread(self._watch_sample)
        errors: list[str] = []
        for source in self._sources():
            try:
                pod_list = await source.fetch_pod_list()
                return Sample(source=self.name, ok=True, data=parse_pod_list(pod_list))
            except Exception as e:
                errors.append(f"{type(source).__name__}: {type(e).__name__}: {e}")
        return Sample(
            source=self.name,
            ok=False,
            data=[],
            error="; ".join(errors) or "k8s collection disabled",
        )
