/* chartcore.js — the dashboard's pure rendering/formatting logic.
 *
 * Replaces the reference's Chart.js dependency (monitor.html:7, CDN)
 * with a hand-rolled engine; this file is the DOM-free core shared by
 * the browser (included before the dashboard's inline script) and by
 * CI, where tests/jsmini.py executes it directly — the only JS engine
 * in that environment (VERDICT r1 weak #3: frontend logic must be
 * executed by a test, not regex-matched).
 *
 * Dialect: the jsmini subset (see tests/jsmini.py docstring) — no
 * classes/this/new/Set/try. The thin DOM adapters (event wiring,
 * canvas sizing, tooltip positioning) stay in dashboard.html.
 */
"use strict";

/* ------------------------------ formatters ----------------------------- */

function fmtPct(v) { return v == null ? "–" : v.toFixed(1) + "%"; }

function fmtGiB(b) { return b == null ? "–" : (b / 2**30).toFixed(1) + " GiB"; }

function fmtBps(v) {
  if (v == null) return "–";
  const u = ["B/s","KB/s","MB/s","GB/s","TB/s"];
  let i = 0; while (v >= 1000 && i < u.length-1) { v /= 1000; i++; }
  return v.toFixed(1) + " " + u[i];
}

/* ------------------------------ line chart ----------------------------- */

/* y-domain: fixed [0, yMax] when configured, else [0, 1.15 * data max]
   (empty/non-finite data still yields a drawable [0, 1]). */
function chartDomain(data, yMax) {
  if (yMax != null) return [0, yMax];
  let max = -Infinity;
  for (const d of data) for (const v of d) if (isFinite(v)) max = Math.max(max, v);
  if (!isFinite(max) || max <= 0) max = 1;
  return [0, max * 1.15];
}

/* data point -> canvas position inside geometry g {w,h,l,r,t,b} */
function chartXY(g, i, v, n, dom) {
  const x = g.l + (n <= 1 ? 0 : (i/(n-1)) * (g.w-g.l-g.r));
  const y = g.t + (1 - (v-dom[0])/(dom[1]-dom[0])) * (g.h-g.t-g.b);
  return [x, y];
}

/* y-axis tick label */
function chartFmtY(v, unit) {
  if (unit === "%") return v.toFixed(0) + "%";
  if (unit === "bps") return fmtBps(v);
  if (v >= 1000) return (v/1000).toFixed(1) + "k";
  return v % 1 ? v.toFixed(1) : v.toFixed(0);
}

/* sparse x labels: at most ~7 across the width */
function chartXStep(n) { return Math.max(1, Math.ceil(n / 7)); }

/* Full draw against a 2D-context-like object; returns {dom, n} for the
   caller's hover geometry. ctx needs: clearRect, beginPath, moveTo,
   lineTo, stroke, fill, closePath, fillText + the style properties. */
function chartDraw(ctx, g, labels, data, series, opts) {
  const dom = chartDomain(data, opts.yMax);
  const n = labels.length;
  ctx.clearRect(0, 0, g.w, g.h);
  // grid + y ticks
  ctx.strokeStyle = "#27325a"; ctx.fillStyle = "#93a0c4";
  ctx.font = "10px system-ui"; ctx.lineWidth = 1;
  for (let i = 0; i <= 4; i++) {
    const v = dom[0] + (dom[1]-dom[0]) * i/4;
    const y = g.t + (1 - i/4) * (g.h-g.t-g.b);
    ctx.globalAlpha = 0.5; ctx.beginPath();
    ctx.moveTo(g.l, y); ctx.lineTo(g.w-g.r, y); ctx.stroke();
    ctx.globalAlpha = 1;
    ctx.textAlign = "right"; ctx.textBaseline = "middle";
    ctx.fillText(chartFmtY(v, opts.unit), g.l-6, y);
  }
  // x labels (sparse)
  if (n > 1) {
    ctx.textAlign = "center"; ctx.textBaseline = "top";
    const step = chartXStep(n);
    for (let i = 0; i < n; i += step) {
      const xy = chartXY(g, i, 0, n, dom);
      ctx.fillText(labels[i], xy[0], g.h-g.b+5);
    }
  }
  // series
  series.forEach((s, si) => {
    const d = data[si]; if (!d.length) return;
    ctx.strokeStyle = s.color; ctx.lineWidth = 2;
    ctx.beginPath();
    d.forEach((v, i) => {
      const xy = chartXY(g, i, v, d.length, dom);
      if (i) { ctx.lineTo(xy[0], xy[1]); } else { ctx.moveTo(xy[0], xy[1]); }
    });
    ctx.stroke();
    if (s.fill && d.length > 1) {
      const x0 = chartXY(g, 0, 0, d.length, dom)[0];
      const x1 = chartXY(g, d.length-1, 0, d.length, dom)[0];
      ctx.lineTo(x1, g.h-g.b); ctx.lineTo(x0, g.h-g.b); ctx.closePath();
      ctx.globalAlpha = 0.12; ctx.fillStyle = s.color; ctx.fill();
      ctx.globalAlpha = 1;
    }
  });
  return { dom: dom, n: n };
}

/* hover x-pixel -> data index, or -1 when outside the data */
function chartTipIndex(px, g, n) {
  const i = Math.round((px - g.l) / Math.max(1, (g.w-g.l-g.r)) * (n-1));
  return (i < 0 || i >= n) ? -1 : i;
}

/* tooltip body HTML for index i (null/non-finite series rows skipped) */
function chartTipRows(series, data, i, opts) {
  return series.map((s, si) => {
    const v = data[si][i];
    if (v == null || !isFinite(v)) return "";
    return `<div><span style="color:${s.color}">●</span> ` +
           `${s.label}: ${chartFmtY(v, opts.unit)}</div>`;
  }).join("");
}

/* ----------------------------- topology map ---------------------------- */

/* MXU duty -> chip fill color: blue (idle) -> red (busy) */
function dutyColor(duty) {
  if (duty == null) return "#2a3550";
  const h = 210 - 170 * Math.min(1, duty / 100);
  return `hsl(${h} 75% 52%)`;
}

/* chip ring stroke: red when the link is down, amber when the libtpu
   SDK health score (0-10) reports a persistent problem */
function chipRingColor(chip) {
  if (chip.ici_link_up === false) return "#ef4444";
  if (chip.ici_link_health > 5) return "#f59e0b";
  return "#0c1220";
}

function uniqSorted(xs) {
  const seen = {};
  const out = [];
  for (const x of xs) {
    const k = "" + x;
    if (!seen[k]) { seen[k] = true; out.push(x); }
  }
  return out.sort();
}

/* chips -> [x, y] mesh positions; falls back to an index grid when ICI
   coords are absent or collide */
function topoLayout(chips) {
  const seen = {};
  let collide = false;
  for (const c of chips) {
    const k = (c.coords?.[0] ?? 0) + "," + (c.coords?.[1] ?? 0);
    if (seen[k]) { collide = true; break; }
    seen[k] = true;
  }
  let hasCoords = false;
  for (const c of chips) if ((c.coords?.length ?? 0) >= 2) hasCoords = true;
  if (!collide && hasCoords) {
    return chips.map(c => [c.coords[0] ?? 0, c.coords[1] ?? 0]);
  }
  const cols = Math.ceil(Math.sqrt(chips.length * 2));
  return chips.map((c, i) => [i % cols, Math.floor(i / cols)]);
}

/* Full topology draw; returns hit targets [{x,y,r,chip}] for hover.
   ctx contract as chartDraw plus arc(); chips laid out per slice. */
function topoDraw(ctx, chips, w, h) {
  const hits = [];
  const slices = uniqSorted(chips.map(c => c.slice));
  const maxBps = Math.max(1, ...chips.map(c => c.tx_bps ?? 0));
  const sliceW = w / slices.length;
  slices.forEach((sid, si) => {
    const sc = chips.filter(c => c.slice === sid);
    const pos = topoLayout(sc);
    const xs = pos.map(p => p[0]), ys = pos.map(p => p[1]);
    const minX = Math.min(...xs), minY = Math.min(...ys);
    const nx = Math.max(...xs) - minX + 1;
    const ny = Math.max(...ys) - minY + 1;
    const pad = 26;
    const cell = Math.min((sliceW - 2*pad) / nx, (h - 2*pad - 14) / ny);
    const r = Math.max(8, Math.min(26, cell * 0.32));
    const ox = si * sliceW + (sliceW - nx * cell) / 2 + cell / 2;
    const oy = 14 + (h - 14 - ny * cell) / 2 + cell / 2;
    const px = i => ox + (pos[i][0] - minX) * cell;
    const py = i => oy + (pos[i][1] - minY) * cell;
    // edges between mesh neighbors, weighted by endpoint ICI traffic
    for (let i = 0; i < sc.length; i++) for (let k = i+1; k < sc.length; k++) {
      const dx = Math.abs(pos[i][0]-pos[k][0]), dy = Math.abs(pos[i][1]-pos[k][1]);
      if (dx + dy !== 1) continue;
      const bps = ((sc[i].tx_bps ?? 0) + (sc[k].tx_bps ?? 0)) / 2;
      const frac = bps / maxBps;
      ctx.strokeStyle = `rgba(244,114,182,${0.15 + 0.75*frac})`;
      ctx.lineWidth = 1 + 4 * frac;
      ctx.beginPath(); ctx.moveTo(px(i), py(i)); ctx.lineTo(px(k), py(k)); ctx.stroke();
    }
    // chips
    sc.forEach((c, i) => {
      const x = px(i), y = py(i);
      ctx.beginPath(); ctx.arc(x, y, r, 0, 2*Math.PI);
      ctx.fillStyle = dutyColor(c.mxu_duty_pct); ctx.fill();
      ctx.lineWidth = 2;
      ctx.strokeStyle = chipRingColor(c);
      ctx.stroke();
      if (c.hbm_pct != null) {  // HBM arc around the chip
        ctx.beginPath();
        ctx.arc(x, y, r + 3.5, -Math.PI/2, -Math.PI/2 + 2*Math.PI*c.hbm_pct/100);
        ctx.strokeStyle = "#22d3ee"; ctx.lineWidth = 2.5; ctx.stroke();
      }
      ctx.fillStyle = "#e7ecf7"; ctx.font = `${Math.max(9, r*0.7)}px system-ui`;
      ctx.textAlign = "center"; ctx.textBaseline = "middle";
      ctx.fillText("" + c.index, x, y);
      hits.push({ x: x, y: y, r: r + 4, chip: c });
    });
    // slice caption
    ctx.fillStyle = "#93a0c4"; ctx.font = "11px system-ui";
    ctx.textAlign = "center"; ctx.textBaseline = "top";
    ctx.fillText(`${sid} · ${sc.length} chips`, si * sliceW + sliceW/2, 2);
  });
  return hits;
}

/* ----------------------------- pods & alerts --------------------------- */

/* status badge: CSS class + label ("Failed · OOMKilled" when a reason
   accompanies a non-Running phase) */
function podBadge(p) {
  const status = p.status || "Unknown";
  const text = p.reason && p.status !== "Running"
    ? `${p.status} · ${p.reason}` : (p.status || "?");
  return { cls: "badge " + status, text: text };
}

/* "TPU chips" cell: requested count + live chip attribution when an
   accel source reports chips */
function podTpuCell(p) {
  if (!p.tpu_request) return "–";
  if (p.chips) return `${p.tpu_request} req · ${p.chips} live`;
  return `${p.tpu_request} req`;
}

/* header dot: worst severity present */
function overallDotClass(a) {
  if ((a?.critical?.length ?? 0) > 0) return "bad";
  if ((a?.serious?.length ?? 0) > 0 || (a?.minor?.length ?? 0) > 0) return "warn";
  return "ok";
}

/* Silence the *condition*, not one severity tier: strip a trailing
   severity leaf so "host.cpu.critical" mutes host.cpu.* (otherwise the
   same signal re-pages the moment it crosses into another tier). */
function silencePrefix(key) {
  const parts = key.split(".");
  const last = parts[parts.length - 1];
  if (["minor", "serious", "critical"].includes(last))
    return parts.slice(0, -1).join(".") + ".";
  return key;
}

/* ------------------------------ aggregates ----------------------------- */

/* mean of the non-null entries, or null (chip-grid MXU card) */
function meanOf(xs) {
  const vals = xs.filter(v => v != null);
  if (!vals.length) return null;
  return vals.reduce((a, b) => a + b, 0) / vals.length;
}
