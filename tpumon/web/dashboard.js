/* dashboard.js — ALL dashboard behavior: fetch handling, SSE frames,
 * card/chip/pod/alert/serving rendering, history windows, chip modal.
 *
 * Like chartcore.js this file is written in the jsmini dialect (see
 * tests/jsmini.py: no classes/this/new/async/try/regex/switch) so CI
 * EXECUTES the exact file the browser loads (tests/test_dashboard_js.py)
 * — a thrown TypeError anywhere in here fails the suite (VERDICT r02
 * item #3; the r01/r02 version of this logic lived as an inline
 * <script> that was only regex-checked).
 *
 * Browser specifics are injected (the inline bootstrap in
 * dashboard.html provides them; tests provide fakes):
 *   doc  { el(id), mk(tag), queryAll(sel) }        DOM access
 *   net  { getJson(url, cb), postJson(url, body, done) }   cb(null) on error
 *   env  { nowMs(), timeStr(), localeTime(ms), winWidth() }
 *   mkSurface(canvasEl) -> { geom(), ctx() }       canvas sizing + 2D ctx
 *
 * Element contract used here (browser DOM satisfies it; the test fake
 * implements exactly this): textContent, innerHTML, title, className,
 * colSpan, dataset, style.<prop>, classList.{add,remove,toggle,
 * contains}, appendChild, append(...), replaceChildren(), onclick.
 */
"use strict";

/* ------------------------------ line chart ----------------------------- */
/* Chart instance over an injected surface; all geometry/drawing comes
   from chartcore.js (chartDraw/chartTipIndex/chartTipRows). */
function makeLineChart(surface, series, opts) {
  let labels = [];
  let data = series.map(() => []);
  let lastGeom = null;

  const draw = () => {
    const g = surface.geom();
    const res = chartDraw(surface.ctx(), g, labels, data, series, opts);
    lastGeom = { g: g, n: res.n };
  };
  /* update(labels, datasets[]): datasets is an ARRAY of series arrays
     (the old inline engine took varargs; the dialect has no rest args) */
  const update = (newLabels, datasets) => {
    labels = newLabels || [];
    (datasets || []).forEach((d, i) => { data[i] = (d || []).map(Number); });
    draw();
  };
  /* hover px -> {label, rows} (tooltip content) or null */
  const tipAt = px => {
    if (!lastGeom || !labels.length) return null;
    const i = chartTipIndex(px, lastGeom.g, lastGeom.n);
    if (i < 0) return null;
    return { label: labels[i], rows: chartTipRows(series, data, i, opts) };
  };
  return { draw: draw, update: update, tipAt: tipAt };
}

/* --------------------------- delta SSE codec --------------------------- */
/* Mirror of tpumon/deltas.py apply_delta — the server diffs successive
   realtime payloads into patch nodes ({"s": replace}, {"o": object
   merge, "d": dropped keys}, {"l": [[index, node]] list patches}) and
   the stream carries only what moved. One deviation forced by the
   dialect (no `delete`): dropped keys are set to undefined instead of
   removed — invisible to every renderer here (all reads are ?.-guarded)
   and to Object.keys consumers of the realtime payload (none). */
function applyDelta(target, node) {
  if (node == null) return target;
  if (node.s !== undefined) return node.s;
  if (node.l !== undefined) {
    for (const p of node.l) target[p[0]] = applyDelta(target[p[0]], p[1]);
    return target;
  }
  if (node.o !== undefined) {
    for (const k of Object.keys(node.o))
      target[k] = applyDelta(target[k], node.o[k]);
  }
  if (node.d !== undefined) {
    for (const k of node.d) target[k] = undefined;
  }
  return target;
}

/* Accelerator-family display terms (ISSUE 15): JSON keys stay the
   TPU-native names (mxu_duty_pct, hbm_*, ici_*) for every payload
   contract; anything the USER reads renders the chip's own family
   vocabulary. Mirror of tpumon.topology.accel_terms. */
function accelTerms(accelKind) {
  return accelKind === "gpu"
    ? { duty: "SM", mem: "VRAM", link: "NVLink" }
    : { duty: "MXU", mem: "HBM", link: "ICI" };
}

/* ------------------------------ dashboard ------------------------------ */

function makeDashboard(doc, net, env, mkSurface) {
  const $ = id => doc.el(id);

  /* ---- charts (ids match dashboard.html canvases) ---- */
  const mkChart = (cid, series, opts) => {
    const c = makeLineChart(mkSurface($(cid)), series, opts);
    c.canvasId = cid;
    return c;
  };
  const charts = {
    cpu:  mkChart("c-cpu",  [{label:"CPU %",  color:"#3b82f6", fill:true}], {yMax:100, unit:"%"}),
    mem:  mkChart("c-mem",  [{label:"Memory %", color:"#a78bfa", fill:true}], {yMax:100, unit:"%"}),
    disk: mkChart("c-disk", [{label:"Disk %", color:"#fbbf24", fill:true}], {yMax:100, unit:"%"}),
    tpu:  mkChart("c-tpu",  [{label:"MXU duty %", color:"#36d399", fill:true},
                             {label:"HBM %", color:"#22d3ee"}], {yMax:100, unit:"%"}),
    temp: mkChart("c-temp", [{label:"°C", color:"#fb923c", fill:true}], {yMax:110}),
    ici:  mkChart("c-ici",  [{label:"ICI tx", color:"#f472b6", fill:true},
                             {label:"DCN tx (NIC)", color:"#60a5fa"}], {unit:"bps"}),
    serving: mkChart("c-serving", [{label:"tokens/s", color:"#36d399", fill:true},
                                   {label:"TTFT p50 ms", color:"#fbbf24"}], {}),
    servingHealth: mkChart("c-serving-health",
      [{label:"spec accept %", color:"#22d3ee"},
       {label:"prefix hit %", color:"#36d399"},
       {label:"KV pool %", color:"#a78bfa", fill:true}], {yMax:100, unit:"%"}),
    tpuHealth: mkChart("c-tpu-health",
      [{label:"worst ICI link score", color:"#f59e0b", fill:true},
       {label:"worst throttle score", color:"#f87171"}], {yMax:10}),
    train: mkChart("c-train", [{label:"loss", color:"#f472b6", fill:true},
                               {label:"tokens/s", color:"#36d399"}], {}),
  };

  /* ---- state ---- */
  let histWindow = "30m";
  let lastHistory = null;   // latest /api/history payload
  let currentChipId = null; // chip shown in the open drill-down modal
  let currentAlerts = { minor: [], serious: [], critical: [] };
  let topoHit = [];         // [{x, y, r, chip}] css px, for hover/click
  let chipChart = null;

  /* ------------------------------ cards ------------------------------ */
  function setCard(prefix, pct, sub) {
    $(prefix + "-v").textContent = fmtPct(pct);
    if (sub != null) $(prefix + "-s").textContent = sub;
    const bar = $(prefix + "-b");
    bar.style.width = (pct == null ? 0 : Math.min(100, pct)) + "%";
    bar.className = pct > 95 ? "bad" : pct > 85 ? "warn" : "";
  }

  function applyHost(host) {
    if (!host) return;
    setCard("cpu", host.cpu?.percent,
            `load ${host.cpu?.load_1min ?? "–"} · ${host.cpu?.cores ?? "?"} cores`);
    setCard("mem", host.memory?.percent,
            `${fmtGiB(host.memory?.used)} / ${fmtGiB(host.memory?.total)}`);
    setCard("disk", host.disk?.percent,
            `${fmtGiB(host.disk?.used)} / ${fmtGiB(host.disk?.total)}`);
    // Live NIC rates — the cross-host DCN-traffic proxy (the chart
    // plots the same series historically; this is the current tick).
    const nr = host.net_rates;
    $("dcn-tag").textContent = nr && nr.tx_bps != null
      ? `now ↑ ${fmtBps(nr.tx_bps)} · ↓ ${fmtBps(nr.rx_bps)}` : "";
  }

  /* --------------------------- chips & topo --------------------------- */
  const mkRow = (a, b) => {
    const r = doc.mk("div"); r.className = "row";
    const l = doc.mk("span"); l.textContent = a;
    const v = doc.mk("span"); v.textContent = b;
    r.append(l, v); return r;
  };

  function renderChips(accel) {
    renderTopo(accel);
    const grid = $("chips");
    const chips = accel?.chips || [];
    const meanDuty = meanOf(chips.map(c => c.mxu_duty_pct));
    // Mixed fleets list every kind present ("v5p+a100"), so the card
    // says what the mean is a mean OF.
    const kinds = uniqSorted(chips.map(c => c.kind)).join("+");
    setCard("mxu", meanDuty,
            chips.length ? `${chips.length} chip(s) · ${kinds}` : "no chips");
    const slices = accel?.slices || [];
    $("topo-tag").textContent = chips.length
      ? `${chips.length} chips · ${slices.length} slice(s)` : "no chips";
    grid.replaceChildren();
    if (!chips.length) {
      const div = doc.mk("div");
      div.className = "empty";
      div.textContent = accel?.health?.error || "no accelerator source";
      grid.appendChild(div);
      return;
    }
    for (const c of chips) {
      const t = accelTerms(c.accel_kind);
      const el = doc.mk("div");
      el.className = "chip";
      el.style.cursor = "pointer";
      el.title = "click for history" +
        (c.counter_source ? ` · counters: ${c.counter_source}` : "");
      el.onclick = () => openChipModal(c.chip);
      const cid = doc.mk("div"); cid.className = "cid";
      cid.textContent = c.chip; cid.title = c.chip; el.appendChild(cid);
      const duty = doc.mk("div"); duty.className = "duty";
      duty.innerHTML = (c.mxu_duty_pct == null ? "–" : c.mxu_duty_pct.toFixed(1)) +
        `<small> % ${t.duty}</small>`;
      el.appendChild(duty);
      const bar = doc.mk("div"); bar.className = "bar";
      const fill = doc.mk("i");
      const hbmPct = c.hbm_pct;
      fill.style.width = (hbmPct ?? 0) + "%";
      if (hbmPct > 95) fill.className = "bad";
      else if (hbmPct > 85) fill.className = "warn";
      bar.appendChild(fill); el.appendChild(bar);
      el.appendChild(mkRow(t.mem, hbmPct == null ? "–" :
        `${fmtGiB(c.hbm_used)} (${hbmPct.toFixed(0)}%)`));
      el.appendChild(mkRow("temp", c.temp_c == null ? "–" : c.temp_c.toFixed(0) + "°C"));
      el.appendChild(mkRow(`${t.link} tx`, fmtBps(c.tx_bps)));
      // libtpu SDK scores (0-10), rendered only when degraded/throttled.
      if (c.ici_link_health != null && c.ici_link_health > 0)
        el.appendChild(mkRow(`${t.link} health`, c.ici_link_health + "/10"));
      if (c.throttle_score != null && c.throttle_score > 0)
        el.appendChild(mkRow("throttle", "~" + (c.throttle_score * 10) + "%"));
      if (c.pod) {
        const parts = c.pod.split("/");
        el.appendChild(mkRow("pod", parts[parts.length - 1]));
      }
      grid.appendChild(el);
    }
  }

  /* Topology card: layout/colors/edges live in chartcore.js topoDraw;
     this owns card visibility and the hit targets. */
  let topoSurface = null;
  function renderTopo(accel) {
    const card = $("topo-card");
    const chips = accel?.chips || [];
    if (chips.length < 2) { card.style.display = "none"; topoHit = []; return; }
    card.style.display = "";
    const slices = uniqSorted(chips.map(c => c.slice));
    $("topo-map-tag").textContent = slices.length > 1
      ? `${slices.length} slices` : (slices[0] || "");
    if (!topoSurface) topoSurface = mkSurface($("c-topo"));
    const g = topoSurface.geom();
    const ctx = topoSurface.ctx();
    ctx.clearRect(0, 0, g.w, g.h);
    topoHit = topoDraw(ctx, chips, g.w, g.h);
  }

  const hitAt = (mx, my) => {
    for (const p of topoHit) {
      if ((p.x - mx) ** 2 + (p.y - my) ** 2 <= p.r * p.r) return p;
    }
    return null;
  };
  /* topo hover -> {title, lines[]} for the tooltip, or null */
  function topoTipAt(mx, my) {
    const hit = hitAt(mx, my);
    if (!hit) return null;
    const c = hit.chip;
    const t = accelTerms(c.accel_kind);
    return {
      title: c.chip,
      lines: [
        `${t.duty}: ${c.mxu_duty_pct == null ? "–" : c.mxu_duty_pct.toFixed(1) + "%"}`,
        `${t.mem}: ${c.hbm_pct == null ? "–" : c.hbm_pct.toFixed(0) + "%"}`,
        `${t.link} tx: ${fmtBps(c.tx_bps)}`, `${t.link} rx: ${fmtBps(c.rx_bps)}`,
        `host: ${c.host}`, `pod: ${c.pod ?? "–"}`,
      ],
    };
  }
  function topoClickAt(mx, my) {
    const hit = hitAt(mx, my);
    if (hit) openChipModal(hit.chip.chip);
  }

  /* ----------------------------- self-trace ---------------------------- */
  /* Per-tick stage timeline (tpumon/tracing.py last_tick, delivered in
     the SSE realtime payload): one proportional segment per stage —
     collect.host, collect.accel, history, alerts — so "where did this
     tick's milliseconds go" is answered at a glance. Stage colors are
     assigned by first-seen order and stay stable across ticks. */
  const traceColors = ["#3b82f6", "#36d399", "#fbbf24", "#a78bfa",
                       "#22d3ee", "#f472b6", "#fb923c", "#f87171"];
  const traceColorByStage = {};
  let traceColorsUsed = 0;
  function traceColor(name) {
    if (traceColorByStage[name] === undefined) {
      traceColorByStage[name] = traceColors[traceColorsUsed % traceColors.length];
      traceColorsUsed += 1;
    }
    return traceColorByStage[name];
  }

  function renderTrace(tr) {
    const card = $("trace-card");
    const stages = tr?.stages || [];
    if (!stages.length) { card.style.display = "none"; return; }
    card.style.display = "";
    $("trace-tag").textContent = `tick ${(tr.total_ms ?? 0).toFixed(1)} ms`;
    const strip = $("trace-strip");
    const legend = $("trace-legend");
    strip.replaceChildren();
    legend.replaceChildren();
    let total = 0;
    for (const s of stages) total += s.ms;
    for (const s of stages) {
      const seg = doc.mk("i");
      seg.style.width = (total > 0 ? (100 * s.ms / total) : 0) + "%";
      seg.style.background = traceColor(s.name);
      seg.title = `${s.name} · ${s.ms.toFixed(2)} ms`;
      strip.appendChild(seg);
      const lab = doc.mk("span");
      const dot = doc.mk("i");
      dot.style.background = traceColor(s.name);
      const txt = doc.mk("span");
      txt.textContent = `${s.name} ${s.ms.toFixed(2)} ms`;
      lab.append(dot, txt);
      legend.appendChild(lab);
    }
  }

  /* Fleet freshness waterfall (ISSUE 19, tpumon/federation.py): one
     bar per origin node on the trace card — how long that node's
     newest sample took to become visible HERE, clock-offset
     corrected. Bars share one scale (the slowest node spans the
     track); fed from the /api/federation payload's freshness block,
     so it costs no extra fetch loop. */
  function renderFleetWaterfall(fresh) {
    const box = $("fleet-waterfall");
    if (!box) return;
    const names = fresh ? Object.keys(fresh).sort() : [];
    if (!names.length) { box.style.display = "none"; return; }
    box.style.display = "";
    box.replaceChildren();
    const head = doc.mk("div");
    head.textContent = "fleet freshness · leaf sample → visible here";
    box.appendChild(head);
    let max = 0;
    for (const n of names) max = Math.max(max, fresh[n].ms || 0);
    for (const n of names) {
      const ms = fresh[n].ms || 0;
      const row = doc.mk("div"); row.className = "fw-row";
      const lab = doc.mk("span"); lab.className = "fw-node";
      lab.textContent = n;
      lab.title = `via ${fresh[n].via || "?"} · offset ` +
        `${(fresh[n].offset_ms ?? 0).toFixed(1)} ms`;
      const track = doc.mk("span"); track.className = "fw-track";
      const bar = doc.mk("i"); bar.className = "fw-bar";
      bar.style.width =
        (max > 0 ? Math.max(2, 100 * ms / max) : 0) + "%";
      track.appendChild(bar);
      const val = doc.mk("span"); val.className = "fw-ms";
      val.textContent = ms.toFixed(0) + " ms";
      row.append(lab, track, val);
      box.appendChild(row);
    }
  }

  /* Polling fallback for the strip: when the SSE stream is down the
     rest of the page refreshes via fetch loops — the trace card must
     not freeze on the last streamed tick. /api/trace rides the epoch
     render cache server-side, so this poll is cached bytes. */
  function fetchTrace() {
    net.getJson("/api/trace", t => { if (t) renderTrace(t.last_tick); });
  }

  /* ---------------------------- event feed ---------------------------- */
  /* Live journal tail (tpumon/events.py): the SSE payload carries the
     last 20 events ({seq, recent}), newest first; /api/events is the
     polling fallback. A severity filter narrows the feed client-side
     (the full window is already on hand — no refetch per click). */
  let eventFilter = "all";
  let lastEvents = null;  // latest {seq, recent} view rendered

  function renderEvents(ev) {
    const card = $("events-card");
    const recent = ev?.recent || [];
    if (!recent.length) { card.style.display = "none"; return; }
    lastEvents = ev;
    card.style.display = "";
    $("events-tag").textContent = `seq ${ev.seq ?? "?"}`;
    const feed = $("events-feed");
    feed.replaceChildren();
    const shown = recent.filter(
      e => eventFilter === "all" || e.severity === eventFilter);
    if (!shown.length) {
      const empty = doc.mk("div");
      empty.className = "event-line";
      empty.textContent = `no recent ${eventFilter} events`;
      feed.appendChild(empty);
      return;
    }
    for (const e of shown) {
      const row = doc.mk("div");
      row.className = "event-line sev-" + (e.severity || "info");
      const when = doc.mk("span");
      when.className = "ev-t";
      when.textContent = env.localeTime((e.ts || 0) * 1000);
      const kind = doc.mk("span");
      kind.className = "ev-k";
      kind.textContent = e.kind || "?";
      const msg = doc.mk("span");
      msg.className = "ev-m";
      msg.textContent =
        (e.source ? e.source + " · " : "") + (e.msg ?? e.title ?? "");
      row.append(when, kind, msg);
      feed.appendChild(row);
    }
  }

  function setEventFilter(sev) {
    eventFilter = sev;
    for (const b of doc.queryAll(".evbtn"))
      b.classList.toggle("on", b.dataset.sev === sev);
    if (lastEvents) renderEvents(lastEvents);
  }

  function fetchEvents() {
    net.getJson("/api/events?limit=20", d => {
      if (!d) return;
      // /api/events pages ascending; the feed wants newest first.
      renderEvents({ seq: d.seq, recent: (d.events || []).slice().reverse() });
    });
  }

  /* ------------------------------ realtime ---------------------------- */
  function fetchRealtime() {
    net.getJson("/api/host/metrics", host => {
      net.getJson("/api/accel/metrics", accel => {
        applyHost(host);
        renderChips(accel);
      });
    });
  }

  /* Live push: delta frames keyed by snapshot epoch (tpumon/server.py
     _stream docstring has the 3-frame protocol). The bootstrap passes
     each JSON-parsed frame here; "resync" tells it to reconnect (a
     fresh connection's first frame is always a keyframe). State: the
     last full payload, patched in place by delta frames. */
  let streamEpoch = -1;
  let streamData = null;

  function renderStream() {
    if (!streamData) return;
    applyHost(streamData.host);
    renderChips(streamData.accel);
    renderTrace(streamData.trace);
    renderEvents(streamData.events);
    renderActuate(streamData.actuate);
    const al = streamData.alerts;
    if (al) {
      $("n-minor").textContent = al.minor ?? 0;
      $("n-serious").textContent = al.serious ?? 0;
      $("n-critical").textContent = al.critical ?? 0;
      $("crit-badge").classList.toggle("active", (al.critical ?? 0) > 0);
    }
  }

  function onStreamFrame(d) {
    if (!d) return "ok";  // malformed frames dropped upstream
    if (d.key !== undefined) {  // keyframe: replace state wholesale
      streamData = d.key;
      streamEpoch = d.epoch;
      renderStream();
      return "ok";
    }
    if (d.prev !== undefined) {  // delta or heartbeat
      if (d.prev !== streamEpoch || streamData === null) {
        // Gap: this patch applies to a payload we don't hold (missed
        // frame, server restart). Drop state and ask for a resync.
        streamEpoch = -1;
        streamData = null;
        return "resync";
      }
      streamEpoch = d.epoch;
      if (d.patch == null) return "ok";  // heartbeat: nothing moved
      streamData = applyDelta(streamData, d.patch);
      renderStream();
      return "ok";
    }
    // Legacy full frame (pre-delta wire): render it directly.
    streamData = d;
    streamEpoch = -1;
    renderStream();
    return "ok";
  }

  /* ------------------------------ history ------------------------------ */
  const WIN_LABELS = { "30m": "30 min", "3h": "3 h", "12h": "12 h", "24h": "24 h" };
  function setWindow(w) {
    histWindow = w;
    for (const b of doc.queryAll(".winbtn"))
      b.classList.toggle("on", b.dataset.w === w);
    for (const e of doc.queryAll(".hwin"))
      e.textContent = WIN_LABELS[w] || w;
    fetchHistory();
  }

  function applyHistory(h, win) {
    // Discard responses from a window the user has since switched away
    // from — a slow 24h fetch must not repaint the 30m view.
    if (!h || win !== histWindow) return;
    lastHistory = h;
    // Keep an open chip drill-down live (its empty state promises that
    // samples accumulate — so re-render it as they do). A fresh fleet
    // payload re-arms the per-chip series= fallback fetch too.
    chipSeriesFetched = null;
    if (currentChipId !== null) openChipModal(currentChipId);
    charts.cpu.update(h.cpu?.labels, [h.cpu?.data]);
    charts.mem.update(h.memory?.labels, [h.memory?.data]);
    charts.disk.update(h.disk?.labels, [h.disk?.data]);
    charts.tpu.update(h.mxu?.labels?.length ? h.mxu.labels : h.hbm?.labels,
                      [h.mxu?.data, h.hbm?.data]);
    charts.temp.update(h.temp?.labels, [h.temp?.data]);
    charts.ici.update(h.ici?.labels?.length ? h.ici.labels : h.dcn?.labels,
                      [h.ici?.data, h.dcn?.data]);
    // Optional multi-series charts: card shows when any series has
    // data; labels come from whichever series has them.
    const optionalChart = (cardId, chart, list) => {
      const has = list.some(s => s?.data?.length);
      $(cardId).style.display = has ? "" : "none";
      if (!has) return;
      const lab = list.find(s => s?.labels?.length);
      chart.update(lab ? lab.labels : [], list.map(s => s?.data));
    };
    optionalChart("tpu-health-card", charts.tpuHealth,
                  [h.ici_health_max, h.throttle_max]);
    optionalChart("serving-chart-card", charts.serving,
                  [h.tokens_per_sec, h.ttft_p50_ms]);
    optionalChart("serving-health-card", charts.servingHealth,
                  [h.spec_accept_pct, h.prefix_hit_pct, h.kv_pool_pct]);
    optionalChart("train-chart-card", charts.train,
                  [h.train_loss, h.train_tokens_per_sec]);
  }

  function fetchHistory() {
    const win = histWindow;
    net.getJson("/api/history?window=" + win, h => applyHistory(h, win));
  }

  /* ------------------------ per-chip drill-down ------------------------ */
  /* The server records chip.<id>.mxu/.hbm/.temp/.link ring series and
     ships them as /api/history per_chip — the reference collected
     per-device history it never drew (SURVEY §2.1 gpuTemp); here every
     chip is clickable. When the fleet payload doesn't carry this
     chip's curves yet, fetch just them via the series= glob (cheap and
     epoch-cached server-side — the 256-chip path). */
  let chipSeriesFetched = null;  // chip a filtered fetch already ran for
  let chipChartKind = null;      // family the modal chart's labels speak
  function openChipModal(chipId) {
    currentChipId = chipId;
    $("chip-modal-title").textContent = chipId;
    $("chip-modal").classList.add("open");
    // GPU-aware units: the modal's series labels speak the clicked
    // chip's family (SM/VRAM vs MXU/HBM) — rebuilt only when the
    // family actually flips (mixed fleets).
    const cinfo = (streamData?.accel?.chips || []).find(c => c.chip === chipId);
    const kind = cinfo?.accel_kind || "tpu";
    const t = accelTerms(kind);
    if (!chipChart || chipChartKind !== kind) {
      chipChartKind = kind;
      chipChart = makeLineChart(mkSurface($("c-chip")),
        [{label:`${t.duty} duty %`, color:"#36d399", fill:true},
         {label:`${t.mem} %`, color:"#22d3ee"},
         {label:"link score ×10", color:"#f59e0b"}], {yMax:100, unit:"%"});
    }
    const mxu = lastHistory?.per_chip?.[chipId + ".mxu"];
    const hbm = lastHistory?.per_chip?.[chipId + ".hbm"];
    const link = lastHistory?.per_chip?.[chipId + ".link"];
    const has = mxu?.data?.length || hbm?.data?.length;
    $("chip-modal-empty").style.display = has ? "none" : "";
    $("c-chip").style.display = has ? "" : "none";
    chipChart.update((mxu?.labels?.length ? mxu.labels : hbm?.labels) || [],
                     [mxu?.data, hbm?.data, link?.data]);
    if (!has && chipSeriesFetched !== chipId) {
      chipSeriesFetched = chipId;  // once per chip until history refreshes
      const win = histWindow;  // a stale-window response must not merge
      net.getJson("/api/history?window=" + win +
                  "&series=chip." + chipId + ".*", h => {
        if (!h || !h.per_chip || currentChipId !== chipId ||
            win !== histWindow) return;
        if (!lastHistory) lastHistory = h;
        else {
          if (!lastHistory.per_chip) lastHistory.per_chip = {};
          for (const k of Object.keys(h.per_chip))
            lastHistory.per_chip[k] = h.per_chip[k];
        }
        openChipModal(chipId);
      });
    }
  }
  function closeChipModal() {
    currentChipId = null;
    $("chip-modal").classList.remove("open");
  }

  /* -------------------------------- pods ------------------------------- */
  function fetchPods() {
    net.getJson("/api/k8s/pods", res => {
      const body = $("pods-body");
      body.replaceChildren();
      const pods = res?.pods || [];
      $("pods-tag").textContent = pods.length;
      if (!pods.length) {
        const tr = doc.mk("tr");
        const td = doc.mk("td");
        td.colSpan = 8; td.style.color = "var(--dim)";
        td.textContent = res?.health?.error || "no pods";
        tr.appendChild(td); body.appendChild(tr);
        return;
      }
      for (const p of pods) {
        const tr = doc.mk("tr");
        for (const c of [p.namespace, p.name]) {
          const td = doc.mk("td"); td.textContent = c ?? ""; tr.appendChild(td);
        }
        const st = doc.mk("td");
        const badge = doc.mk("span");
        const b = podBadge(p);  // chartcore.js
        badge.className = b.cls;
        badge.textContent = b.text;
        st.appendChild(badge); tr.appendChild(st);
        for (const c of [p.restarts, p.age, p.node ?? "–",
                         p.tpu_topology ?? "–", podTpuCell(p)]) {
          const td = doc.mk("td"); td.textContent = c ?? ""; tr.appendChild(td);
        }
        body.appendChild(tr);
      }
    });
  }

  /* ------------------------------- alerts ------------------------------ */
  function fetchAlerts() {
    net.getJson("/api/alerts", a => {
      if (!a) return;
      currentAlerts = a;
      $("n-minor").textContent = (a.minor || []).length;
      $("n-serious").textContent = (a.serious || []).length;
      $("n-critical").textContent = (a.critical || []).length;
      $("crit-badge").classList.toggle("active", (a.critical || []).length > 0);
      $("overall-dot").className = overallDotClass(a);  // chartcore.js
      if ($("modal").classList.contains("open")) renderModal();
    });
  }

  const postAndRefresh = (url, payload) =>
    net.postJson(url, payload, () => fetchAlerts());
  // silencePrefix lives in chartcore.js (severity-leaf stripping).
  const silenceAlert = key =>
    postAndRefresh("/api/silence", { key: silencePrefix(key), duration: "1h" });
  const unsilenceAlert = key => postAndRefresh("/api/unsilence", { key: key });

  function renderModal() {
    const body = $("modal-body");
    body.replaceChildren();
    let any = false;
    for (const sev of ["critical", "serious", "minor"]) {
      for (const a of currentAlerts[sev] || []) {
        any = true;
        const card = doc.mk("div");
        card.className = "alert-card " + sev;
        const t = doc.mk("div"); t.className = "t"; t.textContent = a.title;
        if (a.key) {
          const btn = doc.mk("button");
          btn.className = "silence-btn"; btn.textContent = "silence 1h";
          btn.onclick = () => silenceAlert(a.key);
          t.appendChild(btn);
        }
        const d = doc.mk("div"); d.className = "d"; d.textContent = a.desc;
        const f = doc.mk("div"); f.className = "f"; f.textContent = a.fix;
        card.append(t, d, f); body.appendChild(card);
      }
    }
    for (const a of currentAlerts.silenced || []) {
      any = true;
      const card = doc.mk("div");
      card.className = "alert-card silenced";
      const t = doc.mk("div"); t.className = "t";
      t.textContent = `🔕 ${a.title}`;
      const d = doc.mk("div"); d.className = "d"; d.textContent = a.desc;
      card.append(t, d); body.appendChild(card);
    }
    // Active silences (a silence is a key *prefix*; unsilence removes it).
    for (const s of currentAlerts.silences || []) {
      any = true;
      const row = doc.mk("div");
      row.className = "alert-card silenced";
      const t = doc.mk("div"); t.className = "t";
      const mins = Math.max(0, (s.until * 1000 - env.nowMs()) / 60000);
      t.textContent = `silence "${s.key}" · ${mins.toFixed(0)} min left`;
      const btn = doc.mk("button");
      btn.className = "silence-btn"; btn.textContent = "unsilence";
      btn.onclick = () => unsilenceAlert(s.key);
      t.appendChild(btn);
      row.appendChild(t); body.appendChild(row);
    }
    if (!any) {
      const ok = doc.mk("div");
      ok.style.color = "var(--dim)"; ok.textContent = "No active alerts 🎉";
      body.appendChild(ok);
    }
    const events = currentAlerts.events || [];
    if (events.length) {
      const h = doc.mk("div");
      h.className = "events-h";
      h.textContent = "Recent events";
      body.appendChild(h);
      for (const e of events.slice(0, 20)) {
        const row = doc.mk("div");
        row.className = "event-row";
        const when = env.localeTime(e.ts * 1000);
        row.textContent =
          `${when}  ${e.state === "fired" ? "▲ fired" : "▽ resolved"}  ${e.title}`;
        row.style.color = e.state === "fired" ? "var(--text)" : "var(--dim)";
        body.appendChild(row);
      }
    }
  }
  function openModal() { renderModal(); $("modal").classList.add("open"); }
  function closeModal() { $("modal").classList.remove("open"); }

  /* --------------------------- serving & train ------------------------- */
  function fetchServing() {
    net.getJson("/api/serving", res => {
      const targets = res?.targets || [];
      const card = $("serving-card");
      if (!targets.length) {
        card.style.display = "none";
        $("train-card").style.display = "none";  // no targets => no stale panel
        return;
      }
      card.style.display = "";
      const ok = targets.filter(t => t.ok);
      $("serving-tag").textContent = `${ok.length}/${targets.length} targets up`;
      const agg = (vals, avg) => {
        let s = 0;
        for (const v of vals) s += v;
        return avg ? s / vals.length : s;
      };
      const pick = (k, fmt) => {
        const vals = ok.map(t => t[k]).filter(v => v != null);
        return vals.length ? fmt(agg(vals, k.slice(0, 4) === "ttft")) : "–";
      };
      $("sv-ttft").textContent = pick("ttft_p50_ms", v => v.toFixed(0) + " ms");
      $("sv-ttft99").textContent = pick("ttft_p99_ms", v => v.toFixed(0) + " ms");
      $("sv-tps").textContent = pick("tokens_per_sec", v => v.toFixed(1));
      $("sv-rps").textContent = pick("requests_per_sec", v => v.toFixed(2));
      $("sv-q").textContent = pick("queue_depth", v => v.toFixed(0));
      $("sv-wb").textContent = pick("weight_bytes", v =>
        v >= 2 ** 30 ? (v / 2 ** 30).toFixed(2) + " GiB"
                     : (v / 2 ** 20).toFixed(1) + " MiB");
      // Speculative-decoding acceptance (avg across targets exporting it).
      const specVals = ok.map(t => t.spec_accept_pct).filter(v => v != null);
      $("sv-spec").textContent = specVals.length
        ? (agg(specVals, true)).toFixed(1) + "%" : "–";
      // Prefix-cache hit rate (avg across targets exporting it).
      const pfxVals = ok.map(t => t.prefix_hit_pct).filter(v => v != null);
      $("sv-prefix").textContent = pfxVals.length
        ? (agg(pfxVals, true)).toFixed(1) + "%" : "–";
      // Paged KV pool occupancy (max across targets: the tightest pool).
      const kvVals = ok.map(t => t.kv_pages_used_pct).filter(v => v != null);
      $("sv-kv").textContent = kvVals.length
        ? Math.max(...kvVals).toFixed(0) + "%" : "–";
      // Training panel: targets exporting tpumon_train_* families.
      const trainers = ok.filter(t => t.train_step != null);
      const tcard = $("train-card");
      if (!trainers.length) { tcard.style.display = "none"; return; }
      tcard.style.display = "";
      $("train-tag").textContent = `${trainers.length} job(s)`;
      const tpick = (k, fmt) => {
        const vals = trainers.map(t => t[k]).filter(v => v != null);
        return vals.length ? fmt(agg(vals, true)) : "–";
      };
      $("tr-step").textContent = tpick("train_step", v => v.toFixed(0));
      $("tr-loss").textContent = tpick("train_loss", v => v.toFixed(3));
      $("tr-dt").textContent = tpick("train_step_time_ms", v => v.toFixed(0) + " ms");
      $("tr-tps").textContent = tpick("train_tokens_per_sec", v => v.toFixed(0));
      $("tr-gp").textContent = tpick("train_goodput_pct", v => v.toFixed(1) + "%");
      $("tr-mfu").textContent = tpick("train_mfu_pct", v => v.toFixed(1) + "%");
      $("tr-ckpt").textContent = tpick("train_ckpt_step", v => "step " + v.toFixed(0));
    });
  }

  /* --------------------------- federation fleet ------------------------ */
  /* GET /api/federation — the aggregator-tree fleet view (slices/chips
   * with dark/unreachable failure domains, per-downstream freshness,
   * uplink stream state). Hidden on a standalone monitor: the route
   * always answers, but only a hub (aggregator/root) or an uplinked
   * leaf has anything to show. */
  function fetchFederation() {
    net.getJson("/api/federation", res => {
      const card = $("federation-card");
      const fleet = res ? res.fleet : null;
      const uplink = res ? res.uplink : null;
      // A fleet block means this node aggregates a downstream tree:
      // the hottest-chips query upgrades to distributed (fleet=1).
      topchipsFleet = !!fleet;
      renderFleetWaterfall(res ? res.freshness : null);
      if (!res || (!fleet && !uplink)) {
        card.style.display = "none";
        return;
      }
      card.style.display = "";
      // Root HA leadership (tpumon.leader): which root leads, at what
      // fencing generation — a standby root labels itself plainly.
      const lead = res.leader || null;
      $("fed-tag").textContent = res.role +
        (res.node ? " · " + res.node : "") +
        (lead
          ? (lead.leader ? " · LEADER" : " · standby") +
            " gen " + lead.generation
          : "");
      const put = (id, v, fmt) => {
        $(id).textContent = v == null ? "–" : fmt(v);
      };
      put("fed-slices", fleet ? fleet.slices : null, v => v.toFixed(0));
      put("fed-chips", fleet ? fleet.chips : null, v => v.toFixed(0));
      // Per-accelerator-family partition (ISSUE 15): a mixed TPU/GPU
      // fleet says how many chips each family contributes — blank on
      // single-family fleets (nothing to partition).
      const byAccel = fleet ? fleet.by_accel : null;
      const fams = byAccel ? Object.keys(byAccel).sort() : [];
      $("fed-accel").textContent = fams.length > 1
        ? fams.map(k => `${k} ${byAccel[k].chips}`).join(" · ")
        : "";
      put("fed-dark", fleet ? fleet.dark_slices : null, v => v.toFixed(0));
      $("fed-dark").style.color =
        fleet && fleet.dark_slices > 0 ? "var(--red)" : "";
      put("fed-unreach", fleet ? fleet.unreachable_slices : null,
          v => v.toFixed(0));
      $("fed-unreach").style.color =
        fleet && fleet.unreachable_slices > 0 ? "var(--red)" : "";
      put("fed-duty", fleet ? fleet.duty_mean : null,
          v => v.toFixed(1) + "%");
      const nodes = res.nodes || {};
      const names = Object.keys(nodes);
      let up = 0;
      let oldest = null;
      for (const name of names) {
        const ns = nodes[name];
        if (ns.status === "ok") up += 1;
        if (ns.age_s != null && (oldest == null || ns.age_s > oldest))
          oldest = ns.age_s;
      }
      put("fed-nodes", names.length ? up + "/" + names.length : null,
          v => v);
      put("fed-age", oldest, v => v.toFixed(1) + " s");
      $("fed-uplink").textContent = uplink
        ? (uplink.connected ? "connected" : "down") : "–";
      $("fed-uplink").style.color =
        uplink && !uplink.connected ? "var(--red)" : "";
    });
  }

  /* ---------------------------- SLO burn-down -------------------------- */
  /* GET /api/slo — per-objective error budget + multi-window burn rates
   * (tpumon.slo, docs/slo.md). Hidden when no objectives are
   * configured: the route always answers, with an empty slos list. */
  function fetchSlo() {
    net.getJson("/api/slo", res => {
      const card = $("slo-card");
      const rows = res && res.slos ? res.slos : [];
      if (!rows.length) { card.style.display = "none"; return; }
      card.style.display = "";
      let firing = 0;
      const body = $("slo-body");
      body.replaceChildren();
      const burnText = b => {
        if (!b) return "–";
        const s = b.short == null ? "–" : b.short.toFixed(1) + "x";
        const l = b.long == null ? "–" : b.long.toFixed(1) + "x";
        return s + " / " + l + (b.firing ? " ● FIRING" : "");
      };
      for (const row of rows) {
        const tr = doc.mk("tr");
        const mk = (t, hot) => {
          const td = doc.mk("td");
          td.textContent = t;
          if (hot) td.style.color = "var(--red)";
          return td;
        };
        const budget = row.budget || {};
        const rem = budget.remaining;
        const fast = row.burn ? row.burn.fast : null;
        const slow = row.burn ? row.burn.slow : null;
        if (fast && fast.firing) firing += 1;
        if (slow && slow.firing) firing += 1;
        tr.appendChild(mk(row.name));
        tr.appendChild(mk(row.tenant || "–"));
        tr.appendChild(mk((row.target * 100).toFixed(2) + "%"));
        tr.appendChild(mk(
          rem == null ? "–" : (rem * 100).toFixed(1) + "%",
          rem != null && rem < 0.1));
        tr.appendChild(mk(burnText(fast), !!(fast && fast.firing)));
        tr.appendChild(mk(burnText(slow), !!(slow && slow.firing)));
        body.appendChild(tr);
      }
      $("slo-tag").textContent = firing
        ? firing + " burning" : rows.length + " objective(s)";
      $("slo-tag").style.color = firing ? "var(--red)" : "";
    });
  }

  /* ------------------------------ actuation ---------------------------- */
  /* The closed loop (tpumon/actuate.py, docs/actuation.md): per-policy
   * state machine rows + the last journaled transition. Primary feed is
   * the SSE realtime payload ("actuate" key — a firing policy repaints
   * on the very next tick); fetchActuate is the polling fallback.
   * Hidden when no policies are configured: the route always answers,
   * with an empty policies list. */
  function renderActuate(res) {
    const card = $("actuate-card");
    if (!card) return;
    const rows = res && res.policies ? res.policies : [];
    if (!rows.length) { card.style.display = "none"; return; }
    card.style.display = "";
    let firing = 0;
    let dry = 0;
    const body = $("actuate-body");
    body.replaceChildren();
    for (const row of rows) {
      const tr = doc.mk("tr");
      const mk = (t, hot) => {
        const td = doc.mk("td");
        td.textContent = t;
        if (hot) td.style.color = "var(--red)";
        return td;
      };
      if (row.state === "fired") firing += 1;
      if (row.dry_run) dry += 1;
      tr.appendChild(mk(row.name + (row.dry_run ? " (dry-run)" : "")));
      tr.appendChild(mk(row.action));
      tr.appendChild(mk(row.state, row.state === "fired"));
      tr.appendChild(mk(row.when));
      tr.appendChild(mk(row.value == null ? "–" : String(row.value)));
      tr.appendChild(mk(row.last || "–"));
      tr.appendChild(mk(row.fired + " / " + row.reverted));
      body.appendChild(tr);
    }
    $("actuate-tag").textContent =
      (firing ? firing + " active" : rows.length + " polic" +
        (rows.length === 1 ? "y" : "ies")) +
      (res.engine_bound ? "" : " · no engine") +
      (dry ? " · DRY-RUN" : "");
    $("actuate-tag").style.color = firing ? "var(--red)" : "";
  }

  function fetchActuate() {
    net.getJson("/api/actuate", renderActuate);
  }

  /* --------------------------- hottest chips --------------------------- */
  /* GET /api/query — the in-tree query engine (docs/query.md): a topk
   * over per-chip 5 m duty means. On an aggregator/root with a
   * downstream tree the same expression is planned as a DISTRIBUTED
   * query (fleet=1 merges partial aggregates from the leaves), so the
   * card works at fleet scale without shipping raw points. Hidden when
   * no chip.* series exist (chips absent or per-chip history off). */
  var topchipsFleet = false;  // flips on once /api/federation shows a hub
  function fetchTopChips() {
    /* No-spaces spelling: every character is URL-safe, so the query
     * string needs no encoding step. */
    const expr = "topk(5,avg_over_time(chip.mxu[5m]))";
    const qs = "/api/query?query=" + expr +
               (topchipsFleet ? "&fleet=1" : "");
    net.getJson(qs, res => {
      const card = $("topchips-card");
      const rows = res && res.result ? res.result : [];
      if (!rows.length) { card.style.display = "none"; return; }
      card.style.display = "";
      /* Always set (not only on partial): a recovered tree must clear
       * a previous cycle's "partial: missing ..." note. */
      $("topchips-tag").textContent = res.partial
        ? "partial: missing " + (res.missing || []).join(", ")
        : expr;
      const body = $("topchips-body");
      body.replaceChildren();
      for (const row of rows) {
        const labels = row.labels || {};
        const tr = doc.mk("tr");
        const mk = t => {
          const td = doc.mk("td");
          td.textContent = t;
          return td;
        };
        tr.appendChild(mk(labels.chip || "–"));
        tr.appendChild(mk(labels.host || "–"));
        tr.appendChild(mk(labels.pod || "–"));
        tr.appendChild(mk(row.value == null ? "–" : row.value.toFixed(1) + "%"));
        body.appendChild(tr);
      }
    });
  }

  /* ------------------------------- health ------------------------------ */
  function fetchHealth() {
    net.getJson("/api/health", h => {
      const strip = $("health");
      strip.replaceChildren();
      if (!h) return;
      const sources = h.sources || {};
      for (const name of Object.keys(sources)) {
        const s = sources[name];
        const el = doc.mk("div");
        // A breaker that left "closed" means the source is polled on a
        // backoff cadence and its panels are stale — as loud as a
        // failing scrape even if the last sample happened to succeed.
        const broken = s.breaker && s.breaker.state !== "closed";
        el.className = "src " + (s.ok && !broken ? "ok" : "bad");
        const dot = doc.mk("i");
        const label = doc.mk("span");
        label.textContent = `${name} · ${s.latency_p50_ms ?? "?"} ms p50` +
          (s.ok ? "" : ` · ${(s.error || "down").slice(0, 60)}`) +
          (broken ? ` · breaker ${s.breaker.state}` +
            (s.breaker.retry_in_s != null
              ? ` (retry ${s.breaker.retry_in_s.toFixed(0)}s)` : "")
            : "");
        el.append(dot, label);
        // Source caveats (e.g. "temp_c unavailable", "duty/HBM include
        // workload self-reports") — declared, not silently missing.
        if (s.notes && s.notes.length) {
          el.title = s.notes.join("\n");
          const note = doc.mk("span");
          note.textContent = " ⓘ";
          note.style.opacity = "0.6";
          el.appendChild(note);
        }
        strip.appendChild(el);
      }
    });
  }

  function updateTime() { $("clock").textContent = env.timeStr(); }

  function fetchAll() {
    fetchRealtime(); fetchHistory(); fetchPods();
    fetchAlerts(); fetchServing(); fetchFederation(); fetchHealth();
    fetchSlo(); fetchActuate();
    fetchTopChips();
    fetchTrace();
    fetchEvents();
    updateTime();
  }

  return {
    charts: charts,
    fetchRealtime: fetchRealtime, fetchHistory: fetchHistory,
    fetchPods: fetchPods, fetchAlerts: fetchAlerts,
    fetchServing: fetchServing, fetchFederation: fetchFederation,
    fetchHealth: fetchHealth, fetchTopChips: fetchTopChips,
    fetchSlo: fetchSlo, fetchActuate: fetchActuate,
    renderActuate: renderActuate,
    fetchTrace: fetchTrace, fetchEvents: fetchEvents,
    fetchAll: fetchAll, updateTime: updateTime,
    onStreamFrame: onStreamFrame, setWindow: setWindow,
    renderTrace: renderTrace, renderEvents: renderEvents,
    setEventFilter: setEventFilter,
    openModal: openModal, closeModal: closeModal,
    openChipModal: openChipModal, closeChipModal: closeChipModal,
    topoTipAt: topoTipAt, topoClickAt: topoClickAt,
  };
}
