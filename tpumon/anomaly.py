"""EWMA anomaly detection: catch drifts before thresholds trip.

The alert engine (tpumon.alerts) fires on absolute thresholds — HBM
above 85%, CPU above 95% — which means a slow leak is invisible until
the moment it becomes an incident. This module watches *drift*: each
monitored series keeps an exponentially-weighted moving mean and
variance (the RiskMetrics recursion), and a sample whose z-score
against that baseline clears a gate is an anomaly — recorded in the
event journal (kind ``anomaly``) and surfaced as a minor
``anomaly.<series>`` alert, hours before the hard threshold would have
paged.

Detector per series, three guards against noise:

- **warmup**: no verdicts until ``warmup`` samples establish a
  baseline (a fresh monitor must not page on its first minute).
- **z-score hysteresis**: fire at ``|z| >= z_fire`` (default 4σ),
  clear only once ``|z| <= z_clear`` (default 1.5σ) — the band between
  the two is sticky, so a value oscillating around the fire line
  produces one incident, not a fired/cleared stream.
- **hold counts**: the gate must hold for ``fire_hold`` consecutive
  samples to fire and ``clear_hold`` to clear — single-sample spikes
  (a GC pause, one slow scrape) don't page.

The baseline keeps absorbing samples *while anomalous* (alpha-weighted)
— a sustained shift becomes the new normal and the anomaly clears once
the series stabilizes, rather than pinning "anomalous" forever. A
``min_sigma`` floor keeps a near-constant series (fake backends, idle
chips) from turning numeric dust into infinite z-scores.

The sampler feeds fleet-level series each fast tick (tpumon.sampler
``_anomaly_series``): mean chip duty cycle, mean HBM%, the previous
tick's duration, and each source's recent scrape p95 — the signals
whose slow sag/creep SURVEY §2.2 calls out as invisible to threshold
rules. Tuning knobs ride the config (``anomaly_*`` keys; docs/events.md
has the tuning guide).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass


@dataclass(frozen=True)
class AnomalyConfig:
    alpha: float = 0.05       # EWMA weight: ~20-sample memory
    z_fire: float = 4.0       # enter-anomaly gate (σ)
    z_clear: float = 1.5      # exit-anomaly gate (σ); must be < z_fire
    warmup: int = 30          # samples before any verdict
    fire_hold: int = 3        # consecutive over-gate samples to fire
    clear_hold: int = 5       # consecutive under-gate samples to clear
    min_sigma: float = 0.5    # σ floor (pct points / ms) for flat series


class EwmaDetector:
    """One series' EWMA mean/variance state machine."""

    __slots__ = (
        "name", "cfg", "mean", "var", "n", "state",
        "_over", "_under", "last_z", "last_value", "since",
    )

    def __init__(self, name: str, cfg: AnomalyConfig | None = None):
        self.name = name
        self.cfg = cfg or AnomalyConfig()
        self.mean: float | None = None
        self.var = 0.0
        self.n = 0
        self.state = "normal"  # "normal" | "anomalous"
        self._over = 0
        self._under = 0
        self.last_z = 0.0
        self.last_value: float | None = None
        self.since: float | None = None  # ts the current anomaly fired

    @property
    def sigma(self) -> float:
        return max(math.sqrt(max(self.var, 0.0)), self.cfg.min_sigma)

    def update(self, value: float, ts: float | None = None) -> str | None:
        """Feed one sample; returns "fired" / "cleared" on a state
        transition, else None. Scoring happens against the baseline
        *before* this sample is absorbed into it."""
        cfg = self.cfg
        ts = time.time() if ts is None else ts
        if self.mean is None:
            self.mean = float(value)
            self.n = 1
            self.last_value = float(value)
            return None
        z = (value - self.mean) / self.sigma
        transition: str | None = None
        if self.n >= cfg.warmup:
            if self.state == "normal":
                if abs(z) >= cfg.z_fire:
                    self._over += 1
                    if self._over >= cfg.fire_hold:
                        self.state = "anomalous"
                        self.since = ts
                        self._over = 0
                        transition = "fired"
                else:
                    self._over = 0
            else:
                if abs(z) <= cfg.z_clear:
                    self._under += 1
                    if self._under >= cfg.clear_hold:
                        self.state = "normal"
                        self.since = None
                        self._under = 0
                        transition = "cleared"
                else:
                    self._under = 0
        # Absorb AFTER scoring. One exception: while NORMAL with the
        # fire gate held open (a pending fire accumulating fire_hold
        # evidence), the baseline freezes — otherwise the EWMA variance
        # inflates fast enough to pull z back under the gate before the
        # hold completes, and a clean step change never fires. Once
        # anomalous, absorption resumes so a sustained shift converges
        # to the new normal and the anomaly can clear (module doc).
        pending_fire = (
            self.state == "normal"
            and self.n >= cfg.warmup
            and abs(z) >= cfg.z_fire
            and transition is None
        )
        if not pending_fire:
            d = value - self.mean
            self.mean += cfg.alpha * d
            self.var = (1.0 - cfg.alpha) * (self.var + cfg.alpha * d * d)
        self.n += 1
        self.last_z = z
        self.last_value = float(value)
        return transition

    def to_json(self) -> dict:
        return {
            "state": self.state,
            "n": self.n,
            "mean": round(self.mean, 3) if self.mean is not None else None,
            "sigma": round(self.sigma, 3),
            "z": round(self.last_z, 2),
            **({"since": self.since} if self.since is not None else {}),
        }


class AnomalyBank:
    """Detectors keyed by series name, journal-wired.

    ``observe({series: value}, ts)`` routes each value to its detector
    (created on first sight) and records ``anomaly`` events on
    fire (minor) / clear (info). ``active()`` is the live view the
    alert engine turns into minor ``anomaly.<series>`` alerts.
    """

    def __init__(self, journal=None, cfg: AnomalyConfig | None = None):
        self.journal = journal
        self.cfg = cfg or AnomalyConfig()
        self.detectors: dict[str, EwmaDetector] = {}

    def observe(self, series: dict[str, float | None], ts: float | None = None) -> list[dict]:
        """Feed one tick's samples; returns the transitions as
        ``[{"series", "transition", "z", "value", "mean"}]``."""
        ts = time.time() if ts is None else ts
        transitions: list[dict] = []
        for name, value in series.items():
            if value is None:
                continue
            det = self.detectors.get(name)
            if det is None:
                det = self.detectors[name] = EwmaDetector(name, self.cfg)
            tr = det.update(float(value), ts)
            if tr is None:
                continue
            info = {
                "series": name,
                "transition": tr,
                "z": round(det.last_z, 2),
                "value": round(float(value), 3),
                "mean": round(det.mean or 0.0, 3),
            }
            transitions.append(info)
            if self.journal is not None:
                if tr == "fired":
                    self.journal.record(
                        "anomaly", "minor", name,
                        f"{name} drifting: {value:.2f} vs EWMA mean "
                        f"{det.mean:.2f} (z={det.last_z:.1f})",
                        ts=ts, series=name, z=info["z"],
                        value=info["value"], mean=info["mean"],
                    )
                else:
                    self.journal.record(
                        "anomaly", "info", name,
                        f"{name} back to baseline "
                        f"({value:.2f}, z={det.last_z:.1f})",
                        ts=ts, series=name, z=info["z"],
                        value=info["value"], mean=info["mean"],
                    )
        return transitions

    def active(self) -> list[dict]:
        """Currently-anomalous series, for the alert engine."""
        out = []
        for det in self.detectors.values():
            if det.state != "anomalous":
                continue
            out.append(
                {
                    "series": det.name,
                    "z": round(det.last_z, 2),
                    "value": round(det.last_value or 0.0, 3),
                    "mean": round(det.mean or 0.0, 3),
                    "since": det.since,
                }
            )
        return sorted(out, key=lambda a: a["series"])

    def to_json(self) -> dict:
        return {name: det.to_json() for name, det in sorted(self.detectors.items())}
