"""Structured event journal: the cluster's durable "what happened" record.

Until now every subsystem kept its lifecycle moments in private,
transient state: the alert engine's fired/resolved deque vanished on
restart, breaker transitions lived only as current state in
/api/health, chaos injections and peer fallbacks weren't recorded at
all. Prometheus-style monitors treat events/annotations as first-class
(PAPERS.md: Prometheus annotations, Monarch's exemplars); MPM-style
fleet monitors correlate incidents through exactly this kind of
journal. This module is that record, sized for an always-on monitor:

- ``EventJournal``: an append-only **bounded ring** (``events_ring``
  config / ``--events-ring``, default 4096, overwrite-oldest) with one
  entry point — ``record(kind, severity, source, msg, **attrs)`` —
  called from every subsystem with a lifecycle moment: alert engine
  fired/resolved (tpumon.alerts, which now stores its timeline HERE
  instead of a private deque), circuit-breaker transitions and loop
  watchdogs (tpumon.sampler), chaos injections (collectors.chaos),
  peer up/down/wire-fallback (collectors.accel_peers), history/state
  snapshot restores (tpumon.history / tpumon.app), profiler captures
  (tpumon.profiler), silences and server start (tpumon.server/app).
  Every event carries a monotonic ``seq`` — the cursor /api/events
  paginates on — and lifetime per-(kind, severity) counters back the
  ``tpumon_events_total`` exporter family.
- ``EventLog``: crash-safe JSONL persistence on the HistorySnapshotter
  cadence — the whole ring is written atomically (tmp + fsync + rename,
  tpumon.history.atomic_write_text) every ``events_interval_s``, one
  JSON event per line behind a meta header, and restored at startup so
  a monitor restart doesn't erase the incident record. Sequence numbers
  survive the round trip, so a client's cursor stays valid across a
  restart.
- ``events_cli``: ``tpumon events`` — tail the journal of a running
  server, ``--follow`` live over the delta SSE stream (reusing
  tpumon.deltas.apply_delta client-side), ``--json`` for scripts.

Event kinds are a CLOSED set (``KINDS``): ``record()`` rejects unknown
kinds, and tests/test_events_doc.py lints that every kind recorded
anywhere in the tree is documented in README.md and docs/events.md —
an event vocabulary that drifts from its docs fails CI.
"""

from __future__ import annotations

import asyncio
import json
import time
from collections import deque

# The closed event vocabulary. Adding a kind means documenting it in
# README.md's event table and docs/events.md (tests/test_events_doc.py
# enforces both directions).
KINDS = (
    "actuate",    # actuation engine: policy armed / fired / reverted /
                  # suppressed / rate-limited, actuator bound
                  # (tpumon.actuate)
    "alert",      # alert engine: fired / resolved (tpumon.alerts)
    "anomaly",    # EWMA detector fired / cleared (tpumon.anomaly)
    "breaker",    # circuit-breaker state transition (tpumon.sampler)
    "chaos",      # injected fault (tpumon.collectors.chaos)
    "config",     # monitor configured / reconfigured (tpumon.sampler)
    "federation", # aggregator tree: tier up/down, keyframe resync,
                  # rollup lag (tpumon.federation)
    "history",    # history/state/journal snapshot save+restore moments
    "leader",     # root HA leadership: promoted / demoted / fenced,
                  # peer journal reconciled (tpumon.leader)
    "peer",       # federation peer up / down / wire-fallback
    "profile",    # jax.profiler device capture (tpumon.profiler)
    "query",      # query engine: rejected recording rule, distributed
                  # sub-query timeout, partial-merge degraded
                  # (tpumon.query / tpumon.federation)
    "server",     # HTTP server lifecycle (tpumon.app)
    "silence",    # alert silence added / removed (tpumon.alerts)
    "slo",        # SLO engine: burn-rate alert fired / resolved,
                  # rejected objective (tpumon.slo)
    "watchdog",   # sampler loop overrun / swallowed exception
)

SEVERITIES = ("info", "minor", "serious", "critical")

JOURNAL_VERSION = 1


class EventJournal:
    """Append-only bounded event ring with monotonic sequence numbers.

    O(1) per record; the ring overwrites oldest-first, lifetime
    ``counts`` keep the Prometheus counters honest across overwrite.
    Appends may come from worker threads (peer fetches, snapshot
    writers) — deque.append and the counter update are atomic enough
    under the GIL; the *section-version bump* that makes new events
    visible to the render caches stays on the event loop
    (Sampler._publish_events / mark_events_dirty).
    """

    MIN_CAPACITY = 16  # a ring too small to hold one alert lifecycle is a bug

    def __init__(self, capacity: int = 4096):
        self.capacity = max(self.MIN_CAPACITY, int(capacity))
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self._recorded = 0

        self.counts: dict[tuple[str, str], int] = {}

    @property
    def seq(self) -> int:
        """Sequence number of the newest event (0 = empty journal)."""
        return self._seq

    @property
    def recorded(self) -> int:
        """Lifetime events recorded (including restored ones)."""
        return self._recorded

    @property
    def dropped(self) -> int:
        """Events the bounded ring has overwritten."""
        return max(0, self._recorded - len(self._ring))

    def record(
        self,
        kind: str,
        severity: str,
        source: str,
        msg: str,
        ts: float | None = None,
        **attrs,
    ) -> dict:
        """Append one event; returns the stored dict (with its seq).

        ``kind`` must be in KINDS and ``severity`` in SEVERITIES — an
        unknown kind is a programming error (and would ship
        undocumented), so it raises instead of passing through.
        ``attrs`` ride flat on the event; None values are dropped.
        """
        if kind not in KINDS:
            raise ValueError(f"unknown event kind {kind!r}; known: {KINDS}")
        if severity not in SEVERITIES:
            raise ValueError(
                f"unknown event severity {severity!r}; known: {SEVERITIES}"
            )
        self._seq += 1
        ev = {
            "seq": self._seq,
            "ts": round(time.time() if ts is None else ts, 3),
            "kind": kind,
            "severity": severity,
            "source": source,
            "msg": msg,
        }
        for k, v in attrs.items():
            if v is not None:
                ev[k] = v
        self._ring.append(ev)
        self._recorded += 1
        key = (kind, severity)
        self.counts[key] = self.counts.get(key, 0) + 1
        return ev

    # ------------------------------ views ------------------------------

    def events(self) -> list[dict]:
        """The whole ring, oldest first."""
        return list(self._ring)

    def recent(self, n: int = 50, kind: str | None = None) -> list[dict]:
        """Newest-first tail, optionally filtered by kind — O(matched +
        skipped), walked from the new end."""
        out: list[dict] = []
        for ev in reversed(self._ring):
            if kind is not None and ev.get("kind") != kind:
                continue
            out.append(ev)
            if len(out) >= n:
                break
        return out

    def after(self, seq: int, kind: str | None = None) -> list[dict]:
        """Events with seq > ``seq``, oldest first — O(new), walked from
        the new end (the ring is seq-ordered). The notifier's per-tick
        "what's new" query."""
        out: list[dict] = []
        for ev in reversed(self._ring):
            if ev["seq"] <= seq:
                break
            if kind is None or ev.get("kind") == kind:
                out.append(ev)
        out.reverse()
        return out

    def query(
        self,
        after: int | None = None,
        kind: str | None = None,
        severity: str | None = None,
        since: float | None = None,
        limit: int = 100,
    ) -> list[dict]:
        """Filtered page, ascending by seq (the /api/events contract).

        With ``after`` (a cursor): the FIRST ``limit`` matches past it —
        forward pagination walks the journal oldest→newest without
        skipping. Without: the LAST ``limit`` matches (the tail a human
        asks for first).
        """
        matched = [
            ev
            for ev in self._ring
            if (after is None or ev["seq"] > after)
            and (kind is None or ev.get("kind") == kind)
            and (severity is None or ev.get("severity") == severity)
            and (since is None or ev.get("ts", 0) >= since)
        ]
        return matched[:limit] if after is not None else matched[-limit:]

    # --------------------------- restore path ---------------------------

    def ingest(self, events: list) -> int:
        """Merge restored events (JSONL restore, alert-state restore)
        into the ring: dedup by seq, keep seq order, advance the
        counter past the restored maximum. Malformed entries are
        skipped — a half-written line must not poison the restore.
        Returns the number of events added."""
        existing = {ev["seq"] for ev in self._ring}
        added: list[dict] = []
        for raw in events or []:
            if not isinstance(raw, dict):
                continue
            try:
                seq = int(raw["seq"])
                ts = float(raw.get("ts", 0.0))
            except (KeyError, TypeError, ValueError):
                continue
            if seq in existing:
                continue
            kind = raw.get("kind", "alert")  # pre-journal alert timelines
            severity = raw.get("severity", "info")
            if kind not in KINDS or severity not in SEVERITIES:
                continue
            ev = {
                **raw,
                "seq": seq,
                "ts": ts,
                "kind": kind,
                "severity": severity,
                "source": raw.get("source", "alerts"),
                "msg": raw.get("msg", raw.get("title", "")),
            }
            existing.add(seq)
            added.append(ev)
        if not added:
            return 0
        merged = sorted([*self._ring, *added], key=lambda ev: ev["seq"])
        self._ring = deque(merged, maxlen=self.capacity)
        self._recorded += len(added)
        self._seq = max(self._seq, merged[-1]["seq"])
        for ev in added:
            key = (ev["kind"], ev["severity"])
            self.counts[key] = self.counts.get(key, 0) + 1
        return len(added)

    def to_json(self) -> dict:
        return {
            "seq": self._seq,
            "recorded": self._recorded,
            "dropped": self.dropped,
            "capacity": self.capacity,
        }


# ----------------------------- persistence -----------------------------


class EventLog:
    """Crash-safe JSONL persistence for an EventJournal.

    Same shape as tpumon.history.HistorySnapshotter: a periodic atomic
    snapshot (the whole ring, one JSON event per line behind a meta
    header) plus restore-on-start — the journal is bounded, so a full
    rewrite per cadence is O(ring), and atomic replace means a crash
    mid-write leaves the previous file intact (no torn tail lines to
    repair). Events are a log: restore keeps everything the file holds,
    no staleness cutoff — yesterday's incident record is the point.
    """

    def __init__(self, journal: EventJournal, path: str, interval_s: float = 30.0):
        self.journal = journal
        self.path = path
        self.interval_s = interval_s
        self.last_save_ts: float | None = None
        self.last_error: str | None = None
        self._task: asyncio.Task | None = None

    def _snapshot_text(self) -> str:
        head = {
            "_journal": JOURNAL_VERSION,
            "saved_at": round(time.time(), 3),
            "seq": self.journal.seq,
        }
        lines = [json.dumps(head, separators=(",", ":"))]
        lines.extend(
            json.dumps(ev, separators=(",", ":")) for ev in self.journal.events()
        )
        return "\n".join(lines) + "\n"

    def save(self) -> bool:
        """Snapshot + write in one call (tests, shutdown); the live
        periodic path is save_async()."""
        return self._write(self._snapshot_text())

    async def save_async(self) -> bool:
        """Serialize on the event loop (the ring is only appended there
        or by GIL-atomic thread appends), write in a worker thread."""
        text = self._snapshot_text()
        return await asyncio.to_thread(self._write, text)

    def _write(self, text: str) -> bool:
        from tpumon.history import atomic_write_text

        try:
            atomic_write_text(self.path, text)
        except OSError as e:
            self.last_error = str(e)
            return False
        self.last_save_ts = time.time()
        self.last_error = None
        return True

    def restore(self) -> bool:
        """Best-effort warm start: parse the JSONL file into the
        journal. False (restoring nothing) on a missing/corrupt file or
        wrong version; individually-malformed lines are skipped."""
        try:
            with open(self.path) as f:
                lines = f.read().splitlines()
        except OSError as e:
            self.last_error = str(e)
            return False
        if not lines:
            return False
        try:
            head = json.loads(lines[0])
        except json.JSONDecodeError as e:
            self.last_error = f"bad journal header: {e}"
            return False
        if not isinstance(head, dict) or head.get("_journal") != JOURNAL_VERSION:
            return False
        events = []
        for line in lines[1:]:
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn line: keep what parses
        self.journal.ingest(events)
        # The saved seq high-water mark survives even if the newest
        # events were lost: cursors handed out before the crash stay
        # monotonic (never re-issued for different events).
        try:
            self.journal._seq = max(self.journal._seq, int(head.get("seq", 0)))
        except (TypeError, ValueError):
            pass
        return True

    def to_json(self) -> dict:
        return {
            "path": self.path,
            "interval_s": self.interval_s,
            "last_save_ts": self.last_save_ts,
            "last_error": self.last_error,
        }

    # ---------------------------- lifecycle ----------------------------

    async def start(self) -> None:
        async def loop() -> None:
            while True:
                await asyncio.sleep(self.interval_s)
                try:
                    await self.save_async()
                except Exception as e:  # never let the snapshot loop die
                    self.last_error = str(e)

        self._task = asyncio.create_task(loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        try:
            await self.save_async()  # final snapshot
        except Exception as e:
            self.last_error = str(e)


# ------------------------------ CLI ------------------------------


_SEV_MARK = {"info": "·", "minor": "🟡", "serious": "🟠", "critical": "🔴"}


def render_event_line(ev: dict) -> str:
    """One journal event as a terminal line (``tpumon events``)."""
    t = time.strftime("%H:%M:%S", time.localtime(ev.get("ts", 0)))
    mark = _SEV_MARK.get(ev.get("severity", ""), "·")
    return (
        f"{t} {mark} {ev.get('kind', '?'):<9} "
        f"{ev.get('source', ''):<12} {ev.get('msg', '')}"
    )


def events_cli(argv: list[str]) -> int:
    """``tpumon events`` — tail a running server's event journal.

    usage: tpumon events [--url HOST:8888] [-n N] [--kind KIND]
                         [--severity SEV] [--follow] [--json]

    --follow keeps the tail live over the delta SSE stream (/api/stream)
    — frames are epoch-keyed patches applied client-side, so following
    costs the server no extra render work.
    """
    import sys
    import urllib.request

    from tpumon.deltas import apply_delta

    url = "127.0.0.1:8888"
    limit = 40
    kind = severity = None
    follow = as_json = False
    it = iter(argv)
    for a in it:
        if a == "--url":
            url = next(it, url)
        elif a in ("-n", "--lines"):
            raw = next(it, "40") or "40"
            if not raw.isdigit():
                print(f"{a} wants an integer, got {raw!r}", file=sys.stderr)
                return 2
            limit = int(raw)
        elif a == "--kind":
            kind = next(it, None)
            if kind not in KINDS:
                print(f"unknown kind {kind!r}; known: {', '.join(KINDS)}",
                      file=sys.stderr)
                return 2
        elif a == "--severity":
            severity = next(it, None)
            if severity not in SEVERITIES:
                print(
                    f"unknown severity {severity!r}; known: "
                    f"{', '.join(SEVERITIES)}",
                    file=sys.stderr,
                )
                return 2
        elif a == "--follow":
            follow = True
        elif a == "--json":
            as_json = True
        elif a in ("-h", "--help"):
            print(events_cli.__doc__)
            return 0
        else:
            print(f"unknown argument {a!r}", file=sys.stderr)
            return 2
    if "://" not in url:
        url = f"http://{url}"
    url = url.rstrip("/")

    def emit(ev: dict) -> None:
        print(json.dumps(ev) if as_json else render_event_line(ev), flush=True)

    query = f"limit={limit}"
    if kind:
        query += f"&kind={kind}"
    if severity:
        query += f"&severity={severity}"
    try:
        with urllib.request.urlopen(f"{url}/api/events?{query}", timeout=10) as r:
            page = json.load(r)
    except OSError as e:
        print(f"tpumon at {url} unreachable: {e}", file=sys.stderr)
        return 1
    last_seq = 0
    for ev in page.get("events", []):
        emit(ev)
        last_seq = max(last_seq, ev.get("seq", 0))
    if not follow:
        return 0

    def matches(ev: dict) -> bool:
        if kind and ev.get("kind") != kind:
            return False
        if severity and ev.get("severity") != severity:
            return False
        return True

    # Follow mode: reconstruct the realtime payload from SSE keyframes +
    # patches; new journal entries ride its bounded "events.recent"
    # window. A detected gap reconnects (first frame is a keyframe).
    while True:
        state = None
        epoch = -1
        try:
            with urllib.request.urlopen(f"{url}/api/stream", timeout=60) as r:
                for raw in r:
                    if not raw.startswith(b"data: "):
                        continue
                    frame = json.loads(raw[6:])
                    if "key" in frame:
                        state = frame["key"]
                        epoch = frame["epoch"]
                    elif frame.get("prev") == epoch and state is not None:
                        epoch = frame["epoch"]
                        if frame.get("patch") is not None:
                            state = apply_delta(state, frame["patch"])
                    else:
                        break  # gap: reconnect for a fresh keyframe
                    recent = ((state or {}).get("events") or {}).get("recent") or []
                    for ev in sorted(recent, key=lambda e: e.get("seq", 0)):
                        if ev.get("seq", 0) > last_seq and matches(ev):
                            emit(ev)
                            last_seq = ev["seq"]
        except KeyboardInterrupt:
            return 0
        except OSError as e:
            print(f"stream lost ({e}); retrying", file=sys.stderr)
            time.sleep(1.0)
