"""Columnar time-series core: typed-array rings + compressed chunks.

The history layer used to keep every series as a deque of ``(ts, value)``
tuples — ~120 resident bytes per point (tuple header + two boxed floats
+ deque slot) and O(ring) Python-object churn on every window query. At
the 256-chip federation scale with per-chip series that is thousands of
series × thousands of points, and history became the slowest,
hungriest piece of the data plane after the PR 2 render fast path.

This module is the storage engine production TSDBs use, in pure stdlib
Python (no new deps):

- **Columnar head**: each tier appends into an ``array('d')`` timestamp
  column and an ``array('f')`` value column — 12 bytes/point, no boxed
  objects, C-speed appends.
- **Sealed chunks** (Gorilla, VLDB'15): once the head reaches
  ``seal_points`` it is sealed into one immutable ``bytes`` blob —
  timestamps as delta-of-delta zigzag varints (a steady cadence costs
  1 byte/point), values as float32-bit XOR-with-previous varints (a
  constant series costs 1 byte/point) — typically 2-6 bytes/point, an
  8-16x reduction over the tuple deque.
- **Tiered retention**: a series holds a fine tier (raw tick points)
  plus optional downsampled tiers (bucket means — mid ≈ 30 s, coarse ≈
  1-5 min), each its own ring. Downsampling is incremental at append
  time (running bucket sums, flushed on boundary crossing) — never at
  query time.
- **O(log n) window queries**: chunk time bounds are kept ordered, so a
  window query bisects to the first overlapping chunk and decodes only
  what it returns.

Timestamps are quantized to the millisecond on append — the same
precision the JSON snapshot format always rounded to — so a point reads
back identically whether it sits in the head or a sealed chunk. Values
are float32 (the column dtype); the render layer rounds to 2 decimals,
so the ~1e-7 relative quantization is invisible there.

The binary snapshot codec at the bottom writes sealed chunks verbatim
(no decode/encode, no JSON escaping) under a magic + version header —
the crash-safe history file (tpumon.history.HistorySnapshotter) rides
it for ~10x cheaper writes and restores than the v1 full-JSON format.
"""

from __future__ import annotations

import json
import struct
from array import array
from bisect import bisect_left, bisect_right
from dataclasses import dataclass

# --------------------------- ingest kernel ----------------------------
#
# The write side has a native fast path (tpumon/native/tsdbkern.cpp,
# bound in tpumon.native.load_tsdb): batch quantization, downsample
# bucket accumulation and sealed-chunk encoding run as one C call per
# batch instead of interpreted per-point work. The kernel is stateless
# (all state stays in these Python objects) and the pure-Python code
# below is its bit-exact fallback — tests/test_ingest.py drives both
# over the same fuzz corpus and compares raw bytes. The switch is
# module-global (one process, one policy): config ``ingest_kernel``
# lands in set_kernel_enabled(), and a missing .so simply leaves
# kernel() returning None.

_KERNEL = None
_KERNEL_TRIED = False
_KERNEL_ENABLED = True


def set_kernel_enabled(on: bool) -> None:
    """Process-wide kernel policy (config ``ingest_kernel``); the pure
    Python path is always available and bit-exact, so flipping this is
    a pure performance decision."""
    global _KERNEL_ENABLED
    _KERNEL_ENABLED = bool(on)


def kernel():
    """The loaded native ingest kernel, or None (disabled / not built).
    Loading is lazy and attempted once per process."""
    global _KERNEL, _KERNEL_TRIED
    if not _KERNEL_ENABLED:
        return None
    if not _KERNEL_TRIED:
        _KERNEL_TRIED = True
        try:
            from tpumon import native

            _KERNEL = native.load_tsdb(auto_build=True)
        except Exception:
            _KERNEL = None
    return _KERNEL


# ----------------------------- varints --------------------------------


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else (n << 1)


def _unzigzag(u: int) -> int:
    return (u >> 1) ^ -(u & 1)


def _put_uvarint(buf: bytearray, u: int) -> None:
    while u >= 0x80:
        buf.append((u & 0x7F) | 0x80)
        u >>= 7
    buf.append(u)


def _get_uvarint(data: bytes, i: int) -> tuple[int, int]:
    u = 0
    shift = 0
    while True:
        if i >= len(data):
            raise ValueError("truncated varint")
        b = data[i]
        i += 1
        u |= (b & 0x7F) << shift
        if not b & 0x80:
            return u, i
        shift += 7
        if shift > 70:
            raise ValueError("varint overflow")


# --------------------------- chunk codec ------------------------------

_F32 = struct.Struct("<f")


def f32bits(v: float) -> int:
    """The value column's dtype: a float's 32-bit pattern (NaN-safe —
    the encoder is bit-exact, so NaN round-trips as NaN)."""
    return struct.unpack("<I", _F32.pack(v))[0]


def bits_to_f32(b: int) -> float:
    return _F32.unpack(struct.pack("<I", b))[0]


def encode_chunk(ts_ms: list[int], bits: list[int]) -> bytes:
    """Compress parallel (ms-timestamp, f32-bit-pattern) columns.

    Timestamps: first absolute (zigzag varint), then delta, then
    delta-of-delta — all zigzag varints, so irregular and even
    time-reversed inputs encode (just less tightly). Values: XOR with
    the previous bit pattern, as a plain uvarint — similar floats share
    sign/exponent/high-mantissa bits, so the XOR's high bits are zero
    and the varint drops them; a repeated value is one zero byte.
    """
    buf = bytearray()
    _put_uvarint(buf, len(ts_ms))
    prev_ts = 0
    prev_delta = 0
    prev_bits = 0
    for i, t in enumerate(ts_ms):
        if i == 0:
            _put_uvarint(buf, _zigzag(t))
            prev_ts = t
        else:
            delta = t - prev_ts
            _put_uvarint(buf, _zigzag(delta - prev_delta))
            prev_delta, prev_ts = delta, t
        b = bits[i]
        _put_uvarint(buf, b ^ prev_bits)
        prev_bits = b
    return bytes(buf)


def decode_chunk(data: bytes) -> tuple[list[int], list[int]]:
    """Inverse of encode_chunk; raises ValueError on truncation."""
    n, i = _get_uvarint(data, 0)
    ts_ms: list[int] = []
    bits: list[int] = []
    prev_ts = 0
    prev_delta = 0
    prev_bits = 0
    for k in range(n):
        u, i = _get_uvarint(data, i)
        if k == 0:
            prev_ts = _unzigzag(u)
        else:
            prev_delta += _unzigzag(u)
            prev_ts += prev_delta
        ts_ms.append(prev_ts)
        u, i = _get_uvarint(data, i)
        prev_bits ^= u
        bits.append(prev_bits)
    return ts_ms, bits


@dataclass
class Chunk:
    """One sealed, immutable, compressed run of points."""

    start_ms: int
    end_ms: int
    count: int
    data: bytes


# ------------------------------ tiers ---------------------------------

SEAL_POINTS = 256  # head size that triggers a seal (amortizes encode)


class Tier:
    """One bounded ring of (ts, value) points: sealed chunks + an open
    columnar head. Knows nothing about downsampling — a downsampled
    tier is just a Tier fed bucket means.
    """

    __slots__ = (
        "window_s", "seal_points", "chunks", "head_ts", "head_val",
        "_cutoff_ms", "_decoded", "_last_ts", "out_of_order", "_evict_due",
    )

    def __init__(self, window_s: float, seal_points: int = SEAL_POINTS):
        self.window_s = window_s
        self.seal_points = seal_points
        self.chunks: list[Chunk] = []
        self.head_ts = array("d")
        self.head_val = array("f")
        # High-water timestamp: append's ordering check must not cost a
        # chunk decode (the head is empty right after every seal).
        self._last_ts: float | None = None
        # Times the out-of-order sorted-rebuild slow path ran — a
        # misbehaving clock degrades append from O(1) to O(tier), which
        # must be visible (/api/health history stats), not silent.
        self.out_of_order = 0
        # Batch-path eviction pacing: the per-tick batch ingest loop
        # (tpumon.history.RingHistory.record_batch) evicts a tier only
        # when ``now`` crosses this instead of per point — readers pass
        # explicit window starts, so the overhang is invisible to them
        # and bounded to window_s/16 of extra resident points.
        self._evict_due: float | None = None
        self._cutoff_ms = None  # logical eviction bound (ms) or None
        # Decode cache: {id(chunk): (ts_s list, val list)}. Sized to
        # hold a full window's worth of sealed chunks (a 30 min fine
        # tier at 1 Hz is ~8) so the steady-state query path — every
        # tick invalidates the render memo, every render re-reads the
        # window — pays decode once per SEAL, not once per query. Only
        # tiers actually being queried populate it, so the 1024
        # per-chip series cost nothing until someone drills in.
        self._decoded: dict[int, tuple[list[float], list[float]]] = {}

    # ------------------------------ write ------------------------------

    def append(self, ts: float, value: float) -> None:
        """Append a (quantized) point and maintain retention. Caller
        guarantees ms quantization (see quantize_ts). Appends are
        expected time-ordered (the sampler's are); an out-of-order
        point — restore paths seeding old data into a live tier —
        takes a slow sorted-rebuild path so queries keep their bisect
        invariant."""
        if self._last_ts is not None and ts < self._last_ts:
            self._insert_sorted(ts, value)
            return
        self._last_ts = ts
        self.head_ts.append(ts)
        self.head_val.append(value)
        if len(self.head_ts) >= self.seal_points:
            self.seal()
        self.evict(ts)

    def append_batch(self, ts_q: array, val_q: array) -> None:
        """Bulk append of pre-quantized, time-ordered columns (see
        quantize_batch — the caller checked ordering against last_ts).
        Bit-identical end state to appending the points one by one:
        seals trigger at exactly the same chunk boundaries, and the one
        trailing evict subsumes the per-point evicts it replaces (the
        final cutoff is the largest). The per-point cost collapses to
        an array-slice memcpy plus one encode per sealed chunk."""
        n = len(ts_q)
        if not n:
            return
        i = 0
        while i < n:
            room = self.seal_points - len(self.head_ts)
            if room <= 0:
                self.seal()
                continue
            take = room if room < n - i else n - i
            self.head_ts.extend(ts_q[i : i + take])
            self.head_val.extend(val_q[i : i + take])
            i += take
            if len(self.head_ts) >= self.seal_points:
                self.seal()
        self._last_ts = ts_q[n - 1]
        self.evict(self._last_ts)

    def _insert_sorted(self, ts: float, value: float) -> None:
        """Out-of-order insert: decode everything, insert at the sorted
        position, rebuild as one open head (future appends re-seal).
        O(tier) — fine for the restore paths that hit it, never the
        sampler's append path."""
        self.out_of_order += 1
        pts = self.since(None)
        i = bisect_right([t for t, _ in pts], ts)
        pts.insert(i, (ts, value))
        self.chunks.clear()
        self._decoded.clear()
        self._cutoff_ms = None
        self.head_ts = array("d", (t for t, _ in pts))
        self.head_val = array("f", (v for _, v in pts))
        self._last_ts = pts[-1][0]
        if len(self.head_ts) >= self.seal_points:
            self.seal()
        self.evict(pts[-1][0])

    def seal(self) -> None:
        if not self.head_ts:
            return
        k = kernel()
        if k is not None:
            first_ms, last_ms, data = k.seal_encode(self.head_ts, self.head_val)
            self.chunks.append(Chunk(first_ms, last_ms, len(self.head_ts), data))
        else:
            ts_ms = [int(round(t * 1000.0)) for t in self.head_ts]
            bits = [f32bits(v) for v in self.head_val]
            self.chunks.append(
                Chunk(ts_ms[0], ts_ms[-1], len(ts_ms), encode_chunk(ts_ms, bits))
            )
        del self.head_ts[:], self.head_val[:]

    def evict(self, now: float) -> None:
        """Retention: drop whole chunks that fell out of the window;
        trim the head exactly. A partially-expired oldest chunk stays
        resident but its expired points are masked by ``_cutoff_ms`` —
        readers never see them, and the memory overhang is bounded by
        one chunk."""
        cutoff = now - self.window_s
        cutoff_ms = int(round(cutoff * 1000.0))
        while self.chunks and self.chunks[0].end_ms < cutoff_ms:
            self._decoded.pop(id(self.chunks[0]), None)
            self.chunks.pop(0)
        if self.chunks:
            self._cutoff_ms = cutoff_ms if self.chunks[0].start_ms < cutoff_ms else None
        else:
            self._cutoff_ms = None
            k = bisect_left(self.head_ts, cutoff)
            if k:
                del self.head_ts[:k], self.head_val[:k]

    # ------------------------------ read -------------------------------

    def _chunk_points(self, c: Chunk) -> tuple[list[float], list[float]]:
        hit = self._decoded.get(id(c))
        if hit is not None:
            return hit
        ts_ms, bits = decode_chunk(c.data)
        out = ([t / 1000.0 for t in ts_ms], [bits_to_f32(b) for b in bits])
        if len(self._decoded) >= 12:
            self._decoded.pop(next(iter(self._decoded)))
        self._decoded[id(c)] = out
        return out

    def _start_bound(self, start: float | None) -> float:
        lo = self._cutoff_ms / 1000.0 if self._cutoff_ms is not None else None
        if start is None:
            return lo if lo is not None else float("-inf")
        return start if lo is None or start >= lo else lo

    def since(self, start: float | None) -> list[tuple[float, float]]:
        """Points with ts >= start, oldest first — O(log chunks +
        matched): bisect to the first overlapping chunk, decode from
        there, bisect within it."""
        start = self._start_bound(start)
        out: list[tuple[float, float]] = []
        if self.chunks:
            start_ms = int(round(start * 1000.0)) if start > float("-inf") else None
            first = 0
            if start_ms is not None:
                ends = [c.end_ms for c in self.chunks]
                first = bisect_left(ends, start_ms)
            for ci in range(first, len(self.chunks)):
                ts, vals = self._chunk_points(self.chunks[ci])
                k = bisect_left(ts, start) if ci == first else 0
                out.extend(zip(ts[k:], vals[k:]))
        k = bisect_left(self.head_ts, start) if start > float("-inf") else 0
        out.extend(zip(self.head_ts[k:], self.head_val[k:]))
        return out

    def dump(self) -> list[tuple[float, float]]:
        """All live points, decoded WITHOUT populating the decode cache
        — the bulk-dump path (tpumon.state's JSON checkpoint walks every
        series every save) must not pin boxed-float lists for chunks no
        query is reading, or it would resurrect the deque-era resident
        memory this store exists to eliminate."""
        lo = self._start_bound(None)
        out: list[tuple[float, float]] = []
        for i, c in enumerate(self.chunks):
            cached = self._decoded.get(id(c))
            if cached is not None:
                ts, vals = cached
            else:
                ts_ms, bits = decode_chunk(c.data)
                ts = [t / 1000.0 for t in ts_ms]
                vals = [bits_to_f32(b) for b in bits]
            k = bisect_left(ts, lo) if i == 0 and lo > float("-inf") else 0
            out.extend(zip(ts[k:], vals[k:]))
        out.extend(zip(self.head_ts, self.head_val))
        return out

    def last(self) -> tuple[float, float] | None:
        if self.head_ts:
            return self.head_ts[-1], self.head_val[-1]
        if self.chunks:
            ts, vals = self._chunk_points(self.chunks[-1])
            return ts[-1], vals[-1]
        return None

    def last_ts(self) -> float | None:
        """Newest timestamp without any decode (timestamp-only callers
        — resample's end derivation — must stay cache-neutral)."""
        return self._last_ts

    def sync_last(self) -> None:
        """Recompute the high-water timestamp from resident data (the
        snapshot-adopt path fills chunks/head directly)."""
        if self.head_ts:
            self._last_ts = self.head_ts[-1]
        elif self.chunks:
            self._last_ts = self.chunks[-1].end_ms / 1000.0
        else:
            self._last_ts = None

    def first(self) -> tuple[float, float] | None:
        lo = self._start_bound(None)
        if self.chunks:
            ts, vals = self._chunk_points(self.chunks[0])
            k = bisect_left(ts, lo)
            if k < len(ts):
                return ts[k], vals[k]
            # fully-masked first chunk: fall through to the next data
            rest = self.since(lo)
            return rest[0] if rest else None
        if self.head_ts:
            return self.head_ts[0], self.head_val[0]
        return None

    def __len__(self) -> int:
        n = len(self.head_ts) + sum(c.count for c in self.chunks)
        if self._cutoff_ms is not None and self.chunks:
            ts, _ = self._chunk_points(self.chunks[0])
            n -= bisect_left(ts, self._cutoff_ms / 1000.0)
        return n

    def approx_len(self) -> int:
        """Resident point count ignoring the partial-first-chunk mask —
        O(chunks), no decode; the health/stats path at 1000+ series."""
        return len(self.head_ts) + sum(c.count for c in self.chunks)

    def resident_bytes(self) -> int:
        return (
            sum(len(c.data) + 64 for c in self.chunks)
            + self.head_ts.itemsize * len(self.head_ts)
            + self.head_val.itemsize * len(self.head_val)
        )


def quantize_ts(ts: float) -> float:
    """Millisecond quantization applied on every write — identical to
    the precision the v1 JSON snapshots rounded to, and what makes a
    point bit-stable across head/sealed representations."""
    return round(ts * 1000.0) / 1000.0


def quantize_val(v: float) -> float:
    """The value column is float32; quantize through it so a value
    compares equal before and after a seal."""
    return _F32.unpack(_F32.pack(v))[0]


def quantize_batch(
    ts_list, values, last_ts: float | None
) -> tuple[array, array, bool]:
    """Quantize a batch of raw (ts, value) columns in one step:
    timestamps onto the ms grid, values through float32 — plus the
    in-order check against ``last_ts`` (the tier's high water). Returns
    (ts_q, val_q, ordered); an unordered batch is handed back for the
    caller's per-point slow path. One C call when the kernel is loaded;
    the Python fallback leans on array('f')'s C-speed float32 casts."""
    k = kernel()
    if k is not None:
        tsa = ts_list if isinstance(ts_list, array) else array("d", ts_list)
        va = values if isinstance(values, array) else array("d", values)
        return k.quantize(tsa, va, last_ts)
    ts_q = array("d", [round(t * 1000.0) / 1000.0 for t in ts_list])
    val_q = array("f", values)
    ordered = True
    prev = last_ts
    for t in ts_q:
        if prev is not None and t < prev:
            ordered = False
            break
        prev = t
    return ts_q, val_q, ordered


# ----------------------------- views ----------------------------------


class PointsView:
    """Deque-compatible view over a Tier: the ``points`` / ``coarse``
    attributes history consumers (and tests) index, iterate and extend
    keep working unchanged over the columnar storage. Reads are
    decoded on demand (``[0]``/``[-1]`` without a full decode); writes
    go straight into the tier (the restore paths) and report through
    ``on_write`` so version counters stay honest."""

    __slots__ = ("_tier", "_on_write")

    def __init__(self, tier: "Tier", on_write=None):
        self._tier = tier
        self._on_write = on_write

    def _all(self) -> list[tuple[float, float]]:
        return self._tier.since(None)

    def __len__(self) -> int:
        return len(self._tier)

    def __bool__(self) -> bool:
        return bool(self._tier.head_ts) or len(self._tier) > 0

    def __iter__(self):
        return iter(self._all())

    def __reversed__(self):
        return reversed(self._all())

    def __getitem__(self, i):
        if isinstance(i, int):
            p = None
            if i == 0:
                p = self._tier.first()
            elif i == -1:
                p = self._tier.last()
            if p is not None:
                return p
            pts = self._all()
            return pts[i]
        return self._all()[i]

    def append(self, point) -> None:
        ts, v = point
        self._tier.append(quantize_ts(float(ts)), quantize_val(float(v)))
        if self._on_write is not None:
            self._on_write()

    def extend(self, points) -> None:
        for p in points:
            self.append(p)


# --------------------------- series core ------------------------------


class Downsample:
    """One downsampled tier: a Tier of bucket means plus the running
    accumulator for the open bucket (incremental — never query-time)."""

    __slots__ = ("step_s", "tier", "bucket", "bsum", "bn")

    def __init__(self, step_s: float, window_s: float):
        self.step_s = step_s
        self.tier = Tier(window_s)
        self.bucket: int | None = None
        self.bsum = 0.0
        self.bn = 0

    def observe(self, ts: float, value: float) -> None:
        b = int(ts // self.step_s)
        if self.bucket is not None and b != self.bucket:
            self.flush()
        self.bucket = b
        self.bsum += value
        self.bn += 1
        self.tier.evict(ts)

    def observe_batch(self, ts_q: array, val_q: array) -> None:
        """Accumulate an ordered, quantized batch: bucket sums advance
        per point (same add order as observe — bit-exact), but closed
        buckets are collected and appended in one pass and the tier is
        evicted once at the end instead of per point. One C call when
        the kernel is loaded."""
        n = len(ts_q)
        if not n:
            return
        k = kernel()
        if k is not None:
            flushes = k.accum(ts_q, val_q, self.step_s, self)
        else:
            flushes = []
            step = self.step_s
            bucket, bsum, bn = self.bucket, self.bsum, self.bn
            for i in range(n):
                b = int(ts_q[i] // step)
                if bucket is not None and b != bucket:
                    if bn:
                        flushes.append(
                            (quantize_ts((bucket + 0.5) * step), bsum / bn)
                        )
                    bsum, bn = 0.0, 0
                bucket = b
                bsum += val_q[i]
                bn += 1
            self.bucket, self.bsum, self.bn = bucket, bsum, bn
        for fts, fmean in flushes:
            self.tier.append(fts, quantize_val(fmean))
        self.tier.evict(ts_q[-1])

    def flush(self) -> None:
        if self.bucket is not None and self.bn:
            mid = quantize_ts((self.bucket + 0.5) * self.step_s)
            self.tier.append(mid, quantize_val(self.bsum / self.bn))
        self.bsum, self.bn = 0.0, 0

    def live_point(self) -> tuple[float, float] | None:
        """The open bucket's mean-so-far (not yet flushed)."""
        if self.bucket is None or not self.bn:
            return None
        return quantize_ts((self.bucket + 0.5) * self.step_s), self.bsum / self.bn


class AccumStore:
    """Contiguous (bucket, bsum, bn) columns for a family of same-step
    downsample accumulators — the layout the native ``accum_many``
    kernel updates in ONE call per tick for every per-chip series at
    once (tpumon.history.RingHistory.record_batch). ``bucket`` uses NaN
    for "no open bucket"; ``bn`` rides as float64 (counts are tiny, and
    Python's ``bsum / int(bn)`` and C's ``bsum / (double)bn`` divide the
    same doubles either way)."""

    __slots__ = ("step_s", "bucket", "bsum", "bn")

    def __init__(self, step_s: float):
        self.step_s = step_s
        self.bucket = array("d")
        self.bsum = array("d")
        self.bn = array("d")

    def add_slot(self) -> int:
        self.bucket.append(float("nan"))
        self.bsum.append(0.0)
        self.bn.append(0.0)
        return len(self.bucket) - 1

    def __len__(self) -> int:
        return len(self.bucket)


class SlotDownsample(Downsample):
    """A Downsample whose accumulator state lives in an AccumStore slot:
    ``bucket``/``bsum``/``bn`` become views over the store's columns so
    the batch kernel can update thousands of accumulators in one call,
    while every existing consumer (observe, flush, live_point, the
    snapshot codec, tests poking attributes) keeps working unchanged —
    only the storage moved."""

    __slots__ = ("_store", "_slot")

    def __init__(self, store: AccumStore, slot: int, window_s: float):
        # Deliberately NOT calling Downsample.__init__: the accumulator
        # writes it does would route through the properties below before
        # _store is bound.
        self._store = store
        self._slot = slot
        self.step_s = store.step_s
        self.tier = Tier(window_s)

    @property
    def bucket(self) -> int | None:
        b = self._store.bucket[self._slot]
        return None if b != b else int(b)

    @bucket.setter
    def bucket(self, v) -> None:
        self._store.bucket[self._slot] = float("nan") if v is None else float(v)

    @property
    def bsum(self) -> float:
        return self._store.bsum[self._slot]

    @bsum.setter
    def bsum(self, v: float) -> None:
        self._store.bsum[self._slot] = v

    @property
    def bn(self) -> int:
        return int(self._store.bn[self._slot])

    @bn.setter
    def bn(self, v: int) -> None:
        self._store.bn[self._slot] = float(v)


# Minimum batch size for the native accum_many path: the kernel call's
# fixed cost (three flush-buffer allocations + six pointer casts + the
# FFI round trip, ~13 µs measured) crosses the pure-Python loop
# (~0.4 µs/series) near 32 series. Below it — e.g. the SLO engine's
# per-tick slo.<name>.bad append, a handful of series — the fallback is
# strictly faster; both paths are bit-exact (tests/test_ingest.py), so
# the switch is invisible to state.
ACCUM_KERNEL_MIN = 32


def accum_many(
    ts_q: float, val_q: array, slots: array, store: AccumStore
) -> list[tuple[int, float, float]]:
    """One point per series at a shared quantized timestamp, accumulated
    into ``store``'s columns; returns closed buckets as (slot, mid_ts,
    raw mean) — the multi-series mirror of Downsample.observe_batch.
    One C call when the kernel is loaded and the batch is large enough
    to amortize the call (ACCUM_KERNEL_MIN)."""
    k = kernel()
    if k is not None and len(slots) >= ACCUM_KERNEL_MIN:
        return k.accum_many(ts_q, val_q, slots, store)
    step = store.step_s
    bnew = int(ts_q // step)
    bnew_f = float(bnew)
    bucket_col, bsum_col, bn_col = store.bucket, store.bsum, store.bn
    flushes: list[tuple[int, float, float]] = []
    for i, s in enumerate(slots):
        b = bucket_col[s]
        if b == b and b != bnew_f:
            if bn_col[s]:
                flushes.append(
                    (s, quantize_ts((b + 0.5) * step), bsum_col[s] / bn_col[s])
                )
            bsum_col[s] = 0.0
            bn_col[s] = 0.0
        bucket_col[s] = bnew_f
        bsum_col[s] += val_q[i]
        bn_col[s] += 1.0
    return flushes


def merged(
    fine: Tier, down: list[Downsample], window_s: float, end: float
) -> list[tuple[float, float]]:
    """Points covering [end - window_s, end] across tiers: each coarser
    tier fills only the span older than all finer data (finer data
    wins), output time-ordered coarsest→finest. Unflushed live buckets
    are included exactly when they predate the finer tier's data — the
    newest downsampled value must not vanish just because its bucket
    hasn't closed."""
    start = end - window_s
    fine_pts = fine.since(start)
    bound = fine_pts[0][0] if fine_pts else float("inf")
    parts: list[list[tuple[float, float]]] = []
    for d in down:  # finest downsample first
        pts = [p for p in d.tier.since(start) if p[0] < bound]
        live = d.live_point()
        if live is not None and start <= live[0] < bound:
            pts.append(live)
        if pts:
            bound = pts[0][0]
            parts.append(pts)
    out: list[tuple[float, float]] = []
    for pts in reversed(parts):  # coarsest first in the output
        out.extend(pts)
    out.extend(fine_pts)
    return out


# ----------------------- binary snapshot codec ------------------------

MAGIC = b"TPUHIST\x02"
SNAPSHOT_VERSION = 2


def dump_snapshot(series: dict[str, object], saved_at: float) -> bytes:
    """Serialize a series map: magic + u32 index length + JSON index +
    raw payload. Sealed chunk bytes are written **verbatim** (already
    compressed); heads ride as raw array bytes — no per-point work at
    all, which is where the ~10x over json.dumps comes from."""
    index: dict = {"version": SNAPSHOT_VERSION, "saved_at": saved_at, "series": []}
    payload = bytearray()

    def emit_tier(t: Tier) -> dict:
        chunks = []
        for c in t.chunks:
            chunks.append([c.start_ms, c.end_ms, c.count, len(c.data)])
            payload.extend(c.data)
        head_n = len(t.head_ts)
        payload.extend(t.head_ts.tobytes())
        payload.extend(t.head_val.tobytes())
        return {"window_s": t.window_s, "chunks": chunks, "head_n": head_n}

    for name, s in series.items():
        entry: dict = {"name": name, "fine": emit_tier(s.fine), "down": []}
        for d in s.down:
            entry["down"].append(
                {
                    "step_s": d.step_s,
                    "tier": emit_tier(d.tier),
                    "bucket": d.bucket,
                    "bsum": d.bsum,
                    "bn": d.bn,
                }
            )
        index["series"].append(entry)
    index_json = json.dumps(index, separators=(",", ":")).encode()
    return MAGIC + struct.pack("<I", len(index_json)) + index_json + bytes(payload)


def load_snapshot(data: bytes) -> tuple[float, list[dict]]:
    """Parse a dump_snapshot blob back into plain structures WITHOUT
    touching any live ring — callers adopt the result only after the
    whole parse succeeded. Returns (saved_at, series dumps), where each
    dump is {"name", "fine": tier_dump, "down": [...]} and a tier dump
    is {"window_s", "chunks": [Chunk...], "head_ts": array('d'),
    "head_val": array('f')}.

    Raises ValueError on any truncation/corruption — every length is
    bounds-checked before use, and chunk payloads are verified to
    decode to their declared count (a torn tail can't smuggle garbage
    into a ring)."""
    if data[: len(MAGIC)] != MAGIC:
        raise ValueError("bad magic (not a tpumon binary history snapshot)")
    off = len(MAGIC)
    if len(data) < off + 4:
        raise ValueError("truncated index length")
    (index_len,) = struct.unpack_from("<I", data, off)
    off += 4
    if len(data) < off + index_len:
        raise ValueError("truncated index")
    try:
        index = json.loads(data[off : off + index_len])
    except json.JSONDecodeError as e:
        raise ValueError(f"corrupt index: {e}")
    off += index_len
    if not isinstance(index, dict) or index.get("version") != SNAPSHOT_VERSION:
        raise ValueError(f"unsupported snapshot version {index.get('version')!r}")
    saved_at = index.get("saved_at")
    if not isinstance(saved_at, (int, float)):
        raise ValueError("missing saved_at")

    def read_tier(meta: dict) -> tuple[dict, int]:
        nonlocal off
        chunks: list[Chunk] = []
        for start_ms, end_ms, count, blen in meta["chunks"]:
            if len(data) < off + blen:
                raise ValueError("truncated chunk payload")
            blob = data[off : off + blen]
            off += blen
            ts_ms, _bits = decode_chunk(blob)  # validates
            if len(ts_ms) != count:
                raise ValueError("chunk count mismatch")
            chunks.append(Chunk(int(start_ms), int(end_ms), int(count), blob))
        head_n = int(meta["head_n"])
        need = head_n * (8 + 4)
        if len(data) < off + need:
            raise ValueError("truncated head columns")
        head_ts = array("d")
        head_ts.frombytes(data[off : off + head_n * 8])
        off += head_n * 8
        head_val = array("f")
        head_val.frombytes(data[off : off + head_n * 4])
        off += head_n * 4
        return (
            {
                "window_s": float(meta["window_s"]),
                "chunks": chunks,
                "head_ts": head_ts,
                "head_val": head_val,
            },
            head_n,
        )

    out: list[dict] = []
    try:
        for entry in index["series"]:
            fine, _ = read_tier(entry["fine"])
            down = []
            for dmeta in entry.get("down") or []:
                tier, _ = read_tier(dmeta["tier"])
                down.append(
                    {
                        "step_s": float(dmeta["step_s"]),
                        "tier": tier,
                        "bucket": dmeta.get("bucket"),
                        "bsum": float(dmeta.get("bsum") or 0.0),
                        "bn": int(dmeta.get("bn") or 0),
                    }
                )
            out.append({"name": str(entry["name"]), "fine": fine, "down": down})
    except (KeyError, TypeError, IndexError) as e:
        raise ValueError(f"malformed snapshot index: {e}")
    return float(saved_at), out


def tier_points(dump: dict) -> list[tuple[float, float]]:
    """Decode a load_snapshot tier dump to plain points (the fallback
    path when the live ring's tier geometry doesn't match the file's —
    points are replayed through record() instead of adopted)."""
    out: list[tuple[float, float]] = []
    for c in dump["chunks"]:
        ts_ms, bits = decode_chunk(c.data)
        out.extend((t / 1000.0, bits_to_f32(b)) for t, b in zip(ts_ms, bits))
    out.extend(zip(dump["head_ts"], dump["head_val"]))
    return out
