"""``python -m tpumon.validate`` — prove the monitor sees real load.

Runs the loadgen workloads while sampling the accelerator collector and
checks that the monitored counters respond:

1. HBM: allocate ~30% of HBM -> hbm_used must rise; release -> fall.
2. MXU: run the matmul burn -> duty cycle must rise above baseline.
3. Serving: run the in-tree engine (greedy + speculative + paged),
   scrape its /metrics through the real serving collector, and check
   tokens flow, outputs agree across modes, and the spec/pool counters
   report.

On hosts where a counter source is unavailable (no libtpu metrics
service, memory_stats unsupported) each check reports SKIP with the
reason rather than pretending success — the same honest-degradation
stance as the rest of the framework. Exit code: 0 if no check FAILED.

The verdict logic (counter-delta assertions, skip/fail classification)
is pure functions over sampled values — unit-tested against fake
collectors in tests/test_validate.py — while the hardware entry point
below stays a thin orchestrator. ``--json PATH`` writes the results as
an artifact (VALIDATE_r{N}.json in this repo) so a run's evidence is
committable, not just scrollback.
"""

from __future__ import annotations

import asyncio
import json
import sys
import threading
import time
from dataclasses import asdict, dataclass


@dataclass(frozen=True)
class CheckResult:
    check: str
    verdict: str  # PASS | FAIL | SKIP
    detail: str


def _mean(vals: list[float | None]) -> float | None:
    xs = [v for v in vals if v is not None]
    return sum(xs) / len(xs) if xs else None


# ---------------------------------------------------------------------------
# Pure verdict logic (unit-tested without hardware).
# ---------------------------------------------------------------------------


def classify_chips_visible(chips: list) -> CheckResult:
    if not chips:
        return CheckResult("chips-visible", "FAIL", "no chips reported")
    srcs = sorted(
        {c.counter_source for c in chips if getattr(c, "counter_source", None)}
    )
    return CheckResult(
        "chips-visible",
        "PASS",
        f"{len(chips)} chip(s), kind {chips[0].kind}"
        + (f", counters: {'/'.join(srcs)}" if srcs else ""),
    )


def classify_hbm_response(
    hbm0: float | None,
    hbm_during: float | None,
    hbm_after: float | None,
    synthetic: bool,
    source: str | None = None,
) -> CheckResult:
    """A ~30% HBM fill must register as a >=1.1x rise while held — that
    is the hard gate. The post-release reading is recorded but does not
    gate: allocator reservation semantics and coarse counter cadences
    legitimately hold the peak briefly, so "didn't fall within a second"
    must not flunk a healthy chip (it is noted for the artifact)."""
    if synthetic:
        return CheckResult("hbm-response", "SKIP", "synthetic backend")
    if hbm0 is None:
        return CheckResult("hbm-response", "SKIP", "no HBM counter source")
    if hbm_during is None or hbm_during <= hbm0 * 1.1:
        return CheckResult(
            "hbm-response",
            "FAIL",
            f"hbm_used {hbm0} -> {hbm_during} did not track a 30% fill",
        )
    detail = f"{hbm0 / 2**30:.1f} -> {hbm_during / 2**30:.1f} GiB during fill"
    if hbm_after is None:
        pass
    elif hbm_after < hbm_during * 0.98:
        detail += f" -> {hbm_after / 2**30:.1f} GiB after release"
    else:
        detail += (
            f"; release not yet visible ({hbm_after / 2**30:.1f} GiB — "
            "allocator retention or coarse counter)"
        )
    if source:
        detail += f" [source: {source}]"
    return CheckResult("hbm-response", "PASS", detail)


def classify_mxu_response(
    duty0: float | None,
    duty_during: list[float | None],
    synthetic: bool,
    source: str | None = None,
) -> CheckResult:
    """An MXU burn must push the duty cycle above both the idle baseline
    and an absolute 5% floor (guards against a counter that reads a
    constant small value)."""
    if synthetic:
        return CheckResult("mxu-response", "SKIP", "synthetic backend")
    if duty0 is None:
        return CheckResult("mxu-response", "SKIP", "no duty-cycle counter source")
    peak = max((d for d in duty_during if d is not None), default=None)
    if peak is not None and peak > max(duty0, 5.0):
        return CheckResult(
            "mxu-response",
            "PASS",
            f"duty {duty0:.1f}% -> peak {peak:.1f}% under burn"
            + (f" [source: {source}]" if source else ""),
        )
    return CheckResult(
        "mxu-response", "FAIL", f"duty {duty0} -> {duty_during} under burn"
    )


def classify_serving(outcome: str | None, error: Exception | None) -> CheckResult:
    if error is None:
        return CheckResult("serving-engine", "PASS", outcome or "")
    if isinstance(error, ImportError):
        return CheckResult("serving-engine", "SKIP", f"unavailable: {error}")
    return CheckResult(
        "serving-engine", "FAIL", f"{type(error).__name__}: {error}"
    )


def summarize(results: list[CheckResult]) -> tuple[str, int]:
    """Render the report table; exit code 1 iff any check FAILED."""
    width = max(len(r.check) for r in results)
    lines = [f"{r.check:<{width}}  {r.verdict:<5} {r.detail}" for r in results]
    failed = any(r.verdict == "FAIL" for r in results)
    return "\n".join(lines), 1 if failed else 0


def results_json(results: list[CheckResult], backend: str, seconds: float) -> dict:
    return {
        "backend": backend,
        "seconds": round(seconds, 1),
        "exit": summarize(results)[1],
        "checks": [asdict(r) for r in results],
    }


# ---------------------------------------------------------------------------
# Hardware orchestration (thin; no verdict logic).
# ---------------------------------------------------------------------------


async def _sample_chips(collector):
    s = await collector.collect()
    return list(s.data or [])


def _validate_serving() -> str:
    """Run the in-tree engine on this device in its three KV/decode
    modes, assert greedy outputs agree, and scrape /metrics through the
    real serving collector (the monitor's ingest path)."""
    from tpumon.collectors.serving import distill_serving_metrics
    from tpumon.loadgen.model import ModelConfig
    from tpumon.loadgen.serving import ServeConfig, ServingEngine

    model = ModelConfig(vocab=512, d_model=128, n_layers=2, n_heads=4,
                        n_kv_heads=2, d_ff=256, max_seq=128)
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6], [5, 3, 5, 8, 9, 7]]

    def run(**kw):
        eng = ServingEngine(cfg=ServeConfig(
            model=model, slots=2, prefill_len=16, **kw))
        reqs = [eng.submit(p, max_new=8) for p in prompts]
        eng.drain()
        assert all(r.done.is_set() for r in reqs), "requests did not finish"
        return eng, [r.output for r in reqs]

    dense_eng, dense = run()
    spec_eng, spec = run(spec_len=3)
    paged_eng, paged = run(kv_layout="paged", pool_pages=9)
    _, block = run(decode_block=4)
    _, kvq = run(kv_dtype="int8", decode_block=4)

    def next_logits(context: list):
        """Teacher-forced next-token logits on the dense engine's
        weights: chunked prefill over ``context`` into a fresh cache,
        final-chunk logits — the oracle for deciding whether a
        cross-mode divergence was an argmax near-tie."""
        import jax.numpy as jnp
        import numpy as np

        from tpumon.loadgen.serving import init_cache

        p = dense_eng.cfg.prefill_len
        cache = init_cache(dense_eng.cfg)
        logits = None
        for start in range(0, len(context), p):
            chunk = context[start:start + p]
            padded = chunk + [0] * (p - len(chunk))
            cache, logits = dense_eng._prefill(
                dense_eng.params, cache,
                jnp.asarray(padded, jnp.int32), jnp.int32(len(chunk)),
                jnp.int32(0), jnp.int32(start))
        return np.asarray(logits)

    # bf16 on real chips: block vs step dispatch shapes may flip argmax
    # near-ties (documented; int8 KV adds quantization noise on top), so
    # identity isn't required — but every divergence must be NAMED and
    # PROVEN a near-tie at its first divergent position (VERDICT r04
    # weak #6: an 11/12 pass with no record of which mode diverged
    # would let a real paged/int8 bug hide inside the tolerance).
    import numpy as np

    modes = (("spec", spec, 0.05), ("paged", paged, 0.05),
             ("block", block, 0.05), ("int8-kv", kvq, 0.5))
    agree = 0
    mism: list[str] = []
    for name, outs, tol in modes:
        for i, (a, b) in enumerate(zip(dense, outs)):
            if a == b:
                agree += 1
                continue
            k = next((j for j, (x, y) in enumerate(zip(a, b)) if x != y),
                     min(len(a), len(b)))
            logits = next_logits(prompts[i] + a[:k])
            gap = abs(float(logits[a[k]]) - float(logits[b[k]]))
            ratio = gap / (float(np.std(logits)) + 1e-9)
            tie = ratio <= tol
            mism.append(f"{name}@prompt{i}:pos{k} "
                        f"{a[k]}vs{b[k]} gap/std={ratio:.3f}"
                        f"{'(tie)' if tie else '(NOT A TIE)'}")
            assert tie, (
                f"mode {name!r} diverged from dense at prompt {i} "
                f"pos {k} with logit gap/std {ratio:.3f} > {tol} — "
                "not an argmax near-tie; a decode path is wrong: "
                + "; ".join(mism))
    assert agree >= 8, (
        f"only {agree}/12 outputs agree across modes — beyond bf16 "
        "near-tie/quantization noise; a decode path is diverging: "
        + "; ".join(mism))
    d = distill_serving_metrics(spec_eng.metrics_text())
    pool = distill_serving_metrics(paged_eng.metrics_text())
    assert d.get("tokens_total", 0) > 0, "no tokens counted"
    assert "spec_accept_pct" in d, "spec counters missing"
    assert "kv_pages_used_pct" in pool, "pool gauges missing"
    detail = (f"dense/spec/paged/block/int8-kv ran; {agree}/12 outputs "
              f"agree; spec accept {d['spec_accept_pct']:.0f}%")
    if mism:
        detail += "; divergences all near-ties: " + "; ".join(mism)
    return detail


async def validate(backend: str = "jax") -> list[CheckResult]:
    from tpumon.collectors.accel import make_accel_collector
    from tpumon.config import load_config

    cfg = load_config(env={"TPUMON_ACCEL_BACKEND": backend})
    collector = make_accel_collector(cfg)
    results: list[CheckResult] = []

    # Self-report this process's own device activity/footprint into the
    # collector's workload source (tpumon.collectors.workload). On hosts
    # where every platform counter source is dark (PROBE_libtpu.md
    # finding #3) this is what lets the hbm/mxu checks run at all — the
    # provenance is explicit (counter_source: "workload" per chip).
    reporter = None
    synthetic = backend.startswith("fake:")

    # First sample BEFORE any reporter work: the collector owns the
    # wedged-runtime guard (init_timeout_s), so JAX is only touched
    # inline once this probe proves the backend answers.
    probe_chips = await _sample_chips(collector)
    if probe_chips and not synthetic and cfg.workload_dir:
        from tpumon.loadgen.report import WorkloadReporter

        try:
            reporter = WorkloadReporter(
                name="validate", directory=cfg.workload_dir, interval_s=0.5
            )
            reporter.write_once()  # baseline report before re-sampling
            reporter.start()
        except Exception as e:
            # Unwritable / foreign-owned report dir, or a JAX runtime
            # error: validation must still run — the counter checks
            # just SKIP as before.
            print(f"validate: workload self-report disabled: {e}",
                  file=sys.stderr)
            reporter = None

    try:
        # Per-source probe provenance (VERDICT r03 item #8): one line
        # per counter source saying live/dark and WHY, so a run on a
        # host where libtpu counters answer is immediately
        # distinguishable from the self-report-only evidence chain.
        # After reporter start, so the workload channel reflects this
        # run; before the checks, which consume these sources.
        # Informational: dark platform sources SKIP (the fallback chain
        # existing is the design), they never FAIL.
        if hasattr(collector, "probe_sources"):
            for src, info in (await collector.probe_sources()).items():
                results.append(CheckResult(
                    f"source-{src}",
                    "PASS" if info["live"] else "SKIP",
                    ("live: " if info["live"] else "dark: ")
                    + info["detail"],
                ))
        chips0 = (
            await _sample_chips(collector) if reporter else probe_chips
        )
        results.append(classify_chips_visible(chips0))
        if not chips0:
            print(
                "validate: no chips visible — nothing to validate",
                file=sys.stderr,
            )

        hbm0 = _mean([c.hbm_used for c in chips0]) if chips0 else None

        # ---- HBM response ----
        if synthetic or hbm0 is None:
            results.append(classify_hbm_response(hbm0, None, None, synthetic))
        else:
            from tpumon.loadgen.burn import hbm_fill

            arrays = await asyncio.to_thread(hbm_fill, 0.3)
            await asyncio.sleep(1.0)
            chips_during = await _sample_chips(collector)
            hbm_during = _mean([c.hbm_used for c in chips_during])
            hbm_src = "/".join(
                sorted({c.counter_source for c in chips_during
                        if c.counter_source})
            ) or None
            del arrays
            await asyncio.sleep(1.0)
            hbm_after = _mean(
                [c.hbm_used for c in await _sample_chips(collector)]
            )
            results.append(
                classify_hbm_response(
                    hbm0, hbm_during, hbm_after, synthetic, source=hbm_src
                )
            )

        # ---- MXU duty response ----
        duty0 = _mean([c.mxu_duty_pct for c in chips0]) if chips0 else None
        if synthetic or duty0 is None:
            results.append(classify_mxu_response(duty0, [], synthetic))
        else:
            from tpumon.loadgen.burn import mxu_burn

            stop = threading.Event()

            def burn():
                while not stop.is_set():
                    if reporter is not None:
                        with reporter.device_work():
                            mxu_burn(seconds=0.5, size=2048, iters=16)
                    else:
                        mxu_burn(seconds=0.5, size=2048, iters=16)

            t = threading.Thread(target=burn, daemon=True)
            t.start()
            duty_src = None
            try:
                await asyncio.sleep(2.0)
                duty_during = []
                for _ in range(5):
                    chips = await _sample_chips(collector)
                    duty_during.append(
                        _mean([c.mxu_duty_pct for c in chips])
                    )
                    duty_src = "/".join(
                        sorted({c.counter_source for c in chips
                                if c.counter_source})
                    ) or duty_src
                    await asyncio.sleep(1.0)
            finally:
                stop.set()
            results.append(
                classify_mxu_response(
                    duty0, duty_during, synthetic, source=duty_src
                )
            )
    finally:
        if reporter is not None:
            reporter.stop()

    # ---- serving engine on this device ----
    # Independent of the accel backend (the engine runs on whatever jax
    # device exists, CPU included); hosts without the workload stack
    # SKIP rather than FAIL, like the counter checks above.
    try:
        detail = await asyncio.to_thread(_validate_serving)
        results.append(classify_serving(detail, None))
    except Exception as e:
        results.append(classify_serving(None, e))

    return results


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    backend = "jax"
    json_path = None
    if "--backend" in argv:
        i = argv.index("--backend")
        if i + 1 >= len(argv):
            print("--backend requires a value", file=sys.stderr)
            return 2
        backend = argv[i + 1]
    if "--json" in argv:
        i = argv.index("--json")
        if i + 1 >= len(argv):
            print("--json requires a path", file=sys.stderr)
            return 2
        json_path = argv[i + 1]
    start = time.time()
    results = asyncio.run(validate(backend))
    report, code = summarize(results)
    print(report)
    elapsed = time.time() - start
    if json_path:
        with open(json_path, "w") as f:
            json.dump(results_json(results, backend, elapsed), f, indent=1)
        print(f"validate: wrote {json_path}")
    print(f"validate: done in {elapsed:.1f}s, exit {code}")
    return code


if __name__ == "__main__":
    raise SystemExit(main())
