"""``python -m tpumon.validate`` — prove the monitor sees real load.

Runs the loadgen workloads while sampling the accelerator collector and
checks that the monitored counters respond:

1. HBM: allocate ~30% of HBM -> hbm_used must rise; release -> fall.
2. MXU: run the matmul burn -> duty cycle must rise above baseline.
3. Serving: run the in-tree engine (greedy + speculative + paged),
   scrape its /metrics through the real serving collector, and check
   tokens flow, outputs agree across modes, and the spec/pool counters
   report.

On hosts where a counter source is unavailable (no libtpu metrics
service, memory_stats unsupported) each check reports SKIP with the
reason rather than pretending success — the same honest-degradation
stance as the rest of the framework. Exit code: 0 if no check FAILED.
"""

from __future__ import annotations

import asyncio
import sys
import threading
import time


def _mean(vals: list[float | None]) -> float | None:
    xs = [v for v in vals if v is not None]
    return sum(xs) / len(xs) if xs else None


async def _sample_chips(collector):
    s = await collector.collect()
    return list(s.data or [])


def _validate_serving() -> str:
    """Run the in-tree engine on this device in its three KV/decode
    modes, assert greedy outputs agree, and scrape /metrics through the
    real serving collector (the monitor's ingest path)."""
    from tpumon.collectors.serving import distill_serving_metrics
    from tpumon.loadgen.model import ModelConfig
    from tpumon.loadgen.serving import ServeConfig, ServingEngine

    model = ModelConfig(vocab=512, d_model=128, n_layers=2, n_heads=4,
                        n_kv_heads=2, d_ff=256, max_seq=128)
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6], [5, 3, 5, 8, 9, 7]]

    def run(**kw):
        eng = ServingEngine(cfg=ServeConfig(
            model=model, slots=2, prefill_len=16, **kw))
        reqs = [eng.submit(p, max_new=8) for p in prompts]
        eng.drain()
        assert all(r.done.is_set() for r in reqs), "requests did not finish"
        return eng, [r.output for r in reqs]

    _, dense = run()
    spec_eng, spec = run(spec_len=3)
    paged_eng, paged = run(kv_layout="paged", pool_pages=9)
    # bf16 on real chips: block vs step dispatch shapes may flip argmax
    # near-ties (documented), so require near-agreement, not identity.
    agree = sum(a == b for a, b in zip(dense, spec)) + sum(
        a == b for a, b in zip(dense, paged))
    assert agree >= 4, (
        f"only {agree}/6 outputs agree across modes — beyond bf16 "
        "near-tie noise; a decode path is diverging")
    d = distill_serving_metrics(spec_eng.metrics_text())
    pool = distill_serving_metrics(paged_eng.metrics_text())
    assert d.get("tokens_total", 0) > 0, "no tokens counted"
    assert "spec_accept_pct" in d, "spec counters missing"
    assert "kv_pages_used_pct" in pool, "pool gauges missing"
    return (f"dense/spec/paged ran; {agree}/6 outputs agree; "
            f"spec accept {d['spec_accept_pct']:.0f}%")


async def validate(backend: str = "jax") -> int:
    from tpumon.collectors.accel import make_accel_collector
    from tpumon.config import load_config

    cfg = load_config(env={"TPUMON_ACCEL_BACKEND": backend})
    collector = make_accel_collector(cfg)
    results: list[tuple[str, str, str]] = []  # (check, verdict, detail)

    chips0 = await _sample_chips(collector)
    if not chips0:
        print("validate: no chips visible — nothing to validate", file=sys.stderr)
        results.append(("chips-visible", "FAIL", "no chips reported"))
    else:
        results.append(
            ("chips-visible", "PASS", f"{len(chips0)} chip(s), kind {chips0[0].kind}")
        )

    synthetic = backend.startswith("fake:")
    hbm0 = _mean([c.hbm_used for c in chips0]) if chips0 else None

    # ---- HBM response ----
    if synthetic:
        results.append(("hbm-response", "SKIP", "synthetic backend"))
    elif hbm0 is None:
        results.append(("hbm-response", "SKIP", "no HBM counter source"))
    else:
        from tpumon.loadgen.burn import hbm_fill

        arrays = await asyncio.to_thread(hbm_fill, 0.3)
        await asyncio.sleep(1.0)
        chips1 = await _sample_chips(collector)
        hbm1 = _mean([c.hbm_used for c in chips1])
        del arrays
        if hbm1 is not None and hbm1 > hbm0 * 1.1:
            results.append(
                ("hbm-response", "PASS",
                 f"{hbm0 / 2**30:.1f} -> {hbm1 / 2**30:.1f} GiB during fill")
            )
        else:
            results.append(
                ("hbm-response", "FAIL",
                 f"hbm_used {hbm0} -> {hbm1} did not track a 30% fill")
            )

    # ---- MXU duty response ----
    duty0 = _mean([c.mxu_duty_pct for c in chips0]) if chips0 else None
    if synthetic:
        results.append(("mxu-response", "SKIP", "synthetic backend"))
    elif duty0 is None:
        results.append(("mxu-response", "SKIP", "no duty-cycle counter source"))
    else:
        from tpumon.loadgen.burn import mxu_burn

        stop = threading.Event()

        def burn():
            while not stop.is_set():
                mxu_burn(seconds=0.5, size=2048, iters=16)

        t = threading.Thread(target=burn, daemon=True)
        t.start()
        try:
            await asyncio.sleep(2.0)
            duty_during = []
            for _ in range(5):
                chips = await _sample_chips(collector)
                duty_during.append(_mean([c.mxu_duty_pct for c in chips]))
                await asyncio.sleep(1.0)
        finally:
            stop.set()
        peak = max((d for d in duty_during if d is not None), default=None)
        if peak is not None and peak > max(duty0, 5.0):
            results.append(
                ("mxu-response", "PASS", f"duty {duty0:.1f}% -> peak {peak:.1f}% under burn")
            )
        else:
            results.append(
                ("mxu-response", "FAIL", f"duty {duty0} -> {duty_during} under burn")
            )

    # ---- serving engine on this device ----
    # Independent of the accel backend (the engine runs on whatever jax
    # device exists, CPU included); hosts without the workload stack
    # SKIP rather than FAIL, like the counter checks above.
    try:
        detail = await asyncio.to_thread(_validate_serving)
        results.append(("serving-engine", "PASS", detail))
    except ImportError as e:
        results.append(("serving-engine", "SKIP", f"unavailable: {e}"))
    except Exception as e:
        results.append(("serving-engine", "FAIL", f"{type(e).__name__}: {e}"))

    width = max(len(r[0]) for r in results)
    failed = False
    for check, verdict, detail in results:
        print(f"{check:<{width}}  {verdict:<5} {detail}")
        failed |= verdict == "FAIL"
    return 1 if failed else 0


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    backend = "jax"
    if "--backend" in argv:
        i = argv.index("--backend")
        if i + 1 >= len(argv):
            print("--backend requires a value", file=sys.stderr)
            return 2
        backend = argv[i + 1]
    start = time.time()
    code = asyncio.run(validate(backend))
    print(f"validate: done in {time.time() - start:.1f}s, exit {code}")
    return code


if __name__ == "__main__":
    raise SystemExit(main())
