"""Self-tracing data plane: spans, a bounded ring, native histograms.

The reference monitor's only introspection is ``console.error`` on
scrape failures (monitor_server.js:34,50 — SURVEY §5.1); tpumon already
counts its own samples and request latencies, but none of that can
answer *where a tick's milliseconds went*. This module is the Dapper-
style answer, sized for an always-on monitor:

- ``SpanTracer``: an allocation-light span recorder. Every unit of
  data-plane work — a ``tick_fast`` root, each ``collect.<source>``,
  alert evaluation, history recording, SSE delta computation, every
  HTTP request — opens a span (``with tracer.span(...)``). Parent/child
  nesting rides a ``contextvars.ContextVar`` so concurrent asyncio
  tasks (an HTTP request interleaving with a tick) nest correctly.
- Completed spans land in a **bounded ring** (``--trace-ring``, default
  4096): O(1) per span, overwrite-oldest, never allocates after warmup
  beyond the span objects themselves. ``trace_ring=0`` disables
  recording entirely (a shared no-op span; the bench's comparison
  baseline).
- ``LatencyHistogram``: native Prometheus histograms (cumulative
  ``le``-bucketed counts + ``_sum`` + ``_count``) per stage and per
  HTTP route — the exporter renders them as genuine
  ``tpumon_stage_duration_seconds_*`` / ``tpumon_http_request_duration_
  seconds_*`` triples, replacing gauge-only latency reporting, so
  PromQL ``histogram_quantile`` works against the monitor itself.
- ``export_chrome()``: the ring as Chrome trace-event JSON
  (``ph``/``ts``/``dur``/``pid``/``tid``), loadable in Perfetto or
  ``chrome://tracing`` — ``GET /api/trace/export`` serves it live.

Clocking: one ``perf_counter`` pair per span; wall-clock timestamps are
derived from a single (wall, perf) anchor taken at tracer construction,
so child spans always nest inside their parent's interval exactly.

Fleet tracing (ISSUE 19): spans can carry a **trace id** that crosses
process boundaries — stamped into the optional trailing trace context
of TPWK/TPWD/TPWQ/TPWR frames (tpumon.protowire) and the
``X-Tpumon-Trace`` HTTP header — so a leaf's ``fed.push`` and the
root's ``fed.render`` are one tree. Each node ships only its own
completed trace-correlated spans upstream (a bounded ``outbox``, never
the raw ring), and the root assembles them onto its own clock with
per-link offsets estimated from frame send/recv timestamp pairs
(tpumon.federation — no wall-clock trust).
"""

from __future__ import annotations

import contextvars
import random
import time

# Prometheus-style log-spaced bounds (seconds). 100 µs floor: the data
# plane's cheapest stages (history record, delta diff) land there; 10 s
# ceiling covers a collect that rode its deadline out.
HIST_BOUNDS: tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# Current OPEN span for parent attribution (and trace-id inheritance).
# ContextVar, not a plain stack: each asyncio task runs in its own
# context copy, so an HTTP request span interleaving with a tick span
# cannot adopt its children.
_CURRENT: contextvars.ContextVar["_Span | None"] = contextvars.ContextVar(
    "tpumon_current_span", default=None
)

# Bound on distinct HTTP-route histogram keys: routes are a small fixed
# set by construction (the server never keys on unmatched paths), but a
# histogram map must stay bounded even if that invariant slips.
MAX_HTTP_ROUTES = 64
OTHER_ROUTE = "(other)"

# Fleet-tracing bounds: completed trace-correlated spans queued for the
# uplink (outbox) and remote spans buffered for root assembly. Both
# overwrite-oldest — a wedged uplink or a chatty subtree can never grow
# the tracer's footprint.
OUTBOX_CAP = 256
REMOTE_CAP = 4096

# The cross-node federation stage names (docs/observability.md
# "Distributed tracing" table; pinned by the tpulint registry pass).
# fed.push/fed.collect/fed.encode run on the sending tier each tick;
# fed.accept/fed.ingest/fed.decode/fed.rollup/fed.land on the receiving
# hub per stream/frame; fed.query wraps a pushed-down TPWQ answer;
# fed.render is the root tick stage that lands fleet freshness.
FED_STAGES: tuple[str, ...] = (
    "fed.push",
    "fed.collect",
    "fed.encode",
    "fed.accept",
    "fed.ingest",
    "fed.decode",
    "fed.rollup",
    "fed.land",
    "fed.query",
    "fed.render",
)


def format_trace_header(ctx: tuple[int, int, str]) -> str:
    """``X-Tpumon-Trace`` header value: ``<trace>-<parent sid>-<origin>``
    (ids lower-hex, origin a node name — never contains ``-``-free
    guarantees, so parsing splits at most twice)."""
    tid, psid, origin = ctx
    return f"{tid:x}-{psid:x}-{origin}"


def current_ctx_header() -> str | None:
    """The innermost open span's fleet context as an ``X-Tpumon-Trace``
    value, or None when the caller isn't inside a fleet trace — how
    outbound HTTP hops (peer fan-out) propagate without holding a
    tracer reference. ContextVars ride ``asyncio.to_thread``, so this
    works from fetch worker threads too."""
    cur = _CURRENT.get()
    if cur is None or cur.trace is None:
        return None
    return format_trace_header((cur.trace, cur.sid, cur.tracer.node))


def parse_trace_header(value: str | None) -> tuple[int, int, str] | None:
    """Inverse of format_trace_header; None on anything malformed (an
    unparseable header is dropped, never an error — tracing is advisory)."""
    if not value:
        return None
    parts = value.split("-", 2)
    if len(parts) != 3 or not parts[2] or len(parts[2]) > 128:
        return None
    try:
        return int(parts[0], 16), int(parts[1], 16), parts[2]
    except ValueError:
        return None


def quantiles(xs) -> tuple[float, float, float] | None:
    """(p50, p95, max) from one sort — the single-pass-per-render
    replacement for calling ``statistics.median`` per field."""
    if not xs:
        return None
    s = sorted(xs)
    n = len(s)
    return s[int(0.50 * (n - 1))], s[int(0.95 * (n - 1))], s[-1]


class LatencyHistogram:
    """Fixed-bucket cumulative histogram (Prometheus semantics)."""

    __slots__ = ("counts", "sum", "count")
    bounds = HIST_BOUNDS

    def __init__(self) -> None:
        self.counts = [0] * len(HIST_BOUNDS)  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0

    def observe(self, seconds: float) -> None:
        self.sum += seconds
        self.count += 1
        for i, bound in enumerate(HIST_BOUNDS):
            if seconds <= bound:
                self.counts[i] += 1
                return
        # beyond the last bound: only the +Inf bucket (== count) sees it

    def cumulative(self) -> list[tuple[float, int]]:
        """[(le, cumulative_count)] — excludes the +Inf bucket, whose
        cumulative count is ``self.count`` by definition."""
        out = []
        acc = 0
        for bound, n in zip(HIST_BOUNDS, self.counts):
            acc += n
            out.append((bound, acc))
        return out


class _Span:
    """One traced interval; a context manager recorded on exit."""

    __slots__ = (
        "tracer", "sid", "parent", "name", "cat", "track",
        "t0", "dur_ms", "tags", "trace", "remote_parent",
        "_token", "_mark",
    )

    def __init__(self, tracer: "SpanTracer", name: str, cat: str, track: str):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.track = track
        self.tags: dict | None = None
        # Fleet-trace linkage: ``trace`` is the cross-node trace id
        # (None = purely local span, never shipped), ``remote_parent``
        # is (origin node, parent sid on that node) for spans continuing
        # a context that arrived over the wire.
        self.trace: int | None = None
        self.remote_parent: tuple[str, int] | None = None

    def tag(self, **kw) -> None:
        if self.tags is None:
            self.tags = kw
        else:
            self.tags.update(kw)

    def __enter__(self) -> "_Span":
        tr = self.tracer
        tr._seq += 1
        self.sid = tr._seq
        cur = _CURRENT.get()
        self.parent = cur.sid if cur is not None else None
        if self.trace is None and cur is not None:
            self.trace = cur.trace  # inherit the enclosing trace id
        self._token = _CURRENT.set(self)
        self._mark = tr._n  # ring position at start: children gather range
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, et, ev, tb) -> bool:
        self.dur_ms = (time.perf_counter() - self.t0) * 1e3
        _CURRENT.reset(self._token)
        if et is not None:
            self.tag(error=et.__name__)
        self.tracer._record(self)
        return False


class _NoopSpan:
    """Shared do-nothing span for a disabled tracer."""

    __slots__ = ()

    def tag(self, **kw) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, et, ev, tb) -> bool:
        return False


_NOOP = _NoopSpan()


class SpanTracer:
    """Always-on span recorder over a bounded ring.

    ``capacity=0`` disables: ``span()`` hands back a shared no-op and
    nothing is recorded — the zero-overhead baseline the bench's
    ``observability`` phase compares against.
    """

    def __init__(self, capacity: int = 4096, node: str = "local"):
        self.capacity = max(0, int(capacity))
        # This process's federation node name — stamped on every span
        # shipped upstream and into wire/header trace contexts. The app
        # wiring overwrites it once the federation config is known.
        self.node = node
        self._ring: list = [None] * self.capacity
        self._n = 0  # spans recorded (monotonic)
        self._seq = 0  # span ids (monotonic; enter-ordered)
        # Fleet tracing: completed trace-correlated spans awaiting the
        # next uplink tick (bounded; drained by FederationUplink), and
        # remote spans received from downstream tiers (bounded; the
        # root's assembly buffer).
        self.outbox: list[dict] = []
        self.outbox_dropped = 0
        self.remote: list[dict] = []
        self.remote_dropped = 0
        # Wall-clock anchor: wall = anchor_wall + (perf - anchor_perf).
        self._anchor_wall = time.time()
        self._anchor_perf = time.perf_counter()
        # Per-stage Prometheus histograms (cat: tick/stage/collect) and
        # bounded recent-duration windows for the /api/trace p50/p95/max
        # summary (histograms answer PromQL; the recent window answers
        # "now", without bucket-interpolation error).
        self.stage_hist: dict[str, LatencyHistogram] = {}
        self._stage_recent: dict[str, list] = {}
        self.http_hist: dict[str, LatencyHistogram] = {}
        self._http_recent: dict[str, list] = {}
        # Compact summary of the last completed tick_fast (the SSE
        # timeline strip's payload): {"total_ms", "stages": [...]}.
        self.last_tick: dict | None = None

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    @property
    def recorded(self) -> int:
        return self._n

    @property
    def dropped(self) -> int:
        return max(0, self._n - self.capacity)

    def span(
        self,
        name: str,
        cat: str = "stage",
        track: str = "sampler",
        trace: int | None = None,
        remote: tuple[int, int, str] | None = None,
    ):
        """Open a span. ``trace`` starts/joins a fleet trace explicitly;
        ``remote`` is a wire/header context (trace id, parent sid,
        origin node) — the span joins that trace with a cross-node
        parent link. Without either, the trace id (if any) is inherited
        from the enclosing span."""
        if not self.capacity:
            return _NOOP
        sp = _Span(self, name, cat, track)
        if remote is not None:
            tid, psid, origin = remote
            sp.trace = tid
            sp.remote_parent = (origin, psid)
        elif trace is not None:
            sp.trace = trace
        return sp

    # ------------------------- fleet tracing -------------------------

    @staticmethod
    def new_trace() -> int:
        """A fresh 63-bit trace id (wire varints stay short; nonzero so
        'no trace' needs no sentinel)."""
        return random.getrandbits(63) | 1

    def current_ctx(self) -> tuple[int, int, str] | None:
        """(trace id, span id, node) of the innermost open span, if it
        belongs to a fleet trace — what gets stamped into outgoing
        frames and X-Tpumon-Trace headers."""
        cur = _CURRENT.get()
        if cur is None or cur.trace is None:
            return None
        return cur.trace, cur.sid, self.node

    def ensure_trace(self) -> tuple[int, int, str] | None:
        """Attach a fresh trace id to the innermost open span (no-op if
        it already has one) and return its context — how a request
        handler opts its already-open http span into fleet propagation."""
        if not self.capacity:
            return None
        cur = _CURRENT.get()
        if cur is None:
            return None
        if cur.trace is None:
            cur.trace = self.new_trace()
        return cur.trace, cur.sid, self.node

    def record(
        self,
        name: str,
        cat: str = "stage",
        track: str = "sampler",
        t0: float | None = None,
        dur_ms: float = 0.0,
        trace: int | None = None,
        remote_parent: tuple[str, int] | None = None,
        parent: int | None = None,
        **tags,
    ) -> int:
        """Record an already-completed span with explicit timing —
        for work whose trace context is only known after the fact (a
        hub decoding a frame learns the sender's context from its
        trailer). ``t0`` is a perf_counter mark; returns the span id
        (0 when disabled)."""
        if not self.capacity:
            return 0
        sp = _Span(self, name, cat, track)
        self._seq += 1
        sp.sid = self._seq
        sp.parent = parent
        sp.trace = trace
        sp.remote_parent = remote_parent
        sp.t0 = time.perf_counter() if t0 is None else t0
        sp.dur_ms = dur_ms
        if tags:
            sp.tags = tags
        self._record(sp)
        return sp.sid

    def drain_outbox(self, limit: int = 128) -> list[dict]:
        """Up to ``limit`` queued outbound spans, oldest first — one
        uplink tick's TPWS payload. Never returns raw ring contents."""
        if not self.outbox:
            return []
        out = self.outbox[:limit]
        del self.outbox[:limit]
        return out

    def add_remote(self, spans) -> None:
        """Buffer spans relayed from a downstream tier (already in the
        outbox JSON shape). Bounded overwrite-oldest."""
        for s in spans:
            if not isinstance(s, dict) or "name" not in s or "node" not in s:
                continue
            self.remote.append(s)
        if len(self.remote) > REMOTE_CAP:
            self.remote_dropped += len(self.remote) - REMOTE_CAP
            del self.remote[: len(self.remote) - REMOTE_CAP]

    def fleet_spans(
        self, offsets: dict[str, float] | None = None, limit: int = 2048
    ) -> list[dict]:
        """Local + remote trace-correlated spans as one list, remote
        timestamps shifted onto THIS node's clock by per-origin offsets
        (seconds, ``origin_clock - local_clock``; tpumon.federation
        estimates them from frame send/recv pairs). Sorted by ts."""
        offsets = offsets or {}
        out = []
        for s in self._spans_newest_last(self.capacity or 1):
            if s.trace is None:
                continue
            out.append(self._span_json(s))
        for r in self.remote:
            j = dict(r)
            off = offsets.get(j.get("node"))
            if off is not None and isinstance(j.get("ts"), (int, float)):
                j["ts"] = round(j["ts"] - off, 6)
                j["clock_adjusted"] = True
            out.append(j)
        out.sort(key=lambda j: j.get("ts") or 0)
        return out[-limit:]

    def _wall(self, perf_t: float) -> float:
        return self._anchor_wall + (perf_t - self._anchor_perf)

    @staticmethod
    def _recent_push(window: list, dur_ms: float, cap: int = 256) -> None:
        # Bounded append-only-then-shift window; a plain list beats a
        # deque for the sorted() pass the summary does per render.
        window.append(dur_ms)
        if len(window) > cap:
            del window[: len(window) - cap]

    def _record(self, span: _Span) -> None:
        self._ring[self._n % self.capacity] = span
        self._n += 1
        dur_s = span.dur_ms / 1e3
        if span.cat in ("tick", "stage", "collect"):
            hist = self.stage_hist.get(span.name)
            if hist is None:
                hist = self.stage_hist[span.name] = LatencyHistogram()
            hist.observe(dur_s)
            self._recent_push(
                self._stage_recent.setdefault(span.name, []), span.dur_ms
            )
        elif span.cat == "http":
            route = (span.tags or {}).get("route") or OTHER_ROUTE
            if route not in self.http_hist and len(self.http_hist) >= MAX_HTTP_ROUTES:
                route = OTHER_ROUTE
            hist = self.http_hist.get(route)
            if hist is None:
                hist = self.http_hist[route] = LatencyHistogram()
            hist.observe(dur_s)
            self._recent_push(
                self._http_recent.setdefault(route, []), span.dur_ms
            )
        if span.trace is not None:
            # Queue for the uplink: completed spans only, compact JSON
            # shape, bounded. Purely local spans (trace None) never
            # leave the process.
            self.outbox.append(self._span_json(span))
            if len(self.outbox) > OUTBOX_CAP:
                self.outbox_dropped += len(self.outbox) - OUTBOX_CAP
                del self.outbox[: len(self.outbox) - OUTBOX_CAP]
        if span.cat == "tick" and span.name == "tick_fast":
            self.last_tick = self._tick_summary(span)

    def _tick_summary(self, root: _Span) -> dict:
        """Direct children of a just-closed tick root, gathered from the
        ring slice recorded during it — O(children), no full-ring walk.
        If the tick itself overflowed the ring (tiny capacity), the
        oldest children are gone; the summary is still bounded-correct."""
        stages = []
        lo = max(root._mark, self._n - self.capacity)
        for i in range(lo, self._n):
            s = self._ring[i % self.capacity]
            if s is not None and s is not root and s.parent == root.sid:
                stages.append({"name": s.name, "ms": round(s.dur_ms, 3)})
        return {
            "ts": round(self._wall(root.t0), 3),
            "total_ms": round(root.dur_ms, 3),
            "stages": stages,
        }

    # ----------------------------- views -----------------------------

    def _spans_newest_last(self, limit: int) -> list:
        live = min(self._n, self.capacity)
        take = min(limit, live)
        return [
            self._ring[i % self.capacity]
            for i in range(self._n - take, self._n)
        ]

    def _span_json(self, s: _Span) -> dict:
        out = {
            "sid": s.sid,
            "parent": s.parent,
            "name": s.name,
            "cat": s.cat,
            "track": s.track,
            "ts": round(self._wall(s.t0), 6),
            "dur_ms": round(s.dur_ms, 3),
        }
        if s.trace is not None:
            # Hex string, not an int: trace ids are 63-bit and JS
            # number precision stops at 2**53 (dashboard.js reads this).
            out["trace"] = format(s.trace, "x")
            out["node"] = self.node
        if s.remote_parent is not None:
            out["rp"] = [s.remote_parent[0], s.remote_parent[1]]
        if s.tags:
            out["tags"] = s.tags
        return out

    @staticmethod
    def _summary(hists: dict, recents: dict) -> dict:
        out = {}
        for name, hist in sorted(hists.items()):
            q = quantiles(recents.get(name) or ())
            out[name] = {
                "count": hist.count,
                "total_ms": round(hist.sum * 1e3, 3),
                "p50_ms": round(q[0], 3) if q else None,
                "p95_ms": round(q[1], 3) if q else None,
                "max_ms": round(q[2], 3) if q else None,
            }
        return out

    def stage_summary(self) -> dict:
        """Per-stage p50/p95/max over the recent window + lifetime
        count/total — the /api/trace "stages" table."""
        return self._summary(self.stage_hist, self._stage_recent)

    def http_summary(self) -> dict:
        return self._summary(self.http_hist, self._http_recent)

    def to_json(self, spans: int = 120) -> dict:
        return {
            "enabled": self.enabled,
            "capacity": self.capacity,
            "recorded": self._n,
            "dropped": self.dropped,
            "node": self.node,
            "outbox": len(self.outbox),
            "outbox_dropped": self.outbox_dropped,
            "remote": len(self.remote),
            "remote_dropped": self.remote_dropped,
            "stages": self.stage_summary(),
            "http": self.http_summary(),
            "last_tick": self.last_tick,
            "spans": [self._span_json(s) for s in self._spans_newest_last(spans)],
        }

    def export_chrome(
        self, fleet: bool = False, offsets: dict[str, float] | None = None
    ) -> dict:
        """The ring as Chrome trace-event JSON (Perfetto /
        ``chrome://tracing`` loadable): ``X`` complete events with
        microsecond ``ts``/``dur``, one ``tid`` per logical track, and
        ``M`` metadata naming the process and tracks. Span ids ride
        ``args`` so tooling (and tests) can check parent/child nesting
        without relying on time containment alone.

        One *process* per node: the local node is always pid 1 and its
        name is stamped into the process metadata (a multi-node export
        must never collapse into one anonymous ``pid 1`` track);
        ``fleet=True`` adds the buffered remote spans, each node its own
        pid, timestamps shifted onto this node's clock by ``offsets``."""
        events: list[dict] = []
        pids: dict[str, int] = {}
        tids: dict[tuple[str, str], int] = {}

        def _pid(node: str) -> int:
            pid = pids.get(node)
            if pid is None:
                pid = pids[node] = len(pids) + 1
                events.append({
                    "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
                    "args": {"name": f"tpumon:{node}"},
                })
            return pid

        def _tid(node: str, track: str) -> int:
            key = (node, track)
            tid = tids.get(key)
            if tid is None:
                tid = tids[key] = sum(1 for k in tids if k[0] == node) + 1
                events.append({
                    "ph": "M", "pid": pids[node], "tid": tid,
                    "name": "thread_name", "args": {"name": track},
                })
            return tid

        _pid(self.node)  # local process claims pid 1 before any remote
        rows = [
            self._span_json(s)
            for s in self._spans_newest_last(self.capacity or 1)
        ]
        if fleet:
            offsets = offsets or {}
            for r in self.remote:
                j = dict(r)
                off = offsets.get(j.get("node"))
                if off is not None and isinstance(j.get("ts"), (int, float)):
                    j["ts"] = j["ts"] - off
                rows.append(j)
        for j in rows:
            node = j.get("node") or self.node
            args = {
                "sid": j.get("sid"), "parent": j.get("parent"),
                **(j.get("tags") or {}),
            }
            if j.get("trace"):
                args["trace"] = j["trace"]
            if j.get("rp"):
                args["remote_parent"] = j["rp"]
            pid = _pid(node)
            events.append({
                "ph": "X",
                "pid": pid,
                "tid": _tid(node, j.get("track") or "remote"),
                "name": j["name"],
                "cat": j.get("cat", "stage"),
                "ts": round((j.get("ts") or 0) * 1e6, 1),
                "dur": round((j.get("dur_ms") or 0) * 1e3, 1),
                "args": args,
            })
        return {"displayTimeUnit": "ms", "traceEvents": events}


# ------------------------------ CLI ------------------------------


def _fmt_ms(v) -> str:
    return f"{v:.2f}" if isinstance(v, (int, float)) else "–"


def render_trace_summary(trace: dict) -> str:
    """Terminal rendering of an /api/trace payload (``tpumon trace``)."""
    lines = [
        f"trace ring: {trace.get('recorded', 0)} spans recorded, "
        f"capacity {trace.get('capacity', 0)}, "
        f"dropped {trace.get('dropped', 0)}"
        + ("" if trace.get("enabled", True) else " (DISABLED)")
    ]
    tick = trace.get("last_tick")
    if tick:
        cells = " · ".join(
            f"{s['name']} {_fmt_ms(s['ms'])}" for s in tick.get("stages", [])
        )
        lines.append(f"last tick: {_fmt_ms(tick.get('total_ms'))} ms ({cells})")
    for title, table in (("stage", trace.get("stages") or {}),
                         ("http", trace.get("http") or {})):
        if not table:
            continue
        lines.append(f"{'':2}{title:<24} {'count':>8} {'p50 ms':>9} "
                     f"{'p95 ms':>9} {'max ms':>9}")
        for name, row in table.items():
            lines.append(
                f"{'':2}{name:<24} {row['count']:>8} "
                f"{_fmt_ms(row['p50_ms']):>9} {_fmt_ms(row['p95_ms']):>9} "
                f"{_fmt_ms(row['max_ms']):>9}"
            )
    fleet = trace.get("fleet")
    if fleet:
        fresh = fleet.get("freshness") or {}
        if fresh:
            lines.append(
                f"{'':2}{'node':<24} {'freshness ms':>12} {'offset ms':>10}"
            )
            for node, row in sorted(fresh.items()):
                lines.append(
                    f"{'':2}{node:<24} {_fmt_ms(row.get('ms')):>12} "
                    f"{_fmt_ms(row.get('offset_ms')):>10}"
                )
        spans = fleet.get("spans") or []
        nodes = {s.get("node") for s in spans if s.get("node")}
        lines.append(
            f"fleet: {len(spans)} trace-correlated spans from "
            f"{len(nodes)} node(s)"
        )
    prof = trace.get("profile") or {}
    last = prof.get("last")
    if last:
        lines.append(f"latest device profile: {last.get('dir')} ({last.get('hint')})")
    return "\n".join(lines)


def trace_cli(argv: list[str]) -> int:
    """``tpumon trace`` — dump/summarize a running server's span ring.

    usage: tpumon trace [--url HOST:8888] [--export FILE] [--spans N]
                        [--fleet]

    --fleet assembles the federation view: per-leaf freshness and the
    cross-node span buffer (clock-shifted onto the queried node), and
    makes --export emit one Perfetto process track per node.
    """
    import json
    import sys
    import urllib.request

    url = "127.0.0.1:8888"
    export_path = None
    show_spans = 0
    fleet = False
    it = iter(argv)
    for a in it:
        if a == "--url":
            url = next(it, url)
        elif a == "--export":
            export_path = next(it, None)
            if not export_path:
                print("--export requires a file path", file=sys.stderr)
                return 2
        elif a == "--spans":
            show_spans = int(next(it, "20") or 20)
        elif a == "--fleet":
            fleet = True
        elif a in ("-h", "--help"):
            print(trace_cli.__doc__)
            return 0
        else:
            print(f"unknown argument {a!r}", file=sys.stderr)
            return 2
    if "://" not in url:
        url = f"http://{url}"
    url = url.rstrip("/")
    qs = "?fleet=1" if fleet else ""

    def get(path: str):
        with urllib.request.urlopen(f"{url}{path}", timeout=10) as r:
            return json.load(r)

    try:
        if export_path:
            chrome = get(f"/api/trace/export{qs}")
            with open(export_path, "w") as f:
                json.dump(chrome, f)
            n = sum(1 for e in chrome["traceEvents"] if e["ph"] == "X")
            pids = {
                e["pid"] for e in chrome["traceEvents"] if e["ph"] == "X"
            }
            print(
                f"wrote {n} spans ({len(pids)} node track(s)) to "
                f"{export_path} — load in https://ui.perfetto.dev or "
                "chrome://tracing"
            )
            return 0
        trace = get(f"/api/trace{qs}")
    except OSError as e:
        print(f"tpumon at {url} unreachable: {e}", file=sys.stderr)
        return 1
    print(render_trace_summary(trace))
    if show_spans:
        spans = trace.get("spans") or []
        if show_spans > len(spans):
            # /api/trace ships a bounded recent window; the full ring
            # is only reachable via the export.
            print(
                f"(showing last {len(spans)} of "
                f"{trace.get('recorded', len(spans))} recorded — use "
                "--export for the full ring)"
            )
        for s in spans[-show_spans:]:
            tags = s.get("tags") or {}
            cells = " ".join(f"{k}={v}" for k, v in tags.items())
            print(
                f"  {s['ts']:.3f} {s['name']:<20} {s['dur_ms']:>9.3f} ms"
                + (f"  {cells}" if cells else "")
            )
    return 0
